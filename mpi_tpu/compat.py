"""mpi4py-style compatibility layer: ``from mpi_tpu.compat import MPI``.

The reference's users write against a Go MPI-like API; the Python
world's lingua franca for the same programs is mpi4py. This shim lets
an mpi4py-style script run on this framework by changing ONE line —

    from mpi4py import MPI          ->   from mpi_tpu.compat import MPI

— after which ``MPI.COMM_WORLD``, ``Get_rank``/``Get_size``, lowercase
pickle-based p2p/collectives (``send``/``recv``/``bcast``/``allreduce``
/...), uppercase buffer-based ``Send``/``Recv``/``Bcast``/``Allreduce``/
``Reduce``/``Allgather``/``Gather``/``Scatter``/``Alltoall``/
``Reduce_scatter`` (numpy arrays; the capital-letter convention for
typed buffers),
``Split``/``Dup``/``Free``, nonblocking ``isend``/``irecv`` AND the
MPI-3 nonblocking collectives (``iallreduce``/``ibcast``/``igather``/
``iscatter``/``ialltoall``/``ibarrier``/...) returning ``wait()``-able
requests, ``ANY_SOURCE`` receives with a ``Status``,
and the op constants (``SUM``/``PROD``/``MIN``/``MAX``) behave as an
mpi4py user expects — lowered onto whichever driver is active (tcp,
xla, hybrid), so "mpi4py code" transparently runs its collectives as
compiled XLA programs on TPU.

One-sided RMA (``MPI.Win.Create`` + ``Put``/``Get``/``Accumulate``/
``Get_accumulate``/``Fetch_and_op`` under all THREE sync modes:
``Fence``, passive ``Lock``/``Unlock``/``Flush``, and PSCW
``Post``/``Start``/``Complete``/``Wait``), parallel IO
(``MPI.File.Open`` + ``Read_at``/``Write_at``/collective ``_all``
variants/``Set_view``/the ``*_shared`` shared-pointer family),
Cartesian topologies (``comm.Create_cart`` +
``Get_coords``/``Shift``/``Sub``), distributed graphs
(``Create_dist_graph_adjacent`` + neighbor collectives),
intercommunicators (``Create_intercomm``/``Merge`` + the
``MPI.ROOT``/``MPI.PROC_NULL`` rooted-op protocol), groups
(``Get_group``/``Incl``/``Excl``/``Translate_ranks``/
``Create_group``), matched probes (``mprobe``/``improbe`` →
``MPI.Message`` with race-free ``recv``/``Recv``), ``MPI.Info``
hints, error handlers (``ERRORS_RETURN``/``ERRORS_ARE_FATAL``), comm
attributes/names, and ``COMM_SELF`` are wrapped over the native
:mod:`mpi_tpu.window`, :mod:`mpi_tpu.io`,
:class:`mpi_tpu.comm.CartComm`, :mod:`mpi_tpu.distgraph`, and
:mod:`mpi_tpu.intercomm` subsystems.

Datatypes: the named basics (``MPI.DOUBLE``/``MPI.INT``/...) map onto
numpy dtypes; buffer specs ``[buf, count, datatype]`` work on the
element-wise uppercase ops — ``Send``/``Recv``/``Isend``/``Irecv``/
``Sendrecv``, ``Bcast``, ``Allreduce``/``Reduce`` (send side of
``Reduce_scatter`` too), and the send side of ``Allgather``/``Gather``
— while the block-stacking sides (``Scatter``'s root table,
``Alltoall``, gather-family receive tables) keep their bare-array
leading-axis contract. The derived constructors ``Create_contiguous``
/ ``Create_vector`` / ``Create_subarray`` (+ ``Commit``/``Free``/
``Get_size``/``Get_extent``) pack strided layouts on the way out and
scatter them back through the receive buffer. ``MPI.IN_PLACE`` works
for Allreduce / Reduce / Allgather / Gather / Scatter, and the
v-variants (``Gatherv``/``Scatterv``/``Allgatherv``/``Alltoallv``)
take the ``[buf, counts, displs, datatype]`` spec.

Scope honesty: this is the commonly-used core surface, not all of
mpi4py (``Create_struct`` accepts any component datatype — basics,
vectors, resized strides, nested structs — laying each out by its
own byte pattern and extent;
dynamic process management covers ``Comm.Spawn`` /
``Get_parent`` / ``Disconnect`` and ``Open_port`` /
``Comm.Accept`` / ``Comm.Connect``; the MPI-4 Sessions surface
(``MPI.Session.Init`` → psets → ``Group.Create_from_session_pset``
→ ``Comm.Create_from_group``) works, backed by the driver world —
see :class:`Session` for the honesty note; passive-target RMA
(``Win.Lock``/``Unlock``/``Flush``) needs the window created with
``info={"locks": "true"}`` — see :meth:`Win.Create`; window
displacements scale by ``disp_unit`` exactly as in mpi4py, but the
scaled byte offset must land element-aligned in the exposed array —
no torn-element addressing).
``COMM_WORLD`` auto-initializes
the framework on first use, matching mpi4py's import-time init
ergonomics; call ``MPI.Finalize()`` (or ``mpi_tpu.finalize()``) at the
end as usual. No reference analogue (pure framework-usability work).
"""

from __future__ import annotations

import itertools as _itertools
import threading as _threading
from typing import Any, List, Optional

import numpy as np

from . import api
from . import errclass as _errclass
from .comm import Comm as _NativeComm, comm_self, comm_world

__all__ = ["MPI"]


class Status:
    """Receive status (mpi4py ``MPI.Status``): filled by ``recv``/
    ``Recv``/``probe`` with the actual source and tag; receives also
    record the payload size for :meth:`Get_count`."""

    def __init__(self) -> None:
        self.source: int = -1
        self.tag: int = -1
        self.count: int = -1   # elements (arrays) / bytes (raw) / -1
        self.cancelled: bool = False

    def Is_cancelled(self) -> bool:
        """True when the request this status completed was
        successfully cancelled (MPI_Test_cancelled)."""
        return self.cancelled

    def Set_cancelled(self, flag: bool) -> None:
        self.cancelled = bool(flag)

    def Get_source(self) -> int:
        return self.source

    def Get_tag(self) -> int:
        return self.tag

    def Get_count(self, datatype: Any = None) -> int:
        """Received element count (numpy payloads count elements,
        byte payloads bytes, other objects 1; -1 before any receive).
        ``datatype`` is accepted and ignored — the payload carries its
        own dtype here."""
        return self.count

    Get_elements = Get_count


def _payload_count(obj: Any) -> int:
    # NOTE: a None here counts as 1 like any other pickled object —
    # "no message at all" (MESSAGE_NO_PROC) is decided by the CALLER
    # from the message's source, never inferred from the payload, so a
    # legitimately sent None is not conflated with a no-proc receive.
    if isinstance(obj, np.ndarray):
        return int(obj.size)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    try:
        import jax

        if isinstance(obj, jax.Array):
            return int(obj.size)
    except Exception:  # noqa: BLE001 - jax absence never breaks Status
        pass
    return 1


class Request:
    """Wraps the native request; mpi4py method names (including the
    classmethod set operations ``Waitall``/``Waitany``/``Waitsome``/
    ``Testall``/``Testany``)."""

    def __init__(self, inner: "api.Request"):
        self._inner = inner

    def wait(self, status: Optional[Status] = None) -> Any:
        result = self._inner.wait()
        if status is not None:
            status.Set_cancelled(getattr(self._inner, "cancelled",
                                         False))
        return result

    Wait = wait

    def test(self) -> bool:
        return self._inner.test()

    Test = test

    def Cancel(self) -> None:
        """MPI_Cancel: best-effort — a receive whose message has not
        been matched is retracted (its ``Wait`` then completes with
        ``None`` and ``status.Is_cancelled()`` True); anything else
        completes normally, as MPI permits."""
        cancel = getattr(self._inner, "cancel", None)
        if cancel is not None:
            cancel()

    @classmethod
    def Waitall(cls, requests: List["Request"]) -> List[Any]:
        """Wait on every request; results in order (mpi4py returns
        statuses — here the payloads, which is what the lowercase
        `waitall` idiom consumes). Completion re-routes through each
        wrapper's own ``wait`` (idempotent — the native request caches
        its result) so buffer ``Irecv``s run their fill."""
        api.waitall([r._inner if r is not None else None
                     for r in requests])
        return [r.wait() if r is not None else None for r in requests]

    waitall = Waitall

    @classmethod
    def Waitany(cls, requests: List["Request"]):
        """(index, result) of the first completion; the completed slot
        is set to None in the caller's list (MPI_REQUEST_NULL), so a
        drain loop visits each request once."""
        inner = [r._inner if r is not None else None for r in requests]
        idx, _ = api.waitany(inner)
        result = requests[idx].wait()  # idempotent; runs Irecv fills
        requests[idx] = None
        return idx, result

    waitany = Waitany

    @classmethod
    def Testall(cls, requests: List["Request"]) -> bool:
        """True iff every (non-null) request has completed — without
        blocking and WITHOUT consuming results (call Waitall to
        collect them, as in mpi4py's uppercase form)."""
        return all(r is None or r.test() for r in requests)

    @classmethod
    def testall(cls, requests: List["Request"]):
        """mpi4py's lowercase contract: ``(flag, msgs)`` — when every
        request has completed, the payloads come along (consumed, as
        ``waitall`` would); otherwise ``(False, None)``."""
        if not cls.Testall(requests):
            return False, None
        return True, cls.Waitall(requests)

    @classmethod
    def testany(cls, requests: List["Request"]):
        """(index, flag, result): the first already-completed request
        (consumed: its slot becomes None); ``(MPI.UNDEFINED, True,
        None)`` when there are no active requests at all (MPI's
        no-active-handles case — flag TRUE, so drain loops terminate);
        ``(MPI.UNDEFINED, False, None)`` when active requests exist
        but none is ready. The payload rides along because object-mode
        receives have no user buffer for it to land in — the lowercase
        twin of :meth:`Testany`, as ``waitall`` is to ``Waitall``."""
        if all(r is None for r in requests):
            return UNDEFINED, True, None
        for i, r in enumerate(requests):
            if r is not None and r.test():
                result = r.wait()
                requests[i] = None
                return i, True, result
        return UNDEFINED, False, None

    @classmethod
    def Testany(cls, requests: List["Request"]):
        """mpi4py's exact ``(index, flag)`` shape — drop-in code doing
        ``idx, flag = Request.Testany(reqs)`` unpacks cleanly. A
        completed request is consumed (slot becomes None) and buffer
        ``Irecv``s run their fill; object-mode payloads are surfaced
        by the lowercase :meth:`testany` instead."""
        idx, flag, _ = cls.testany(requests)
        return idx, flag

    @classmethod
    def Testsome(cls, requests: List["Request"]):
        """Nonblocking :meth:`Waitsome`: (indices, results) of every
        request complete RIGHT NOW (all consumed: slots become None);
        ``([], [])`` when active requests exist but none is ready;
        ``(None, None)`` when every slot is already null
        (MPI_UNDEFINED case). Like ``Waitall``'s documented contract,
        a completed-with-error request re-raises its error (its slot
        is consumed; results collected before it are carried on the
        exception as ``exc.partial = (indices, results)`` so a drain
        loop can keep the delivered payloads)."""
        if all(r is None for r in requests):
            return None, None
        indices, results = [], []
        for i, r in enumerate(requests):
            if r is not None and r.test():
                try:
                    result = r.wait()
                except Exception as exc:
                    requests[i] = None     # complete, just failed
                    exc.partial = (indices, results)
                    raise
                results.append(result)
                indices.append(i)
                requests[i] = None
        return indices, results

    testsome = Testsome

    @classmethod
    def Waitsome(cls, requests: List["Request"]):
        """Block until at least one request completes; returns
        (indices, results) for EVERY request complete at that moment
        (all consumed: their slots become None), or ``(None, None)``
        when every slot is already null (MPI_UNDEFINED case)."""
        if all(r is None for r in requests):
            return None, None
        first, first_result = cls.Waitany(requests)
        indices, results = [first], [first_result]
        for i, r in enumerate(requests):
            if r is not None and r.test():
                results.append(r.wait())
                indices.append(i)
                requests[i] = None
        return indices, results

    waitsome = Waitsome


class Message:
    """mpi4py ``MPI.Message`` over :class:`mpi_tpu.comm.Message`: a
    matched-and-claimed message handle from ``mprobe``/``improbe``."""

    def __init__(self, native):
        self._m = native

    @property
    def source(self) -> int:
        return self._m.source

    def _is_no_proc(self) -> bool:
        # The native no-proc message (PROC_NULL mprobe) carries
        # source None — "no message at all" is decided from the
        # SOURCE, never inferred from a None payload, so a
        # legitimately sent None keeps its object count.
        return self._m.source is None

    def recv(self, status: Optional[Status] = None) -> Any:
        no_proc = self._is_no_proc()
        obj = self._m.recv()
        if status is not None:
            status.source = PROC_NULL if no_proc else self._m.source
            status.tag = self._m.tag
            # mpi4py's MPI_MESSAGE_NO_PROC recv reports count 0.
            status.count = 0 if no_proc else _payload_count(obj)
        return obj

    def Recv(self, buf: Any, status: Optional[Status] = None) -> None:
        """Buffer form (MPI_Mrecv): the payload lands in ``buf``.
        The no-proc message completes immediately with ``buf``
        untouched and count 0 (MPI_MESSAGE_NO_PROC contract)."""
        target = _RecvTarget(buf, "Message.Recv")
        if self._is_no_proc():
            self._m.recv()  # consume: the handle is single-use
            if status is not None:
                status.source, status.tag = PROC_NULL, self._m.tag
                status.count = 0
            return
        obj = self._m.recv()
        target.fill(obj)
        if status is not None:
            status.source, status.tag = self._m.source, self._m.tag
            status.count = _payload_count(np.asarray(obj))


class _AnySourceRequest(Request):
    """irecv(ANY_SOURCE): the native op yields (source, payload);
    ``wait(status)`` fills the status with the real sender — the
    information mpi4py callers reply to — and returns the payload."""

    def wait(self, status: Optional[Status] = None) -> Any:
        src, obj = self._inner.wait()
        if status is not None:
            status.source = src
        return obj

    Wait = wait


class Prequest(Request):
    """mpi4py ``MPI.Prequest`` over the native partitioned send/recv
    (MPI-4 partitioned communication). A :class:`Request` subclass, as
    in mpi4py, so the set operations accept it — Waitall on a
    Prequest completes its current iteration."""

    def __init__(self, native):
        # The trivial inner request keeps Waitall/Waitany's parallel
        # join happy; the REAL completion is this wrapper's Wait().
        super().__init__(api.Request(lambda: None))
        self._p = native

    def Start(self) -> None:
        self._p.start()

    def Pready(self, partition: int) -> None:
        self._p.pready(partition)

    def Pready_range(self, lo: int, hi: int) -> None:
        self._p.pready_range(lo, hi)

    def Parrived(self, partition: int) -> bool:
        return self._p.parrived(partition)

    def Wait(self, status: Optional[Status] = None) -> None:
        """Complete the open iteration; a no-op when none is open
        (MPI: waiting an inactive persistent request returns
        immediately — this is what lets Waitall mix Prequests with
        ordinary requests)."""
        if self._p.active:
            self._p.wait()

    wait = Wait

    def Test(self) -> bool:
        """Complete iff no iteration is open (MPI: a started
        partitioned request completes at Wait)."""
        return not self._p.active

    test = Test


# Outstanding buffered sends (MPI_Bsend family): the payload is
# detached at the call, but MPI_Finalize must not tear the transport
# from under a rendezvous still waiting for its receiver — Finalize
# drains this registry first. Completed entries are swept
# opportunistically on each new bsend so a long-running rank doesn't
# accumulate request objects.
_pending_bsends: List["api.Request"] = []
_pending_bsends_lock = _threading.Lock()


def _track_bsend(req: "api.Request") -> "api.Request":
    with _pending_bsends_lock:
        # ONE test() per request: a second pass could see a request
        # complete in between and purge it without ever reaching the
        # error warning below.
        done, still = [], []
        for r in _pending_bsends:
            (done if r.test() else still).append(r)
        still.append(req)
        _pending_bsends[:] = still
    for r in done:
        if r._exc is not None:  # surface, don't silently drop the msg
            import warnings as _warnings

            _warnings.warn(
                f"mpi_tpu: a buffered send failed: "
                f"{type(r._exc).__name__}: {r._exc}",
                RuntimeWarning, stacklevel=3)
    return req


def _drain_bsends(timeout: float = 30.0) -> None:
    import time as _time
    import warnings as _warnings

    with _pending_bsends_lock:
        pending = list(_pending_bsends)
        _pending_bsends.clear()
    # One SHARED deadline across the set: N undeliverable sends must
    # stall Finalize for ~timeout total, not N * timeout — once the
    # deadline passes, the remainder is abandoned with one warning.
    deadline = _time.monotonic() + timeout
    for i, r in enumerate(pending):
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            _warnings.warn(
                f"mpi_tpu: {len(pending) - i} buffered send(s) still "
                f"undelivered after the {timeout:.0f}s finalize drain "
                f"window", RuntimeWarning, stacklevel=2)
            break
        try:
            r.wait(remaining)
        except Exception as exc:  # noqa: BLE001 - finalize proceeds
            # A buffered send's error has nowhere else to surface
            # (nobody waits the request) — say so instead of silently
            # dropping the message.
            _warnings.warn(
                f"mpi_tpu: a buffered send could not complete before "
                f"finalize: {type(exc).__name__}: {exc}",
                RuntimeWarning, stacklevel=2)


class _GrequestInner:
    """Event-backed stand-in for :class:`api.Request`: completion is
    the user's :meth:`Grequest.Complete` call, not a worker thread —
    shaped like the native request so the Waitall/Waitany set
    operations mix Grequests with ordinary requests."""

    def __init__(self) -> None:
        self._ev = _threading.Event()
        self.cancelled = False

    def test(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: Optional[float] = None) -> Any:
        if not self._ev.wait(timeout):
            raise api.MpiError(
                f"mpi_tpu.compat: Grequest.wait timed out after "
                f"{timeout}s (Complete() never called)")
        return None

    def cancel(self) -> bool:
        return False  # Grequest cancellation is the cancel_fn's job


class Grequest(Request):
    """mpi4py ``MPI.Grequest`` — generalized requests: user-defined
    operations that complete when the USER calls :meth:`Complete`,
    integrating with the whole request machinery (Wait/Test/Waitall).

    Callback contract (MPI_Grequest_start): ``query_fn(status,
    *args)`` fills the status at completion-query time; ``free_fn
    (*args)`` runs at :meth:`Free`; ``cancel_fn(completed, *args)``
    runs at :meth:`Cancel` with whether the operation had already
    completed. Callbacks may be None."""

    def __init__(self, query_fn=None, free_fn=None, cancel_fn=None,
                 args: tuple = ()):
        super().__init__(_GrequestInner())
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._cancel_fn = cancel_fn
        self._args = tuple(args or ())

    @classmethod
    def Start(cls, query_fn=None, free_fn=None, cancel_fn=None,
              args=None) -> "Grequest":
        return cls(query_fn, free_fn, cancel_fn, args or ())

    def Complete(self) -> None:
        """Mark the operation complete: pending/future ``Wait``s
        return (MPI_Grequest_complete)."""
        self._inner._ev.set()

    def wait(self, status: Optional[Status] = None) -> Any:
        result = self._inner.wait()
        if status is not None:
            if self._query_fn is not None:
                self._query_fn(status, *self._args)
            status.Set_cancelled(self._inner.cancelled)
        return result

    Wait = wait

    def Cancel(self) -> None:
        if self._cancel_fn is not None:
            self._cancel_fn(self._inner.test(), *self._args)
        if not self._inner.test():
            self._inner.cancelled = True
            self.Complete()  # a cancelled grequest completes, per MPI

    def Free(self) -> None:
        if self._free_fn is not None:
            self._free_fn(*self._args)


class _FillOnWaitRequest(Request):
    """Uppercase ``Irecv``: completion must land the payload in the
    caller's buffer (and run any datatype unpack), so ``wait`` routes
    through a fill closure. ``Waitall``/``Waitany`` complete the inner
    native request; the fill still runs exactly once, on first
    observation, via the api.Request result cache — so this wrapper
    fills eagerly inside the closure instead."""

    def __init__(self, inner: "api.Request", wait_fill) -> None:
        super().__init__(inner)
        self._wait_fill = wait_fill
        self._done = False

    def wait(self, status: Optional[Status] = None) -> Any:
        got = self._wait_fill(status)
        self._done = True
        return got

    Wait = wait

    def test(self) -> bool:
        if not self._done and self._inner.test():
            self.wait()
        return self._done

    Test = test


# MPI_Comm_split_type's standard type (shared-memory domain) — defined
# before Comm so Split_type's signature can default to it, like
# mpi4py's.
COMM_TYPE_SHARED = 1


class Comm:
    """mpi4py-flavoured view over a native communicator."""

    def __init__(self, native: _NativeComm):
        self._c = native
        # MPI attribute caching + names live on the NATIVE communicator
        # so every wrapper of the same Comm object sees them (wrappers
        # are cheap views; fresh wrappers of a fresh native — e.g. a
        # second comm_world() — start clean, which mpi4py's handle
        # semantics also allow). Keyed BY GROUP RANK: under the
        # thread-per-rank drivers every rank-thread shares one native
        # world comm, and MPI attributes are per-process state — one
        # rank's Set_attr must never be visible to another.
        if not hasattr(native, "_compat_attrs"):
            native._compat_attrs = {}
            native._compat_names = {}

    def _attrs(self) -> dict:
        return self._c._compat_attrs.setdefault(self._c.rank(), {})

    def __eq__(self, other: Any) -> bool:
        # Wrapper objects are cheap views; communicator identity is the
        # underlying (driver, context, membership) — so fresh wrappers
        # of one communicator compare equal, as mpi4py code expects of
        # `comm == MPI.COMM_WORLD`.
        if not isinstance(other, Comm):
            return NotImplemented
        return (self._c._impl is other._c._impl
                and self._c.context == other._c.context
                and self._c.members == other._c.members)

    def __hash__(self) -> int:
        return hash((id(self._c._impl), self._c.context, self._c.members))

    # -- identity -----------------------------------------------------------

    def Get_rank(self) -> int:
        return self._c.rank()

    def Get_size(self) -> int:
        return self._c.size()

    rank = property(Get_rank)
    size = property(Get_size)

    def Is_inter(self) -> bool:
        """False: this is an intracommunicator (MPI_Comm_test_inter);
        :class:`Intercomm` answers True."""
        return False

    def Is_intra(self) -> bool:
        return not self.Is_inter()

    is_inter = property(Is_inter)
    is_intra = property(Is_intra)

    @property
    def native(self) -> _NativeComm:
        """The underlying :class:`mpi_tpu.comm.Comm` (escape hatch)."""
        return self._c

    # -- pickle-based p2p (lowercase, mpi4py semantics) ---------------------
    #
    # Tag wildcards do not exist here (tags are unbounded i64, so an
    # ANY_TAG match cannot be probed): receive-side tags default to 0
    # — matching send's default, so default-tag scripts pair up — and
    # passing ANY_TAG raises loudly instead of silently hanging.

    def send(self, obj: Any, dest: int, tag: int = 0) -> None:
        self._c.send(obj, dest, tag)

    def recv(self, source: int = -1, tag: int = 0,
             status: Optional[Status] = None) -> Any:
        _check_tag_not_wild(tag, "recv")
        if source == ANY_SOURCE:
            src, obj = self._c.receive_any(tag)
        else:
            src, obj = source, self._c.receive(source, tag)
        if status is not None:
            status.source, status.tag = src, tag
            status.count = _payload_count(obj)
        return obj

    def sendrecv(self, sendobj: Any, dest: int, sendtag: int = 0,
                 recvbuf: Any = None, source: int = -1,
                 recvtag: Optional[int] = None,
                 status: Optional[Status] = None) -> Any:
        """mpi4py parameter ORDER (recvbuf is the 4th positional — it
        is accepted and ignored, as the pickle path needs no scratch
        buffer). ``recvtag`` defaults to ``sendtag``; ANY_TAG raises."""
        if recvtag is None:
            recvtag = sendtag
        _check_tag_not_wild(recvtag, "sendrecv")
        if source == ANY_SOURCE:
            # wildcard source: concurrent tagged send + ANY_SOURCE recv
            sreq = self._c.isend(sendobj, dest, sendtag)
            src, obj = self._c.receive_any(recvtag)
            sreq.wait()
        else:
            if sendtag == recvtag:
                obj = self._c.sendrecv(sendobj, dest=dest, source=source,
                                       tag=sendtag)
            else:
                sreq = self._c.isend(sendobj, dest, sendtag)
                obj = self._c.receive(source, recvtag)
                sreq.wait()
            src = source
        if status is not None:
            status.source, status.tag = src, recvtag
        return obj

    def isend(self, obj: Any, dest: int, tag: int = 0) -> Request:
        return Request(self._c.isend(obj, dest, tag))

    # Send MODES (MPI_Ssend / MPI_Bsend families). The base send IS
    # synchronous here (rendezvous: it returns only once the receive
    # accepted — network.go:569 parity), so the S-forms alias it
    # honestly. The B-forms provide real BUFFERED semantics: the
    # payload is detached (deep-copied) immediately and the rendezvous
    # completes on a background worker, so the caller returns at once
    # and may reuse its buffer — code relying on MPI_Bsend's local
    # completion to avoid head-to-head deadlocks works unchanged
    # (buffering is automatic; no Attach_buffer needed).

    ssend = send
    issend = isend

    def bsend(self, obj: Any, dest: int, tag: int = 0) -> None:
        import copy as _copy

        # Eager envelope validation: the background worker defers
        # _check_peer, and an unwaited buffered send would otherwise
        # swallow even an invalid destination silently.
        self._c._check_peer(dest)
        _track_bsend(self._c.isend(_copy.deepcopy(obj), dest, tag))

    def ibsend(self, obj: Any, dest: int, tag: int = 0) -> Request:
        """Buffered isend: returns a request that completes when the
        detached payload has been delivered (waiting it is optional —
        MPI says a buffered send's completion never depends on a
        matching receive having started, and the copy already
        happened)."""
        import copy as _copy

        self._c._check_peer(dest)
        return Request(_track_bsend(
            self._c.isend(_copy.deepcopy(obj), dest, tag)))

    def irecv(self, source: int = -1, tag: int = 0) -> Request:
        _check_tag_not_wild(tag, "irecv")
        if source == ANY_SOURCE:
            return _AnySourceRequest(api.Request(
                lambda: self._c.receive_any(tag)))
        return Request(self._c.irecv(source, tag))

    def probe(self, source: int = -1, tag: int = 0,
              status: Optional[Status] = None) -> bool:
        """Blocking probe; ``source`` defaults to ANY_SOURCE as in
        mpi4py (polls every rank until a matching message appears)."""
        import time as _time

        _check_tag_not_wild(tag, "probe")
        if source != ANY_SOURCE:
            self._c.probe(source, tag)
            src = source
        else:
            while True:
                src = self._iprobe_any(tag)
                if src is not None:
                    break
                _time.sleep(0.0005)
        if status is not None:
            status.source, status.tag = src, tag
        return True

    def iprobe(self, source: int = -1, tag: int = 0,
               status: Optional[Status] = None) -> bool:
        _check_tag_not_wild(tag, "iprobe")
        if source != ANY_SOURCE:
            hit = self._c.iprobe(source, tag)
            src = source
        else:
            src = self._iprobe_any(tag)
            hit = src is not None
        if hit and status is not None:
            status.source, status.tag = src, tag
        return hit

    def _iprobe_any(self, tag: int) -> Optional[int]:
        for src in range(self._c.size()):
            if self._c.iprobe(src, tag):
                return src
        return None

    # mpi4py exposes both spellings (probe == Probe etc.).
    Probe = probe
    Iprobe = iprobe

    # -- partitioned p2p (MPI-4 MPI_Psend_init family) ----------------------

    def Psend_init(self, buf: Any, partitions: int, dest: int,
                   tag: int = 0) -> "Prequest":
        """Persistent partitioned send (MPI_Psend_init): Start() opens
        an iteration, Pready(i) ships partition i immediately
        (overlapping the producer's remaining work), Wait() completes;
        then Start() again."""
        return Prequest(self._c.psend_init(
            np.asarray(buf), int(partitions), dest, tag))

    def Precv_init(self, buf: Any, partitions: int, source: int,
                   tag: int = 0) -> "Prequest":
        return Prequest(self._c.precv_init(
            _writable_buffer(buf, "Precv_init"), int(partitions),
            source, tag))

    # -- matched probe (MPI_Mprobe family) ----------------------------------

    def mprobe(self, source: int = -1, tag: int = 0,
               status: Optional[Status] = None) -> "Message":
        """Matched probe: the returned :class:`Message` is claimed —
        no sibling receive can steal it (the thread-safe wildcard
        pattern MPI_Mprobe exists for)."""
        _check_tag_not_wild(tag, "mprobe")
        if source == ANY_SOURCE:
            native = self._c.mprobe_any(tag)
        elif source == PROC_NULL:
            native = self._c.mprobe(None, tag)  # no-proc message
        else:
            native = self._c.mprobe(source, tag)
        if status is not None:
            no_proc = native.source is None
            status.source = PROC_NULL if no_proc else native.source
            status.tag = tag
            status.count = (0 if no_proc
                            else _payload_count(native._payload))
        return Message(native)

    def improbe(self, source: int = -1, tag: int = 0,
                status: Optional[Status] = None) -> Optional["Message"]:
        _check_tag_not_wild(tag, "improbe")
        if source == ANY_SOURCE:
            src = self._iprobe_any(tag)
            if src is None:
                return None
            source = src
        if source == PROC_NULL:
            # MPI_Improbe from PROC_NULL: flag true immediately with
            # the no-proc message (same as the blocking Mprobe path).
            native = self._c.mprobe(None, tag)
        else:
            native = self._c.improbe(source, tag)
        if native is None:
            return None
        if status is not None:
            no_proc = native.source is None
            status.source = PROC_NULL if no_proc else native.source
            status.tag = tag
            status.count = (0 if no_proc
                            else _payload_count(native._payload))
        return Message(native)

    Mprobe = mprobe
    Improbe = improbe

    # -- buffer-based p2p (uppercase: numpy arrays, no repickling) ----------
    #
    # ``buf`` is a bare array or an mpi4py buffer spec ``[buf, count,
    # datatype]`` (see the datatype section): derived datatypes pack on
    # the way out and scatter back through the layout on the way in.

    def Send(self, buf: Any, dest: int, tag: int = 0) -> None:
        self._c.send(_spec_payload(buf, "Send"), dest, tag)

    def Recv(self, buf: Any, source: int = -1, tag: int = 0,
             status: Optional[Status] = None) -> None:
        _check_tag_not_wild(tag, "Recv")
        target = _RecvTarget(buf, "Recv")  # validate before communicating
        if source == ANY_SOURCE:
            src, got = self._c.receive_any(tag)
        else:
            src, got = source, self._c.receive(source, tag)
        target.fill(got)
        if status is not None:
            status.source, status.tag = src, tag
            status.count = _payload_count(np.asarray(got))

    def Isend(self, buf: Any, dest: int, tag: int = 0) -> Request:
        return Request(self._c.isend(_spec_payload(buf, "Isend"),
                                     dest, tag))

    # Buffer-form send modes (see the object-form block above for the
    # semantics: S-forms alias the already-synchronous send; B-forms
    # snapshot the packed payload and complete in the background).
    Ssend = Send
    Issend = Isend

    def Bsend(self, buf: Any, dest: int, tag: int = 0) -> None:
        self._c._check_peer(dest)
        payload = _spec_payload(buf, "Bsend")
        _track_bsend(self._c.isend(np.array(payload, copy=True),
                                   dest, tag))

    def Ibsend(self, buf: Any, dest: int, tag: int = 0) -> Request:
        self._c._check_peer(dest)
        payload = _spec_payload(buf, "Ibsend")
        return Request(_track_bsend(
            self._c.isend(np.array(payload, copy=True), dest, tag)))

    def Irecv(self, buf: Any, source: int = -1, tag: int = 0) -> Request:
        """Nonblocking buffer receive: the buffer fills when the
        returned request's ``wait()``/``Waitall`` completes."""
        _check_tag_not_wild(tag, "Irecv")
        target = _RecvTarget(buf, "Irecv")
        if source == ANY_SOURCE:
            inner = api.Request(lambda: self._c.receive_any(tag))

            def _wait_fill_any(status: Optional[Status] = None) -> Any:
                src, got = inner.wait()
                target.fill(got)
                if status is not None:
                    status.source, status.tag = src, tag
                    status.count = _payload_count(np.asarray(got))
                return got
        else:
            inner = self._c.irecv(source, tag)

            def _wait_fill_any(status: Optional[Status] = None) -> Any:
                got = inner.wait()
                target.fill(got)
                if status is not None:
                    status.source, status.tag = source, tag
                    status.count = _payload_count(np.asarray(got))
                return got
        return _FillOnWaitRequest(inner, _wait_fill_any)

    def Sendrecv_replace(self, buf: Any, dest: int, sendtag: int = 0,
                         source: int = -1,
                         recvtag: Optional[int] = None,
                         status: Optional[Status] = None) -> None:
        """Buffer sendrecv where ONE buffer (or buffer spec) is both
        the outgoing data and the landing zone
        (MPI_Sendrecv_replace): the payload is snapshotted before the
        exchange, so overlap is safe."""
        _RecvTarget(buf, "Sendrecv_replace")  # validate before moving
        # ONE snapshot copy: _spec_payload may return the caller's own
        # contiguous buffer, which the receive below writes through.
        payload = _spec_payload(buf, "Sendrecv_replace").copy()
        self.Sendrecv(payload, dest, sendtag,
                      recvbuf=buf, source=source, recvtag=recvtag,
                      status=status)

    def Sendrecv(self, sendbuf: Any, dest: int, sendtag: int = 0,
                 recvbuf: Any = None, source: int = -1,
                 recvtag: Optional[int] = None,
                 status: Optional[Status] = None) -> None:
        """Buffer sendrecv (deadlock-free pairwise exchange); the
        received payload lands in ``recvbuf``."""
        if recvtag is None:
            recvtag = sendtag
        _check_tag_not_wild(recvtag, "Sendrecv")
        target = _RecvTarget(recvbuf, "Sendrecv")
        payload = _spec_payload(sendbuf, "Sendrecv")
        if source == ANY_SOURCE:
            sreq = self._c.isend(payload, dest, sendtag)
            src, got = self._c.receive_any(recvtag)
            sreq.wait()
        elif sendtag == recvtag:
            src, got = source, self._c.sendrecv(
                payload, dest=dest, source=source, tag=sendtag)
        else:
            sreq = self._c.isend(payload, dest, sendtag)
            src, got = source, self._c.receive(source, recvtag)
            sreq.wait()
        target.fill(got)
        if status is not None:
            status.source, status.tag = src, recvtag
            status.count = _payload_count(np.asarray(got))

    # -- collectives --------------------------------------------------------

    def barrier(self) -> None:
        self._c.barrier()

    Barrier = barrier

    def bcast(self, obj: Any = None, root: int = 0) -> Any:
        return self._c.bcast(obj, root=root)

    def Bcast(self, buf: Any, root: int = 0) -> None:
        if self.Get_rank() == root:
            # Root's buffer IS the data; nothing to write back.
            self._c.bcast(_spec_payload(buf, "Bcast"), root=root)
        else:
            target = _RecvTarget(buf, "Bcast")
            target.fill(self._c.bcast(None, root=root))

    def allreduce(self, sendobj: Any, op: "Op" = None) -> Any:
        return self._c.allreduce(sendobj, op=_op(op))

    def Allreduce(self, sendbuf: Any, recvbuf: Any,
                  op: "Op" = None) -> None:
        """``sendbuf`` may be ``MPI.IN_PLACE``: this rank's
        contribution is then read from ``recvbuf`` (mpi4py semantics);
        either side may be a ``[buf, count, datatype]`` spec."""
        target = _RecvTarget(recvbuf, "Allreduce")
        if sendbuf is IN_PLACE:
            payload = _spec_payload(recvbuf, "Allreduce")
        else:
            payload = _spec_payload(sendbuf, "Allreduce")
        target.fill(self._c.allreduce(payload, op=_op(op)))

    def reduce(self, sendobj: Any, op: "Op" = None,
               root: int = 0) -> Optional[Any]:
        return self._c.reduce(sendobj, root=root, op=_op(op))

    def Reduce(self, sendbuf: Any, recvbuf: Any, op: "Op" = None,
               root: int = 0) -> None:
        """At the root, ``sendbuf=MPI.IN_PLACE`` reads the root's
        contribution from ``recvbuf`` (mpi4py semantics)."""
        at_root = self.Get_rank() == root
        target = _RecvTarget(recvbuf, "Reduce") if at_root else None
        if sendbuf is IN_PLACE:
            if not at_root:
                raise api.MpiError(
                    "mpi_tpu.compat: Reduce with MPI.IN_PLACE is only "
                    "valid at the root (non-roots pass their sendbuf)")
            payload = _spec_payload(recvbuf, "Reduce")
        else:
            payload = _spec_payload(sendbuf, "Reduce")
        got = self._c.reduce(payload, root=root, op=_op(op))
        if at_root:
            target.fill(got)

    def Allgather(self, sendbuf: Any, recvbuf: Any) -> None:
        """Buffer allgather: ``recvbuf`` holds every rank's sendbuf
        stacked in rank order (shape ``(size, *sendbuf.shape)`` or any
        same-size reshape of it). ``sendbuf=MPI.IN_PLACE`` reads this
        rank's contribution from its slot of ``recvbuf``."""
        if sendbuf is IN_PLACE:
            out = _writable_buffer(recvbuf, "Allgather")
            _leading_axis_is_size(out, self.Get_size(), "Allgather")
            payload = np.ascontiguousarray(out[self.Get_rank()])
        else:
            payload = _spec_payload(sendbuf, "Allgather")
        got = self._c.allgather(payload)
        _fill_stacked(recvbuf, got, "Allgather")

    def Gather(self, sendbuf: Any, recvbuf: Any, root: int = 0) -> None:
        """At the root, ``sendbuf=MPI.IN_PLACE`` reads the root's
        contribution from its slot of ``recvbuf``."""
        if sendbuf is IN_PLACE:
            if self.Get_rank() != root:
                raise api.MpiError(
                    "mpi_tpu.compat: Gather with MPI.IN_PLACE is only "
                    "valid at the root")
            out = _writable_buffer(recvbuf, "Gather")
            _leading_axis_is_size(out, self.Get_size(), "Gather")
            payload = np.ascontiguousarray(out[root])
        else:
            payload = _spec_payload(sendbuf, "Gather")
        got = self._c.gather(payload, root=root)
        if self.Get_rank() == root:
            _fill_stacked(recvbuf, got, "Gather")

    def Scatter(self, sendbuf: Any, recvbuf: Any, root: int = 0) -> None:
        """Buffer scatter: the root's ``sendbuf`` splits along its
        leading axis (which must equal the comm size). At the root,
        ``recvbuf=MPI.IN_PLACE`` leaves the root's block in place."""
        if self.Get_rank() == root:
            arr = np.ascontiguousarray(sendbuf)
            _leading_axis_is_size(arr, self.Get_size(), "Scatter")
            parts: Optional[List[Any]] = list(arr)
        else:
            parts = None
        if recvbuf is IN_PLACE:
            if self.Get_rank() != root:
                raise api.MpiError(
                    "mpi_tpu.compat: Scatter with recvbuf=MPI.IN_PLACE "
                    "is only valid at the root")
            self._c.scatter(parts, root=root)
            return
        got = self._c.scatter(parts, root=root)
        _fill(recvbuf, got, "Scatter")

    def Alltoall(self, sendbuf: Any, recvbuf: Any) -> None:
        """Buffer all-to-all: leading axis = comm size on both sides;
        row j of ``sendbuf`` goes to rank j."""
        arr = np.ascontiguousarray(sendbuf)
        _leading_axis_is_size(arr, self.Get_size(), "Alltoall")
        got = self._c.alltoall(list(arr))
        _fill_stacked(recvbuf, got, "Alltoall")

    def Reduce_scatter(self, sendbuf: Any, recvbuf: Any,
                       recvcounts: Any = None, op: "Op" = None) -> None:
        """Equal-block reduce-scatter (``MPI_Reduce_scatter_block``
        semantics): ``sendbuf`` reduces elementwise across ranks and
        this rank receives its 1/size block. ``recvcounts`` is
        accepted only as equal blocks."""
        if recvcounts is not None and len(set(recvcounts)) != 1:
            raise api.MpiError(
                "mpi_tpu.compat: Reduce_scatter supports equal "
                "recvcounts only (MPI_Reduce_scatter_block)")
        got = self._c.reduce_scatter(
            _spec_payload(sendbuf, "Reduce_scatter"), op=_op(op))
        _fill(recvbuf, got, "Reduce_scatter")

    # -- v-variant collectives (per-rank counts + displacements) ------------
    #
    # MPI_Gatherv / Scatterv / Allgatherv / Alltoallv: the varying side
    # takes a ``[buf, counts, displs(, datatype)]`` spec (displs=None
    # means packed). Blocks travel as independent payloads over the
    # object collectives — unequal sizes cost nothing here because the
    # wire layer frames each payload anyway (unlike MPI's contiguous
    # recvbuf contract, which this reassembles at the edges).

    def Gatherv(self, sendbuf: Any, recvbuf: Any, root: int = 0) -> None:
        payload = _spec_payload(sendbuf, "Gatherv")
        parts = self._c.gather(payload, root=root)
        if self.Get_rank() != root:
            return
        flat, counts, displs, _ = _parse_vspec(
            recvbuf, self.Get_size(), "Gatherv")
        for r, part in enumerate(parts):
            data = np.asarray(part).reshape(-1)
            if data.size != counts[r]:
                raise api.MpiError(
                    f"mpi_tpu.compat: Gatherv: rank {r} sent "
                    f"{data.size} elements, recv counts[{r}] is "
                    f"{counts[r]}")
            flat[displs[r]:displs[r] + counts[r]] = data

    def Scatterv(self, sendbuf: Any, recvbuf: Any, root: int = 0) -> None:
        if self.Get_rank() == root:
            flat, counts, displs, _ = _parse_vspec(
                sendbuf, self.Get_size(), "Scatterv")
            parts: Optional[List[Any]] = [
                np.ascontiguousarray(flat[displs[r]:displs[r] + counts[r]])
                for r in range(self.Get_size())]
        else:
            parts = None
        got = self._c.scatter(parts, root=root)
        target = _RecvTarget(recvbuf, "Scatterv")
        target.fill(got)

    def Allgatherv(self, sendbuf: Any, recvbuf: Any) -> None:
        payload = _spec_payload(sendbuf, "Allgatherv")
        parts = self._c.allgather(payload)
        flat, counts, displs, _ = _parse_vspec(
            recvbuf, self.Get_size(), "Allgatherv")
        for r, part in enumerate(parts):
            data = np.asarray(part).reshape(-1)
            if data.size != counts[r]:
                raise api.MpiError(
                    f"mpi_tpu.compat: Allgatherv: rank {r} sent "
                    f"{data.size} elements, recv counts[{r}] is "
                    f"{counts[r]}")
            flat[displs[r]:displs[r] + counts[r]] = data

    def Alltoallv(self, sendbuf: Any, recvbuf: Any) -> None:
        """Per-rank varying all-to-all: block j of the send spec goes
        to rank j; block i of the recv spec fills from rank i."""
        sflat, scounts, sdispls, _ = _parse_vspec(
            sendbuf, self.Get_size(), "Alltoallv")
        blocks = [np.ascontiguousarray(
            sflat[sdispls[r]:sdispls[r] + scounts[r]])
            for r in range(self.Get_size())]
        parts = self._c.alltoall(blocks)
        rflat, rcounts, rdispls, _ = _parse_vspec(
            recvbuf, self.Get_size(), "Alltoallv")
        for r, part in enumerate(parts):
            data = np.asarray(part).reshape(-1)
            if data.size != rcounts[r]:
                raise api.MpiError(
                    f"mpi_tpu.compat: Alltoallv: rank {r} sent "
                    f"{data.size} elements, recv counts[{r}] is "
                    f"{rcounts[r]}")
            rflat[rdispls[r]:rdispls[r] + rcounts[r]] = data

    def gather(self, sendobj: Any, root: int = 0) -> Optional[List[Any]]:
        return self._c.gather(sendobj, root=root)

    def allgather(self, sendobj: Any) -> List[Any]:
        return self._c.allgather(sendobj)

    def scatter(self, sendobj: Optional[List[Any]] = None,
                root: int = 0) -> Any:
        return self._c.scatter(sendobj, root=root)

    def alltoall(self, sendobj: List[Any]) -> List[Any]:
        return self._c.alltoall(sendobj)

    def scan(self, sendobj: Any, op: "Op" = None) -> Any:
        return self._c.scan(sendobj, op=_op(op))

    def exscan(self, sendobj: Any, op: "Op" = None) -> Optional[Any]:
        return self._c.exscan(sendobj, op=_op(op))

    def _scan_payload(self, sendbuf: Any, recvbuf: Any,
                      what: str) -> np.ndarray:
        # IN_PLACE reads the contribution from recvbuf — which fill()
        # will MUTATE while slower rank-threads are still prefix-
        # folding the aliased in-process payload (the scan engines
        # fold per-rank AFTER the allgather rendezvous, unlike
        # Allreduce's combine-inside-the-rendezvous). ONE snapshot
        # copy breaks the alias, exactly as Sendrecv_replace does.
        if sendbuf is IN_PLACE:
            return np.array(_spec_payload(recvbuf, what), copy=True)
        return _spec_payload(sendbuf, what)

    def Scan(self, sendbuf: Any, recvbuf: Any, op: "Op" = None) -> None:
        """Buffer-form inclusive prefix reduction (``MPI_Scan``);
        ``sendbuf=MPI.IN_PLACE`` reads this rank's contribution from
        ``recvbuf``, mpi4py semantics."""
        target = _RecvTarget(recvbuf, "Scan")
        payload = self._scan_payload(sendbuf, recvbuf, "Scan")
        target.fill(self._c.scan(payload, op=_op(op)))

    def Exscan(self, sendbuf: Any, recvbuf: Any, op: "Op" = None
               ) -> None:
        """Buffer-form EXCLUSIVE prefix reduction (``MPI_Exscan``).
        Rank 0's receive buffer is left untouched (its exclusive
        prefix is undefined, per MPI)."""
        target = _RecvTarget(recvbuf, "Exscan")
        payload = self._scan_payload(sendbuf, recvbuf, "Exscan")
        out = self._c.exscan(payload, op=_op(op))
        if out is not None:
            target.fill(out)

    def Split_type(self, split_type: int = COMM_TYPE_SHARED,
                   key: int = 0,
                   info: Any = None) -> Optional["Comm"]:
        """``MPI_Comm_split_type`` with ``MPI.COMM_TYPE_SHARED`` (the
        only standard type): one communicator per shared-memory
        domain — here the driver's host grouping (``split_type
        ("host")``), which is exactly the shared-memory boundary on
        the hybrid driver and the whole world on single-host drivers.
        ``MPI.UNDEFINED`` participates in the collective and returns
        ``COMM_NULL`` (``None``), per MPI — raising instead would
        deadlock the ranks that did ask for a grouping. ``info``
        accepted and ignored."""
        if split_type == UNDEFINED:
            # split_type('host') IS split(color=host_key): color=None
            # joins that same collective as a non-member, which by
            # split's contract always yields no communicator.
            self._c.split(color=None, key=key)
            return None
        if split_type != COMM_TYPE_SHARED:
            raise api.MpiError(
                f"mpi_tpu.compat: Split_type supports "
                f"MPI.COMM_TYPE_SHARED or MPI.UNDEFINED, got "
                f"{split_type}")
        return Comm(self._c.split_type("host", key=key))

    # -- nonblocking collectives (lowercase pickle, mpi4py-style) -----------
    #
    # Each returns a Request whose wait() yields what the blocking
    # twin returns; launch order chains per communicator (the native
    # _icoll contract), matching MPI's ordered-collectives rule.

    def ibarrier(self) -> Request:
        return Request(self._c.ibarrier())

    def iallreduce(self, sendobj: Any, op: "Op" = None) -> Request:
        return Request(self._c.iallreduce(sendobj, op=_op(op)))

    def ireduce(self, sendobj: Any, op: "Op" = None,
                root: int = 0) -> Request:
        return Request(self._c.ireduce(sendobj, root=root, op=_op(op)))

    def ibcast(self, obj: Any = None, root: int = 0) -> Request:
        return Request(self._c.ibcast(obj, root=root))

    def igather(self, sendobj: Any, root: int = 0) -> Request:
        return Request(self._c.igather(sendobj, root=root))

    def iallgather(self, sendobj: Any) -> Request:
        return Request(self._c.iallgather(sendobj))

    def iscatter(self, sendobj: Optional[List[Any]] = None,
                 root: int = 0) -> Request:
        return Request(self._c.iscatter(sendobj, root=root))

    def ialltoall(self, sendobj: List[Any]) -> Request:
        return Request(self._c.ialltoall(sendobj))

    # -- error handlers -----------------------------------------------------

    def Set_errhandler(self, errhandler: "Errhandler") -> None:
        """Route to the native error-handler setting ('return' raises
        MpiError to the caller — the default here AND what mpi4py code
        usually sets; 'fatal' aborts the job). Deviation from mpi4py:
        the handler is PROCESS-global (the native facade has one), not
        per-communicator — under thread-per-rank drivers every rank
        shares it, so set it once at startup, not per-rank."""
        if not isinstance(errhandler, Errhandler):
            raise api.MpiError(
                f"mpi_tpu.compat: Set_errhandler expects MPI.ERRORS_"
                f"RETURN / MPI.ERRORS_ARE_FATAL / a Get_errhandler "
                f"result, got {errhandler!r}")
        api.set_errhandler(errhandler._native)

    def Get_errhandler(self) -> "Errhandler":
        native = api.get_errhandler()
        if native == "return":
            return ERRORS_RETURN
        if native == "fatal":
            return ERRORS_ARE_FATAL
        return Errhandler(native)  # user callable: restorable as-is

    # -- attribute caching and names ----------------------------------------

    # itertools.count.__next__ is atomic in CPython — rank-threads
    # calling Create_keyval concurrently can never share a keyval.
    _keyval_counter = _itertools.count(1)

    @classmethod
    def Create_keyval(cls, copy_fn: Any = None, delete_fn: Any = None,
                      nopython: bool = False) -> int:
        """A fresh attribute key (MPI_Comm_create_keyval). Copy/delete
        callbacks are accepted and ignored — attributes here never
        propagate on Dup (callers re-attach), matching the default
        MPI_COMM_NULL_COPY_FN behavior."""
        return next(cls._keyval_counter)

    @classmethod
    def Free_keyval(cls, keyval: int) -> int:
        return KEYVAL_INVALID

    def Set_attr(self, keyval: int, attrval: Any) -> None:
        self._attrs()[keyval] = attrval

    def Get_attr(self, keyval: int) -> Any:
        """The cached value, or None when unset (mpi4py convention)."""
        return self._attrs().get(keyval)

    def Delete_attr(self, keyval: int) -> None:
        self._attrs().pop(keyval, None)

    def Set_name(self, name: str) -> None:
        self._c._compat_names[self._c.rank()] = str(name)

    def Get_name(self) -> str:
        name = self._c._compat_names.get(self._c.rank())
        if name is not None:
            return name
        if self._c.context == 0:
            return "MPI_COMM_WORLD"
        from .comm import SELF_CTX

        if self._c.context == SELF_CTX and len(self._c.members) == 1:
            return "MPI_COMM_SELF"
        return f"mpi_tpu comm ctx={self._c.context}"

    # -- construction -------------------------------------------------------

    def Split(self, color: Optional[int] = 0, key: int = 0
              ) -> Optional["Comm"]:
        child = self._c.split(color=color, key=key)
        return None if child is None else Comm(child)

    def Dup(self) -> "Comm":
        return Comm(self._c.dup())

    def Free(self) -> None:
        self._c.free()

    def Abort(self, errorcode: int = 1) -> None:
        api.abort(errorcode)

    # -- topology -----------------------------------------------------------

    def Create_cart(self, dims, periods=None,
                    reorder: bool = False) -> "Cartcomm":
        """Cartesian communicator over this comm's ranks
        (``MPI_Cart_create``). ``reorder`` is accepted and ignored —
        rank order is always preserved, matching the native
        :func:`mpi_tpu.comm.cart_create` (reorder=false) semantics."""
        from .comm import cart_create

        return Cartcomm(cart_create(self._c, dims, periods))

    def Create_dist_graph_adjacent(self, sources, destinations,
                                   sourceweights=None, destweights=None,
                                   info: Any = None,
                                   reorder: bool = False
                                   ) -> "Distgraphcomm":
        """Distributed-graph communicator
        (``MPI_Dist_graph_create_adjacent``). Weights and ``reorder``
        are accepted and ignored (rank order is preserved; the native
        graph engine is unweighted)."""
        from .distgraph import dist_graph_create_adjacent

        return Distgraphcomm(dist_graph_create_adjacent(
            self._c, list(sources), list(destinations)))

    def Create_graph(self, index, edges, reorder: bool = False
                     ) -> "Graphcomm":
        """Legacy general-graph topology (``MPI_Graph_create``):
        every rank passes the same global ``index``/``edges`` arrays
        in the MPI-1 cumulative convention. ``reorder`` is accepted
        and ignored (rank order is preserved). The graph must be
        symmetric for the neighbor collectives, and ``len(index)``
        must equal the comm size — see
        :func:`mpi_tpu.distgraph.graph_create`."""
        from .distgraph import graph_create

        return Graphcomm(graph_create(self._c, list(index),
                                      list(edges)))

    def Get_group(self) -> "Group":
        """This comm's group (``MPI_Comm_group``): all ranks, comm
        order."""
        return Group(self, range(self.Get_size()))

    def Create_group(self, group: "Group", tag: int = 0
                     ) -> Optional["Comm"]:
        """Communicator from an explicit subset
        (``MPI_Comm_create_group``): collective among the group's
        members ONLY. Non-members (who in MPI would receive
        ``COMM_NULL``) must not call — the native engine's contract —
        and get ``None`` returned if they do appear in no-op form."""
        if group._parent != self:
            # The group's ranks number in ITS parent communicator; a
            # foreign group's ranks fed to this comm would build a
            # communicator over the wrong processes (and, since the
            # misresolution differs per process, likely hang the
            # members-only bootstrap). mpi4py errors too.
            raise api.MpiError(
                "mpi_tpu.compat: Create_group with a group from a "
                "different communicator")
        me = self.Get_rank()
        if me not in group._ranks:
            return None
        return Comm(self._c.create_group(group._ranks, tag=tag))

    @classmethod
    def Create_from_group(cls, group: "Group", stringtag: str = "",
                          info: Any = None, errhandler: Any = None
                          ) -> "Comm":
        """MPI-4 Sessions: a communicator directly from a group
        (``MPI_Comm_create_from_group``) — collective among the
        group's members ONLY, no parent communicator named at the call
        site. ``stringtag`` disambiguates concurrent constructions
        exactly as in MPI; it maps onto the bounded bootstrap tag
        space by a stable hash, so distinct concurrent stringtags on
        overlapping groups collide with probability 1/4096 — use
        distinct literal tags there, as MPI itself requires.
        ``info``/``errhandler`` accepted and ignored."""
        import zlib

        tag = zlib.crc32(str(stringtag).encode()) % 4096
        return Comm(group._parent._c.create_group(group._ranks,
                                                  tag=tag))

    def Create_intercomm(self, local_leader: int, peer_comm: "Comm",
                         remote_leader: int, tag: int = 0
                         ) -> "Intercomm":
        """Intercommunicator between this comm's group and a disjoint
        remote group (``MPI_Intercomm_create``); ``peer_comm`` is the
        bridge both leaders share (typically ``COMM_WORLD``)."""
        from .intercomm import create_intercomm

        return Intercomm(create_intercomm(
            self._c, local_leader, peer_comm._c, remote_leader, tag=tag))

    def Spawn(self, command: str, args: Any = None, maxprocs: int = 1,
              info: Any = None, root: int = 0) -> "Intercomm":
        """``MPI_Comm_spawn``: launch ``maxprocs`` copies of the
        Python program ``command`` on this host and return the
        intercommunicator to them (mpi4py shape: collective over this
        comm; the children's ``MPI.COMM_WORLD`` contains exactly the
        children, and their ``MPI.Comm.Get_parent()`` reaches back
        here). ``info`` accepts mpi4py's argument slot and is ignored
        (single-host spawn, one configuration). See
        :mod:`mpi_tpu.spawn` for the bridge design."""
        from . import spawn as _spawn

        return Intercomm(_spawn.spawn(
            self._c, command, tuple(args or ()), int(maxprocs),
            root=root))

    @staticmethod
    def Get_parent() -> Any:
        """``MPI_Comm_get_parent``: the intercomm to the spawning
        processes, or ``COMM_NULL`` (``None``) when this process was
        not spawned — gate with ``parent != MPI.COMM_NULL`` exactly as
        with mpi4py."""
        from . import spawn as _spawn

        p = _spawn.get_parent()
        return Intercomm(p) if p is not None else COMM_NULL

    def Accept(self, port_name: str, info: Any = None, root: int = 0,
               timeout: Optional[float] = None) -> "Intercomm":
        """``MPI_Comm_accept``: block until a client group
        ``Connect``\\ s to ``port_name`` (from :func:`Open_port`),
        then return the intercomm to it. Collective over this comm;
        ``info`` accepted and ignored. Blocks indefinitely by default
        — MPI's own semantics (a server routinely starts long before
        its clients); the extra ``timeout`` kwarg bounds the wait for
        callers that want one (mpi4py code never passes it)."""
        from . import spawn as _spawn

        return Intercomm(_spawn.accept(self._c, port_name, root=root,
                                       timeout=timeout))

    def Connect(self, port_name: str, info: Any = None, root: int = 0,
                timeout: Optional[float] = None) -> "Intercomm":
        """``MPI_Comm_connect``: rendezvous with the server group
        accepting on ``port_name``; returns the intercomm. Collective
        over this comm; ``info`` accepted and ignored. Retries the
        dial until the server reaches ``Accept`` — indefinitely by
        default, like MPI; bound it with the extra ``timeout``
        kwarg."""
        from . import spawn as _spawn

        return Intercomm(_spawn.connect(self._c, port_name, root=root,
                                        timeout=timeout))


class Cartcomm(Comm):
    """mpi4py ``MPI.Cartcomm`` over :class:`mpi_tpu.comm.CartComm`."""

    def __init__(self, native):
        super().__init__(native)

    # mpi4py properties
    @property
    def dims(self) -> List[int]:
        return list(self._c.dims)

    @property
    def periods(self) -> List[int]:
        return [int(p) for p in self._c.periods]

    @property
    def coords(self) -> List[int]:
        return list(self._c.coords())

    @property
    def ndim(self) -> int:
        return len(self._c.dims)

    @property
    def topo(self):
        return self.Get_topo()

    def Get_topo(self):
        return (self.dims, self.periods, self.coords)

    def Get_cart_rank(self, coords) -> int:
        return self._c.rank_of(coords)

    def Get_coords(self, rank: int) -> List[int]:
        return list(self._c.coords(rank))

    def Shift(self, direction: int, disp: int = 1):
        """(source, dest) for a ``disp`` displacement along axis
        ``direction`` (``MPI_Cart_shift``); ``MPI.PROC_NULL`` marks the
        edge of a non-periodic axis."""
        src, dst = self._c.shift(direction, disp)
        return (PROC_NULL if src is None else src,
                PROC_NULL if dst is None else dst)

    def Sub(self, remain_dims) -> "Cartcomm":
        return Cartcomm(self._c.sub(remain_dims))


class Group:
    """mpi4py ``MPI.Group``: an ordered rank subset of a parent comm.

    Ranks are the PARENT communicator's group ranks (as in MPI, where
    a group born of ``Get_group`` numbers like its communicator);
    ``Incl``/``Excl`` derive subsets, ``Create_group`` on the parent
    materializes a communicator from one."""

    def __init__(self, parent: "Comm", ranks):
        self._parent = parent
        self._ranks = tuple(int(r) for r in ranks)

    def Get_size(self) -> int:
        return len(self._ranks)

    def Get_rank(self) -> int:
        """This process's rank in the group, or ``MPI.UNDEFINED``."""
        me = self._parent.Get_rank()
        return (self._ranks.index(me) if me in self._ranks
                else UNDEFINED)

    size = property(Get_size)
    rank = property(Get_rank)

    @property
    def ranks(self):
        """Parent-comm ranks, in group order."""
        return list(self._ranks)

    def _check_range(self, r: int) -> int:
        # MPI raises MPI_ERR_RANK for out-of-range group ranks; a
        # Python negative-index wraparound would hand back a
        # plausible-looking wrong group instead.
        r = int(r)
        if not 0 <= r < len(self._ranks):
            raise api.MpiError(
                f"mpi_tpu.compat: group rank {r} out of range "
                f"[0, {len(self._ranks)})")
        return r

    @classmethod
    def Create_from_session_pset(cls, session: "Session",
                                 pset_name: str) -> "Group":
        """MPI-4 Sessions: the group of a named process set
        (``MPI_Group_from_session_pset``). Feed the result to
        :meth:`Comm.Create_from_group`."""
        return session._pset_group(pset_name)

    def Incl(self, ranks) -> "Group":
        """Subset containing ``ranks`` (group ranks), in that order."""
        return Group(self._parent,
                     [self._ranks[self._check_range(r)] for r in ranks])

    def Excl(self, ranks) -> "Group":
        """Subset with the given group ranks removed, order kept."""
        drop = {self._check_range(r) for r in ranks}
        return Group(self._parent,
                     [m for i, m in enumerate(self._ranks)
                      if i not in drop])

    def Translate_ranks(self, ranks=None, other: "Group" = None):
        """Map this group's ranks into ``other``'s numbering
        (``MPI.UNDEFINED`` where absent). ``ranks=None`` means every
        rank of this group, as in mpi4py."""
        if other is None:
            raise api.MpiError(
                "mpi_tpu.compat: Translate_ranks needs a target group")
        if ranks is None:
            ranks = range(len(self._ranks))
        out = []
        for r in ranks:
            m = self._ranks[self._check_range(r)]
            out.append(other._ranks.index(m) if m in other._ranks
                       else UNDEFINED)
        return out

    def Free(self) -> None:
        """Groups hold no driver resources; provided for parity."""


class Distgraphcomm(Comm):
    """mpi4py ``MPI.Distgraphcomm`` over
    :class:`mpi_tpu.distgraph.DistGraphComm`."""

    def Get_dist_neighbors_count(self):
        """(indegree, outdegree, weighted=False)."""
        return (len(self._c.in_neighbors), len(self._c.out_neighbors),
                False)

    def Get_dist_neighbors(self):
        """(sources, destinations, weights=None) — declaration order,
        the order the neighbor collectives use."""
        return (list(self._c.in_neighbors), list(self._c.out_neighbors),
                None)

    @property
    def inedges(self) -> List[int]:
        return list(self._c.in_neighbors)

    @property
    def outedges(self) -> List[int]:
        return list(self._c.out_neighbors)

    def neighbor_allgather(self, sendobj: Any) -> List[Any]:
        """Send ``sendobj`` along every out-edge; one payload per
        in-edge, in declaration order (MPI_Neighbor_allgather)."""
        return self._c.neighbor_allgather(sendobj)

    def neighbor_alltoall(self, sendobj: List[Any]) -> List[Any]:
        """``sendobj[i]`` travels out-edge ``i``; returns one payload
        per in-edge (MPI_Neighbor_alltoall)."""
        return self._c.neighbor_alltoall(sendobj)

    def ineighbor_allgather(self, sendobj: Any) -> Request:
        """Nonblocking :meth:`neighbor_allgather`
        (MPI_Ineighbor_allgather); complete via ``Request.wait()``."""
        return Request(self._c.ineighbor_allgather(sendobj))

    def ineighbor_alltoall(self, sendobj: List[Any]) -> Request:
        """Nonblocking :meth:`neighbor_alltoall`
        (MPI_Ineighbor_alltoall); complete via ``Request.wait()``."""
        return Request(self._c.ineighbor_alltoall(sendobj))


class Graphcomm(Distgraphcomm):
    """mpi4py ``MPI.Graphcomm`` over
    :class:`mpi_tpu.distgraph.GraphComm` — the legacy MPI-1 general
    graph: the whole ``(index, edges)`` adjacency is global knowledge,
    so any rank can query any node; neighbor collectives are inherited
    from the distributed-graph engine."""

    def Get_dims(self):
        """(nnodes, nedges) — MPI_Graphdims_get."""
        return self._c.graph_dims()

    dims = property(Get_dims)

    def Get_topo(self):
        """(index, edges) as passed to ``Create_graph``
        (MPI_Graph_get)."""
        return list(self._c.index), list(self._c.edges)

    topo = property(Get_topo)

    @property
    def index(self) -> List[int]:
        return list(self._c.index)

    @property
    def edges(self) -> List[int]:
        return list(self._c.edges)

    @property
    def nnodes(self) -> int:
        return self._c.graph_dims()[0]

    @property
    def nedges(self) -> int:
        return self._c.graph_dims()[1]

    def Get_neighbors_count(self, rank: int) -> int:
        """MPI_Graph_neighbors_count."""
        return self._c.graph_neighbors_count(rank)

    def Get_neighbors(self, rank: int) -> List[int]:
        """MPI_Graph_neighbors."""
        return list(self._c.graph_neighbors(rank))

    @property
    def nneighbors(self) -> int:
        return self.Get_neighbors_count(self.Get_rank())

    @property
    def neighbors(self) -> List[int]:
        return self.Get_neighbors(self.Get_rank())


class Intercomm:
    """mpi4py ``MPI.Intercomm`` over :class:`mpi_tpu.intercomm
    .Intercomm`. P2p addresses REMOTE ranks; ``allreduce`` returns the
    remote group's reduction; rooted ops use the MPI root protocol —
    ``root=MPI.ROOT`` on the root, ``MPI.PROC_NULL`` on its group
    peers, the root's remote rank on the receiving side."""

    def __init__(self, native):
        self._c = native

    def Is_inter(self) -> bool:
        """True (MPI_Comm_test_inter)."""
        return True

    def Is_intra(self) -> bool:
        return False

    is_inter = property(Is_inter)
    is_intra = property(Is_intra)

    @property
    def native(self):
        return self._c

    def Get_rank(self) -> int:
        return self._c.rank()

    def Get_size(self) -> int:
        return self._c.size()

    def Get_remote_size(self) -> int:
        return self._c.remote_size()

    rank = property(Get_rank)
    size = property(Get_size)
    remote_size = property(Get_remote_size)

    @staticmethod
    def _root(root):
        from .intercomm import ROOT as _NATIVE_ROOT

        if root is ROOT_SENTINEL:
            return _NATIVE_ROOT
        if root == PROC_NULL:
            return None
        return root

    def send(self, obj: Any, dest: int, tag: int = 0) -> None:
        self._c.send(obj, dest, tag)

    def recv(self, source: int = -1, tag: int = 0,
             status: Optional[Status] = None) -> Any:
        _check_tag_not_wild(tag, "recv")
        if source == ANY_SOURCE:
            raise api.MpiError(
                "mpi_tpu.compat: intercomm recv needs an explicit "
                "remote source rank")
        obj = self._c.receive(source, tag)
        if status is not None:
            status.source, status.tag = source, tag
        return obj

    def sendrecv(self, sendobj: Any, dest: int, sendtag: int = 0,
                 recvbuf: Any = None, source: int = -1,
                 recvtag: Optional[int] = None) -> Any:
        """mpi4py parameter order (``recvbuf`` accepted and ignored —
        pickle path); ``recvtag`` defaults to ``sendtag``; distinct
        tags run as concurrent isend + receive."""
        if recvtag is None:
            recvtag = sendtag
        _check_tag_not_wild(recvtag, "sendrecv")
        _check_tag_not_wild(sendtag, "sendrecv")
        if sendtag == recvtag:
            return self._c.sendrecv(sendobj, dest=dest, source=source,
                                    tag=sendtag)
        sreq = self._c.isend(sendobj, dest, sendtag)
        obj = self._c.receive(source, recvtag)
        sreq.wait()
        return obj

    def barrier(self) -> None:
        self._c.barrier()

    Barrier = barrier

    # Send modes (same contracts as Comm's: the base send is already
    # synchronous; the B-forms detach the payload and are drained by
    # MPI.Finalize). dest addresses a REMOTE rank, like every
    # intercomm p2p call; the envelope validates EAGERLY — an
    # unwaited buffered send must not swallow an invalid remote rank.
    ssend = send

    def bsend(self, obj: Any, dest: int, tag: int = 0) -> None:
        import copy as _copy

        self._c._remote_to_union(dest)
        _track_bsend(self._c.isend(_copy.deepcopy(obj), dest, tag))

    def ibsend(self, obj: Any, dest: int, tag: int = 0) -> Request:
        import copy as _copy

        self._c._remote_to_union(dest)
        return Request(_track_bsend(
            self._c.isend(_copy.deepcopy(obj), dest, tag)))

    def Free(self) -> None:
        """Release the intercomm's private union communicator
        (``MPI_Comm_free`` analogue)."""
        self._c.free()

    def Disconnect(self) -> None:
        """``MPI_Comm_disconnect``: what mpi4py code calls on a
        spawn/Get_parent intercomm when done with the other group —
        frees the communicator and tears down the spawn bridge network
        (sockets + reader threads; without this a long-running master
        leaks one TCP mesh per ``Spawn``). On a non-spawn intercomm
        this is :meth:`Free`."""
        from . import spawn as _spawn

        _spawn.disconnect(self._c)

    def allgather(self, sendobj: Any) -> List[Any]:
        return self._c.allgather(sendobj)

    def alltoall(self, sendobj: List[Any]) -> List[Any]:
        return self._c.alltoall(sendobj)

    def allreduce(self, sendobj: Any, op: Optional[Op] = None) -> Any:
        return self._c.allreduce(sendobj, op=_op(op))

    def bcast(self, obj: Any = None, root: Any = None) -> Optional[Any]:
        return self._c.bcast(obj, root=self._root(root))

    def reduce(self, sendobj: Any = None, op: Optional[Op] = None,
               root: Any = None) -> Optional[Any]:
        return self._c.reduce(sendobj, root=self._root(root),
                              op=_op(op))

    def Merge(self, high: bool = False) -> Comm:
        """Collapse into an intracommunicator
        (``MPI_Intercomm_merge``); the low (``high=False``) group
        orders first."""
        return Comm(self._c.merge(high=high))


class Win:
    """mpi4py ``MPI.Win`` over :class:`mpi_tpu.window.Window` —
    active-target fence synchronization (``MPI_Win_fence`` epochs).

    Target displacements are in units of ``disp_unit`` BYTES (MPI's
    and mpi4py's exact semantics; the default ``disp_unit=1`` means
    byte offsets — element-offset code passes
    ``disp_unit=arr.dtype.itemsize``). The scaled byte offset must
    land element-aligned in the exposed array (no torn elements;
    checked per call). ``Get`` and the fetching accumulates land in
    the caller's buffer at the closing :meth:`Fence`, per the MPI
    completion rules."""

    def __init__(self, native):
        self._w = native
        # (handle, destination buffer): resolved at the closing fence.
        self._pending: List[Any] = []

    @classmethod
    def Create(cls, memory: Any, disp_unit: int = 1, info: Any = None,
               comm: Optional[Comm] = None) -> "Win":
        """Collective window creation (``MPI_Win_create``). ``memory``
        is this rank's exposed 1-D numpy array; ``comm`` defaults to
        ``COMM_WORLD`` (``MPI.COMM_SELF`` works too — a single-rank
        window). Passive-target
        ``Lock``/``Unlock`` needs ``info={"locks": "true"}`` (every
        member must pass it — it starts the per-rank service thread;
        the inverse of MPI's ``no_locks`` hint, off by default because
        the software progress engine polls)."""
        from .window import win_create

        # np.asarray on a list would expose a detached COPY: remote
        # puts would land where the caller can never see them.
        mem = _writable_buffer(memory, "Win.Create")
        if disp_unit < 1:
            raise api.MpiError(
                f"mpi_tpu.compat: Win disp_unit must be >= 1, got "
                f"{disp_unit}")
        locks = bool(info) and str(
            dict(info).get("locks", "false")).lower() == "true"
        c = (MPI.COMM_WORLD if comm is None else comm)._c
        win = cls(win_create(c, mem, locks=locks))
        # mpi4py-exact displacement semantics: target displacements
        # are in units of disp_unit BYTES (default 1, MPI's own
        # default) and must land element-aligned in the exposed array
        # — checked per call in _disp. Element-offset code passes
        # disp_unit=mem.dtype.itemsize, the portable mpi4py spelling.
        win._disp_unit = int(disp_unit)
        win._itemsize = int(mem.dtype.itemsize)
        return win

    @classmethod
    def Allocate(cls, size: int, disp_unit: int = 1, info: Any = None,
                 comm: Optional[Comm] = None) -> "Win":
        """``MPI_Win_allocate``: allocate ``size`` bytes on this rank
        and expose them as a window (retrieve the buffer with
        :meth:`tomemory`). Collective; same ``disp_unit``/``info``
        semantics as :meth:`Create`."""
        size = int(size)
        if size < 0:
            raise api.MpiError(
                f"mpi_tpu.compat: Win.Allocate size must be >= 0, "
                f"got {size}")
        return cls.Create(np.zeros(size, np.uint8),
                          disp_unit=disp_unit, info=info, comm=comm)

    @classmethod
    def Allocate_shared(cls, size: int, disp_unit: int = 1,
                        info: Any = None,
                        comm: Optional[Comm] = None) -> "Win":
        """``MPI_Win_allocate_shared``: like :meth:`Allocate`, with
        the members' buffers addressable via :meth:`Shared_query`.
        Direct cross-rank loads/stores need a shared address space —
        the thread-per-rank xla driver provides one; on cross-process
        drivers ``Shared_query`` raises and RMA goes through
        put/get + fences (the window itself works everywhere)."""
        return cls.Allocate(size, disp_unit=disp_unit, info=info,
                            comm=comm)

    @property
    def native(self):
        """The underlying :class:`mpi_tpu.window.Window`."""
        return self._w

    def tomemory(self) -> np.ndarray:
        """This rank's exposed window memory (local access is legal
        between fences)."""
        return self._w.local

    def _disp(self, target, origin_size: int) -> int:
        # mpi4py spells the target as disp or [disp, count, datatype].
        # A count that disagrees with the origin buffer would silently
        # transfer the wrong span — fail loudly instead (this shim
        # always moves exactly the origin's elements).
        if target is None:
            return 0
        if isinstance(target, (list, tuple)):
            if len(target) >= 2 and int(target[1]) != origin_size:
                raise api.MpiError(
                    f"mpi_tpu.compat: target spec count {target[1]} != "
                    f"origin buffer size {origin_size}; this shim "
                    f"transfers exactly the origin's elements")
            raw = int(target[0]) if target else 0
        else:
            raw = int(target)
        # Displacements are disp_unit-BYTE offsets (mpi4py/MPI
        # semantics; window attrs set in Create — default itemsize for
        # windows built through the native layer directly).
        unit = getattr(self, "_disp_unit", None)
        itemsize = getattr(self, "_itemsize", None)
        if unit is None or itemsize is None or unit == itemsize:
            return raw
        nbytes = raw * unit
        if nbytes % itemsize:
            raise api.MpiError(
                f"mpi_tpu.compat: target displacement {raw} x "
                f"disp_unit {unit} = byte offset {nbytes}, which is "
                f"not aligned to the window dtype's itemsize "
                f"{itemsize}")
        return nbytes // itemsize

    def Put(self, origin: Any, target_rank: int, target=None) -> None:
        arr = np.asarray(origin)
        self._w.put(arr, target_rank, self._disp(target, arr.size))

    def _deliver(self, h: Any, out: np.ndarray) -> None:
        """Passive (lock-epoch) results are ready immediately — land
        them now; fence-epoch results wait for the closing Fence."""
        if h.ready:
            np.copyto(out, h.array.reshape(out.shape))
        else:
            self._pending.append((h, out))

    def Get(self, origin: Any, target_rank: int, target=None) -> None:
        out = _writable_buffer(origin, "Win.Get")
        h = self._w.get(target_rank, self._disp(target, out.size),
                        count=out.size)
        self._deliver(h, out)

    def Accumulate(self, origin: Any, target_rank: int, target=None,
                   op: Optional[Op] = None) -> None:
        arr = np.asarray(origin)
        self._w.accumulate(arr, target_rank, self._disp(target, arr.size),
                           op=_op(op))

    def Get_accumulate(self, origin: Any, result: Any, target_rank: int,
                       target=None, op: Optional[Op] = None) -> None:
        out = _writable_buffer(result, "Win.Get_accumulate")
        arr = np.asarray(origin)
        h = self._w.get_accumulate(arr, target_rank,
                                   self._disp(target, arr.size),
                                   op=_op(op))
        self._deliver(h, out)

    def Fetch_and_op(self, origin: Any, result: Any, target_rank: int,
                     target=0, op: Optional[Op] = None) -> None:
        out = _writable_buffer(result, "Win.Fetch_and_op")
        h = self._w.fetch_and_op(np.asarray(origin), target_rank,
                                 self._disp(target, 1), op=_op(op))
        self._deliver(h, out)

    def Fence(self, assertion: int = 0) -> None:
        """Close the epoch (collective): all queued RMA completes, and
        every pending ``Get``/``Get_accumulate``/``Fetch_and_op``
        result is copied into its caller-supplied buffer."""
        self._w.fence()
        pending, self._pending = self._pending, []
        for handle, out in pending:
            np.copyto(out, handle.array.reshape(out.shape))

    # -- PSCW (MPI_Win_post/start/complete/wait) ----------------------------

    def _group_ranks(self, group) -> set:
        """Window-comm ranks for a PSCW group. An ``MPI.Group``
        identifies PROCESSES (its ranks number in its parent comm, as
        in mpi4py), so each member is translated parent-rank → world
        rank → this window's comm rank; a plain iterable of ints is
        taken as window-comm ranks directly."""
        wmembers = self._w.comm.members
        if isinstance(group, Group):
            out = set()
            for g in group._ranks:
                world = group._parent._c.translate(g)
                try:
                    out.add(wmembers.index(world))
                except ValueError:
                    raise api.MpiError(
                        f"mpi_tpu.compat: PSCW group member (world "
                        f"rank {world}) is not in the window's "
                        f"communicator") from None
            return out
        return {int(r) for r in group}

    def Post(self, group, assertion: int = 0) -> None:
        """Open an exposure epoch to ``group`` (an ``MPI.Group`` or an
        iterable of window-comm ranks); needs
        ``info={"locks": "true"}``."""
        self._w.post(self._group_ranks(group))

    def Start(self, group, assertion: int = 0) -> None:
        self._w.start(self._group_ranks(group))

    def Complete(self) -> None:
        self._w.complete()

    def Wait(self) -> None:
        self._w.wait()

    # -- passive target (MPI_Win_lock/unlock) -------------------------------

    def Lock(self, rank: int, lock_type: Optional[int] = None,
             assertion: int = 0) -> None:
        """Open a passive epoch at ``rank`` (needs the window created
        with ``info={"locks": "true"}``). ``lock_type`` defaults to
        ``MPI.LOCK_EXCLUSIVE``, as in mpi4py."""
        self._w.lock(rank, exclusive=(lock_type != LOCK_SHARED))

    def Unlock(self, rank: int) -> None:
        self._w.unlock(rank)

    def Lock_all(self, assertion: int = 0) -> None:
        self._w.lock_all()

    def Unlock_all(self) -> None:
        self._w.unlock_all()

    def Flush(self, rank: int) -> None:
        self._w.flush(rank)

    def Flush_all(self) -> None:
        self._w.flush_all()

    def Shared_query(self, rank: int):
        """(buffer, disp_unit) — a direct reference to ``rank``'s
        window memory when the driver shares one address space
        (``MPI_Win_shared_query``); raises otherwise."""
        arr = self._w.shared_query(rank)
        return arr, arr.dtype.itemsize

    def Free(self) -> None:
        if self._pending:
            raise api.MpiError(
                "mpi_tpu.compat: Win.Free() with un-fenced Get pending")
        self._w.free()


class File:
    """mpi4py ``MPI.File`` over :mod:`mpi_tpu.io` — open with
    :meth:`Open`; byte offsets follow the default byte-etype view, so
    ``Read_at(offset, buf)``/``Write_at`` address absolute file bytes
    exactly as mpi4py's default view does."""

    def __init__(self, native):
        self._f = native

    @classmethod
    def Open(cls, comm: Comm, filename: str, amode: Optional[int] = None,
             info: Any = None) -> "File":
        """Collective open (``MPI_File_open``). ``amode`` combines the
        ``MPI.MODE_*`` bits; RDONLY opens existing read-only, CREATE
        opens read-write creating if missing (MPI semantics: open never
        truncates), plain RDWR/WRONLY requires the file to exist."""
        from .io import open_file

        if amode is None:
            amode = MODE_RDONLY
        if amode & MODE_RDONLY:
            mode = "r"
        elif amode & MODE_CREATE:
            mode = "a"
        else:
            # RDWR/WRONLY on an existing file: "a" never truncates, and
            # existence is enforced here (native "a" would create).
            import os as _os

            if not _os.path.exists(filename):
                raise api.MpiError(
                    f"mpi_tpu.compat: File.Open({filename!r}) without "
                    f"MPI.MODE_CREATE: file does not exist")
            mode = "a"
        return cls(open_file(comm._c, filename, mode))

    @property
    def native(self):
        """The underlying :class:`mpi_tpu.io.File`."""
        return self._f

    def Get_size(self) -> int:
        return self._f.size()

    def Set_size(self, size: int) -> None:
        self._f.set_size(size)

    def Read_at(self, offset: int, buf: Any, status: Any = None) -> None:
        out = _writable_buffer(buf, "File.Read_at")
        got = self._f.read_at(int(offset), out.size, out.dtype)
        np.copyto(out, got.reshape(out.shape))

    def Write_at(self, offset: int, buf: Any) -> None:
        self._f.write_at(int(offset), np.ascontiguousarray(buf))

    def Iread_at(self, offset: int, buf: Any) -> Request:
        """Nonblocking :meth:`Read_at` (``MPI_File_iread_at``): the
        buffer fills when the returned request completes. Independent
        (non-collective), like the blocking form, whose fill logic it
        delegates to (the buffer validates eagerly so a bad target
        raises here, not on the worker)."""
        _writable_buffer(buf, "File.Iread_at")
        return Request(api.Request(
            lambda: self.Read_at(int(offset), buf)))

    def Iwrite_at(self, offset: int, buf: Any) -> Request:
        """Nonblocking :meth:`Write_at` (``MPI_File_iwrite_at``). The
        payload is snapshotted at the call (ONE copy, contiguous), so
        the caller may reuse its buffer immediately (MPI permits
        either; the copy is the safe contract for a fire-and-forget
        request)."""
        data = np.array(buf, copy=True, order="C")
        return Request(api.Request(
            lambda: self.Write_at(int(offset), data)))

    def Read_at_all(self, offset: int, buf: Any,
                    status: Any = None) -> None:
        out = _writable_buffer(buf, "File.Read_at_all")
        got = self._f.read_at_all(int(offset), out.size, out.dtype)
        np.copyto(out, got.reshape(out.shape))

    def Write_at_all(self, offset: int, buf: Any) -> None:
        self._f.write_at_all(int(offset), np.ascontiguousarray(buf))

    def Set_view(self, disp: int = 0, etype: Any = np.uint8,
                 block: int = 1, stride: Optional[int] = None,
                 index: Optional[int] = None) -> None:
        """Install this rank's strided view. mpi4py spells the filetype
        as a derived datatype; here the ``MPI_Type_vector`` parameters
        are passed directly (``block`` elements of ``etype`` per round
        of ``stride``, this rank at round offset ``index * block`` —
        defaults give the row-cyclic rank split)."""
        self._f.set_view(disp, etype, block=block, stride=stride,
                         index=index)

    def Read_all(self, buf: Any, status: Any = None) -> None:
        out = _writable_buffer(buf, "File.Read_all")
        got = self._f.read_all(out.size)
        np.copyto(out, got.astype(out.dtype, copy=False)
                  .reshape(out.shape))

    def Write_all(self, buf: Any) -> None:
        self._f.write_all(np.ascontiguousarray(buf))

    def Write_ordered(self, buf: Any, offset: int = 0) -> int:
        return self._f.write_ordered(np.ascontiguousarray(buf), offset)

    # -- shared file pointer ------------------------------------------------
    #
    # One deviation from mpi4py: the shared pointer's counter window
    # must be created collectively first (Init_shared_pointer — it
    # runs a per-rank service thread, the same opt-in as Win locks).

    def Init_shared_pointer(self) -> None:
        """COLLECTIVE: enable the ``*_shared`` family on this file."""
        self._f.init_shared_pointer()

    def Write_shared(self, buf: Any) -> int:
        """Atomic append at the shared pointer (MPI_File_write_shared);
        returns the start offset actually claimed."""
        return self._f.write_shared(np.ascontiguousarray(buf))

    def Read_shared(self, buf: Any) -> int:
        """Fills ``buf`` from the shared pointer; at EOF the claim
        shrinks (MPI short-read semantics), only the prefix is
        written, and the ELEMENT COUNT actually read is returned
        (mpi4py surfaces it via a Status; here it is the return
        value)."""
        out = _writable_buffer(buf, "Read_shared")
        if not out.flags.c_contiguous:
            raise api.MpiError(
                "mpi_tpu.compat: Read_shared needs a C-contiguous "
                "buffer (a strided view's flattening would be a copy "
                "and the data would vanish)")
        got = self._f.read_shared(out.size, out.dtype)
        out.reshape(-1)[:got.size] = got
        return int(got.size)

    def Seek_shared(self, offset: int, whence: Optional[int] = None) -> None:
        if whence not in (None, 0, SEEK_SET):
            raise api.MpiError(
                "mpi_tpu.compat: Seek_shared supports whence="
                "MPI.SEEK_SET only")
        self._f.seek_shared(int(offset))

    def Get_position_shared(self) -> int:
        return self._f.get_position_shared()

    def Sync(self) -> None:
        self._f.sync()

    def Close(self) -> None:
        self._f.close()

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.Close()


class Session:
    """MPI-4 Sessions (``MPI.Session.Init`` → psets → groups →
    communicators → ``Finalize``): the world-free initialization model
    mpi4py 4.x exposes.

    Scope honesty: the Sessions MODEL promises initialization with no
    global state; this rebuild backs every session with the driver's
    world transport (one refcounted ``init`` under the hood, same as
    ``MPI.Init``) while preserving the session-LOCAL API — multiple
    concurrent sessions, pset introspection, and communicator
    construction from a pset group without ever touching
    ``COMM_WORLD`` — so sessions-model mpi4py code runs verbatim. The
    two built-in process sets are ``mpi://WORLD`` and ``mpi://SELF``
    (names case-insensitive in the scheme/authority part, per MPI)."""

    _PSETS = ("mpi://WORLD", "mpi://SELF")

    def __init__(self):
        api.init()
        self._finalized = False

    @classmethod
    def Init(cls, info: Any = None, errhandler: Any = None
             ) -> "Session":
        """``MPI_Session_init``; ``info``/``errhandler`` accepted and
        ignored (one transport configuration)."""
        return cls()

    def _check_live(self) -> None:
        if self._finalized:
            raise api.MpiError(
                "mpi_tpu.compat: operation on a finalized Session")

    def Get_num_psets(self, info: Any = None) -> int:
        self._check_live()
        return len(self._PSETS)

    def Get_nth_pset(self, n: int, info: Any = None) -> str:
        self._check_live()
        if not 0 <= n < len(self._PSETS):
            raise api.MpiError(
                f"mpi_tpu.compat: pset index {n} out of range "
                f"[0, {len(self._PSETS)})")
        return self._PSETS[n]

    def _pset_ranks(self, pset_name: str) -> tuple:
        self._check_live()
        name = str(pset_name).lower()
        if name == "mpi://world":
            return tuple(range(api.size()))
        if name == "mpi://self":
            return (api.rank(),)
        raise api.MpiError(
            f"mpi_tpu.compat: unknown process set {pset_name!r} "
            f"(have {', '.join(self._PSETS)})")

    def Get_pset_info(self, pset_name: str) -> "Info":
        """``MPI_Session_get_pset_info``: at minimum ``mpi_size``,
        per the standard."""
        info = Info()
        info.Set("mpi_size", str(len(self._pset_ranks(pset_name))))
        return info

    def _pset_group(self, pset_name: str) -> "Group":
        """Backs ``Group.Create_from_session_pset``."""
        ranks = self._pset_ranks(pset_name)
        return Group(MPI.COMM_WORLD, ranks)

    def Finalize(self) -> None:
        """``MPI_Session_finalize`` (refcounted with any other
        sessions / ``MPI.Init`` holders of the transport)."""
        if not self._finalized:
            self._finalized = True
            api.finalize()

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        state = "finalized" if self._finalized else "live"
        return f"Session({state})"


class Info(dict):
    """mpi4py ``MPI.Info``: string key/value hints. A dict subclass so
    every consumer that takes ``info`` (``Win.Create``, ``File.Open``)
    accepts either spelling; the Create/Set/Get methods are the MPI
    names."""

    @classmethod
    def Create(cls) -> "Info":
        return cls()

    def Set(self, key: str, value: str) -> None:
        self[str(key)] = str(value)

    def Get(self, key: str) -> Optional[str]:
        return self.get(str(key))

    def Delete(self, key: str) -> None:
        self.pop(str(key), None)

    def Get_nkeys(self) -> int:
        return len(self)

    def Free(self) -> None:
        self.clear()

    def Dup(self) -> "Info":
        return Info(self)


class Errhandler:
    """Error-handler handle: wraps the native handler value ('return',
    'fatal', or a user callable installed through
    ``mpi_tpu.api.set_errhandler``), so a Get/Set round-trip restores
    EXACTLY what was installed — including callables."""

    def __init__(self, native: Any):
        self._native = native

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        if self._native in ("return", "fatal"):
            return f"MPI.ERRORS_{'RETURN' if self._native == 'return' else 'ARE_FATAL'}"
        return f"MPI.Errhandler({self._native!r})"


ERRORS_RETURN = Errhandler("return")
ERRORS_ARE_FATAL = Errhandler("fatal")


class Op:
    """Reduction-op constant (SUM/PROD/MIN/MAX)."""

    def __init__(self, name: str):
        self.name = name

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"MPI.{self.name.upper()}"

    def Reduce_local(self, inbuf: Any, inoutbuf: Any) -> None:
        """``inoutbuf = inbuf op inoutbuf`` elementwise, locally
        (MPI_Reduce_local) — the user-side combine step, sharing the
        exact arithmetic every driver reduces with."""
        from .collectives_generic import combine

        out = _writable_buffer(inoutbuf, "Reduce_local")
        np.copyto(out, np.asarray(
            combine(np.ascontiguousarray(inbuf), out, self.name)
        ).reshape(out.shape))


def _op(op: Optional[Op]) -> Any:
    if op is None:
        return "sum"
    if isinstance(op, Op):
        return op.name
    return op  # a callable or native op string passes straight through


ANY_SOURCE = -1
ANY_TAG = -2
# PROC_NULL marks the edge of a non-periodic Cartesian axis; -3 avoids
# this shim's ANY_SOURCE/ANY_TAG values (implementations differ on the
# exact integers; mpi4py code compares against the constant, not -1).
PROC_NULL = -3
# MPI.ROOT for the intercomm rooted-op protocol (the root's own side).
ROOT_SENTINEL = -4
# MPI.UNDEFINED: Group rank queries for processes outside the group.
UNDEFINED = -32766
# MPI.COMM_NULL: what Get_parent returns in a non-spawned process.
# None, so the mpi4py gate `parent != MPI.COMM_NULL` works: a real
# Intercomm compares unequal to None, and a non-spawned process's
# None compares equal.
COMM_NULL = None

# MPI_File amode bits (the ROMIO/MPICH values — mpi4py exposes the same
# names; code combines them with |).
MODE_CREATE = 1
MODE_RDONLY = 2
MODE_WRONLY = 4
MODE_RDWR = 8
MODE_DELETE_ON_CLOSE = 16
MODE_UNIQUE_OPEN = 32
MODE_EXCL = 64
MODE_APPEND = 128
MODE_SEQUENTIAL = 256

# MPI_Win_lock types (mpi4py exposes the same names).
LOCK_EXCLUSIVE = 234
LOCK_SHARED = 235

KEYVAL_INVALID = -1

# MPI_File seek whence constants (mpi4py's values).
SEEK_SET = 600
SEEK_CUR = 602
SEEK_END = 604


def _writable_buffer(buf: Any, what: str) -> np.ndarray:
    """The caller's receive buffer, as the ndarray written THROUGH
    (mpi4py buffer semantics). A non-ndarray (e.g. a list) would make
    ``np.asarray`` a temporary and the received data silently vanish;
    so would copying into a flattened COPY of a non-contiguous view —
    reject the former loudly, and let callers ``np.copyto`` into the
    original array (which handles strided views correctly)."""
    if not isinstance(buf, np.ndarray):
        raise api.MpiError(
            f"mpi_tpu.compat: {what} needs a writable numpy array as "
            f"its receive buffer (got {type(buf).__name__}); a "
            f"{type(buf).__name__} cannot be written through")
    if not buf.flags.writeable:
        raise api.MpiError(
            f"mpi_tpu.compat: {what} receive buffer is read-only")
    return buf


def _fill(buf: Any, got: Any, what: str) -> None:
    """Copy a received payload into the caller's buffer through the
    shared validation (one place to improve size/dtype diagnostics)."""
    out = _writable_buffer(buf, what)
    np.copyto(out, np.asarray(got).reshape(out.shape))


def _fill_stacked(buf: Any, parts: Any, what: str) -> None:
    """:func:`_fill` for list-of-payload results (rank order)."""
    out = _writable_buffer(buf, what)
    np.copyto(out, np.stack([np.asarray(p) for p in parts])
              .reshape(out.shape))


def _leading_axis_is_size(arr: np.ndarray, size: int, what: str) -> None:
    if arr.ndim < 1 or arr.shape[0] != size:
        raise api.MpiError(
            f"mpi_tpu.compat: {what} sendbuf needs leading axis == comm "
            f"size {size}, got shape {arr.shape}")


def _check_tag_not_wild(tag: int, what: str) -> None:
    if tag == ANY_TAG:
        raise api.MpiError(
            f"mpi_tpu.compat: {what} with MPI.ANY_TAG is not supported "
            f"(tags are unbounded 64-bit values here, so a tag wildcard "
            f"cannot be probed); pass the sender's tag explicitly — "
            f"receive-side tags default to 0, matching send's default")


# -- datatypes -------------------------------------------------------------
#
# mpi4py's MPI.Datatype, re-expressed over numpy: a datatype is a base
# numpy dtype plus an ELEMENT-OFFSET LAYOUT — the positions (in base
# elements) one "item" of the type occupies inside its extent. Basic
# types are the single-offset identity layout; the derived constructors
# (Create_contiguous / Create_vector / Create_subarray) compose layouts
# exactly the way MPI type maps compose. Packing a count of items
# gathers ``count x len(offsets)`` elements into a contiguous wire
# array; unpacking scatters them back through the caller's buffer —
# which is how a strided column or an interior 2D block travels without
# the caller copying it out first. No reference analogue (the reference
# moves whole gob-encoded values, /root/reference/network.go:537-541);
# this exists for mpi4py drop-in fidelity.

class _InPlace:
    """The MPI.IN_PLACE sentinel (identity compares, repr for errors)."""

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return "MPI.IN_PLACE"


IN_PLACE = _InPlace()

ORDER_C = 0
ORDER_FORTRAN = ORDER_F = 1


class Datatype:
    """A numpy-backed MPI datatype. ``base`` is the element dtype;
    ``offsets`` (int64 array, units of base elements) is the layout one
    item occupies; ``extent`` (base elements) is the stride between
    consecutive items. Basic named instances (``MPI.DOUBLE`` etc.) are
    the identity layout and always committed; derived types must be
    ``Commit()``-ed before use, as in MPI."""

    def __init__(self, base: Any, offsets: Any = None,
                 extent: Optional[int] = None, name: str = "",
                 committed: bool = True):
        self._base = np.dtype(base)
        if offsets is None:
            offsets = np.zeros(1, dtype=np.int64)
        self._offsets = np.asarray(offsets, dtype=np.int64).reshape(-1)
        if self._offsets.size == 0:
            raise api.MpiError("mpi_tpu.compat: empty datatype layout")
        self._extent_elems = int(self._extent_default()
                                 if extent is None else extent)
        self._name = name or self._base.name
        self._committed = committed
        self._predefined = False   # set True on the named module basics
        self._freed = False
        # Struct datatypes (Create_struct) address the buffer's BYTES:
        # base is uint8 and _flat views any-dtype buffers as bytes —
        # only under this flag, so MPI.BYTE et al. keep the strict
        # no-silent-reinterpretation contract.
        self._struct = False
        # Strictest component alignment in bytes — the MPI "alignment
        # epsilon" Create_struct pads its default extent to (basics:
        # the base dtype's own alignment; composites propagate the max
        # of their components').
        self._alignment = max(1, int(self._base.alignment))
        # Dense prefix layouts pack/unpack as one slice, no gather.
        n = self._offsets.size
        self._contig = bool(n == self._extent_elems
                            and np.array_equal(self._offsets,
                                               np.arange(n)))

    def _extent_default(self) -> int:
        return int(self._offsets.max()) + 1

    # -- introspection ------------------------------------------------------

    @property
    def dtype(self) -> np.dtype:
        """The base numpy dtype (escape hatch for allocation)."""
        return self._base

    def Get_size(self) -> int:
        """Bytes of DATA per item (holes excluded), MPI_Type_size."""
        return int(self._offsets.size * self._base.itemsize)

    size = property(Get_size)

    def Get_extent(self):
        """(lb, extent) in bytes, MPI_Type_get_extent (lb always 0)."""
        return 0, int(self._extent_elems * self._base.itemsize)

    @property
    def extent(self) -> int:
        return self.Get_extent()[1]

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"MPI.Datatype({self._name})"

    # -- lifecycle ----------------------------------------------------------

    def Commit(self) -> "Datatype":
        self._check_not_freed("Commit")
        self._committed = True
        return self

    def Free(self) -> None:
        if self._predefined:
            # MPI forbids freeing predefined types; here it would also
            # poison the shared module-level singleton for the process.
            raise api.MpiError(
                f"mpi_tpu.compat: cannot Free the predefined {self!r}")
        self._freed = True

    def _check_not_freed(self, what: str) -> None:
        if self._freed:
            raise api.MpiError(
                f"mpi_tpu.compat: {what} on a freed {self!r}")

    def _check_usable(self, what: str) -> None:
        self._check_not_freed(what)
        if not self._committed:
            raise api.MpiError(
                f"mpi_tpu.compat: {what} with uncommitted {self!r} — "
                f"call .Commit() after deriving, as in MPI")

    # -- derived constructors ----------------------------------------------

    def _derive(self, item_positions: np.ndarray, extent_items: int,
                name: str) -> "Datatype":
        """Compose: place one copy of THIS layout at each position
        (units of this type's extent) — the MPI type-map product."""
        self._check_not_freed(name)
        pos = np.asarray(item_positions, dtype=np.int64).reshape(-1)
        offs = (pos[:, None] * self._extent_elems
                + self._offsets[None, :]).reshape(-1)
        out = Datatype(self._base, offs,
                       extent=extent_items * self._extent_elems,
                       name=name, committed=False)
        # Byte addressing is a property of the LAYOUT LINEAGE: a
        # vector-of-struct (the documented nesting spelling) must keep
        # viewing buffers as bytes, exactly like its component.
        out._struct = self._struct
        out._alignment = self._alignment
        return out

    def Create_contiguous(self, count: int) -> "Datatype":
        if count < 1:
            raise api.MpiError(
                f"mpi_tpu.compat: Create_contiguous count must be >= 1, "
                f"got {count}")
        return self._derive(np.arange(count), count,
                            f"contiguous({count})x{self._name}")

    def Create_vector(self, count: int, blocklength: int,
                      stride: int) -> "Datatype":
        if count < 1 or blocklength < 1 or stride < blocklength:
            raise api.MpiError(
                f"mpi_tpu.compat: Create_vector needs count,blocklength "
                f">= 1 and stride >= blocklength, got ({count}, "
                f"{blocklength}, {stride})")
        pos = (np.arange(count)[:, None] * stride
               + np.arange(blocklength)[None, :]).reshape(-1)
        return self._derive(pos, (count - 1) * stride + blocklength,
                            f"vector({count},{blocklength},{stride})"
                            f"x{self._name}")

    def Create_subarray(self, sizes, subsizes, starts,
                        order: int = ORDER_C) -> "Datatype":
        sizes = [int(s) for s in sizes]
        subsizes = [int(s) for s in subsizes]
        starts = [int(s) for s in starts]
        if not (len(sizes) == len(subsizes) == len(starts)) or not sizes:
            raise api.MpiError(
                "mpi_tpu.compat: Create_subarray needs equal-length "
                "non-empty sizes/subsizes/starts")
        for d, (sz, sub, st) in enumerate(zip(sizes, subsizes, starts)):
            if sub < 1 or st < 0 or st + sub > sz:
                raise api.MpiError(
                    f"mpi_tpu.compat: Create_subarray dim {d}: block "
                    f"[{st}, {st + sub}) outside array of size {sz}")
        np_order = "C" if order == ORDER_C else "F"
        axes = np.meshgrid(*[st + np.arange(sub) for st, sub
                             in zip(starts, subsizes)], indexing="ij")
        pos = np.ravel_multi_index(
            [a.reshape(-1) for a in axes], sizes, order=np_order)
        # Pack order = ascending memory address of the full array, so
        # the wire form reads as the block in storage order.
        pos = np.sort(pos)
        extent = 1
        for s in sizes:
            extent *= s
        return self._derive(pos, extent,
                            f"subarray({subsizes}@{starts} of {sizes})"
                            f"x{self._name}")

    @staticmethod
    def Create_struct(blocklengths, displacements,
                      datatypes) -> "Datatype":
        """Mixed-base records (``MPI_Type_create_struct``): block ``i``
        is ``blocklengths[i]`` items of ``datatypes[i]`` at BYTE offset
        ``displacements[i]`` — the numpy-structured-array layout, whose
        field offsets feed ``displacements`` directly. The result
        addresses the buffer's raw bytes (any buffer dtype works; a
        structured record array is the natural one), so alignment
        holes between fields never travel.

        Components may be ANY datatype (round 5): a derived component
        contributes its own byte layout per item, with consecutive
        items of a block striding by the component's EXTENT — so
        vector-typed fields, resized basics (stride = resized
        extent, MPI's meaning), and nested structs all lay out
        exactly as mpi4py would."""
        blocklengths = [int(b) for b in blocklengths]
        displacements = [int(d) for d in displacements]
        if not (len(blocklengths) == len(displacements)
                == len(datatypes)) or not blocklengths:
            raise api.MpiError(
                "mpi_tpu.compat: Create_struct needs equal-length "
                "non-empty blocklengths/displacements/datatypes")
        spans, tails = [], []
        for i, (bl, disp, dt) in enumerate(
                zip(blocklengths, displacements, datatypes)):
            if not isinstance(dt, Datatype):
                raise api.MpiError(
                    f"mpi_tpu.compat: Create_struct datatypes[{i}] is "
                    f"not an MPI.Datatype")
            dt._check_not_freed(f"Create_struct (datatypes[{i}])")
            if bl < 1 or disp < 0:
                raise api.MpiError(
                    f"mpi_tpu.compat: Create_struct block {i}: need "
                    f"blocklength >= 1 and displacement >= 0, got "
                    f"({bl}, {disp})")
            # One item's byte layout: every element offset expanded to
            # its bytes (identity for a basic: arange(itemsize); the
            # component's own gather order for derived/struct types).
            isz = dt._base.itemsize
            elem_bytes = (dt._offsets.astype(np.int64)[:, None] * isz
                          + np.arange(isz, dtype=np.int64)).reshape(-1)
            stride = int(dt._extent_elems) * isz     # item-to-item
            item = (np.arange(bl, dtype=np.int64)[:, None] * stride
                    + elem_bytes[None, :]).reshape(-1)
            spans.append(disp + item)
            # A resized component's TRAILING padding is part of the
            # record too (mpi4py's ub marker sits at
            # disp + bl*extent): track it so the struct's extent
            # matches, or count>1 sends would stride records
            # differently than a real MPI peer.
            tails.append(disp + bl * stride)
        offsets = np.concatenate(spans)
        if np.unique(offsets).size != offsets.size:
            raise api.MpiError(
                "mpi_tpu.compat: Create_struct blocks overlap "
                "(a receive through this layout would be ambiguous)")
        names = ",".join(f"{bl}x{dt._name}@{disp}" for bl, disp, dt in
                         zip(blocklengths, displacements, datatypes))
        # MPI's alignment epsilon (round-5 advisor): the default extent
        # pads the ub to the strictest component alignment, as
        # MPICH/mpi4py do — {double@0, char@8} gets extent 16, not 9 —
        # so count>1 sends stride records like a compiler would.
        # Create_resized remains the escape hatch for packed layouts.
        align = max(dt._alignment for dt in datatypes)
        raw_extent = max(int(offsets.max()) + 1, int(max(tails)))
        out = Datatype(np.uint8, offsets,
                       extent=-(-raw_extent // align) * align,
                       name=f"struct({names})", committed=False)
        out._struct = True
        out._alignment = align
        return out

    def Create_resized(self, lb: int, extent: int) -> "Datatype":
        """``MPI_Type_create_resized``: same layout, caller-chosen
        extent (bytes). Growing carries trailing padding (struct
        records striding like the compiler's); SHRINKING interleaves
        consecutive items — the textbook column-scatter pattern
        ``Create_vector(n, 1, n).Create_resized(0, itemsize)``, which
        this engine's index arithmetic supports directly. ``lb`` must
        be 0 (layouts here are zero-based)."""
        if lb != 0:
            raise api.MpiError(
                f"mpi_tpu.compat: Create_resized lb must be 0 here, "
                f"got {lb}")
        itemsize = self._base.itemsize
        if extent <= 0 or extent % itemsize:
            raise api.MpiError(
                f"mpi_tpu.compat: Create_resized extent {extent} must "
                f"be a positive multiple of the base itemsize "
                f"({itemsize})")
        out = Datatype(self._base, self._offsets.copy(),
                       extent=extent // itemsize,
                       name=f"resized({extent})x{self._name}",
                       committed=False)
        out._struct = self._struct
        out._alignment = self._alignment
        return out

    # -- explicit pack / unpack (MPI_Pack family) ---------------------------

    def Pack_size(self, count: int, comm: Any = None) -> int:
        """Upper bound (here: exact) bytes ``count`` items occupy in a
        pack buffer (``MPI_Pack_size``; ``comm`` accepted and
        ignored — the wire format is driver-independent)."""
        return int(count) * self.Get_size()

    def _pack_spec(self, spec: Any, what: str):
        """(buf, count|None) through the SHARED spec grammar
        (``_parse_spec``: bare array / [buf, count] / [buf, count,
        datatype]); a datatype entry must be THIS datatype (MPI_Pack's
        datatype is the method receiver) and counts must be >= 0 —
        a negative count would silently slice the wrong span."""
        buf, count, dt = _parse_spec(spec, what)
        if dt is not None and dt is not self:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: spec names datatype "
                f"{dt!r} but was invoked on {self!r} — MPI_Pack's "
                f"datatype is the method receiver")
        if count is not None and count < 0:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: count must be >= 0, got "
                f"{count}")
        return buf, count

    @staticmethod
    def _byte_view(spec: Any, what: str, writable: bool) -> np.ndarray:
        """A Pack buffer (bare writable numpy array, any dtype) as a
        flat byte view of its storage."""
        buf = spec[0] if isinstance(spec, (list, tuple)) and spec \
            else spec
        arr = buf if isinstance(buf, np.ndarray) else np.asarray(buf)
        if writable:
            _writable_buffer(arr if isinstance(buf, np.ndarray)
                             else buf, what)
            if not arr.flags.c_contiguous:
                raise api.MpiError(
                    f"mpi_tpu.compat: {what} needs a C-contiguous "
                    f"buffer")
            return arr.reshape(-1).view(np.uint8)
        return np.ascontiguousarray(arr).reshape(-1).view(np.uint8)

    def Pack(self, inbuf: Any, outbuf: Any, position: int,
             comm: Any = None) -> int:
        """``MPI_Pack``: append ``inbuf`` (a bare array or
        ``[buf, count]``, in THIS datatype's layout) to ``outbuf`` (a
        writable numpy array — bytes are written through its storage)
        at byte ``position``; returns the new position. Heterogeneous
        messages pack by calling this with each datatype in turn,
        sharing one position cursor."""
        buf, count = self._pack_spec(inbuf, "Pack")
        data = self._pack(buf, count, "Pack")
        raw = np.ascontiguousarray(data).view(np.uint8)
        out = self._byte_view(outbuf, "Pack", writable=True)
        position = int(position)
        if position < 0 or position + raw.size > out.size:
            raise api.MpiError(
                f"mpi_tpu.compat: Pack of {raw.size} bytes at position "
                f"{position} overruns the {out.size}-byte buffer")
        out[position:position + raw.size] = raw
        return position + raw.size

    def Unpack(self, inbuf: Any, position: int, outbuf: Any,
               comm: Any = None) -> int:
        """``MPI_Unpack``: the inverse — read items of THIS datatype
        from ``inbuf`` at byte ``position`` into ``outbuf`` (a bare
        array or ``[buf, count]``; a bare array unpacks as many whole
        items as it holds); returns the new position."""
        src = self._byte_view(inbuf, "Unpack", writable=False)
        buf, count = self._pack_spec(outbuf, "Unpack")
        if count is None:
            # writable=True: fail fast on a read-only/strided
            # destination here, instead of copying it just to size it
            # and erroring later in the real unpack.
            flat = self._flat(buf, "Unpack", writable=True)
            count = self._infer_count(flat.size, "Unpack")
        nbytes = count * self.Get_size()
        position = int(position)
        if position < 0 or position + nbytes > src.size:
            raise api.MpiError(
                f"mpi_tpu.compat: Unpack of {nbytes} bytes at position "
                f"{position} overruns the {src.size}-byte buffer")
        data = src[position:position + nbytes].view(self._base)
        self._unpack(buf, data, count, "Unpack")
        return position + nbytes

    # -- external32 portable pack (MPI_Pack_external family) ---------------

    def _external_check(self, datarep: str, what: str) -> None:
        if datarep != "external32":
            raise api.MpiError(
                f"mpi_tpu.compat: {what} supports datarep "
                f"'external32' only, got {datarep!r}")
        if self._struct:
            raise api.MpiError(
                f"mpi_tpu.compat: {what} on struct datatypes is not "
                f"supported (per-component representations differ); "
                f"pack components with their own datatypes sharing "
                f"one position cursor")

    def Pack_external_size(self, datarep: str, count: int) -> int:
        """``MPI_Pack_external_size``: bytes ``count`` items occupy in
        the portable external32 representation (big-endian IEEE — the
        same sizes as the native layout for the basic types here)."""
        self._external_check(datarep, "Pack_external_size")
        return int(count) * self.Get_size()

    def Pack_external(self, datarep: str, inbuf: Any, outbuf: Any,
                      position: int) -> int:
        """``MPI_Pack_external``: like :meth:`Pack`, but the packed
        bytes are the canonical big-endian external32 encoding, so a
        buffer packed here unpacks identically on any platform."""
        self._external_check(datarep, "Pack_external")
        buf, count = self._pack_spec(inbuf, "Pack_external")
        data = np.ascontiguousarray(self._pack(buf, count,
                                               "Pack_external"))
        raw = data.astype(data.dtype.newbyteorder(">"),
                          copy=False).view(np.uint8)
        out = self._byte_view(outbuf, "Pack_external", writable=True)
        position = int(position)
        if position < 0 or position + raw.size > out.size:
            raise api.MpiError(
                f"mpi_tpu.compat: Pack_external of {raw.size} bytes "
                f"at position {position} overruns the {out.size}-byte "
                f"buffer")
        out[position:position + raw.size] = raw
        return position + raw.size

    def Unpack_external(self, datarep: str, inbuf: Any, position: int,
                        outbuf: Any) -> int:
        """``MPI_Unpack_external``: inverse of :meth:`Pack_external`
        — reads the big-endian external32 bytes and delivers items in
        this platform's native layout."""
        self._external_check(datarep, "Unpack_external")
        src = self._byte_view(inbuf, "Unpack_external", writable=False)
        buf, count = self._pack_spec(outbuf, "Unpack_external")
        if count is None:
            flat = self._flat(buf, "Unpack_external", writable=True)
            count = self._infer_count(flat.size, "Unpack_external")
        nbytes = count * self.Get_size()
        position = int(position)
        if position < 0 or position + nbytes > src.size:
            raise api.MpiError(
                f"mpi_tpu.compat: Unpack_external of {nbytes} bytes "
                f"at position {position} overruns the {src.size}-byte "
                f"buffer")
        big = src[position:position + nbytes].view(
            self._base.newbyteorder(">"))
        self._unpack(buf, big.astype(self._base), count,
                     "Unpack_external")
        return position + nbytes

    # -- pack / unpack ------------------------------------------------------

    def _flat(self, buf: Any, what: str, writable: bool) -> np.ndarray:
        arr = buf if isinstance(buf, np.ndarray) else np.asarray(buf)
        if writable:
            _writable_buffer(arr if isinstance(buf, np.ndarray) else buf,
                             what)
        if self._struct and arr.dtype != self._base:
            # A struct layout addresses raw bytes: view the buffer's
            # storage (works for structured records and any plain
            # dtype alike). The view needs contiguity; the writable
            # path checks it below as usual.
            if writable and not arr.flags.c_contiguous:
                raise api.MpiError(
                    f"mpi_tpu.compat: {what} needs a C-contiguous "
                    f"receive buffer for a struct datatype")
            arr = (arr if arr.flags.c_contiguous
                   else np.ascontiguousarray(arr)).reshape(-1)
            arr = arr.view(np.uint8)
        if arr.dtype != self._base:
            raise api.MpiError(
                f"mpi_tpu.compat: {what} buffer dtype {arr.dtype} does "
                f"not match {self!r} (base {self._base}) — no silent "
                f"byte reinterpretation here; view the buffer "
                f"explicitly if that is intended")
        if writable:
            if not arr.flags.c_contiguous:
                raise api.MpiError(
                    f"mpi_tpu.compat: {what} needs a C-contiguous "
                    f"receive buffer to write a datatype layout through "
                    f"(got a strided view — express the stride in the "
                    f"datatype instead)")
            return arr.reshape(-1)
        return np.ascontiguousarray(arr).reshape(-1)

    def _span(self, count: int) -> int:
        """Base elements the first ``count`` items touch."""
        if count <= 0:
            return 0
        return (count - 1) * self._extent_elems + self._extent_default()

    def _infer_count(self, flat_size: int, what: str) -> int:
        span1 = self._extent_default()
        if flat_size < span1:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: buffer of {flat_size} base "
                f"elements cannot hold one {self!r} "
                f"(needs {span1})")
        return (flat_size - span1) // self._extent_elems + 1

    def _check_count(self, flat: np.ndarray, count: int,
                     what: str) -> None:
        need = self._span(count)
        if flat.size < need:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: count {count} of {self!r} "
                f"spans {need} base elements; buffer has {flat.size}")

    def _indices(self, count: int) -> np.ndarray:
        return (np.arange(count)[:, None] * self._extent_elems
                + self._offsets[None, :]).reshape(-1)

    def _pack(self, buf: Any, count: Optional[int],
              what: str) -> np.ndarray:
        """``count`` items from ``buf`` as one contiguous base-dtype
        array (the wire form). ``count=None`` packs as many as fit."""
        self._check_usable(what)
        flat = self._flat(buf, what, writable=False)
        if count is None:
            count = self._infer_count(flat.size, what)
        self._check_count(flat, count, what)
        if self._contig:
            return np.ascontiguousarray(flat[:count * self._extent_elems])
        return np.ascontiguousarray(flat[self._indices(count)])

    def _unpack(self, buf: Any, got: Any, count: Optional[int],
                what: str) -> None:
        """Scatter a received contiguous array back through ``buf``'s
        layout positions (count inferred from the payload if omitted)."""
        self._check_usable(what)
        flat = self._flat(buf, what, writable=True)
        data = np.asarray(got).reshape(-1)
        if data.dtype != self._base:
            data = data.astype(self._base)
        n = self._offsets.size
        if count is None:
            if data.size % n:
                raise api.MpiError(
                    f"mpi_tpu.compat: {what}: payload of {data.size} "
                    f"elements is not a whole number of {self!r} items "
                    f"({n} data elements each)")
            count = data.size // n
        elif data.size != count * n:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: payload has {data.size} "
                f"elements, count {count} of {self!r} needs {count * n}")
        self._check_count(flat, count, what)
        if self._contig:
            flat[:count * self._extent_elems] = data
        else:
            idx = self._indices(count)
            if np.unique(idx).size != idx.size:
                # A shrunk extent can make consecutive items OVERLAP:
                # legal to read (pack), ambiguous to write — numpy's
                # fancy assignment would silently last-write-win
                # (same stance as Create_struct's overlap rejection).
                raise api.MpiError(
                    f"mpi_tpu.compat: {what}: {count} items of "
                    f"{self!r} overlap in the receive buffer — an "
                    f"overlapping layout is ambiguous to write")
            flat[idx] = data


# Named basic datatypes (the C-name set mpi4py exposes, mapped onto the
# numpy dtypes the buffers actually carry).
BYTE = Datatype(np.uint8, name="BYTE")
CHAR = Datatype(np.int8, name="CHAR")
SIGNED_CHAR = Datatype(np.int8, name="SIGNED_CHAR")
UNSIGNED_CHAR = Datatype(np.uint8, name="UNSIGNED_CHAR")
C_BOOL = BOOL = Datatype(np.bool_, name="BOOL")
SHORT = Datatype(np.int16, name="SHORT")
UNSIGNED_SHORT = Datatype(np.uint16, name="UNSIGNED_SHORT")
INT = Datatype(np.int32, name="INT")
UNSIGNED = UNSIGNED_INT = Datatype(np.uint32, name="UNSIGNED")
LONG = Datatype(np.int64, name="LONG")
UNSIGNED_LONG = Datatype(np.uint64, name="UNSIGNED_LONG")
LONG_LONG = Datatype(np.int64, name="LONG_LONG")
FLOAT = Datatype(np.float32, name="FLOAT")
DOUBLE = Datatype(np.float64, name="DOUBLE")
C_FLOAT_COMPLEX = COMPLEX = Datatype(np.complex64, name="COMPLEX")
C_DOUBLE_COMPLEX = DOUBLE_COMPLEX = Datatype(np.complex128,
                                             name="DOUBLE_COMPLEX")
INT8_T = Datatype(np.int8, name="INT8_T")
INT16_T = Datatype(np.int16, name="INT16_T")
INT32_T = Datatype(np.int32, name="INT32_T")
INT64_T = Datatype(np.int64, name="INT64_T")
UINT8_T = Datatype(np.uint8, name="UINT8_T")
UINT16_T = Datatype(np.uint16, name="UINT16_T")
UINT32_T = Datatype(np.uint32, name="UINT32_T")
UINT64_T = Datatype(np.uint64, name="UINT64_T")

for _dt in list(globals().values()):
    if isinstance(_dt, Datatype):
        _dt._predefined = True
del _dt


# -- buffer-spec lists -----------------------------------------------------

def _parse_spec(spec: Any, what: str):
    """An mpi4py buffer spec — ``buf`` | ``[buf, datatype]`` |
    ``[buf, count]`` | ``[buf, count, datatype]`` — as
    ``(buf, count, datatype)`` with the absent parts None. Counts+
    displacements lists belong to the v-variants (Gatherv etc.), which
    parse with :func:`_parse_vspec`; passing one here raises with that
    pointer."""
    if not isinstance(spec, (list, tuple)):
        return spec, None, None
    if not spec:
        raise api.MpiError(f"mpi_tpu.compat: {what}: empty buffer spec")
    buf, count, dt = spec[0], None, None
    if len(spec) > 3:
        raise api.MpiError(
            f"mpi_tpu.compat: {what}: buffer spec has {len(spec)} "
            f"entries; the [buf, counts, displs, datatype] form is the "
            f"v-variant spec — use {what}v for per-rank counts")
    for item in spec[1:]:
        if isinstance(item, Datatype):
            dt = item
        elif isinstance(item, (int, np.integer)):
            count = int(item)
        elif item is None:
            continue
        else:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: unsupported buffer-spec entry "
                f"{type(item).__name__} (per-rank count lists are the "
                f"v-variant spec; use {what}v)")
    return buf, count, dt


def _spec_payload(spec: Any, what: str) -> np.ndarray:
    """The contiguous wire array a send-side buffer spec denotes."""
    buf, count, dt = _parse_spec(spec, what)
    if buf is IN_PLACE:
        raise api.MpiError(
            f"mpi_tpu.compat: {what}: MPI.IN_PLACE is only meaningful "
            f"as the sendbuf of a reduction/gather family op")
    if dt is not None:
        return dt._pack(buf, count, what)
    arr = np.ascontiguousarray(buf)
    if count is not None:
        if arr.size < count:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: count {count} exceeds buffer "
                f"size {arr.size}")
        arr = arr.reshape(-1)[:count]
    return arr


class _RecvTarget:
    """A receive-side buffer spec, validated BEFORE the communication
    happens (a bad buffer should fail before bytes move, not after),
    then filled from the received payload."""

    def __init__(self, spec: Any, what: str):
        self.what = what
        self.buf, self.count, self.dt = _parse_spec(spec, what)
        if self.buf is IN_PLACE:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: MPI.IN_PLACE cannot be a "
                f"plain receive buffer")
        if self.dt is not None:
            self.dt._check_usable(what)
            flat = self.dt._flat(self.buf, what, writable=True)
            if self.count is not None:
                self.dt._check_count(flat, self.count, what)
        else:
            _writable_buffer(self.buf, what)
            if self.count is not None:
                if self.buf.size < self.count:
                    raise api.MpiError(
                        f"mpi_tpu.compat: {what}: count {self.count} "
                        f"exceeds buffer size {self.buf.size}")
                if not self.buf.flags.c_contiguous:
                    # reshape(-1) on a strided view would be a COPY and
                    # the received data would silently vanish — the
                    # hazard _writable_buffer documents. Express the
                    # stride as a datatype instead.
                    raise api.MpiError(
                        f"mpi_tpu.compat: {what}: a [buf, count] spec "
                        f"needs a C-contiguous buffer (got a strided "
                        f"view); describe the stride with a derived "
                        f"datatype instead")

    def fill(self, got: Any) -> None:
        if self.dt is not None:
            self.dt._unpack(self.buf, got, self.count, self.what)
        elif self.count is not None:
            flat = self.buf.reshape(-1)
            data = np.asarray(got).reshape(-1)
            if data.size != self.count:
                raise api.MpiError(
                    f"mpi_tpu.compat: {self.what}: payload has "
                    f"{data.size} elements, spec count is {self.count}")
            flat[:self.count] = data
        else:
            _fill(self.buf, got, self.what)


def _parse_vspec(spec: Any, size: int, what: str):
    """A v-variant spec — ``[buf, counts]`` | ``[buf, counts, displs]``
    | ``[buf, counts, displs, datatype]`` (``displs`` may be None for
    packed = cumulative) — as ``(flat_view, counts, displs, datatype)``
    with bounds fully validated. The datatype must be basic (MPI allows
    derived here; this shim scopes v-variants to element counts)."""
    if not isinstance(spec, (list, tuple)) or len(spec) < 2:
        raise api.MpiError(
            f"mpi_tpu.compat: {what} needs a [buf, counts(, displs"
            f"(, datatype))] spec on the varying side")
    if len(spec) > 4:
        raise api.MpiError(
            f"mpi_tpu.compat: {what}: spec has {len(spec)} entries")
    buf = spec[0]
    counts = spec[1]
    displs = spec[2] if len(spec) > 2 else None
    dt = spec[3] if len(spec) > 3 else None
    if isinstance(displs, Datatype):  # [buf, counts, datatype]
        dt, displs = displs, None
    if dt is not None:
        if not isinstance(dt, Datatype):
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: last spec entry must be a "
                f"Datatype, got {type(dt).__name__}")
        dt._check_usable(what)
        if dt._offsets.size != 1 or dt._extent_elems != 1:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: derived datatypes are not "
                f"supported in v-variant specs (counts are element "
                f"counts); pack with the datatype via {what[:-1]} "
                f"instead")
    counts = [int(c) for c in counts]
    if len(counts) != size:
        raise api.MpiError(
            f"mpi_tpu.compat: {what}: counts has {len(counts)} entries "
            f"for a size-{size} communicator")
    if any(c < 0 for c in counts):
        raise api.MpiError(f"mpi_tpu.compat: {what}: negative count")
    if displs is None:
        displs = [0] * size
        run = 0
        for i, c in enumerate(counts):
            displs[i] = run
            run += c
    else:
        displs = [int(d) for d in displs]
        if len(displs) != size:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: displs has {len(displs)} "
                f"entries for a size-{size} communicator")
    arr = buf if isinstance(buf, np.ndarray) else None
    if arr is None:
        raise api.MpiError(
            f"mpi_tpu.compat: {what} needs a numpy array buffer, got "
            f"{type(buf).__name__}")
    if dt is not None and arr.dtype != dt.dtype:
        raise api.MpiError(
            f"mpi_tpu.compat: {what}: buffer dtype {arr.dtype} does "
            f"not match {dt!r}")
    if not arr.flags.c_contiguous:
        raise api.MpiError(
            f"mpi_tpu.compat: {what}: buffer must be C-contiguous")
    flat = arr.reshape(-1)
    for r in range(size):
        if displs[r] < 0 or displs[r] + counts[r] > flat.size:
            raise api.MpiError(
                f"mpi_tpu.compat: {what}: rank {r} block "
                f"[{displs[r]}, {displs[r] + counts[r]}) outside "
                f"buffer of {flat.size} elements")
    return flat, counts, displs, dt


class _MPI:
    """The module-object stand-in mpi4py scripts address as ``MPI``."""

    ANY_SOURCE = ANY_SOURCE
    ANY_TAG = ANY_TAG
    PROC_NULL = PROC_NULL
    ROOT = ROOT_SENTINEL
    UNDEFINED = UNDEFINED
    COMM_NULL = COMM_NULL
    COMM_TYPE_SHARED = COMM_TYPE_SHARED
    IN_PLACE = IN_PLACE
    ORDER_C = ORDER_C
    ORDER_F = ORDER_F
    ORDER_FORTRAN = ORDER_FORTRAN
    Datatype = Datatype
    BYTE = BYTE
    CHAR = CHAR
    SIGNED_CHAR = SIGNED_CHAR
    UNSIGNED_CHAR = UNSIGNED_CHAR
    BOOL = BOOL
    C_BOOL = C_BOOL
    SHORT = SHORT
    UNSIGNED_SHORT = UNSIGNED_SHORT
    INT = INT
    UNSIGNED = UNSIGNED
    UNSIGNED_INT = UNSIGNED_INT
    LONG = LONG
    UNSIGNED_LONG = UNSIGNED_LONG
    LONG_LONG = LONG_LONG
    FLOAT = FLOAT
    DOUBLE = DOUBLE
    COMPLEX = COMPLEX
    C_FLOAT_COMPLEX = C_FLOAT_COMPLEX
    DOUBLE_COMPLEX = DOUBLE_COMPLEX
    C_DOUBLE_COMPLEX = C_DOUBLE_COMPLEX
    INT8_T = INT8_T
    INT16_T = INT16_T
    INT32_T = INT32_T
    INT64_T = INT64_T
    UINT8_T = UINT8_T
    UINT16_T = UINT16_T
    UINT32_T = UINT32_T
    UINT64_T = UINT64_T
    MODE_CREATE = MODE_CREATE
    MODE_RDONLY = MODE_RDONLY
    MODE_WRONLY = MODE_WRONLY
    MODE_RDWR = MODE_RDWR
    MODE_DELETE_ON_CLOSE = MODE_DELETE_ON_CLOSE
    MODE_UNIQUE_OPEN = MODE_UNIQUE_OPEN
    MODE_EXCL = MODE_EXCL
    MODE_APPEND = MODE_APPEND
    MODE_SEQUENTIAL = MODE_SEQUENTIAL
    LOCK_EXCLUSIVE = LOCK_EXCLUSIVE
    LOCK_SHARED = LOCK_SHARED
    SEEK_SET = SEEK_SET
    SEEK_CUR = SEEK_CUR
    SEEK_END = SEEK_END
    SUM = Op("sum")
    PROD = Op("prod")
    MIN = Op("min")
    MAX = Op("max")
    Status = Status
    Request = Request
    Prequest = Prequest
    Grequest = Grequest
    Comm = Comm
    Message = Message
    Info = Info
    INFO_NULL = None
    Errhandler = Errhandler
    ERRORS_RETURN = ERRORS_RETURN
    ERRORS_ARE_FATAL = ERRORS_ARE_FATAL
    # mpi4py raises MPI.Exception; here every error IS MpiError, and
    # it carries the mpi4py error-class protocol (Get_error_class /
    # Get_error_code / Get_error_string — api.py), so
    # `except MPI.Exception as e: e.Get_error_class() == MPI.ERR_RANK`
    # works unchanged. The MPI.ERR_* constants (MPICH numbering) and
    # module-level Get_error_class/Get_error_string live below.
    Exception = api.MpiError
    SUCCESS = _errclass.SUCCESS
    ERR_LASTCODE = _errclass.ERR_LASTCODE

    @staticmethod
    def Attach_buffer(buf: Any) -> None:
        """Accepted for mpi4py source compatibility and ignored:
        buffered sends here detach their payload automatically (each
        ``bsend`` deep-copies at the call), so no user-provided
        staging buffer exists to attach — the argument's size never
        limits anything."""

    @staticmethod
    def Detach_buffer() -> None:
        """Inverse shim of :meth:`Attach_buffer`: waits out any
        outstanding buffered sends (MPI_Buffer_detach's blocking
        contract) and returns None."""
        _drain_bsends()

    @staticmethod
    def Get_error_class(errorcode: int) -> int:
        """MPI_Error_class for an integer error code."""
        return _errclass.error_class(errorcode)

    @staticmethod
    def Get_error_string(errorcode: int) -> str:
        """MPI_Error_string for an integer error code."""
        return _errclass.error_string(errorcode)
    Group = Group
    Cartcomm = Cartcomm
    Distgraphcomm = Distgraphcomm
    Graphcomm = Graphcomm
    Intercomm = Intercomm
    Session = Session
    Win = Win
    File = File

    KEYVAL_INVALID = KEYVAL_INVALID

    _world_cache: Optional[Comm] = None
    # Thread-local: under thread-per-rank drivers the self-comm's
    # member list is rank-specific — a shared cache would hand one
    # rank another rank's COMM_SELF.
    _self_tls = _threading.local()

    @property
    def COMM_SELF(self) -> Comm:
        """A communicator containing only this process (MPI_COMM_SELF)
        — created locally, no negotiation; the usual spelling for
        per-rank private file IO (``MPI.File.Open(MPI.COMM_SELF, ...)``)."""
        if not self.Is_initialized():
            api.init()
            self._self_tls.comm = None
        self._connect_parent_if_spawned()
        cached = getattr(self._self_tls, "comm", None)
        if cached is None or cached._c._impl is not api.registered() \
                or cached._c.members != (api.registered().rank(),):
            cached = Comm(comm_self())
            self._self_tls.comm = cached
        return cached

    _world_lock = _threading.Lock()

    @property
    def COMM_WORLD(self) -> Comm:
        # mpi4py initializes at import; the nearest safe analogue is
        # lazy init on first world access. The wrapper is cached so
        # `comm is MPI.COMM_WORLD` identity checks behave like
        # mpi4py's singleton (and __eq__ covers fresh wrappers).
        # init() runs OUTSIDE the cache lock (it can be collective —
        # holding the lock across it would deadlock the other rank-
        # threads it waits for); the cache itself is locked so racing
        # rank-threads agree on ONE wrapper/native — otherwise
        # attributes Set_attr'ed through a losing wrapper would
        # silently vanish from later COMM_WORLD accesses.
        if not self.Is_initialized():
            api.init()
            with self._world_lock:
                self._world_cache = None
        # Outside the cache lock: the bridge join is collective (it
        # waits for parents + sibling children).
        self._connect_parent_if_spawned()
        with self._world_lock:
            if self._world_cache is None \
                    or self._world_cache._c._impl is not api.registered():
                self._world_cache = Comm(comm_world())
            return self._world_cache

    def Init(self) -> None:
        if not self.Is_initialized():
            api.init()
        self._connect_parent_if_spawned()

    @staticmethod
    def _connect_parent_if_spawned() -> None:
        """In a spawned child, MPI_Init is the moment the parents'
        blocked ``spawn`` expects the child to connect (mpi4py
        semantics) — join the bridge eagerly so a child that never
        calls Get_parent doesn't strand its parents. Idempotent and
        cached; a no-op for normal processes."""
        from . import spawn as _spawn

        if _spawn.is_spawned():
            _spawn.get_parent()

    def Finalize(self) -> None:
        # MPI_Finalize must complete pending communication: buffered
        # sends whose receivers haven't matched yet get their drain
        # window here, instead of dying with the transport.
        _drain_bsends()
        if self.Is_initialized():
            api.finalize()
        self._world_cache = None

    def Is_initialized(self) -> bool:
        return api._init_count > 0

    def Get_processor_name(self) -> str:
        import socket

        return socket.gethostname()

    @staticmethod
    def Open_port(info: Any = None) -> str:
        """``MPI_Open_port``: a rendezvous address for
        ``Comm.Accept``/``Comm.Connect`` (advertise it out of band,
        as with mpi4py). ``info`` accepted and ignored."""
        from . import spawn as _spawn

        return _spawn.open_port()

    @staticmethod
    def Close_port(port_name: str) -> None:
        """``MPI_Close_port`` (surface parity; see
        :func:`mpi_tpu.spawn.close_port`)."""
        from . import spawn as _spawn

        _spawn.close_port(port_name)

    @staticmethod
    def Publish_name(service_name: str, port_name: str,
                     info: Any = None) -> None:
        """``MPI_Publish_name``: register ``port_name`` under a
        service name so ``Lookup_name`` finds it (host-scoped
        file registry; ``info`` accepted and ignored)."""
        from . import spawn as _spawn

        _spawn.publish_name(service_name, port_name)

    @staticmethod
    def Unpublish_name(service_name: str, port_name: str = "",
                       info: Any = None) -> None:
        """``MPI_Unpublish_name``."""
        from . import spawn as _spawn

        _spawn.unpublish_name(service_name)

    @staticmethod
    def Lookup_name(service_name: str, info: Any = None) -> str:
        """``MPI_Lookup_name``: the port published under
        ``service_name`` (raises MPI_ERR_NAME-style when absent, as
        mpi4py does)."""
        from . import spawn as _spawn

        return _spawn.lookup_name(service_name)

    def Get_version(self):
        """(major, minor) of the MPI standard surface this shim
        tracks. (4, 0): on top of the full MPI-3.1 core (nonblocking
        collectives, RMA incl. passive target and PSCW, neighborhood
        collectives, matched probes), the headline MPI-4 facilities
        all work — partitioned point-to-point
        (``Psend_init``/``Precv_init``/``Prequest``), persistent
        collectives (``allreduce_init`` et al.), Sessions
        (:class:`Session`), and dynamic process management
        (``Comm.Spawn``/``Get_parent``, ``Open_port``/``Accept``/
        ``Connect``; :mod:`mpi_tpu.spawn`). As with any
        implementation, feature-test specific calls (e.g.
        ``hasattr(comm, "Psend_init")``) rather than gating broad
        behavior on this tuple."""
        return (4, 0)

    def Get_library_version(self) -> str:
        import mpi_tpu

        return (f"mpi_tpu {getattr(mpi_tpu, '__version__', 'dev')} "
                f"(tpu-native; drivers: tcp/shm/xla/hybrid)")

    def Wtime(self) -> float:
        return api.wtime()

    def Wtick(self) -> float:
        return api.wtick()


# The full MPI.ERR_* constant set (MPICH numbering, errclass.py) —
# attached programmatically so the table lives in ONE place.
for _name, _code in _errclass._NAME_TO_CODE.items():
    setattr(_MPI, _name, _code)
del _name, _code

MPI = _MPI()

"""Sharded, prefetching data pipeline for the training workloads.

The reference has no data subsystem (it moves opaque payloads); this is
the rebuild's tpu-native loader: deterministic step-indexed batches
(checkpoint/resume replays the exact stream — pairs with
:mod:`mpi_tpu.utils.checkpoint`), dp-sharded placement onto the mesh, a
host-side prefetch thread that overlaps batch construction and
host→device transfer with the previous step's compute, and multi-host
slicing (each process materialises only its ``process_index`` share, the
``jax.distributed`` convention).

Sources are pluggable: :class:`SyntheticLM` (seeded token stream, used by
benchmarks/examples) or :func:`from_token_array` over a memory-mapped /
in-memory corpus.
"""

from __future__ import annotations

import os
import queue
import threading
from collections import OrderedDict
from typing import Any, Callable, Iterator, Optional, Union

import numpy as np

__all__ = ["SyntheticLM", "from_token_array", "from_token_file",
           "ShardedLoader"]

# dtypes the native gather kernel understands (widened to int32)
_NATIVE_GATHER_DTYPES = {
    np.dtype(np.uint8): 1,
    np.dtype(np.uint16): 2,
    np.dtype(np.int32): 4,
    np.dtype(np.uint32): 4,
}


def _gather_windows(tokens: np.ndarray, picks: np.ndarray,
                    seq: int) -> np.ndarray:
    """(batch, seq) int32 batch from window indices ``picks``.

    Uses the native gather+widen kernel (native/dataloader.cpp) when
    available — one GIL-free call, threaded on multi-core hosts — so
    batch assembly genuinely overlaps with device compute under the
    prefetch thread; otherwise a NumPy fallback with identical output."""
    from . import native as _native

    batch = len(picks)
    lib = _native.dataloader()
    dt = tokens.dtype
    if lib is not None and dt in _NATIVE_GATHER_DTYPES \
            and tokens.flags.c_contiguous and batch:
        import ctypes

        out = np.empty((batch, seq), dtype=np.int32)
        idx = np.ascontiguousarray(picks, dtype=np.int64)
        # Threads only pay off when the copy dwarfs thread create/join
        # (~tens of µs): gate on output size, not just core count.
        ncpu = os.cpu_count() or 1
        nthreads = min(4, ncpu) if batch * seq >= (1 << 16) else 1
        rc = lib.dl_gather(
            tokens.ctypes.data_as(ctypes.c_void_p), tokens.size,
            _NATIVE_GATHER_DTYPES[dt],
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            batch, seq, out.ctypes.data_as(ctypes.c_void_p), nthreads)
        if rc == 0:
            return out
        # fall through on -EINVAL (shouldn't happen: indices validated)
    return np.stack(
        [tokens[w * seq:(w + 1) * seq] for w in picks]).astype(np.int32)


class SyntheticLM:
    """Deterministic synthetic token source: ``sample(step) -> (B, S)``
    int32, a pure function of (seed, step) — the stream is identical
    across restarts, hosts, and prefetch depths."""

    def __init__(self, vocab: int, batch: int, seq: int, seed: int = 0):
        self.vocab, self.batch, self.seq, self.seed = vocab, batch, seq, seed

    def __call__(self, step: int) -> np.ndarray:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, step]))
        return rng.integers(0, self.vocab, (self.batch, self.seq),
                            dtype=np.int32)


def from_token_array(tokens: np.ndarray, batch: int, seq: int,
                     shuffle_seed: Optional[int] = 0
                     ) -> Callable[[int], np.ndarray]:
    """Batch source over a flat token array (e.g. np.memmap of a corpus).

    Step ``t`` yields ``batch`` windows of ``seq`` tokens. With
    ``shuffle_seed`` the window order is a seeded permutation per epoch
    (deterministic, resumable); ``None`` reads sequentially."""
    tokens = np.asarray(tokens)
    n_windows = len(tokens) // seq
    if n_windows < 1:
        raise ValueError(
            f"mpi_tpu: corpus of {len(tokens)} tokens is shorter than one "
            f"sequence ({seq})")
    if n_windows < batch:
        raise ValueError(
            f"mpi_tpu: corpus has {n_windows} windows of {seq} tokens — "
            f"fewer than one batch of {batch}")
    windows_per_epoch = n_windows // batch * batch
    perm_cache: "OrderedDict[int, np.ndarray]" = OrderedDict()
    perm_lock = threading.Lock()

    def _order(epoch: int) -> np.ndarray:
        if shuffle_seed is None:
            return np.arange(n_windows)
        # One O(n_windows) permutation per *epoch*, not per step — at
        # memmap-corpus scale the per-step cost must stay O(batch). The
        # two most-recently-*used* epochs are kept (not one) so iterators
        # straddling an epoch boundary — or a lagging iterator sharing
        # the source — don't thrash the permutation; the lock keeps
        # concurrent callers coherent.
        with perm_lock:
            if epoch in perm_cache:
                perm_cache.move_to_end(epoch)
            else:
                rng = np.random.default_rng(
                    np.random.SeedSequence([shuffle_seed, epoch]))
                perm_cache[epoch] = rng.permutation(n_windows)
                while len(perm_cache) > 2:
                    perm_cache.popitem(last=False)
            return perm_cache[epoch]

    def sample(step: int) -> np.ndarray:
        idx0 = step * batch
        epoch, offset = divmod(idx0, windows_per_epoch)
        order = _order(epoch)
        picks = order[(offset + np.arange(batch)) % n_windows]
        return _gather_windows(tokens, picks, seq)

    return sample


def from_token_file(path: Union[str, os.PathLike], batch: int, seq: int,
                    dtype: Any = np.uint16,
                    shuffle_seed: Optional[int] = 0
                    ) -> Callable[[int], np.ndarray]:
    """Batch source over a raw binary token file (the flat-corpus
    format: tokens back to back, no header). The file is memory-mapped
    read-only, so corpora far larger than RAM stream through the page
    cache, and the per-step gather runs in the native kernel when
    available. ``dtype`` is the on-disk token width (``uint16`` for
    vocabularies < 64K, the common LM corpus format)."""
    mm = np.memmap(os.fspath(path), dtype=np.dtype(dtype), mode="r")
    if mm.size == 0:
        raise ValueError(f"mpi_tpu: token file {os.fspath(path)!r} is empty")
    return from_token_array(mm, batch, seq, shuffle_seed=shuffle_seed)


class ShardedLoader:
    """Iterate device-resident, dp-sharded batches with prefetch.

    ``source(step) -> (B, S)`` is the *global* batch; each process keeps
    its contiguous per-process row slice (the
    ``jax.make_array_from_process_local_data`` layout convention), then
    commits the result to ``P('dp', None)`` over ``mesh`` (sanitized, so
    meshes without a ``dp`` axis get replication).

    Resumable: construct with ``start_step`` (e.g. the restored
    checkpoint step) and the stream continues exactly where it left off.
    """

    def __init__(self, source: Callable[[int], np.ndarray],
                 mesh: Optional[Any] = None, start_step: int = 0,
                 prefetch: int = 2):
        self.source = source
        self.mesh = mesh
        self.start_step = start_step
        self.prefetch = max(0, prefetch)
        self._sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .models import sanitize_spec

            self._sharding = NamedSharding(
                mesh, sanitize_spec(P("dp", None), mesh))

    # -- single-batch path ----------------------------------------------------

    def batch_at(self, step: int):
        """The device-placed batch for ``step`` (pure, thread-safe)."""
        import jax

        host = self._process_slice(self.source(step))
        if self._sharding is not None:
            if jax.process_count() > 1:
                # Each process holds only its slice; assemble the global
                # array from per-process local data (device_put with a
                # global sharding would misread the slice as the whole).
                return jax.make_array_from_process_local_data(
                    self._sharding, host)
            return jax.device_put(host, self._sharding)
        return jax.device_put(host)

    def _process_slice(self, global_batch: np.ndarray) -> np.ndarray:
        import jax

        nproc = jax.process_count()
        if nproc == 1:
            return global_batch
        b = global_batch.shape[0]
        if b % nproc:
            raise ValueError(
                f"mpi_tpu: global batch {b} not divisible by "
                f"{nproc} processes")
        share = b // nproc
        i = jax.process_index()
        return global_batch[i * share:(i + 1) * share]

    # -- prefetching iterator -------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        if self.prefetch == 0:
            step = self.start_step
            while True:
                yield self.batch_at(step)
                step += 1
            return

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def producer() -> None:
            step = self.start_step
            def put(entry) -> bool:
                # Bounded put that stays responsive to stop().
                while not stop.is_set():
                    try:
                        q.put(entry, timeout=0.2)
                        return True
                    except queue.Full:
                        continue
                return False

            while not stop.is_set():
                try:
                    item = self.batch_at(step)
                except BaseException as exc:  # noqa: BLE001 - handed to consumer
                    put(("error", exc))
                    return
                put(("ok", item))
                step += 1

        t = threading.Thread(target=producer, daemon=True,
                             name="mpi-data-prefetch")
        t.start()
        try:
            while True:
                kind, item = q.get()
                if kind == "error":
                    raise item
                yield item
        finally:
            stop.set()

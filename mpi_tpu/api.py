"""Public MPI-like API facade and pluggable backend SPI.

tpu-native rebuild of the reference's L2 layer (/root/reference/mpi.go):

  * package-level operations delegating to one registered backend —
    ``init``/``finalize``/``rank``/``size``/``send``/``receive``
    (mpi.go:93-159);
  * a backend SPI (``Interface``, mpi.go:163-170) with a process-global
    registry (``register``, mpi.go:61-67 — second registration is an error);
  * the ``Raw`` passthrough payload type (mpi.go:75-91, re-exported from
    :mod:`mpi_tpu.utils.serialize`);
  * the duplicate-tag misuse error (``TagError``; the reference declares
    ``TagExists`` at mpi.go:174-182 but never constructs it — its runtime
    panics instead, network.go:469,481,493. Here the declared error type is
    actually raised.)

Semantics preserved from the reference's package doc (mpi.go:20-48):
all calls **block**; ``send`` does not return until the destination has
accepted the message (rendezvous); concurrent sends must use distinct
``{dest, tag}`` pairs and concurrent receives distinct ``{source, tag}``
pairs (mpi.go:122-125, 153-156) — pairs may be reused once the earlier call
returns. Callers use threads for asynchrony, as the reference uses
goroutines.

**New capability beyond the reference** (the north star): collectives.
``reduce``/``bcast``/``allgather``/``allreduce``/``barrier``/``scatter``/
``gather``/``alltoall``/``scan``/``exscan`` — the reference stubs
``AllReduce`` out entirely
(mpi.go:130, 69-71). Backends may implement them natively (the XLA driver
lowers them to ``jax.lax`` collectives over ICI); otherwise the facade falls
back to generic tree/ring algorithms built on ``send``/``receive``
(:mod:`mpi_tpu.collectives_generic`), so every backend gets the full API.
"""

from __future__ import annotations

import os
import threading
import time
from typing import (TYPE_CHECKING, Any, Callable, List, Optional, Protocol,
                    Tuple, runtime_checkable)

if TYPE_CHECKING:
    from .collectives_generic import OpLike

from .utils.serialize import Raw

__all__ = [
    "Interface",
    "register",
    "registered",
    "init",
    "finalize",
    "rank",
    "size",
    "send",
    "receive",
    "sendrecv",
    "iprobe",
    "probe",
    "Request",
    "PersistentRequest",
    "isend",
    "irecv",
    "send_init",
    "recv_init",
    "waitall",
    "waitany",
    "reduce",
    "allreduce",
    "reduce_scatter",
    "bcast",
    "allgather",
    "gather",
    "scatter",
    "alltoall",
    "scan",
    "exscan",
    "barrier",
    "iallreduce",
    "ireduce",
    "ibcast",
    "igather",
    "iallgather",
    "iscatter",
    "ialltoall",
    "ireduce_scatter",
    "ibarrier",
    "Raw",
    "MpiError",
    "TagError",
    "NotInitializedError",
    "set_errhandler",
    "get_errhandler",
    "allreduce_init",
    "bcast_init",
    "barrier_init",
    "pack",
    "unpack",
    "wtime",
    "wtick",
    "receive_any",
    "abort",
]


class MpiError(RuntimeError):
    """Base class for all framework errors.

    Carries the mpi4py ``MPI.Exception`` error-class protocol: code
    written against ``exc.Get_error_class() == MPI.ERR_RANK`` works
    unchanged (classes derive from the exception's type and message —
    :mod:`mpi_tpu.errclass`)."""

    def Get_error_class(self) -> int:
        from . import errclass

        return errclass.classify(self)

    def Get_error_code(self) -> int:
        # No implementation-specific codes beyond the classes here.
        return self.Get_error_class()

    def Get_error_string(self) -> str:
        from . import errclass

        return errclass.error_string(self.Get_error_class())


class TagError(MpiError):
    """A live ``{peer, tag}`` pair was used by a second concurrent call.

    Realizes the reference's declared-but-dead ``TagExists`` error
    (mpi.go:174-182); the reference's runtime instead panics inside
    ``tagManager`` (network.go:469)."""

    def __init__(self, tag: int, peer: int, direction: str = "send"):
        self.tag = tag
        self.peer = peer
        self.direction = direction
        super().__init__(
            f"mpi_tpu: tag {tag} already live for concurrent {direction} "
            f"with peer {peer}; {{peer, tag}} pairs must be unique among "
            f"in-flight operations"
        )


class NotInitializedError(MpiError):
    """An operation was called before ``init()`` / after ``finalize()``."""


@runtime_checkable
class Interface(Protocol):
    """Backend SPI — the rebuild of ``mpi.Interface`` (mpi.go:163-170).

    The six required operations match the reference one-for-one. The
    collective methods are optional: the facade probes for them and falls
    back to the generic send/receive implementations when absent.
    """

    def init(self) -> None: ...
    def finalize(self) -> None: ...
    def rank(self) -> int: ...
    def size(self) -> int: ...
    def send(self, data: Any, dest: int, tag: int) -> None: ...
    def receive(self, source: int, tag: int, out: Optional[Any] = None) -> Any: ...


_lock = threading.Lock()
_backend: Optional[Interface] = None
_registered_explicitly = False
# Reference-counted: under thread-per-rank backends (xla driver) every rank
# thread calls init()/finalize() once, and one rank finishing early must
# not tear the facade down under its siblings. Single-process drivers see
# the same 0→1→0 behavior as the reference's boolean.
_init_count = 0


def _default_backend() -> Interface:
    # The reference wires &Network{} as the default at package init
    # (mpi.go:56). Importing the TCP driver lazily keeps `import mpi_tpu`
    # free of socket/jax side effects.
    from .backends.tcp import TcpNetwork

    return TcpNetwork()


def register(impl: Interface) -> None:
    """Swap in a backend. Mirrors ``mpi.Register`` (mpi.go:61-67): may be
    called at most once, and only before ``init``."""
    global _backend, _registered_explicitly
    with _lock:
        if _registered_explicitly:
            raise MpiError("mpi_tpu: register called twice (mpi.go:63-65 contract)")
        if _init_count > 0:
            raise MpiError("mpi_tpu: register called after init")
        _backend = impl
        _registered_explicitly = True


def registered() -> Interface:
    """Return the active backend, creating the default on first use."""
    global _backend
    with _lock:
        if _backend is None:
            _backend = _default_backend()
        return _backend


def _release_backend(impl: Interface) -> None:
    """Deregister ``impl`` if it is the active backend — used by re-runnable
    hosts (``run_spmd``) so a second run in the same process can register
    again. Not part of the reference surface (Register there is once per
    process-lifetime, mpi.go:61-67); internal on purpose."""
    global _backend, _registered_explicitly, _init_count
    with _lock:
        if _backend is impl:
            _backend = None
            _registered_explicitly = False
            _init_count = 0


def _reset_for_testing() -> None:
    """Clear global registry state (no reference analogue; test hook)."""
    global _backend, _registered_explicitly, _init_count
    with _lock:
        _backend = None
        _registered_explicitly = False
        _init_count = 0


def _require_init() -> Interface:
    if _init_count <= 0:
        raise NotInitializedError("mpi_tpu: call init() first (mpi.go:26-30)")
    return registered()


def init() -> None:
    """Initialize the communication network (mpi.go:96-98). Blocks until
    every rank has connected (network.go:53-65)."""
    global _init_count
    impl = registered()
    impl.init()
    with _lock:
        _init_count += 1
    # Observability bring-up (rank binding for the flight recorder,
    # SIGUSR1 top handler, implicit span enable when a trace sink is
    # configured) — defensive: it must never take init down.
    try:
        from . import observe

        observe.on_init(impl)
    except Exception:  # noqa: BLE001 - observability is best-effort
        pass


def finalize() -> None:
    """Tear down the network (mpi.go:102-104).

    Delegates on *every* call: backends whose ranks are threads (xla,
    hybrid) refcount internally so one rank finishing early cannot tear
    the transport down under its siblings; the facade's own refcount only
    gates ``_require_init``."""
    global _init_count
    impl = registered()
    # Drain and drop this thread's nonblocking-collective chain: a
    # retained tail request would pin its result, and a stale entry
    # could chain a future run (id() reuse) onto this one's corpse.
    chains = getattr(_icoll_tls, "chains", None)
    if chains:
        for key in [k for k in chains if k[0] == id(impl)]:
            _drain_chain(key)
            chains.pop(key, None)
    # Job-wide observability flush BEFORE transport teardown: trace
    # collection is a gather over the live transport (collective when
    # --mpi-trace-out is set on every rank), metrics/summary are local.
    if _init_count > 0:
        try:
            from . import observe

            observe.on_finalize(impl)
        except Exception:  # noqa: BLE001 - observability is best-effort
            pass
    with _lock:
        _init_count = max(0, _init_count - 1)
    impl.finalize()


def rank() -> int:
    """This process's rank in [0, size) (mpi.go:112-114)."""
    return _require_init().rank()


def size() -> int:
    """Total number of ranks (mpi.go:117-119)."""
    return _require_init().size()


# --------------------------------------------------------------------------
# Error handlers (MPI_Errhandler analogue)
# --------------------------------------------------------------------------
#
# The reference documents both styles — "errors may be returned or the
# implementation may panic" (mpi.go:20-21) — which is exactly MPI's
# MPI_ERRORS_RETURN vs MPI_ERRORS_ARE_FATAL choice. The facade defaults
# to returning (raising MpiError); "fatal" aborts the process like
# MPI_ERRORS_ARE_FATAL (and like the reference's panics); a callable is
# an observer hook (logging/cleanup) invoked before the error re-raises.
# The handler fires wherever a facade op EXECUTES — including the
# worker threads of nonblocking/persistent ops, whose bodies are the
# guarded blocking calls. "fatal" therefore aborts the process even
# for an isend misuse (matching MPI_ERRORS_ARE_FATAL's abort-the-job
# semantics); callable handlers must be thread-safe. With "return"
# (default), a worker-thread error is stored and re-raised at wait().

_errhandler: Any = "return"


def set_errhandler(handler: Any) -> Any:
    """Install the world error handler; returns the previous one.

    ``"return"`` (default) raises :class:`MpiError` to the caller;
    ``"fatal"`` prints the error and terminates the process with exit
    code 13 (MPI_ERRORS_ARE_FATAL — matching the reference's panic
    stance, mpi.go:20-21); a callable ``handler(exc)`` is called first,
    then the error raises normally (unless the handler itself raises
    something else)."""
    global _errhandler
    if handler not in ("return", "fatal") and not callable(handler):
        raise MpiError(
            f"mpi_tpu: errhandler must be 'return', 'fatal', or a "
            f"callable, got {handler!r}")
    previous, _errhandler = _errhandler, handler
    return previous


def get_errhandler() -> Any:
    return _errhandler


def _dispatch_error(exc: MpiError) -> None:
    """Route ``exc`` through the installed handler; never returns
    normally (raises or exits)."""
    # Flight recorder: the FIRST fatal typed failure (remote abort,
    # deadline, peer death, wire corruption) dumps this rank's
    # postmortem before the error propagates (docs/OBSERVABILITY.md).
    try:
        from . import observe

        observe.fatal_error_hook(exc)
    except Exception:  # noqa: BLE001 - never mask the real error
        pass
    handler = _errhandler
    if handler == "fatal":
        import sys as _sys
        import traceback as _tb

        _tb.print_exception(type(exc), exc, exc.__traceback__,
                            file=_sys.stderr)
        print("mpi_tpu: aborting (errhandler=fatal)", file=_sys.stderr)
        # MPI_ERRORS_ARE_FATAL aborts the JOB: propagate before exiting
        # so peers raise instead of hanging until their deadlines.
        try:
            notify = getattr(registered(), "notify_abort", None)
            if notify is not None:
                notify(13)
        except BaseException:  # noqa: BLE001 - exiting anyway
            pass
        os._exit(13)
    if callable(handler):
        handler(exc)
    raise exc


def _guarded(fn: Callable) -> Callable:
    """Wrap a facade op so MpiErrors route through the errhandler."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any):
        try:
            return fn(*args, **kwargs)
        except MpiError as exc:
            _dispatch_error(exc)
    return wrapped


def wtime() -> float:
    """Elapsed wall-clock seconds from an arbitrary fixed origin
    (MPI_Wtime; no reference analogue — bounce times with Go's
    ``time.Now``, bounce.go:90-101). Monotonic and per-process: like
    MPI with MPI_WTIME_IS_GLOBAL false, origins differ across ranks,
    so difference timestamps taken on ONE rank."""
    return time.perf_counter()


def wtick() -> float:
    """Resolution of :func:`wtime` in seconds (MPI_Wtick)."""
    info = time.get_clock_info("perf_counter")
    return float(info.resolution)


def _payload_bytes(data: Any) -> int:
    """Best-effort payload size for comm accounting (tracing only)."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        return len(data)
    nbytes = getattr(data, "nbytes", None)
    return int(nbytes) if isinstance(nbytes, int) else 0


@_guarded
def send(data: Any, dest: int, tag: int) -> None:
    """Blocking rendezvous send (mpi.go:126-128): returns only once rank
    ``dest`` has accepted the message (network.go:569,617-624)."""
    impl = _require_init()
    _check_peer(dest, impl)
    _check_tag(tag)
    from .observe import flight
    from .utils import trace

    tracing = trace.enabled()
    if not tracing and not flight.enabled:
        return impl.send(data, dest, tag)
    nbytes = _payload_bytes(data)
    tok = flight.begin("send", dest, tag, nbytes) if flight.enabled \
        else None
    try:
        if tracing:
            trace.count("comm.send.calls")
            trace.count("comm.send.bytes", nbytes)
            with trace.span("mpi.send", dest=dest, tag=tag, bytes=nbytes):
                impl.send(data, dest, tag)
        else:
            impl.send(data, dest, tag)
    except BaseException as exc:
        if tok is not None:
            flight.end(tok, f"error:{type(exc).__name__}")
        raise
    if tok is not None:
        flight.end(tok)


@_guarded
def receive(source: int, tag: int, out: Optional[Any] = None) -> Any:
    """Blocking receive (mpi.go:157-159). Returns the decoded payload.

    ``out`` optionally supplies a preallocated buffer/ndarray to decode
    into, mirroring the reference's receive-into-pointer + ``Raw`` buffer
    reuse semantics (mpi.go:84-90)."""
    impl = _require_init()
    _check_peer(source, impl)
    _check_tag(tag)
    from .observe import flight
    from .utils import trace

    tracing = trace.enabled()
    if not tracing and not flight.enabled:
        return impl.receive(source, tag, out=out)
    tok = flight.begin("receive", source, tag) if flight.enabled else None
    try:
        if tracing:
            with trace.span("mpi.receive", source=source, tag=tag):
                result = impl.receive(source, tag, out=out)
            trace.count("comm.receive.calls")
            trace.count("comm.receive.bytes", _payload_bytes(result))
        else:
            result = impl.receive(source, tag, out=out)
    except BaseException as exc:
        if tok is not None:
            flight.end(tok, f"error:{type(exc).__name__}")
        raise
    if tok is not None:
        flight.end(tok)
    return result


def _poll_until(predicate: Callable[[], bool], timeout: Optional[float],
                what: str) -> None:
    """Shared poll-until-deadline loop for blocking probes: raises
    ``MpiError`` naming ``what`` when ``timeout`` elapses. The predicate
    should be pre-validated (it runs every ~0.5 ms)."""
    import time as _time

    deadline = None if timeout is None else _time.monotonic() + timeout
    while not predicate():
        if deadline is not None and _time.monotonic() >= deadline:
            raise MpiError(
                f"mpi_tpu: {what} timed out after {timeout}s")
        _time.sleep(0.0005)


def _iprobe_fn(impl: Interface) -> Callable[[int, int], bool]:
    probe_fn = getattr(impl, "iprobe", None)
    if probe_fn is None:
        raise MpiError(
            f"mpi_tpu: backend {type(impl).__name__} does not support "
            f"iprobe")
    return probe_fn


@_guarded
def iprobe(source: int, tag: int) -> bool:
    """Non-consuming message probe (MPI_Iprobe): True when a message
    from ``source`` with ``tag`` is available — a matching ``receive``
    would complete without blocking on the sender. Never consumes the
    message and never blocks; raises the link failure if the peer's
    connection is poisoned. (No reference analogue; the rendezvous
    drivers report a parked/arrived sender.)"""
    impl = _require_init()
    _check_peer(source, impl)
    _check_tag(tag)
    return bool(_iprobe_fn(impl)(source, tag))


@_guarded
def probe(source: int, tag: int, timeout: Optional[float] = None) -> None:
    """Blocking probe (MPI_Probe): return once a message from ``source``
    with ``tag`` is available (without consuming it); ``MpiError`` on
    timeout."""
    impl = _require_init()
    _check_peer(source, impl)
    _check_tag(tag)
    probe_fn = _iprobe_fn(impl)
    _poll_until(lambda: bool(probe_fn(source, tag)), timeout,
                f"probe(source={source}, tag={tag})")


def exchange(impl: Interface, data: Any, dest: int, source: int, tag: int,
             out: Optional[Any] = None,
             recv_tag: Optional[int] = None) -> Any:
    """Concurrent send+receive against ``impl`` — the shared engine for
    :func:`sendrecv` and the generic collectives' pairwise rounds.
    Deadlock-free where a sequential send-then-receive would
    rendezvous-deadlock. ``recv_tag`` defaults to ``tag``."""
    rtag = tag if recv_tag is None else recv_tag
    result: List[Any] = [None]
    err: List[Optional[BaseException]] = [None]

    def _recv() -> None:
        try:
            result[0] = impl.receive(source, rtag, out=out)
        except BaseException as exc:  # noqa: BLE001 - propagated below
            err[0] = exc

    t = threading.Thread(target=_recv, name="mpi-sendrecv", daemon=True)
    t.start()
    try:
        impl.send(data, dest, tag)
    except BaseException:
        # Don't orphan the posted receive: it would hold its {source, tag}
        # claim forever and could consume-and-ack a message meant for a
        # later call. Backends may support cancellation; fall back to a
        # bounded join otherwise.
        cancel = getattr(impl, "cancel_receive", None)
        if cancel is not None:
            cancel(source, rtag)
        t.join(timeout=30.0)
        raise
    t.join()
    if err[0] is not None:
        raise err[0]
    return result[0]


def _claim_probed(recv: Callable[[int, int], Any],
                  cancel: Optional[Callable[[int, int], bool]],
                  src: int, tag: int) -> Tuple[bool, Any]:
    """ONE bounded claim attempt on a just-probed ``(src, tag)`` — the
    subtle heart of every probe-then-claim loop (receive_any, mprobe,
    improbe), defined once. A probe hit is only a HINT: a sibling may
    consume the message between probe and claim, so the claim is a
    short bounded receive; if nothing lands, the parked receive is
    cancelled (the driver's generation-tagged cancel — the machinery
    ``exchange`` uses). Returns ``(True, payload)`` on a successful
    claim, ``(False, None)`` when a sibling holds the pair (TagError)
    or consumed the message (cancelled); re-raises the receive's own
    errors."""
    req = Request(lambda: recv(src, tag))
    try:
        return True, req.wait(timeout=0.05)
    except TagError:
        return False, None  # a sibling holds this {src, tag} right now
    except MpiError:
        if req.test():
            raise  # the operation's own error — surface it
        # Bounded wait expired: probably consumed by someone else.
        # Cancel our parked receive; if cancellation lost the race (a
        # sender engaged after all), the receive is completing — take it.
        if cancel is not None and cancel(src, tag):
            return False, None
        return True, req.wait(None)


def _receive_any_loop(probe: Callable[[int, int], bool],
                      recv: Callable[[int, int], Any],
                      cancel: Optional[Callable[[int, int], bool]],
                      me: int, n: int, tag: int,
                      timeout: Optional[float],
                      what: str) -> Tuple[int, Any]:
    """Shared ANY_SOURCE engine for the facade and :class:`Comm`:
    poll every source's probe, :func:`_claim_probed` on a hit."""
    deadline = None if timeout is None else time.monotonic() + timeout
    # Rotate the probe order by own rank so N concurrent wildcard
    # receivers don't all stampede the same source first (starting at
    # self is arbitrary).
    order = [(me + i) % n for i in range(n)]
    # A peer that already finalized (its connections closed) makes its
    # probe RAISE — but a wildcard receive awaiting a LIVE sender must
    # not die because an unrelated peer exited first (a legal MPI
    # program: finalize when none of YOUR communication is pending).
    # Transport-death probe errors count as nothing-to-probe; the
    # blacklist clears periodically so a TRANSIENT error cannot turn
    # into permanent deafness. When every remote peer is dead the
    # death is surfaced (self never raises, and a self-only wildcard
    # wait after every peer died is not a supported pattern — use the
    # matched receive(me, tag) for that).
    dead: dict = {}
    sweeps = 0
    while True:
        for src in order:
            if src in dead:
                continue
            try:
                hit = probe(src, tag)
            except (ConnectionError, OSError, MpiError) as exc:
                dead[src] = exc
                continue
            if not hit:
                continue
            won, payload = _claim_probed(recv, cancel, src, tag)
            if won:
                return src, payload
        if n > 1 and len(dead) >= n - 1:
            err = next(iter(dead.values()))
            raise MpiError(
                f"mpi_tpu: {what}(tag={tag}): every remote source is "
                f"unreachable (peers closed); first error: "
                f"{err}") from err
        if deadline is not None and time.monotonic() >= deadline:
            raise MpiError(
                f"mpi_tpu: {what}(tag={tag}) timed out after "
                f"{timeout}s with no matching message")
        sweeps += 1
        if sweeps % 512 == 0:
            dead.clear()  # re-probe: transient errors must recover
        time.sleep(0.0005)


@_guarded
def receive_any(tag: int, timeout: Optional[float] = None
                ) -> Tuple[int, Any]:
    """Receive a message with ``tag`` from WHICHEVER rank sends first —
    MPI_Recv with MPI_ANY_SOURCE, returning ``(source, payload)`` (the
    status' MPI_SOURCE). Works on every driver: available sources are
    discovered via the driver's non-consuming probe, then the winning
    message is claimed with a cancellable bounded receive (see
    :func:`_receive_any_loop` for the race story).

    Concurrency: multiple threads may call ``receive_any`` with the
    same tag — a message taken by a sibling is re-polled past.
    ``timeout=None`` blocks forever; on expiry :class:`MpiError`
    raises with no message consumed. There is no ANY_TAG: tags are
    unbounded 64-bit values here, so a wildcard over them cannot be
    probed."""
    impl = _require_init()
    _check_tag(tag)
    cancel = getattr(impl, "cancel_receive", None)
    return _receive_any_loop(_iprobe_fn(impl), impl.receive, cancel,
                             impl.rank(), impl.size(), tag, timeout,
                             "receive_any")


def abort(code: int = 1) -> None:
    """Terminate this rank immediately (MPI_Abort analogue).

    Best effort: the transport is torn down first so peer ranks fail
    fast — their pending/future operations on this rank poison with a
    connection error instead of hanging until a timeout — then the
    process exits with ``code`` (no atexit handlers; the job is being
    killed). MPI_Abort's whole-job kill reduces to this under the
    fail-fast doctrine the reference documents (mpi.go:10-14): every
    surviving rank errors on its next interaction with the dead one."""
    import sys as _sys

    print(f"mpi_tpu: abort({code})", file=_sys.stderr)
    try:
        from .observe import flight as _flight

        _flight.dump(f"abort({code})")
    except BaseException:  # noqa: BLE001 - exiting anyway
        pass
    try:
        impl = registered()
        # Failure propagation (docs/FAULT_TOLERANCE.md): drivers with an
        # ABORT control frame tell every peer first, so remote ranks
        # raise a typed RemoteAbortError on their pending/future ops
        # instead of discovering the death via connection errors or
        # deadlines.
        notify = getattr(impl, "notify_abort", None)
        if notify is not None:
            notify(code)
        impl.finalize()
    except BaseException:  # noqa: BLE001 - exiting anyway
        pass
    os._exit(code)


@_guarded
def sendrecv(data: Any, dest: int, source: int, tag: int,
             out: Optional[Any] = None) -> Any:
    """Concurrent send+receive, the idiom every reference example spells
    with goroutines (helloworld.go:53-81, bounce.go:86-137). Provided as a
    convenience so Python callers don't need a thread for the common
    exchange pattern."""
    impl = _require_init()
    _check_peer(dest, impl)
    _check_peer(source, impl)
    _check_tag(tag)
    from .observe import flight
    from .utils import trace

    tracing = trace.enabled()
    if not tracing and not flight.enabled:
        return exchange(impl, data, dest, source, tag, out=out)
    tok = flight.begin("sendrecv", dest, tag, _payload_bytes(data)) \
        if flight.enabled else None
    try:
        if tracing:
            # Count the exchange's two legs at this level — the internal
            # engine (`exchange`) is also used by collectives_generic,
            # whose traffic is accounted under its own collective name
            # instead.
            trace.count("comm.send.calls")
            trace.count("comm.send.bytes", _payload_bytes(data))
            trace.count("comm.receive.calls")
            with trace.span("mpi.sendrecv", dest=dest, source=source,
                            tag=tag):
                result = exchange(impl, data, dest, source, tag, out=out)
            trace.count("comm.receive.bytes", _payload_bytes(result))
        else:
            result = exchange(impl, data, dest, source, tag, out=out)
    except BaseException as exc:
        if tok is not None:
            flight.end(tok, f"error:{type(exc).__name__}")
        raise
    if tok is not None:
        flight.end(tok)
    return result


def _check_peer(peer: int, impl: Interface) -> None:
    n = impl.size()
    if not 0 <= peer < n:
        raise MpiError(f"mpi_tpu: peer rank {peer} out of range [0, {n})")


def _check_tag(tag: int) -> None:
    """World traffic owns the non-negative tag space; the negative half
    is reserved for sub-communicator context regions
    (:mod:`mpi_tpu.comm`), so a negative world tag could capture — or be
    captured by — another communicator's traffic."""
    if tag < 0:
        raise MpiError(
            f"mpi_tpu: tag {tag} is negative; the negative tag space is "
            f"reserved for sub-communicator contexts (mpi_tpu.comm)")


# ---------------------------------------------------------------------------
# Collectives — new capability (reference stub: mpi.go:130, 69-71).
# Native backend methods win; otherwise generic algorithms over send/receive.
# ---------------------------------------------------------------------------

@_guarded
def _collective(name: str, *args: Any, **kwargs: Any) -> Any:
    impl = _require_init()
    # A blocking collective must not race this thread's outstanding
    # nonblocking ones into the positional rendezvous (see
    # _drain_chain); it joins the chain by draining it first.
    _drain_chain((id(impl), 0))
    native = getattr(impl, name, None)
    if native is not None:
        call = lambda: native(*args, **kwargs)  # noqa: E731
    else:
        from . import collectives_generic as gen

        generic = getattr(gen, name)
        call = lambda: generic(impl, *args, **kwargs)  # noqa: E731
    from .observe import flight
    from .utils import trace

    tracing = trace.enabled()
    if not tracing and not flight.enabled:
        return call()
    # Straggler substrate: every rank stamps its local arrival at this
    # collective; the in-process drivers report exact skew, and the
    # finalize-time merge computes cross-process skew from the
    # clock-aligned stamps (mpi_tpu.observe.collect).
    from .observe import metrics as _metrics

    _metrics.note_collective_entry(name)
    tok = flight.begin(name, -1, -1,
                       _payload_bytes(args[0]) if args else 0) \
        if flight.enabled else None
    try:
        if tracing:
            trace.count(f"comm.{name}.calls")
            if args:
                trace.count(f"comm.{name}.bytes", _payload_bytes(args[0]))
            with trace.span(f"mpi.{name}"):
                result = call()
        else:
            result = call()
    except BaseException as exc:
        if tok is not None:
            flight.end(tok, f"error:{type(exc).__name__}")
        raise
    if tok is not None:
        flight.end(tok)
    return result


def allreduce(data: Any, op: "OpLike" = "sum") -> Any:
    """Combine ``data`` across all ranks with ``op`` and return the result
    on every rank. ``op``: "sum"/"prod"/"min"/"max", or any associative
    callable ``op(a, b) -> combined`` (the MPI_Op_create analogue —
    combination strictly in rank order, so non-commutative ops are
    well-defined; callables reduce on the host tree since XLA cannot
    compile them). The north-star collective (BASELINE.json)."""
    return _collective("allreduce", data, op=op)


def reduce(data: Any, root: int = 0, op: "OpLike" = "sum") -> Optional[Any]:
    """Combine across ranks; result only on ``root`` (None elsewhere)."""
    return _collective("reduce", data, root=root, op=op)


def reduce_scatter(data: Any, op: "OpLike" = "sum") -> Any:
    """Combine ``data`` across ranks, then return only this rank's block:
    the leading axis splits into ``size`` equal blocks and rank ``i``
    gets reduced block ``i`` — the bandwidth-optimal half of ring
    allreduce, exposed directly (ZeRO-style optimizers shard state this
    way). Requires ``data.shape[0] % size == 0``."""
    return _collective("reduce_scatter", data, op=op)


def bcast(data: Any, root: int = 0) -> Any:
    """Broadcast ``root``'s payload to every rank."""
    return _collective("bcast", data, root=root)


def allgather(data: Any) -> List[Any]:
    """Gather every rank's payload to every rank, ordered by rank."""
    return _collective("allgather", data)


def gather(data: Any, root: int = 0) -> Optional[List[Any]]:
    """Gather payloads to ``root`` (list ordered by rank; None elsewhere)."""
    return _collective("gather", data, root=root)


def scatter(data: Optional[List[Any]], root: int = 0) -> Any:
    """Scatter ``root``'s list of per-rank payloads; returns this rank's."""
    return _collective("scatter", data, root=root)


def alltoall(data: List[Any]) -> List[Any]:
    """Personalized all-to-all: element j of this rank's list goes to rank
    j; returns the list of payloads received, ordered by source rank."""
    return _collective("alltoall", data)


class Request:
    """Handle for a nonblocking operation — the async design the
    reference sketches but never builds (the commented-out Send/Wait
    pair at /root/reference/mpi.go:132-152). ``isend``/``irecv`` start
    the blocking operation on a worker thread (the reference's
    "callers use goroutines" doctrine made first-class) and return one
    of these; ``wait()`` joins it, re-raising any error (including
    ``TagError`` for a duplicate live ``{peer, tag}``) and returning
    the received payload for receives. Once ``wait`` returns, the
    ``{peer, tag}`` pair is free for reuse — exactly the contract the
    sketch specifies."""

    def __init__(self, fn, cancel_hook=None):
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self._cancel_hook = cancel_hook
        self._cancelled = False

        def run():
            try:
                self._result = fn()
            except BaseException as exc:  # re-raised at wait()
                self._exc = exc

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def test(self) -> bool:
        """True once the operation has completed (without blocking).
        Completion includes failure — ``wait`` reports which."""
        return not self._thread.is_alive()

    def cancel(self) -> bool:
        """MPI_Cancel: best-effort cancellation of a pending operation.

        True when the operation was actually cancelled (a receive whose
        message had not yet been matched); the canonical completion
        sequence is still ``cancel(); wait()`` — after a successful
        cancel, ``wait`` returns ``None`` and :attr:`cancelled` is
        True, rather than raising (MPI's cancelled-request contract).
        A request with nothing cancellable (sends mid-rendezvous, an
        already-matched receive, collectives) returns False and
        completes normally — MPI says cancellation is permitted to
        fail.

        The retract hook only bites once the worker thread has CLAIMED
        the tag — a cancel racing a just-posted irecv would no-op and
        leave ``wait()`` blocked forever — so this retries over a
        short bounded window until the claim exists (normally
        microseconds away) or the operation completes by itself."""
        if self._cancel_hook is None:
            return False
        deadline = time.monotonic() + 1.0
        while not self.test():
            try:
                hit = self._cancel_hook()
            except Exception:
                return False  # invalid envelope etc: wait() reports it
            if hit:
                self._cancelled = True
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return False

    @property
    def cancelled(self) -> bool:
        """True when :meth:`cancel` succeeded (MPI_Test_cancelled)."""
        return self._cancelled

    def wait(self, timeout: Optional[float] = None) -> Any:
        """Block until completion; return the received payload (None for
        sends). Raises the operation's error, or ``MpiError`` on
        timeout. A successfully cancelled request completes with
        ``None`` instead of raising (check :attr:`cancelled`)."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise MpiError(
                f"mpi_tpu: Request.wait timed out after {timeout}s")
        if self._exc is not None:
            from .backends.rendezvous import ReceiveCancelled

            if self._cancelled and isinstance(self._exc,
                                              ReceiveCancelled):
                return None  # cancelled completion, per MPI semantics
            raise self._exc
        # The payload arrived despite a racing cancel (MPI: a
        # successful cancel means NO part of the message was received
        # — so a delivered message proves the cancel did not happen).
        self._cancelled = False
        return self._result


def isend(data: Any, dest: int, tag: int) -> Request:
    """Nonblocking send: returns immediately with a :class:`Request`;
    ``wait()`` blocks until the receiver accepted the payload (the
    rendezvous ack — the reference sketch's ``Wait``, mpi.go:145-151).

    Routed through the facade's :func:`send` so peer validation and
    trace accounting cover nonblocking traffic too (validation errors
    surface at ``wait()``)."""
    _require_init()
    return Request(lambda: send(data, dest, tag))


def irecv(source: int, tag: int, out: Optional[Any] = None) -> Request:
    """Nonblocking receive: ``wait()`` returns the payload. Supports
    ``Request.cancel()`` when the backend can retract an unmatched
    receive (``cancel_receive`` — the tcp/shm and xla drivers can)."""
    _require_init()
    impl = registered()
    hook = getattr(impl, "cancel_receive", None)
    return Request(lambda: receive(source, tag, out),
                   cancel_hook=(None if hook is None
                                else lambda: hook(source, tag)))


def waitall(requests: List[Optional[Request]],
            timeout: Optional[float] = None) -> List[Any]:
    """Wait on every request; results in order; first error re-raised.
    ``None`` slots (requests already consumed by :func:`waitany` —
    MPI_REQUEST_NULL) are skipped with a ``None`` result. ``timeout`` is
    a TOTAL deadline across the whole set — a hung request makes the
    call raise after ~``timeout`` seconds, not ``len(requests) *
    timeout`` (requests still running at the deadline are reported in
    the error and keep their daemon worker threads)."""
    import time as _time

    deadline = None if timeout is None else _time.monotonic() + timeout
    results: List[Any] = []
    first_exc: Optional[BaseException] = None
    for req in requests:
        if req is None:
            results.append(None)
            continue
        left = None if deadline is None else max(
            0.0, deadline - _time.monotonic())
        try:
            results.append(req.wait(left))
        except BaseException as exc:
            if first_exc is None:
                first_exc = exc
            results.append(None)
    if first_exc is not None:
        pending = [i for i, r in enumerate(requests)
                   if r is not None and not r.test()]
        if pending:
            exc = MpiError(
                f"mpi_tpu: waitall deadline expired with "
                f"{len(pending)}/{len(requests)} requests still running "
                f"(indices {pending})")
            exc.partial_results = results
            raise exc from first_exc
        raise first_exc
    return results


class PersistentRequest:
    """A restartable communication operation (MPI_Send_init /
    MPI_Recv_init): the envelope — peer, tag, and for sends a payload
    *supplier* — is fixed once, then each :meth:`start` launches one
    instance and :meth:`wait` completes it, freeing the ``{peer, tag}``
    pair for the next ``start``. The idiom for fixed communication
    patterns in iterative codes (halo exchanges, pipelined rings), where
    MPI amortizes envelope setup; here it amortizes the closure and
    keeps the call sites declarative."""

    def __init__(self, fn: Callable[[], Any],
                 launcher: Optional[Callable[[Callable[[], Any]],
                                             "Request"]] = None):
        self._fn = fn
        # How start() turns fn into a Request. Persistent COLLECTIVES
        # pass a launcher that chains onto the caller thread's
        # i-collective chain (see _persistent_collective) so their
        # instances keep the collective ordering contract; p2p ops use
        # a plain Request.
        self._launch = launcher if launcher is not None else Request
        self._active: Optional[Request] = None

    def start(self) -> "PersistentRequest":
        """Launch one instance. Every started instance must be completed
        with :meth:`wait` before the next ``start`` (the MPI contract) —
        otherwise a quickly-failed instance's stored error (or a
        receive's payload) would be silently discarded here."""
        if self._active is not None:
            if not self._active.test():
                raise MpiError(
                    "mpi_tpu: PersistentRequest.start() while the "
                    "previous instance is still in flight; wait() first")
            raise MpiError(
                "mpi_tpu: PersistentRequest.start() before wait() on the "
                "completed previous instance (its result/error would be "
                "lost)")
        self._active = self._launch(self._fn)
        return self

    def test(self) -> bool:
        return self._active is not None and self._active.test()

    def wait(self, timeout: Optional[float] = None) -> Any:
        """Complete the in-flight instance (payload for receives).

        A timeout leaves the instance active so ``wait`` can be retried
        — discarding it would orphan a live ``{peer, tag}`` operation
        and lose its eventual result. Operation errors consume the
        instance (it completed; ``start`` may be called again)."""
        if self._active is None:
            raise MpiError(
                "mpi_tpu: PersistentRequest.wait() before start()")
        active = self._active
        try:
            result = active.wait(timeout)
        except MpiError:
            if not active.test():
                raise  # genuine timeout: instance retained for retry
            # Completed during the timeout window, or the operation's
            # own MpiError: consume the instance and surface its outcome.
            self._active = None
            return active.wait(0)
        except BaseException:
            # Consume only if the instance actually completed; an
            # interrupted join (KeyboardInterrupt/SystemExit) leaves the
            # operation live — keep it so a later wait() can finish it
            # instead of orphaning a live {peer, tag}.
            if active.test():
                self._active = None
            raise
        self._active = None
        return result


def send_init(data_or_supplier: Any, dest: int, tag: int) -> PersistentRequest:
    """Persistent send (MPI_Send_init). ``data_or_supplier`` may be the
    payload itself (same bytes every start) or a zero-arg callable
    evaluated at each :meth:`~PersistentRequest.start` — the analogue of
    MPI's buffer re-read, for payloads that change between iterations."""
    _require_init()
    supplier = _as_supplier(data_or_supplier)
    return PersistentRequest(lambda: send(supplier(), dest, tag))


def recv_init(source: int, tag: int,
              out: Optional[Any] = None) -> PersistentRequest:
    """Persistent receive (MPI_Recv_init); each completed ``wait()``
    returns that instance's payload."""
    _require_init()
    return PersistentRequest(lambda: receive(source, tag, out))


def _as_supplier(data_or_supplier: Any) -> Callable[[], Any]:
    """The callable-vs-payload coercion every ``*_init`` shares: a
    zero-arg callable is re-read at each start (MPI's buffer re-read);
    anything else is the fixed payload."""
    if callable(data_or_supplier):
        return data_or_supplier
    return lambda: data_or_supplier


def _persistent_collective(name: str, supplier: Callable[[], Tuple],
                           ) -> PersistentRequest:
    impl = _require_init()
    # start() must join the caller thread's i-collective chain — a
    # plain Request would run _collective in a fresh worker thread
    # whose empty TLS makes its _drain_chain a no-op, letting the
    # instance race outstanding nonblocking collectives (or another
    # in-flight persistent instance) into the positional rendezvous.
    return PersistentRequest(
        lambda: _collective(name, *supplier()),
        launcher=lambda fn: _chained_request((id(impl), 0), fn))


def allreduce_init(data_or_supplier: Any,
                   op: "OpLike" = "sum") -> PersistentRequest:
    """Persistent allreduce (MPI-4 MPI_Allreduce_init). Each
    :meth:`~PersistentRequest.start` runs one allreduce round; as with
    every collective, all ranks must start their instances in the same
    collective order. ``data_or_supplier`` may be a zero-arg callable
    re-read at each start (the MPI buffer-re-read analogue)."""
    supplier = _as_supplier(data_or_supplier)
    return _persistent_collective("allreduce", lambda: (supplier(), op))


def bcast_init(data_or_supplier: Any = None,
               root: int = 0) -> PersistentRequest:
    """Persistent broadcast (MPI_Bcast_init); each completed ``wait()``
    returns that round's payload."""
    supplier = _as_supplier(data_or_supplier)
    return _persistent_collective("bcast", lambda: (supplier(), root))


def barrier_init() -> PersistentRequest:
    """Persistent barrier (MPI_Barrier_init)."""
    return _persistent_collective("barrier", lambda: ())


# --------------------------------------------------------------------------
# Pack / Unpack (MPI_Pack / MPI_Unpack analogue)
# --------------------------------------------------------------------------

def pack(*items: Any) -> bytes:
    """Serialize ``items`` into one contiguous buffer (MPI_Pack).

    Each item is encoded with the wire codec (the same typed encoding
    ``send`` uses — ndarrays round-trip dtype/shape losslessly) behind
    a u64 length prefix, so a packed buffer is self-describing and can
    ride any transport or file as a single payload. The reference's
    gob encoding plays this role implicitly; here it is explicit."""
    import struct as _struct

    from .utils.serialize import encode as _encode

    parts: List[bytes] = []
    for item in items:
        payload = _encode(item)
        parts.append(_struct.pack("<Q", len(payload)))
        parts.append(payload)
    return b"".join(parts)


def unpack(buf: Any) -> Tuple[Any, ...]:
    """Inverse of :func:`pack`: decode every packed item, in order."""
    import struct as _struct

    from .utils.serialize import decode as _decode

    # Normalize to a byte-granular view: a caller-supplied memoryview
    # with itemsize > 1 (e.g. over a uint64 array) would make len()
    # count elements while unpack_from offsets count bytes.
    if isinstance(buf, memoryview):
        view = buf.cast("B") if buf.contiguous else memoryview(bytes(buf))
    elif isinstance(buf, (bytes, bytearray)):
        view = memoryview(buf)
    else:
        view = memoryview(bytes(buf))
    out: List[Any] = []
    pos = 0
    total = len(view)
    while pos < total:
        if pos + 8 > total:
            raise MpiError(
                f"mpi_tpu: truncated pack buffer at offset {pos}")
        (n,) = _struct.unpack_from("<Q", view, pos)
        pos += 8
        if pos + n > total:
            raise MpiError(
                f"mpi_tpu: pack item of {n} bytes overruns buffer "
                f"({total - pos} left)")
        out.append(_decode(bytearray(view[pos:pos + n])))
        pos += n
    return tuple(out)


def waitany(requests: List[Optional[Request]],
            timeout: Optional[float] = None) -> Tuple[int, Any]:
    """Block until ANY request completes; return ``(index, result)`` and
    leave the rest running (MPI_Waitany). The completed slot is set to
    ``None`` in the caller's list — MPI's MPI_REQUEST_NULL convention —
    so the standard drain loop (`for _ in range(n): waitany(reqs)`)
    visits every request exactly once; ``None`` slots are skipped.
    Raises the completed operation's error; ``MpiError`` if every slot
    is already ``None`` or the deadline passes with nothing done."""
    import time as _time

    live = [i for i, r in enumerate(requests) if r is not None]
    if not live:
        raise MpiError(
            "mpi_tpu: waitany with no live requests (empty list or all "
            "slots already consumed)")
    deadline = None if timeout is None else _time.monotonic() + timeout
    while True:
        for i in live:
            req = requests[i]
            if req.test():
                requests[i] = None  # consumed: MPI_REQUEST_NULL
                return i, req.wait(0)
        if deadline is not None and _time.monotonic() >= deadline:
            raise MpiError(
                f"mpi_tpu: waitany timed out after {timeout}s with "
                f"{len(live)} requests still running")
        _time.sleep(0.0005)


# ---------------------------------------------------------------------------
# Nonblocking collectives (MPI-3 MPI_Iallreduce family): the blocking
# collective launched on a worker thread, completion via Request — the
# same doctrine as isend/irecv ("callers use goroutines", made
# first-class). The MPI ordering rule carries over: every rank must
# START its nonblocking collectives in the same order — and because the
# drivers match collectives positionally (shared barrier sessions /
# sequential tag blocks), consecutive nonblocking collectives on the
# same communicator are internally CHAINED in launch order: each
# executes only after the previous one launched by this thread
# completed. Progress therefore overlaps with the caller's compute
# (the point of I-collectives), not with each other — racing worker
# threads into the rendezvous would otherwise pair rank A's allreduce
# with rank B's bcast.
# ---------------------------------------------------------------------------

_icoll_tls = threading.local()


def _chain_slot(key: Any) -> Optional["Request"]:
    """This thread's outstanding chained request for ``key`` (pruned
    once complete, so finished results don't stay pinned)."""
    chains = getattr(_icoll_tls, "chains", None)
    if chains is None:
        chains = _icoll_tls.chains = {}
    prev = chains.get(key)
    if prev is not None and prev.test():
        del chains[key]
        prev = None
    return prev


def _drain_chain(key: Any) -> None:
    """Complete any outstanding chained i-collective for ``key`` before
    a BLOCKING collective on the same communicator proceeds — otherwise
    the blocking call would race the chained worker into the positional
    rendezvous and mismatch collective kinds across ranks. Errors stay
    with their own request."""
    prev = _chain_slot(key)
    if prev is not None:
        try:
            prev.wait()
        except BaseException:
            # prev's own stored error belongs to prev's owner — swallow.
            # But an interrupt of the join (KeyboardInterrupt/SystemExit
            # with prev still live) must propagate: proceeding would race
            # the still-running worker into the rendezvous.
            if not prev.test():
                raise
        _chain_slot(key)  # prune the completed entry


def _chained_request(key: Any, fn: Callable[[], Any]) -> "Request":
    """Launch ``fn`` on a worker thread AFTER the previous chained
    request for ``key`` (per launching thread) completes; errors stay
    with their own request (the successor still runs — matching MPI,
    where a failed collective does not cancel queued ones)."""
    prev = _chain_slot(key)

    def run() -> Any:
        if prev is not None:
            try:
                prev.wait()
            except BaseException:  # noqa: BLE001 — surfaced on prev
                pass
        return fn()

    req = Request(run)
    _icoll_tls.chains[key] = req
    return req


def _icollective(name: str) -> Callable[..., "Request"]:
    def launch(*args: Any, **kwargs: Any) -> Request:
        impl = _require_init()
        blocking = globals()[name]
        return _chained_request((id(impl), 0),
                                lambda: blocking(*args, **kwargs))

    launch.__name__ = f"i{name}"
    launch.__qualname__ = f"i{name}"
    launch.__doc__ = (
        f"Nonblocking {name} (MPI_I{name}): starts the "
        f"collective and returns a :class:`Request`; ``wait()`` yields "
        f"what blocking :func:`{name}` returns. All ranks must start "
        f"their nonblocking collectives in the same order; consecutive "
        f"ones chain in launch order (overlap is with caller compute).")
    return launch


iallreduce = _icollective("allreduce")
ireduce = _icollective("reduce")
ibcast = _icollective("bcast")
igather = _icollective("gather")
iallgather = _icollective("allgather")
iscatter = _icollective("scatter")
ialltoall = _icollective("alltoall")
ireduce_scatter = _icollective("reduce_scatter")
ibarrier = _icollective("barrier")


def scan(data: Any, op: "OpLike" = "sum") -> Any:
    """Inclusive prefix reduction in rank order: rank r gets the
    combination of ranks 0..r (MPI_Scan)."""
    return _collective("scan", data, op=op)


def exscan(data: Any, op: "OpLike" = "sum") -> Optional[Any]:
    """Exclusive prefix reduction: rank r gets ranks 0..r-1 combined;
    rank 0 gets None (MPI_Exscan)."""
    return _collective("exscan", data, op=op)


def barrier() -> None:
    """Block until every rank has entered the barrier."""
    return _collective("barrier")

"""Communicators — rank groups with isolated tag spaces (MPI_Comm).

The reference has exactly one implicit communicator: the whole world
(``Rank()``/``Size()`` address every process, mpi.go:112-119; every
``Send``/``Receive`` peer is a world rank, mpi.go:126-159). This module is
framework-completeness work with no reference analogue: it supplies the
communicator surface an MPI user expects — ``split`` /
``split_type("host")`` / ``dup`` / ``create_group`` / ``free`` for
construction, group-translated p2p (blocking, nonblocking, persistent,
probe), the full collective suite (blocking and MPI-3 I-variants),
and Cartesian topologies (:class:`CartComm`: coords/shift/sub plus
neighborhood collectives) — ordered sub-groups with their own dense
rank numbering and *context isolation* so traffic on one communicator
can never be captured by a matching ``{peer, tag}`` pair on another.
One-sided windows build on top in :mod:`mpi_tpu.window`.

Design (tpu-first, but transport-agnostic):

* A :class:`Comm` implements the backend SPI (rank/size/send/receive) by
  translating group ranks to world ranks and mapping tags into a
  per-communicator **context region** of the 64-bit tag space, then
  delegating to the underlying driver. Every facility built on the SPI —
  the generic collectives (:mod:`mpi_tpu.collectives_generic`), the
  concurrent-exchange engine (:func:`mpi_tpu.api.exchange`), nonblocking
  requests — therefore works on a sub-communicator unchanged, over any
  driver (tcp, xla, hybrid). On the xla driver, array payloads inside a
  group still ride the DevicePipe's compiled device-to-device transfers.

* **Context ids are negotiated, not hashed** (the approach real MPI
  implementations use): ``split`` runs a max-allreduce of every member's
  context high-water mark over the *parent* communicator, and the
  agreed ``max + 1`` becomes the child's context. Any two communicators
  that share a pair of ranks are therefore guaranteed distinct contexts
  (the shared member's high-water mark makes the later negotiation bid
  higher); disjoint communicators may reuse a context, which is safe
  because tag collision requires a shared ``{src, dst}`` link. The one
  rule inherited from this scheme: a rank must not run two ``split``
  calls concurrently on *overlapping* communicators (MPI imposes the
  same ordering requirement for collectives on a given communicator).

* **Tag layout**: world traffic uses non-negative tags (user tags below
  ``collectives_generic.COLL_TAG_BASE``, collective rounds above it).
  Communicator traffic uses the negative half of the i64 tag space —
  context ``c`` owns ``[-(c+1)*2^44, -c*2^44)`` — so no communicator tag
  can ever collide with world traffic, and the TCP wire format's i64 tag
  field (backends/tcp.py frame header) carries it unchanged. Within a
  region, user tags occupy the low ``2^40`` offsets and collective
  rounds the rest.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Any, List, Optional, Tuple

if TYPE_CHECKING:
    from .collectives_generic import OpLike

from .api import Interface, MpiError, Request, exchange as _exchange

__all__ = ["Comm", "CartComm", "Message", "PartitionedRecv",
           "PartitionedSend", "cart_create", "comm_world",
           "comm_self", "SELF_CTX", "CTX_SPAN",
           "USER_TAG_SPAN"]

CTX_SPAN = 1 << 44        # tag-space region per context
USER_TAG_SPAN = 1 << 40   # user tags within a region: [0, 2^40)
# CartComm neighborhood collectives own the TOP slice of each context's
# collective offset space — outside the user tag range entirely (no
# user tag can alias a halo message) and fenced off from the generic
# collectives' growing sequence by _map_tag's exhaustion check.
_NEIGHBOR_SLICE = 1 << 20
# RMA window passive-target service traffic owns the slice directly
# below the neighborhood slice (two tags per window: requests +
# replies; see window._svc_tags). Same fencing rule: the generic
# collective sequence is capped below both slices. WIN_TAG_BASE is the
# slice's first tag — the ONE definition window.py and the hybrid
# driver's cross-host remap both build on.
_WIN_SLICE = 1 << 20
# MPI-4 partitioned point-to-point ships each partition as its own
# tagged message from the slice directly below the window slice
# (tag*_MAX_PARTITIONS + i; see Comm.psend_init). The hybrid driver's
# cross-host remap covers this slice together with the window slice
# (they are contiguous by construction).
_PART_SLICE = 1 << 20
_MAX_PARTITIONS = 64
_PART_USER_TAGS = _PART_SLICE // _MAX_PARTITIONS  # user tags < 2^14


def _win_tag_base() -> int:
    from .collectives_generic import COLL_TAG_BASE

    return COLL_TAG_BASE + (CTX_SPAN - USER_TAG_SPAN - _NEIGHBOR_SLICE
                            - _WIN_SLICE)


def _part_tag_base() -> int:
    return _win_tag_base() - _PART_SLICE
# Context numbering: negotiated contexts grow monotonically from 1 and
# can never plausibly reach the top of the space, so the topmost
# _CREATE_GROUP_TAGS contexts are reserved as create_group's bootstrap
# band (one per bootstrap tag).
_CTX_MAX = (1 << 62) // CTX_SPAN
_CREATE_GROUP_TAGS = 1 << 12

_ctx_lock = threading.Lock()


class _CollState:
    """Collective tag-sequence state for one ``(rank, context)``.

    ``collectives_generic._next_tag_base`` reads/writes ``_coll_lock`` /
    ``_coll_seq`` attributes on whatever impl it is handed. Storing them
    on a :class:`Comm` instance would reset the sequence whenever the
    user constructs a second Comm for the same group (e.g. calling
    ``comm_world()`` twice), desynchronizing tag blocks across ranks
    that cache communicators differently. Instead every Comm for a given
    ``(rank, ctx)`` shares one of these, registered on the *driver* —
    and it is keyed by rank (not just ctx) because under thread-per-rank
    drivers (xla) all ranks share one driver object while each rank must
    allocate the sequence 0, 1, 2, ... independently."""

    __slots__ = ("_coll_lock", "_coll_seq")

    def __init__(self) -> None:
        self._coll_lock = threading.Lock()
        self._coll_seq = 0


def _ctx_high(impl: Interface) -> int:
    """This process's context high-water mark (0 = only the world ctx)."""
    return getattr(impl, "_comm_ctx_high", 0)


def _raise_ctx_high(impl: Interface, ctx: int) -> None:
    with _ctx_lock:
        if ctx > getattr(impl, "_comm_ctx_high", 0):
            setattr(impl, "_comm_ctx_high", ctx)


def _propose_ctx(impl: Interface) -> int:
    """Atomically reserve the next context bid for a split in flight, so
    two concurrent splits from this process never bid the same value."""
    with _ctx_lock:
        bid = getattr(impl, "_comm_ctx_high", 0) + 1
        setattr(impl, "_comm_ctx_high", bid)
        return bid


class Comm:
    """An ordered group of world ranks with its own rank numbering, tag
    space, and collectives. Implements the backend SPI (over translated
    ranks/tags), so it can be passed anywhere an ``Interface`` goes.

    Obtain the root via :func:`comm_world`; derive sub-communicators with
    :meth:`split` / :meth:`dup`. All SPI calls require the underlying
    driver to be initialized (``mpi_tpu.init()``).
    """

    def __init__(self, impl: Interface, members: Tuple[int, ...], ctx: int,
                 _ephemeral_tags: bool = False):
        if ctx < 0:
            raise MpiError(f"mpi_tpu: negative comm context {ctx}")
        if len(set(members)) != len(members):
            raise MpiError(f"mpi_tpu: duplicate world ranks in comm "
                           f"members {members}")
        self._impl = impl
        self._members = tuple(int(m) for m in members)
        self._ctx = int(ctx)
        self._world_to_group = {w: g for g, w in enumerate(self._members)}
        # Ephemeral tag state (create_group bootstraps): an instance-
        # local collective tag sequence restarting at 0, instead of the
        # persistent per-(rank, ctx) state — bootstrap contexts are
        # REUSED across calls with varying member sets, and a persistent
        # sequence would desynchronize ranks whose participation
        # histories differ (sequential same-tag bootstraps would hang).
        self._ephemeral_coll_state = _CollState() if _ephemeral_tags \
            else None

    # -- identity ----------------------------------------------------------

    @property
    def context(self) -> int:
        """The negotiated context id (0 = the world communicator)."""
        return self._ctx

    @property
    def members(self) -> Tuple[int, ...]:
        """World ranks of this group, ordered by group rank."""
        return self._members

    def translate(self, group_rank: int) -> int:
        """World rank of ``group_rank`` (MPI_Group_translate_ranks)."""
        self._check_peer(group_rank)
        return self._members[group_rank]

    def __repr__(self) -> str:
        return (f"Comm(ctx={self._ctx}, size={len(self._members)}, "
                f"members={self._members})")

    # -- SPI ---------------------------------------------------------------

    def init(self) -> None:
        raise MpiError("mpi_tpu: a Comm does not own the network; call "
                       "mpi_tpu.init() on the driver instead")

    def finalize(self) -> None:
        raise MpiError("mpi_tpu: a Comm does not own the network; call "
                       "mpi_tpu.finalize() on the driver instead")

    def Abort(self, errorcode: int = 1) -> None:
        """MPI_Abort (mpi4py spelling): terminate the job.

        Propagates an ABORT control frame to every peer (drivers that
        support it — the remote ranks' pending and future operations
        raise), then exits this process with ``errorcode``. Like
        MPI_Abort, this makes a best effort to kill the whole job, not
        just this communicator's group."""
        from . import api as _api

        # Notify through THIS comm's driver first: a Comm built over an
        # unregistered impl (in-process harnesses) would otherwise only
        # notify whatever the facade registry holds. When the impl IS
        # the registered backend, api.abort() already notifies it —
        # skip the duplicate (each notify pays timed-lock acquisitions).
        notify = getattr(self._impl, "notify_abort", None)
        if notify is not None and _api._backend is not self._impl:
            try:
                notify(errorcode)
            except BaseException:  # noqa: BLE001 - exiting anyway
                pass
        _api.abort(errorcode)

    def rank(self) -> int:
        """This process's rank within the group."""
        w = self._impl.rank()
        g = self._world_to_group.get(w)
        if g is None:
            raise MpiError(
                f"mpi_tpu: world rank {w} is not a member of {self!r}")
        return g

    def size(self) -> int:
        return len(self._members)

    def send(self, data: Any, dest: Optional[int], tag: int) -> None:
        """Blocking rendezvous send to group rank ``dest``.

        ``dest=None`` is PROC_NULL (the value :meth:`CartComm.shift`
        yields at a non-periodic edge): the send is a no-op, per MPI
        semantics — halo-exchange loops need no edge special-casing."""
        if dest is None:
            return
        self._check_peer(dest)
        from .utils import trace

        if not trace.enabled():
            return self._impl.send(data, self._members[dest],
                                   self._map_tag(tag))
        from .api import _payload_bytes

        trace.count("comm.send.calls")
        trace.count("comm.send.bytes", _payload_bytes(data))
        with trace.span("mpi.send", ctx=self._ctx, dest=dest, tag=tag):
            self._impl.send(data, self._members[dest], self._map_tag(tag))

    def receive(self, source: Optional[int], tag: int,
                out: Optional[Any] = None) -> Any:
        """Blocking receive from group rank ``source``.

        ``source=None`` is PROC_NULL: completes immediately and returns
        ``None`` (MPI's receive-from-MPI_PROC_NULL contract)."""
        if source is None:
            return None
        self._check_peer(source)
        from .utils import trace

        if not trace.enabled():
            return self._impl.receive(self._members[source],
                                      self._map_tag(tag), out=out)
        from .api import _payload_bytes

        with trace.span("mpi.receive", ctx=self._ctx, source=source,
                        tag=tag):
            result = self._impl.receive(self._members[source],
                                        self._map_tag(tag), out=out)
        trace.count("comm.receive.calls")
        trace.count("comm.receive.bytes", _payload_bytes(result))
        return result

    def cancel_receive(self, source: int, tag: int) -> bool:
        """Forwarded so :func:`mpi_tpu.api.exchange` can clean up a posted
        receive when its paired send fails (drivers without support are
        detected by the engine via getattr, so only forward if present)."""
        cancel = getattr(self._impl, "cancel_receive", None)
        if cancel is None:
            raise AttributeError("underlying driver has no cancel_receive")
        self._check_peer(source)
        return cancel(self._members[source], self._map_tag(tag))

    def sendrecv(self, data: Any, dest: Optional[int],
                 source: Optional[int], tag: int,
                 out: Optional[Any] = None) -> Any:
        """Concurrent send+receive within the group (deadlock-free where
        sequential send-then-receive would rendezvous-deadlock).
        ``None`` on either side is PROC_NULL: that leg is skipped (a
        None source yields a ``None`` result) — so a non-periodic
        :meth:`CartComm.shift` pair drops straight in."""
        if dest is None and source is None:
            return None
        if dest is None:
            return self.receive(source, tag, out=out)
        if source is None:
            self.send(data, dest, tag)
            return None
        self._check_peer(dest)
        self._check_peer(source)
        from .utils import trace

        if not trace.enabled():
            return _exchange(self, data, dest, source, tag, out=out)
        # The engine's two legs run through the traced send/receive
        # above; this span groups them like the facade's mpi.sendrecv.
        with trace.span("mpi.sendrecv", ctx=self._ctx, dest=dest,
                        source=source, tag=tag):
            return _exchange(self, data, dest, source, tag, out=out)

    def iprobe(self, source: Optional[int], tag: int) -> bool:
        """Non-consuming group probe (MPI_Iprobe). ``source=None``
        (PROC_NULL) is immediately 'available' — the matching receive
        completes at once with ``None``, per MPI."""
        if source is None:
            return True
        self._check_peer(source)
        probe_fn = getattr(self._impl, "iprobe", None)
        if probe_fn is None:
            raise MpiError(
                f"mpi_tpu: backend {type(self._impl).__name__} does not "
                f"support iprobe")
        return bool(probe_fn(self._members[source], self._map_tag(tag)))

    def probe(self, source: Optional[int], tag: int,
              timeout: Optional[float] = None) -> None:
        """Blocking group probe (MPI_Probe)."""
        from .api import _poll_until

        _poll_until(lambda: self.iprobe(source, tag), timeout,
                    f"probe(source={source}, tag={tag})")

    def isend(self, data: Any, dest: int, tag: int) -> Request:
        """Nonblocking group send; ``wait()`` blocks until the rendezvous
        ack (same contract as :func:`mpi_tpu.isend`)."""
        return Request(lambda: self.send(data, dest, tag))

    def irecv(self, source: int, tag: int, out: Optional[Any] = None
              ) -> Request:
        """Nonblocking group receive; ``wait()`` returns the payload.
        Cancellable while unmatched (``Request.cancel()``) — the hook
        retracts the claim under the same member/context-tag mapping
        the receive itself uses."""
        hook = None
        if getattr(self._impl, "cancel_receive", None) is not None \
                and source is not None:
            # Lazy: validation/mapping happen inside cancel_receive AT
            # CANCEL TIME, so an invalid source/tag surfaces at wait()
            # on every driver alike (eager mapping here would make the
            # error site depend on whether the backend is cancellable).
            hook = lambda: self.cancel_receive(source, tag)  # noqa: E731
        return Request(lambda: self.receive(source, tag, out=out),
                       cancel_hook=hook)

    def receive_any(self, tag: int, timeout: Optional[float] = None
                    ) -> Tuple[int, Any]:
        """Receive ``tag`` from whichever GROUP member sends first —
        MPI_ANY_SOURCE scoped to this communicator; returns
        ``(group_source, payload)``. Same engine and concurrency
        contract as :func:`mpi_tpu.receive_any` (probe-then-claim with
        cancellable bounded receives); group traffic from other
        communicators can never match (context isolation)."""
        from .api import _receive_any_loop

        return _receive_any_loop(
            self.iprobe, self.receive, self.cancel_receive,
            self.rank(), self.size(), tag, timeout, "Comm.receive_any")

    # -- partitioned point-to-point (MPI-4 MPI_Psend_init family) ----------

    def _part_check(self, buf, partitions: int, tag: int):
        import numpy as np

        arr = np.asarray(buf)
        if arr.ndim != 1:
            raise MpiError(
                f"mpi_tpu: partitioned buffers are 1-D arrays, got "
                f"shape {arr.shape}")
        if not 1 <= partitions <= _MAX_PARTITIONS:
            raise MpiError(
                f"mpi_tpu: partitions must be in [1, {_MAX_PARTITIONS}]"
                f", got {partitions}")
        if arr.shape[0] % partitions:
            raise MpiError(
                f"mpi_tpu: buffer of {arr.shape[0]} elements does not "
                f"split into {partitions} equal partitions")
        if not 0 <= tag < _PART_USER_TAGS:
            raise MpiError(
                f"mpi_tpu: partitioned tag must be in "
                f"[0, {_PART_USER_TAGS}), got {tag}")
        return arr

    def psend_init(self, buf, partitions: int, dest: int,
                   tag: int = 0) -> "PartitionedSend":
        """Persistent partitioned send (MPI-4 MPI_Psend_init): ``buf``
        (1-D numpy array, ``partitions`` equal chunks) ships chunk by
        chunk — ``start()`` opens an iteration, ``pready(i)`` marks
        partition i final (it ships immediately, overlapping the
        producer's remaining work), ``wait()`` completes the
        iteration. Restart with ``start()`` — the persistent-request
        model. The matching ``precv_init`` must use the same
        ``partitions`` and ``tag``. A numpy array is REQUIRED: the
        producer writes into it between start() and each pready(), so
        a detached copy (what np.asarray makes of a list) would
        silently ship stale init-time contents forever."""
        import numpy as np

        self._check_peer(dest)
        if not isinstance(buf, np.ndarray):
            raise MpiError(
                "mpi_tpu: psend_init needs a numpy array (partitions "
                "are read from it at each pready)")
        arr = self._part_check(buf, partitions, tag)
        return PartitionedSend(self, arr, partitions, dest, tag)

    def precv_init(self, buf, partitions: int, source: int,
                   tag: int = 0) -> "PartitionedRecv":
        """Persistent partitioned receive (MPI_Precv_init): partitions
        land in ``buf`` (written through) as they arrive;
        ``parrived(i)`` tests one without blocking, ``wait()`` blocks
        for the full buffer."""
        import numpy as np

        self._check_peer(source)
        arr = self._part_check(buf, partitions, tag)
        if not isinstance(buf, np.ndarray):
            raise MpiError(
                "mpi_tpu: precv_init needs a writable numpy array "
                "(partitions are written through)")
        return PartitionedRecv(self, arr, partitions, source, tag)

    # -- matched probe (MPI_Mprobe / MPI_Improbe) --------------------------

    def mprobe(self, source: Optional[int], tag: int,
               timeout: Optional[float] = None) -> "Message":
        """Matched probe: block until a message with ``tag`` from
        ``source`` is matched AND claimed by this caller — after
        return, no sibling receive can steal it (the thread-safety
        hole MPI_Mprobe exists to close). ``source=None`` follows this
        class's PROC_NULL convention (probe/sendrecv do the same):
        the result is the no-proc message, whose ``recv()`` returns
        ``None`` immediately (MPI_MESSAGE_NO_PROC). For ANY_SOURCE
        use :meth:`mprobe_any`. Claiming completes the transfer here,
        so the sender's rendezvous ack fires at mprobe, not at
        :meth:`Message.recv` — a documented deviation (MPI permits
        buffering at match time)."""
        import time as _time

        from .api import _claim_probed

        if source is None:  # PROC_NULL: immediate no-proc message
            return Message(None, tag, None)
        deadline = (None if timeout is None
                    else _time.monotonic() + timeout)
        while True:
            remaining = (None if deadline is None
                         else max(0.0, deadline - _time.monotonic()))
            self.probe(source, tag, remaining)
            won, payload = _claim_probed(self.receive,
                                         self.cancel_receive,
                                         source, tag)
            if won:
                return Message(source, tag, payload)
            _time.sleep(0.0005)  # sibling took it; re-probe

    def mprobe_any(self, tag: int,
                   timeout: Optional[float] = None) -> "Message":
        """Matched probe with MPI_ANY_SOURCE: first matching message
        from any group member, claimed (same engine as
        :meth:`receive_any`)."""
        src, payload = self.receive_any(tag, timeout)
        return Message(src, tag, payload)

    def improbe(self, source: int, tag: int) -> Optional["Message"]:
        """Nonblocking matched probe (MPI_Improbe): a claimed
        :class:`Message`, or ``None`` when nothing is matchable now
        (including losing the claim race to a sibling)."""
        from .api import _claim_probed

        if not self.iprobe(source, tag):
            return None
        won, payload = _claim_probed(self.receive, self.cancel_receive,
                                     source, tag)
        return Message(source, tag, payload) if won else None

    # -- tag mapping -------------------------------------------------------

    def _map_tag(self, tag: int) -> int:
        from .collectives_generic import COLL_TAG_BASE

        if self._ctx == 0:
            # World comm: the driver's tag space verbatim — but never the
            # negative half, which belongs to sub-communicator contexts
            # (a negative world tag could forge a context-region tag and
            # capture another communicator's traffic).
            if tag < 0:
                raise MpiError(
                    f"mpi_tpu: tag {tag} is negative; the negative tag "
                    f"space is reserved for sub-communicator contexts")
            return tag
        if 0 <= tag < USER_TAG_SPAN:
            offset = tag
        elif tag >= COLL_TAG_BASE:
            # Generic collective rounds (allocated below the neighbor
            # slice — the _coll_seq setter enforces that) and synthetic
            # neighborhood tags (constructed inside the slice) share
            # this arithmetic.
            offset = USER_TAG_SPAN + (tag - COLL_TAG_BASE)
            if offset >= CTX_SPAN:
                raise MpiError(
                    "mpi_tpu: communicator collective tag space exhausted")
        else:
            raise MpiError(
                f"mpi_tpu: tag {tag} out of range for a sub-communicator "
                f"(user tags must be in [0, 2^40))")
        if (self._ctx + 1) * CTX_SPAN > (1 << 62):
            # Regions below -2^62 belong to the hybrid driver's group-
            # engine TCP blocks; ~2^18 contexts per run is the cap.
            raise MpiError(
                f"mpi_tpu: communicator context space exhausted "
                f"(ctx={self._ctx})")
        return -((self._ctx + 1) * CTX_SPAN) + offset

    def _check_peer(self, peer: int) -> None:
        n = len(self._members)
        if not 0 <= peer < n:
            raise MpiError(
                f"mpi_tpu: group rank {peer} out of range [0, {n})")

    # -- collective tag-sequence state (see _CollState) --------------------

    def _coll_state(self) -> _CollState:
        if self._ephemeral_coll_state is not None:
            return self._ephemeral_coll_state
        key = (self._impl.rank(), self._ctx)
        with _ctx_lock:
            states = self._impl.__dict__.setdefault("_comm_coll_states", {})
            st = states.get(key)
            if st is None:
                st = states[key] = _CollState()
        return st

    # collectives_generic._next_tag_base reads/writes these attributes on
    # the impl it is handed; proxy them to the shared per-(rank, ctx)
    # state so every Comm instance for the same group stays in lockstep.
    @property
    def _coll_lock(self) -> threading.Lock:
        return self._coll_state()._coll_lock

    @property
    def _coll_seq(self) -> int:
        return self._coll_state()._coll_seq

    @_coll_seq.setter
    def _coll_seq(self, value: int) -> None:
        from .collectives_generic import _TAGS_PER_COLLECTIVE

        # Cap the generic sequence below the neighborhood + window +
        # partitioned slices at the top of the collective offset space:
        # allocation-time exhaustion beats a silently mis-routed halo
        # or RMA service tag ~4e9 collectives later.
        limit = (CTX_SPAN - USER_TAG_SPAN - _NEIGHBOR_SLICE
                 - _WIN_SLICE - _PART_SLICE) // _TAGS_PER_COLLECTIVE
        if value >= limit:
            raise MpiError(
                "mpi_tpu: communicator collective tag space exhausted")
        self._coll_state()._coll_seq = value

    # -- collectives -------------------------------------------------------
    #
    # Context 0 (world) has the driver's exact membership and tag space,
    # so it dispatches like the facade: the driver's native collective
    # (e.g. the xla driver's compiled XLA programs) when present, else
    # the generic algorithm over the DRIVER — sharing the driver's tag
    # sequence with facade-level collectives. Sub-communicators run the
    # generic algorithms over the translated SPI (self).

    def _coll(self, name: str, *args: Any, **kwargs: Any) -> Any:
        from .api import _drain_chain
        from .utils import trace

        # Blocking group collectives join this thread's nonblocking
        # chain for the communicator (see api._drain_chain).
        _drain_chain((id(self._impl), self._ctx))
        if not trace.enabled():
            return self._coll_inner(name, *args, **kwargs)
        from .api import _payload_bytes

        trace.count(f"comm.{name}.calls")
        if args:
            trace.count(f"comm.{name}.bytes", _payload_bytes(args[0]))
        # Note: when a group collective falls back to the generic
        # algorithms, its internal rounds go through the traced
        # send/receive above, so that traffic is additionally visible
        # under comm.send/receive — unlike world collectives, whose
        # generic rounds hit the driver directly. Deliberate: the extra
        # visibility is worth more than symmetric counters.
        with trace.span(f"mpi.{name}", ctx=self._ctx,
                        group_size=len(self._members)):
            return self._coll_inner(name, *args, **kwargs)

    def _coll_inner(self, name: str, *args: Any, **kwargs: Any) -> Any:
        from . import collectives_generic as gen

        if self._ctx == 0:
            native = getattr(self._impl, name, None)
            if native is not None:
                return native(*args, **kwargs)
            return getattr(gen, name)(self._impl, *args, **kwargs)
        # Drivers that expose compiled group engines (the xla driver's
        # sub-mesh _MeshCollectives) serve the whole suite as single
        # compiled XLA programs over the group's devices; ops an engine
        # lacks (scan/exscan) and engineless drivers use the generic
        # algorithms over this Comm's translated send/receive.
        group_engine = getattr(self._impl, "group_collectives", None)
        if group_engine is not None:
            native = getattr(group_engine(self._members, self._ctx),
                             name, None)
            if native is not None:
                return native(*args, **kwargs)
        return getattr(gen, name)(self, *args, **kwargs)

    def allreduce(self, data: Any, op: "OpLike" = "sum") -> Any:
        return self._coll("allreduce", data, op=op)

    def reduce(self, data: Any, root: int = 0, op: "OpLike" = "sum") -> Optional[Any]:
        return self._coll("reduce", data, root=root, op=op)

    def reduce_scatter(self, data: Any, op: "OpLike" = "sum") -> Any:
        return self._coll("reduce_scatter", data, op=op)

    def bcast(self, data: Any, root: int = 0) -> Any:
        return self._coll("bcast", data, root=root)

    def gather(self, data: Any, root: int = 0) -> Optional[List[Any]]:
        return self._coll("gather", data, root=root)

    def allgather(self, data: Any) -> List[Any]:
        return self._coll("allgather", data)

    def scatter(self, data: Optional[List[Any]], root: int = 0) -> Any:
        return self._coll("scatter", data, root=root)

    def alltoall(self, data: List[Any]) -> List[Any]:
        return self._coll("alltoall", data)

    def scan(self, data: Any, op: "OpLike" = "sum") -> Any:
        return self._coll("scan", data, op=op)

    def exscan(self, data: Any, op: "OpLike" = "sum") -> Optional[Any]:
        return self._coll("exscan", data, op=op)

    def barrier(self) -> None:
        return self._coll("barrier")

    # -- nonblocking collectives (MPI-3 I-variants) ------------------------
    #
    # The blocking group collective on a worker thread, completion via
    # Request — same contract as the facade's iallreduce family: every
    # member must START its nonblocking collectives in the same order,
    # and consecutive ones on the same communicator chain in launch
    # order (see api._chained_request — racing worker threads into the
    # shared rendezvous would mismatch collective kinds across ranks).

    def _icoll(self, name: str, *args: Any, **kwargs: Any) -> Request:
        from .api import _chained_request

        return _chained_request(
            (id(self._impl), self._ctx),
            lambda: getattr(self, name)(*args, **kwargs))

    def iallreduce(self, data: Any, op: "OpLike" = "sum") -> Request:
        return self._icoll("allreduce", data, op=op)

    def ireduce(self, data: Any, root: int = 0,
                op: "OpLike" = "sum") -> Request:
        return self._icoll("reduce", data, root=root, op=op)

    def ibcast(self, data: Any, root: int = 0) -> Request:
        return self._icoll("bcast", data, root=root)

    def igather(self, data: Any, root: int = 0) -> Request:
        return self._icoll("gather", data, root=root)

    def iallgather(self, data: Any) -> Request:
        return self._icoll("allgather", data)

    def iscatter(self, data: Optional[List[Any]], root: int = 0) -> Request:
        return self._icoll("scatter", data, root=root)

    def ialltoall(self, data: List[Any]) -> Request:
        return self._icoll("alltoall", data)

    def ireduce_scatter(self, data: Any, op: "OpLike" = "sum") -> Request:
        return self._icoll("reduce_scatter", data, op=op)

    def ibarrier(self) -> Request:
        return self._icoll("barrier")

    # -- construction ------------------------------------------------------

    def split(self, color: Optional[int], key: int = 0) -> Optional["Comm"]:
        """Partition this communicator (MPI_Comm_split semantics).

        Collective: **every** member must call it. Members with the same
        ``color`` form a new communicator, ranked by ``(key, rank in
        self)``; ``color=None`` (MPI_UNDEFINED) participates in the
        exchange but gets ``None`` back.
        """
        me = self.rank()
        # One collective exchange serves both membership and the context
        # negotiation: each member contributes (color, key, ctx bid). The
        # bid is reserved up front so concurrent splits from one process
        # bid distinct values; the agreed context is the max bid, which
        # every member then records as its new high-water mark.
        bid = _propose_ctx(self._impl)
        entries = self.allgather((color, key, bid))
        new_ctx = max(int(e[2]) for e in entries)
        _raise_ctx_high(self._impl, new_ctx)
        if color is None:
            return None
        group = sorted(
            (int(e[1]), r) for r, e in enumerate(entries) if e[0] == color)
        members = tuple(self._members[r] for _, r in group)
        child = Comm(self._impl, members, new_ctx)
        assert child._world_to_group.get(self._members[me]) is not None
        return child

    def split_type(self, kind: str = "host", key: int = 0
                   ) -> Optional["Comm"]:
        """Split into communicators of co-located ranks —
        MPI_Comm_split_type with MPI_COMM_TYPE_SHARED semantics.

        ``kind="host"`` groups members that share a machine, as reported
        by the driver's ``host_key()``: the address host for the TCP
        driver (textual match, localhost forms collapsed), the host index
        for the hybrid driver, and a single key for the xla driver (all
        ranks live in one process). Drivers without ``host_key`` are
        treated as single-host. Collective, like :meth:`split`."""
        if kind != "host":
            raise MpiError(
                f"mpi_tpu: unknown split_type kind {kind!r}; only 'host'")
        hk = getattr(self._impl, "host_key", None)
        return self.split(color=hk() if hk is not None else 0, key=key)

    def dup(self) -> "Comm":
        """A communicator with identical membership and ordering but a
        fresh context — isolates library traffic (MPI_Comm_dup)."""
        child = self.split(color=0, key=self.rank())
        assert child is not None
        return child

    def create_group(self, members, tag: int = 0) -> "Comm":
        """Create a communicator from an explicit subset of this comm's
        ranks (MPI_Comm_create_group): collective among ``members``
        ONLY — non-members do not participate at all, which is the
        point (vs :meth:`split`, where every rank must call). Group
        ranks follow the order of ``members``.

        ``tag`` disambiguates the bootstrap exactly as in MPI:
        concurrent ``create_group`` calls whose groups OVERLAP must use
        distinct tags (disjoint groups may share one) — here that rule
        spans parent communicators, slightly stricter than MPI's
        per-communicator tag scope. ``tag`` must be in ``[0, 4096)``.
        The caller must be listed in ``members``. Sequential calls may
        freely reuse a tag (each bootstrap's tag sequence is
        instance-local)."""
        members = tuple(int(m) for m in members)
        for m in members:
            self._check_peer(m)
        if len(set(members)) != len(members):
            raise MpiError(
                f"mpi_tpu: duplicate ranks in create_group members "
                f"{members}")
        if not 0 <= tag < _CREATE_GROUP_TAGS:
            raise MpiError(
                f"mpi_tpu: create_group tag must be in [0, "
                f"{_CREATE_GROUP_TAGS}), got {tag}")
        me = self.rank()
        if me not in members:
            raise MpiError(
                f"mpi_tpu: create_group caller (group rank {me}) is not "
                f"in members {members} — only members may call "
                f"(MPI_Comm_create_group contract)")
        # Bootstrap: a temporary communicator in a reserved context band
        # at the top of the context space, keyed by the user tag, runs
        # the standard ctx negotiation among the members only. Tag-
        # disambiguation makes overlapping concurrent bootstraps safe,
        # per the MPI contract above; negotiated contexts are monotone
        # small integers and cannot reach the band.
        world_members = tuple(self._members[m] for m in members)
        boot = Comm(self._impl, world_members, _CTX_MAX - 1 - tag,
                    _ephemeral_tags=True)
        try:
            bid = _propose_ctx(self._impl)
            bids = boot.allgather(bid)
            new_ctx = max(int(b) for b in bids)
            _raise_ctx_high(self._impl, new_ctx)
        finally:
            boot.free()  # release bootstrap engines/buffers
        return Comm(self._impl, world_members, new_ctx)

    def free(self) -> None:
        """Release driver resources held for this communicator —
        compiled group-collective programs and their device buffers on
        the xla driver (MPI_Comm_free). Call it from every member once
        no operation is in flight; the Comm must not be used afterwards
        (a stray call would silently rebuild the engine). No-op on
        drivers without per-group state and on the world communicator."""
        if self._ctx == 0:
            return
        release = getattr(self._impl, "release_group_collectives", None)
        if release is not None:
            release(self._members, self._ctx)


def comm_world(impl: Optional[Interface] = None) -> Comm:
    """The world communicator over the active (or given) driver: every
    rank, identity numbering, context 0 (driver tag space verbatim)."""
    from . import api

    if impl is None:
        impl = api._require_init()
    return Comm(impl, tuple(range(impl.size())), 0)


# Reserved context for self-communicators: directly BELOW the
# create_group bootstrap band (_CTX_MAX - 1 - tag for tag in
# [0, _CREATE_GROUP_TAGS)) so no bootstrap comm can ever alias it, and
# far above anything split negotiation reaches in a real run. Safe to
# share across ranks — every self-comm link is {me, me}, so two ranks'
# self-comms can never exchange (or capture) each other's traffic.
SELF_CTX = (1 << 62) // CTX_SPAN - _CREATE_GROUP_TAGS - 1


def comm_self(impl: Optional[Interface] = None) -> Comm:
    """MPI_COMM_SELF: a communicator containing only this rank.
    Creation is purely local (no negotiation round — MPI requires
    COMM_SELF to exist without collective calls), at the reserved
    :data:`SELF_CTX` context. Collectives on it are identities;
    send/receive are self-rendezvous; it makes e.g. per-rank private
    file IO (``open_file(comm_self(), ...)``) spell the same as MPI."""
    from . import api

    if impl is None:
        impl = api._require_init()
    return Comm(impl, (impl.rank(),), SELF_CTX)


class Message:
    """A matched-and-claimed message (MPI_Message, from
    :meth:`Comm.mprobe`/:meth:`Comm.improbe`): the payload is already
    transferred, so :meth:`recv` hands it over race-free. Single-use."""

    __slots__ = ("source", "tag", "_payload", "_taken")

    def __init__(self, source: int, tag: int, payload: Any):
        self.source = source
        self.tag = tag
        self._payload = payload
        self._taken = False

    def recv(self) -> Any:
        """The matched payload (MPI_Mrecv). Raises on second use
        (the handle is consumed, like MPI_MESSAGE_NULL)."""
        if self._taken:
            raise MpiError(
                "mpi_tpu: Message.recv on an already-received message")
        self._taken = True
        payload, self._payload = self._payload, None
        return payload

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        state = "consumed" if self._taken else "pending"
        return f"Message(source={self.source}, tag={self.tag}, {state})"


class _PartitionedOp:
    """Shared state machine for the partitioned send/receive pair.
    Partition i of user tag t travels as its own message on synthetic
    tag ``_part_tag_base() + t * _MAX_PARTITIONS + i``; iterations are
    serialized by wait() on both sides (the sender's rendezvous acks
    mean iteration n is fully received before n+1's first pready can
    complete), so the same tags are safely reused every iteration."""

    def __init__(self, comm: Comm, arr, partitions: int, peer: int,
                 tag: int):
        self._comm = comm
        self._arr = arr
        self._n = partitions
        self._peer = peer
        self._chunk = arr.shape[0] // partitions
        self._base = _part_tag_base() + tag * _MAX_PARTITIONS
        self._active = False

    @property
    def partitions(self) -> int:
        return self._n

    @property
    def active(self) -> bool:
        """True while an iteration is open (between start and wait)."""
        return self._active

    def _slice(self, i: int):
        if not 0 <= i < self._n:
            raise MpiError(
                f"mpi_tpu: partition {i} out of range [0, {self._n})")
        return self._arr[i * self._chunk:(i + 1) * self._chunk]

    def _require_active(self, what: str) -> None:
        if not self._active:
            raise MpiError(
                f"mpi_tpu: {what} outside an iteration — call start() "
                f"first (persistent-request model)")


class PartitionedSend(_PartitionedOp):
    def start(self) -> None:
        if self._active:
            raise MpiError(
                "mpi_tpu: PartitionedSend.start before the previous "
                "iteration's wait()")
        self._active = True
        self._ready: set = set()
        self._reqs: List[Request] = []

    def pready(self, partition: int) -> None:
        """Partition ``partition`` is final — ship it now
        (MPI_Pready). The buffer slice is snapshotted, so the producer
        may immediately reuse it."""
        self._require_active("pready")
        if partition in self._ready:
            raise MpiError(
                f"mpi_tpu: pready({partition}) twice in one iteration")
        data = self._slice(partition).copy()
        self._ready.add(partition)
        self._reqs.append(self._comm.isend(
            data, self._peer, self._base + partition))

    def pready_range(self, lo: int, hi: int) -> None:
        """MPI_Pready_range: ``pready`` for every partition in
        [lo, hi] (MPI's inclusive convention)."""
        for i in range(lo, hi + 1):
            self.pready(i)

    def wait(self) -> None:
        """Complete the iteration: every partition must be pready and
        acked by the receiver. The request restarts with start()."""
        self._require_active("wait")
        if len(self._ready) != self._n:
            raise MpiError(
                f"mpi_tpu: PartitionedSend.wait with only "
                f"{len(self._ready)}/{self._n} partitions pready")
        for r in self._reqs:
            r.wait()
        self._active = False


class PartitionedRecv(_PartitionedOp):
    def start(self) -> None:
        if self._active:
            raise MpiError(
                "mpi_tpu: PartitionedRecv.start before the previous "
                "iteration's wait()")
        self._active = True
        self._done: set = set()

    def parrived(self, partition: int) -> bool:
        """True once partition ``partition`` has landed in the buffer
        (MPI_Parrived); claims it from the wire on first success."""
        self._require_active("parrived")
        if partition in self._done:
            return True
        self._slice(partition)  # range check
        if not self._comm.iprobe(self._peer, self._base + partition):
            return False
        self._comm.receive(self._peer, self._base + partition,
                           out=self._slice(partition))
        self._done.add(partition)
        return True

    def wait(self) -> None:
        """Block until every partition has landed in the buffer."""
        self._require_active("wait")
        for i in range(self._n):
            if i not in self._done:
                self._comm.receive(self._peer, self._base + i,
                                   out=self._slice(i))
                self._done.add(i)
        self._active = False


class CartComm(Comm):
    """Cartesian-topology communicator (MPI_Cart_create family).

    Group ranks are laid out row-major over ``dims`` (the MPI
    convention: the LAST dimension varies fastest), each optionally
    periodic. Everything a :class:`Comm` does still works; on top of it:
    :meth:`coords`/:meth:`rank_of` translate between ranks and grid
    coordinates, :meth:`shift` yields the (source, dest) pair for a
    displacement along one axis (``None`` standing in for MPI_PROC_NULL
    at a non-periodic edge), and :meth:`sub` (MPI_Cart_sub) slices the
    grid into lower-dimensional Cartesian communicators. The mesh-axis
    analogy is direct: a ``CartComm`` is the host-side mirror of a
    ``jax.sharding.Mesh``'s named axes, so halo exchanges and per-axis
    collectives can be written against the same grid either way."""

    def __init__(self, impl: Interface, members: Tuple[int, ...], ctx: int,
                 dims: Tuple[int, ...], periods: Tuple[bool, ...]):
        super().__init__(impl, members, ctx)
        self._dims = tuple(int(d) for d in dims)
        self._periods = tuple(bool(p) for p in periods)
        _check_cart_shape(self._dims, self._periods, len(members))

    @property
    def dims(self) -> Tuple[int, ...]:
        return self._dims

    @property
    def periods(self) -> Tuple[bool, ...]:
        return self._periods

    def coords(self, rank: Optional[int] = None) -> Tuple[int, ...]:
        """Grid coordinates of ``rank`` (default: this rank)."""
        r = self.rank() if rank is None else rank
        self._check_peer(r)
        out = []
        for d in reversed(self._dims):
            out.append(r % d)
            r //= d
        return tuple(reversed(out))

    def rank_of(self, coords) -> int:
        """Group rank at ``coords`` (row-major; periodic axes wrap)."""
        if len(coords) != len(self._dims):
            raise MpiError(
                f"mpi_tpu: expected {len(self._dims)} coords, got "
                f"{len(coords)}")
        r = 0
        for c, d, p in zip(coords, self._dims, self._periods):
            c = int(c)
            if p:
                c %= d
            elif not 0 <= c < d:
                raise MpiError(
                    f"mpi_tpu: coordinate {c} out of range [0, {d}) on a "
                    f"non-periodic axis")
            r = r * d + c
        return r

    def shift(self, axis: int, disp: int = 1
              ) -> Tuple[Optional[int], Optional[int]]:
        """(source, dest) group ranks for a ``disp`` displacement along
        ``axis`` (MPI_Cart_shift): ``dest`` is where this rank's data
        goes, ``source`` is whose data arrives here. ``None`` marks the
        edge of a non-periodic axis (MPI_PROC_NULL)."""
        if not 0 <= axis < len(self._dims):
            raise MpiError(f"mpi_tpu: cart axis {axis} out of range")
        me = list(self.coords())

        def at(offset: int) -> Optional[int]:
            c = me[axis] + offset
            if not self._periods[axis] and not 0 <= c < self._dims[axis]:
                return None
            trial = list(me)
            trial[axis] = c
            return self.rank_of(trial)

        return at(-disp), at(disp)

    def neighbors(self) -> List[Optional[int]]:
        """This rank's grid neighbors in MPI neighborhood-collective
        order: for each axis, the -1 then the +1 displacement
        (``[axis0-, axis0+, axis1-, axis1+, ...]``), ``None`` at
        non-periodic edges (PROC_NULL)."""
        out: List[Optional[int]] = []
        for ax in range(len(self._dims)):
            src, dst = self.shift(ax, 1)
            out.extend((src, dst))
        return out

    def _neighbor_tag(self, tag: int, slot: int) -> int:
        """Synthetic tag inside the reserved neighborhood slice at the
        top of this context's collective offset space — no user tag can
        reach it, and the generic collectives' sequence is capped below
        it (the _coll_seq setter)."""
        from .collectives_generic import COLL_TAG_BASE

        if not 0 <= tag < (1 << 13):
            raise MpiError(
                f"mpi_tpu: neighbor collective tag must be in [0, 8192), "
                f"got {tag}")
        assert slot < 64
        return COLL_TAG_BASE + (CTX_SPAN - USER_TAG_SPAN
                                - _NEIGHBOR_SLICE) + tag * 64 + slot

    def neighbor_allgather(self, data: Any, tag: int = 0
                           ) -> List[Optional[Any]]:
        """Exchange ``data`` with every grid neighbor
        (MPI_Neighbor_allgather over the Cartesian topology): returns one
        payload per :meth:`neighbors` slot, ``None`` where the neighbor
        is PROC_NULL — the bulk-synchronous halo exchange, spelled once
        for any rank count and dimensionality. Exactly
        :meth:`neighbor_alltoall` with the same payload in every slot."""
        return self.neighbor_alltoall(
            [data] * (2 * len(self._dims)), tag=tag)

    def neighbor_alltoall(self, data: List[Any], tag: int = 0
                          ) -> List[Optional[Any]]:
        """Per-neighbor payloads (MPI_Neighbor_alltoall): ``data[i]``
        goes to ``neighbors()[i]``; returns what each neighbor sent this
        rank, ``None`` for PROC_NULL slots. Slot pairing follows MPI:
        what arrives in the ``axis-`` slot is what the minus-neighbor
        sent through its ``axis+`` slot, and vice versa. All exchanges
        for all axes run concurrently (one Request per direction)."""
        nbrs = self.neighbors()
        if len(data) != len(nbrs):
            raise MpiError(
                f"mpi_tpu: neighbor_alltoall needs {len(nbrs)} payloads "
                f"(2 per axis), got {len(data)}")
        if len(self._dims) > 15:
            raise MpiError(
                "mpi_tpu: neighborhood collectives support at most 15 "
                "grid axes (tag slot budget)")
        if not getattr(self._impl, "SUPPORTS_COMM_CROSS_HOST_P2P", True):
            # The hybrid driver cannot carry communicator p2p between
            # hosts (the composed cross-host tag has no room for a
            # context), so pairwise halo sendrecv would deadlock on any
            # host-spanning grid. Its group allgather IS hierarchical
            # (compiled local + one TCP leg), so exchange everything
            # and pick this rank's slots: slot i receives what neighbor
            # i addressed to its OPPOSITE slot.
            all_sends = self.allgather(list(data))
            out: List[Optional[Any]] = []
            for ax in range(len(self._dims)):
                src, dst = self.shift(ax, 1)
                lo_idx, hi_idx = ax * 2, ax * 2 + 1
                out.append(None if src is None
                           else all_sends[src][hi_idx])
                out.append(None if dst is None
                           else all_sends[dst][lo_idx])
            return out
        reqs: List[Request] = []
        for ax in range(len(self._dims)):
            src, dst = self.shift(ax, 1)
            lo_idx, hi_idx = ax * 2, ax * 2 + 1
            # Slot i is received FROM neighbor i and data[i] is sent TO
            # neighbor i. Payloads moving in the + direction (my hi-slot
            # payload to dst) arrive as the receiver's lo slot, so each
            # exchange pairs (send data[hi] to dst, receive lo from src)
            # and vice versa; distinct tags keep the two directions
            # unmixable when src == dst (a 2-wide periodic axis).
            reqs.append(Request(
                lambda d=data[hi_idx], s=src, t=dst,
                g=self._neighbor_tag(tag, ax * 2):
                self.sendrecv(d, dest=t, source=s, tag=g)))
            reqs.append(Request(
                lambda d=data[lo_idx], s=dst, t=src,
                g=self._neighbor_tag(tag, ax * 2 + 1):
                self.sendrecv(d, dest=t, source=s, tag=g)))
        return [r.wait(timeout=None) for r in reqs]

    def sub(self, keep) -> "CartComm":
        """Slice the grid (MPI_Cart_sub): ranks sharing coordinates on
        the DROPPED axes form one lower-dimensional CartComm each,
        keeping the kept axes' layout and periodicity. Collective."""
        if len(keep) != len(self._dims):
            raise MpiError(
                f"mpi_tpu: keep mask needs {len(self._dims)} entries")
        me = self.coords()
        color = key = 0
        for c, d, k in zip(me, self._dims, keep):
            if k:
                key = key * d + c
            else:
                color = color * d + c
        child = self.split(color=color, key=key)
        assert child is not None
        kept_dims = tuple(d for d, k in zip(self._dims, keep) if k)
        kept_periods = tuple(p for p, k in zip(self._periods, keep) if k)
        return CartComm(child._impl, child._members, child._ctx,
                        kept_dims or (1,), kept_periods or (False,))


def _check_cart_shape(dims: Tuple[int, ...], periods: Tuple[bool, ...],
                      size: int) -> None:
    """Shape validation shared by cart_create and CartComm.__init__ —
    called BEFORE any collective so an invalid shape fails for free
    instead of after a membership allgather that leaks a context."""
    if len(dims) != len(periods):
        raise MpiError("mpi_tpu: dims/periods length mismatch")
    n = 1
    for d in dims:
        if d < 1:
            raise MpiError(f"mpi_tpu: cart dims must be >= 1, got {dims}")
        n *= d
    if n != size:
        raise MpiError(
            f"mpi_tpu: cart dims {dims} cover {n} ranks, communicator "
            f"has {size}")


def cart_create(comm: Comm, dims, periods=None) -> CartComm:
    """A Cartesian communicator over ``comm``'s ranks (MPI_Cart_create
    with ``reorder=false`` — rank order is preserved). Collective:
    every member must call it. ``periods`` is a bool per axis (default
    all False)."""
    dims = tuple(int(d) for d in dims)
    if periods is None:
        periods = (False,) * len(dims)
    periods = tuple(bool(p) for p in periods)
    _check_cart_shape(dims, periods, comm.size())
    base = comm.split(color=0, key=comm.rank())
    assert base is not None
    return CartComm(base._impl, base._members, base._ctx, dims, periods)

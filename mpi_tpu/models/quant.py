"""Weight-only int8 quantization for serving the flagship model.

Decode is HBM-bandwidth-bound: every generated token re-reads every
weight matrix, so halving (vs bf16) or quartering (vs f32) the bytes
per matrix is a direct tokens/s lever on TPU — the standard weight-only
serving recipe. This module quantizes matmul weights to int8 with a
**per-output-channel absmax scale** (symmetric, last-axis channels);
activations stay in the compute dtype, and the dequantize
(``q.astype(dtype) * scale``) fuses into the consuming matmul under
XLA, so the HBM read is int8 while the MXU contraction stays bf16 —
bandwidth win without an activation-quantization accuracy cliff.

Usage::

    qparams = quantize_params(params)            # QTensor leaves
    toks = generate(qparams, prompt, cfg, n)     # same entry points

Every weight consumer in the model calls ``.astype(compute_dtype)`` on
its weight leaf; :class:`QTensor` implements ``astype`` as dequantize,
so the float and quantized paths share one forward with no call-site
changes (plus two gather/logits fast paths below that keep the
embedding int8 through the memory-heavy ops). Quantized training is
deliberately unsupported (QTensor carries no gradient story); quantize
at serving time. No reference analogue (btracey/mpi has no models).

What gets quantized: floating-point leaves with ndim >= 2 — the qkv/o
projections, FFN and MoE expert weights, and the embedding (which also
serves as the logits matrix; its dequantize folds into the gather /
the pre-logits activation). Layernorm scales/biases (1-D) and the
positional table (additive, precision-sensitive, tiny) stay in their
original dtype.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["QTensor", "quantize_params", "quantize", "dequantize",
           "embed_lookup", "logits_matmul"]


class QTensor(NamedTuple):
    """int8 values + per-last-axis-channel float32 scale. Registered as
    a pytree via NamedTuple, so it flows through jit/scan/device_put."""

    q: jax.Array       # int8, original shape
    scale: jax.Array   # float32, shape (..., 1 broadcast) = per channel

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def deq(self, dtype) -> jax.Array:
        """Dequantize to ``dtype``; fuses into the consumer under XLA."""
        return (self.q.astype(dtype) * self.scale.astype(dtype))

    def astype(self, dtype) -> jax.Array:
        # Weight consumers call .astype(compute_dtype); behaving like
        # the dequantized array keeps call sites uniform.
        return self.deq(dtype)


def quantize(w: jax.Array) -> QTensor:
    """Symmetric per-channel (last axis) absmax int8 quantization."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=tuple(
        range(w.ndim - 1)), keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale.astype(jnp.float32))


def dequantize(t: QTensor, dtype=jnp.float32) -> jax.Array:
    return t.deq(dtype)


def embed_lookup(emb: Any, tokens: jax.Array, dtype) -> jax.Array:
    """Token-embedding gather that stays int8 until after the gather:
    indexing the int8 table then scaling the (b, s, d) result reads
    only the needed rows from HBM, instead of dequantizing the whole
    (vocab, d) table per step. Plain arrays pass through."""
    if isinstance(emb, QTensor):
        return emb.q[tokens].astype(dtype) * \
            emb.scale.reshape(-1).astype(dtype)
    return emb.astype(dtype)[tokens]


def logits_matmul(x: jax.Array, emb: Any) -> jax.Array:
    """Tied-embedding logits projection ``x @ emb.T`` with the
    per-channel scale folded into the activations — the (vocab, d)
    operand streams from HBM as int8. Plain arrays pass through."""
    if isinstance(emb, QTensor):
        scaled = x * emb.scale.reshape(-1).astype(x.dtype)
        return jnp.einsum("bsd,vd->bsv", scaled, emb.q.astype(x.dtype))
    return jnp.einsum("bsd,vd->bsv", x, emb.astype(x.dtype))


def _should_quantize(path: str, leaf: Any) -> bool:
    arr = jnp.asarray(leaf)
    if arr.ndim < 2 or not jnp.issubdtype(arr.dtype, jnp.floating):
        return False
    # Additive positional table: tiny, precision-sensitive — skip.
    return "pos" != path.split("/")[-1]


def quantize_params(params: Any) -> Any:
    """Return ``params`` with every matmul weight replaced by a
    :class:`QTensor` (see module doc for the selection rule)."""
    def walk(node: Any, path: str) -> Any:
        if isinstance(node, dict):
            return {k: walk(v, f"{path}/{k}") for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            out = [walk(v, f"{path}/{i}") for i, v in enumerate(node)]
            return type(node)(out) if isinstance(node, tuple) else out
        if _should_quantize(path, node):
            return quantize(jnp.asarray(node))
        return node

    return walk(params, "")

"""Decoder-only Transformer LM, sharded tpu-first over a device mesh.

Design (the "pick a mesh, annotate shardings, let XLA insert collectives"
recipe):

  * parameters are a plain pytree; every leaf carries a
    :class:`jax.sharding.PartitionSpec` from :func:`param_specs` —
    tensor-parallel (``tp``) sharding on attention heads and the FFN hidden
    dimension (Megatron-style column/row split, so the only tp collective
    is one psum per block, inserted by GSPMD);
  * the batch axis is data-parallel (``dp``), the sequence axis is
    sequence-parallel (``sp``) — activations are constrained to
    ``P('dp', 'sp', None)`` between blocks so layernorm/FFN/elementwise
    work runs fully sharded and only attention gathers the sequence;
  * compute in bfloat16 on TPU (params kept float32), matmuls shaped to
    land on the MXU (head_dim / d_ff multiples of 128 at real sizes);
  * no data-dependent Python control flow — the whole step is one
    ``jit``-compiled program.

The reference contains no models (SURVEY.md §2); this module is the
framework's flagship workload, exercising the collectives the way the
reference's ``bounce`` example exercises Send/Receive
(/root/reference/examples/bounce/bounce.go:37-153).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "TransformerConfig",
    "init_params",
    "forward",
    "forward_with_aux",
    "param_specs",
    "sanitize_spec",
    "apply_rope",
    "make_optimizer",
    "make_train_parts",
    "make_train_step",
    "make_mesh_nd",
]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_seq: int = 128
    dtype: Any = jnp.float32          # compute dtype (bfloat16 on TPU)
    param_dtype: Any = jnp.float32    # master params
    # "dense" | "flash" (Pallas kernel, mpi_tpu.ops) | "blockwise"
    # (checkpointed scan) | "ring" (kv ring over the sp axis,
    # parallel.ring_attention) | "ring_flash" (same ring, Pallas flash
    # kernel per chunk with the FA-2 Pallas backward) | "zigzag" (ring
    # with the work-balanced zigzag causal layout) | "zigzag_flash"
    # (zigzag layout + flash chunks) | "ulysses" (all-to-all head/seq
    # reshard, parallel.ulysses) | "ulysses_flash" (same, Pallas kernel
    # per head group). The ring/zigzag/ulysses family needs a mesh
    # with 'sp'.
    attention_impl: str = "dense"
    # Decode-time (KV-cache) attention: "dense" (jnp einsum chain, the
    # oracle) | "flash" (Pallas flash-decode kernel — one VMEM pass
    # over the cache per step, ops/decode_attention.py). Applies to
    # single-token decode steps only; prefill always uses the dense
    # cached path.
    decode_attention: str = "dense"
    # Mixture-of-Experts FFN (0 = dense). Experts shard over the 'ep'
    # mesh axis (mpi_tpu.models.moe); aux load-balance loss is added to
    # the training objective with coefficient moe_aux_coef. moe_top_k
    # selects routing (1 = Switch, 2 = GShard top-2).
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    moe_top_k: int = 1
    # Rematerialise each block in the backward pass (jax.checkpoint):
    # activations per block are recomputed instead of stored, trading
    # ~1/3 more FLOPs for O(n_layers) less residual memory — the switch
    # that lets long sequences train on one chip's HBM.
    remat: bool = False
    # Grouped-query attention: number of k/v heads (None = n_heads,
    # plain MHA; 1 = MQA). Queries keep n_heads; k/v project to
    # n_kv_heads, shrinking k/v projection weights and the KV cache by
    # n_heads/n_kv_heads. The flash kernel and the decode path read
    # grouped heads natively; other impls repeat k/v before the kernel
    # (repeat_kv_heads). Must divide n_heads (and the tp axis size when
    # tensor-parallel).
    n_kv_heads: Optional[int] = None
    # Rotary position embeddings instead of the learned absolute table:
    # q/k are phase-rotated by their global positions before attention
    # (and before any sequence sharding, so ring/zigzag layouts carry
    # the already-encoded values). head_dim must be even.
    rope: bool = False
    rope_theta: float = 10000.0
    # Causal (autoregressive) masking. False gives a bidirectional
    # encoder stack (ViT, BERT-style) through the same blocks — the
    # dense/flash/blockwise kernels, the contiguous ring, and ulysses
    # all take it directly; only the ZIGZAG layouts are causal-only
    # (the work-balance trick assumes the triangular mask) and raise
    # at the ring layer.
    causal: bool = True

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_heads if self.n_kv_heads is None else self.n_kv_heads
        if not 1 <= kv <= self.n_heads or self.n_heads % kv:
            raise ValueError(
                f"mpi_tpu: n_kv_heads={kv} must divide n_heads="
                f"{self.n_heads}")
        return kv


# --------------------------------------------------------------------------
# Parameters
# --------------------------------------------------------------------------

def _dense_init(key, shape, dtype, fan_in):
    return (jax.random.normal(key, shape) / math.sqrt(fan_in)).astype(dtype)


def init_params(key: jax.Array, cfg: TransformerConfig) -> Dict[str, Any]:
    """Initialise the parameter pytree (plain dicts — easy to shard,
    checkpoint, and inspect)."""
    pd = cfg.param_dtype
    keys = jax.random.split(key, 2 + cfg.n_layers)
    params: Dict[str, Any] = {
        "embed": _dense_init(keys[0], (cfg.vocab, cfg.d_model), pd,
                             cfg.d_model),
        "final_ln": {"scale": jnp.ones((cfg.d_model,), pd),
                     "bias": jnp.zeros((cfg.d_model,), pd)},
        "blocks": [],
    }
    if not cfg.rope:  # rope needs no learned position table
        params["pos"] = _dense_init(keys[1], (cfg.max_seq, cfg.d_model),
                                    pd, cfg.d_model)
    for i in range(cfg.n_layers):
        ks = jax.random.split(keys[2 + i], 6)
        h, d, f = cfg.n_heads, cfg.d_model, cfg.d_ff
        hd, kv = cfg.head_dim, cfg.kv_heads
        blk = {
            "ln1": {"scale": jnp.ones((d,), pd), "bias": jnp.zeros((d,), pd)},
            "ln2": {"scale": jnp.ones((d,), pd), "bias": jnp.zeros((d,), pd)},
            "wq": _dense_init(ks[0], (d, h, hd), pd, d),
            "wk": _dense_init(ks[1], (d, kv, hd), pd, d),
            "wv": _dense_init(ks[2], (d, kv, hd), pd, d),
            "wo": _dense_init(ks[3], (h, hd, d), pd, d),
        }
        if cfg.n_experts > 0:
            from .moe import init_moe_params

            blk["moe"] = init_moe_params(ks[4], d, f, cfg.n_experts, pd)
        else:
            blk["w1"] = _dense_init(ks[4], (d, f), pd, d)
            blk["w2"] = _dense_init(ks[5], (f, d), pd, f)
        params["blocks"].append(blk)
    return params


def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs mirroring :func:`init_params`'s tree.

    Megatron-style tp split: q/k/v column-parallel over heads, wo
    row-parallel; w1 column-, w2 row-parallel over d_ff. Everything small
    (layernorms, biases, positional table) is replicated. The embedding is
    vocab-sharded over tp (the logits matmul then reduces over tp)."""
    blk = {
        "ln1": {"scale": P(), "bias": P()},
        "ln2": {"scale": P(), "bias": P()},
        "wq": P(None, "tp", None),
        "wk": P(None, "tp", None),
        "wv": P(None, "tp", None),
        "wo": P("tp", None, None),
    }
    if cfg.n_experts > 0:
        from .moe import moe_specs

        blk["moe"] = moe_specs()
    else:
        blk["w1"] = P(None, "tp")
        blk["w2"] = P("tp", None)
    specs = {
        "embed": P("tp", None),
        "final_ln": {"scale": P(), "bias": P()},
        "blocks": [dict(blk) for _ in range(cfg.n_layers)],
    }
    if not cfg.rope:
        specs["pos"] = P()
    return specs


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------

def _layernorm(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * scale + bias


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding: rotate each half-dim pair of ``x``
    ``(b, s, h, hd)`` by its position's phase. ``positions`` is ``(s,)``
    int32 global positions (works for shifted windows — decode passes
    ``n_valid + arange``). Phases are computed in float32 and the result
    cast back to x's dtype."""
    hd = x.shape[-1]
    if hd % 2:
        raise ValueError(f"mpi_tpu: rope needs an even head_dim, got {hd}")
    half = hd // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs  # (s, half)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def repeat_kv_heads(k, v, cfg: TransformerConfig):
    """Expand GQA k/v ``(b, s, kv_heads, hd)`` to full ``n_heads`` for
    kernels that expect equal q/k head counts — every impl EXCEPT
    ``flash``, whose Pallas kernels read grouped heads natively through
    their index maps, and the decode path (generate._attend_cached),
    whose contraction stays grouped. Here the repeat MATERIALISES the
    group-times-larger k/v, so for these impls GQA saves projection
    weights but not attention activation memory."""
    group = cfg.n_heads // cfg.kv_heads
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    return k, v


def _attention(x, blk, cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """Causal multi-head attention; heads are the tp-sharded axis, so every
    einsum below is head-batched and GSPMD keeps it local to each tp shard
    until ``wo`` reduces back to d_model. The score/value kernel is
    selected by ``cfg.attention_impl`` (see :mod:`mpi_tpu.ops`)."""
    b, s, _ = x.shape
    q = jnp.einsum("bsd,dhk->bshk", x, blk["wq"].astype(x.dtype))
    k = jnp.einsum("bsd,dhk->bshk", x, blk["wk"].astype(x.dtype))
    v = jnp.einsum("bsd,dhk->bshk", x, blk["wv"].astype(x.dtype))
    if cfg.rope:
        # Global positions, applied BEFORE any sequence sharding — the
        # ring/zigzag layouts then carry already-rotated values.
        pos = jnp.arange(s, dtype=jnp.int32)
        q = apply_rope(q, pos, cfg.rope_theta)
        k = apply_rope(k, pos, cfg.rope_theta)
    impl = cfg.attention_impl
    if impl != "flash":
        # The flash kernel reads grouped kv heads natively through its
        # index maps; every other impl expects equal head counts.
        k, v = repeat_kv_heads(k, v, cfg)
    if impl == "flash":
        from ..ops import flash_attention

        ctx = flash_attention(q, k, v, cfg.causal)
    elif impl == "blockwise":
        from ..ops import blockwise_attention

        ctx = blockwise_attention(q, k, v, causal=cfg.causal)
    elif impl in ("ring", "zigzag", "ring_flash", "zigzag_flash"):
        from ..parallel.ring_attention import ring_attention_sharded

        if mesh is None:
            raise ValueError(
                f"attention_impl={impl!r} needs a mesh with an 'sp' axis")
        layout = "zigzag" if impl.startswith("zigzag") else "contiguous"
        chunk = "flash" if impl.endswith("_flash") else "fold"
        # causal=False works on the contiguous ring; the zigzag layout
        # is causal-only and ring_attention_sharded raises for it at
        # its own layer (the balance trick assumes the triangle).
        ctx = ring_attention_sharded(q, k, v, mesh, axis_name="sp",
                                     causal=cfg.causal, layout=layout,
                                     chunk_impl=chunk)
    elif impl in ("ulysses", "ulysses_flash"):
        from ..parallel.ulysses import ulysses_attention_sharded

        if mesh is None:
            raise ValueError(
                f"attention_impl={impl!r} needs a mesh with an 'sp' axis")
        kernel = "flash" if impl.endswith("_flash") else "blockwise"
        ctx = ulysses_attention_sharded(q, k, v, mesh, axis_name="sp",
                                        causal=cfg.causal,
                                        kernel_impl=kernel)
    elif impl == "dense":
        from ..ops import dense_attention

        ctx = dense_attention(q, k, v, causal=cfg.causal)
    else:
        raise ValueError(
            f"unknown attention_impl {impl!r}: expected dense|flash|"
            f"blockwise|ring|ring_flash|zigzag|zigzag_flash|ulysses|"
            f"ulysses_flash")
    return jnp.einsum("bshk,hkd->bsd", ctx, blk["wo"].astype(x.dtype))


def sanitize_spec(spec: P, mesh: Optional[Mesh]) -> P:
    """Drop axis names the mesh doesn't have (→ replicated) so one set of
    canonical specs works on any mesh shape (e.g. a dp x ep MoE mesh has
    no 'tp'; a pure-tp mesh has no 'sp')."""
    if mesh is None:
        return spec
    names = set(mesh.axis_names)

    def keep(p):
        if p is None:
            return None
        if isinstance(p, tuple):
            kept = tuple(q for q in p if q in names)
            return kept if kept else None
        return p if p in names else None

    return P(*(keep(p) for p in spec))


def _act_constraint(x, mesh: Optional[Mesh]):
    """Keep activations dp-sharded on batch and sp-sharded on sequence
    between blocks; a no-op when tracing without a mesh (single chip)."""
    if mesh is None:
        return x
    return lax.with_sharding_constraint(
        x, NamedSharding(mesh, sanitize_spec(P("dp", "sp", None), mesh)))


def _ffn(x, blk, cfg: TransformerConfig, mesh: Optional[Mesh]):
    """Position-wise FFN: Megatron-split dense (default) or top-1 routed
    MoE over the 'ep' axis. Returns (y, aux_loss)."""
    if cfg.n_experts > 0:
        from .moe import moe_ffn

        return moe_ffn(x, blk["moe"], cfg.n_experts,
                       capacity_factor=cfg.capacity_factor, mesh=mesh,
                       top_k=cfg.moe_top_k)
    h = jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, blk["w1"].astype(x.dtype)))
    y = jnp.einsum("bsf,fd->bsd", h, blk["w2"].astype(x.dtype))
    return y, jnp.zeros((), jnp.float32)


def block_body(x, blk, cfg: TransformerConfig,
               mesh: Optional[Mesh] = None):
    """ONE transformer block (pre-norm attention + FFN residuals) —
    the single definition shared by the sequential stack
    (:func:`forward_with_aux`) and the pipelined stages
    (:mod:`mpi_tpu.models.pipeline_lm`), so the two paths cannot
    drift. Returns ``(x, aux_loss)``."""
    h = _layernorm(x, blk["ln1"]["scale"].astype(x.dtype),
                   blk["ln1"]["bias"].astype(x.dtype))
    x = x + _attention(h, blk, cfg, mesh)
    x = _act_constraint(x, mesh)
    h = _layernorm(x, blk["ln2"]["scale"].astype(x.dtype),
                   blk["ln2"]["bias"].astype(x.dtype))
    y, blk_aux = _ffn(h, blk, cfg, mesh)
    x = x + y
    return _act_constraint(x, mesh), blk_aux


def token_xent(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy as ``logsumexp - target_logit`` —
    the fused form that never materialises the (b, s, vocab) float32
    log-softmax. Shared by the sequential and pipelined losses."""
    logits32 = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits32, axis=-1)
    tgt = jnp.take_along_axis(logits32, targets[..., None],
                              axis=-1)[..., 0]
    return jnp.mean(lse - tgt)


def forward_with_aux(params: Dict[str, Any], tokens: jax.Array,
                     cfg: TransformerConfig,
                     mesh: Optional[Mesh] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """tokens (batch, seq) int32 → (logits (batch, seq, vocab), aux_loss).
    ``aux_loss`` is the summed MoE load-balance penalty (0 for dense)."""
    _, s = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    if not cfg.rope:
        x = x + params["pos"].astype(cfg.dtype)[:s][None]
    x = _act_constraint(x, mesh)
    aux = jnp.zeros((), jnp.float32)

    block = functools.partial(block_body, cfg=cfg, mesh=mesh)
    if cfg.remat:
        block = jax.checkpoint(block)
    for blk in params["blocks"]:
        x, blk_aux = block(x, blk)
        aux = aux + blk_aux
    x = _layernorm(x, params["final_ln"]["scale"].astype(x.dtype),
                   params["final_ln"]["bias"].astype(x.dtype))
    return jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype)), aux


def forward(params: Dict[str, Any], tokens: jax.Array,
            cfg: TransformerConfig, mesh: Optional[Mesh] = None) -> jax.Array:
    """tokens (batch, seq) int32 → logits (batch, seq, vocab)."""
    return forward_with_aux(params, tokens, cfg, mesh)[0]


def loss_fn(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None) -> jax.Array:
    """Next-token cross-entropy (mean over all predicted positions), plus
    the MoE load-balance penalty when experts are enabled.

    Written as ``logsumexp - target_logit`` rather than gathering from a
    materialised ``log_softmax``: the full (batch, seq, vocab) float32
    log-prob tensor never exists, saving its HBM round-trips at large
    vocab (the backward of logsumexp produces the softmax directly)."""
    logits, aux = forward_with_aux(params, tokens[:, :-1], cfg, mesh)
    return token_xent(logits, tokens[:, 1:]) + cfg.moe_aux_coef * aux


# --------------------------------------------------------------------------
# Training step
# --------------------------------------------------------------------------

def make_optimizer(optimizer: str = "adamw", learning_rate: float = 1e-3,
                   warmup_steps: int = 0, total_steps: Optional[int] = None):
    """An optax optimizer by name with an optional schedule.

    ``optimizer``: ``"adamw"`` (default), ``"adafactor"`` (factored
    second moment — the TPU-classic choice when optimizer state must not
    double the parameter memory), or ``"sgd"`` (momentum 0.9).

    Schedule: with ``total_steps``, linear warmup over ``warmup_steps``
    into cosine decay to 10% of peak at ``total_steps``; with only
    ``warmup_steps``, linear warmup then constant; otherwise constant
    ``learning_rate``."""
    import optax

    if total_steps is not None:
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=learning_rate,
            warmup_steps=max(warmup_steps, 1), decay_steps=total_steps,
            end_value=0.1 * learning_rate)
    elif warmup_steps:
        lr = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    else:
        lr = learning_rate
    if optimizer == "adamw":
        return optax.adamw(lr)
    if optimizer == "adafactor":
        return optax.adafactor(learning_rate=lr)
    if optimizer == "sgd":
        return optax.sgd(lr, momentum=0.9)
    raise ValueError(
        f"mpi_tpu: unknown optimizer {optimizer!r}: expected "
        f"adamw|adafactor|sgd")


def sane_param_specs(cfg: TransformerConfig, params: Any,
                     mesh: Optional[Mesh]):
    """:func:`param_specs` restructured to ``params``'s tree with every
    spec sanitized against ``mesh`` (axes the mesh lacks drop out)."""
    specs = param_specs(cfg)
    return jax.tree.unflatten(
        jax.tree.structure(params),
        [sanitize_spec(s, mesh) for s in jax.tree.leaves(
            specs, is_leaf=lambda s: isinstance(s, P))])


def init_sharded_params(key: jax.Array, cfg: TransformerConfig,
                        mesh: Mesh) -> Dict[str, Any]:
    """Fresh parameters committed to their mesh shardings — params
    only, no optimizer state (callers that need just a base model, e.g.
    LoRA fine-tuning, avoid allocating and discarding AdamW moments)."""
    params = init_params(key, cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, sane_param_specs(cfg, params, mesh))


def make_train_parts(cfg: TransformerConfig, mesh: Optional[Mesh] = None,
                     learning_rate: float = 1e-3, grad_accum: int = 1,
                     optimizer: str = "adamw", warmup_steps: int = 0,
                     total_steps: Optional[int] = None,
                     zero1: bool = False, fsdp: bool = False):
    """Build (init_state, step_body) with ``step_body`` left un-jitted —
    for callers that embed the step in a larger program (the bench
    harness scans it; :func:`make_train_step` jits it as-is). Both
    callers therefore run the *same* optimizer step by construction.

    ``grad_accum=k`` splits the batch into ``k`` microbatches scanned
    inside the step: gradients average across microbatches before ONE
    optimizer update, so a batch k× larger than fits in HBM trains with
    the full-batch math up to float reduction order (with MoE, the
    load-balance aux loss is additionally computed per microbatch and
    averaged). The batch must divide by ``k``.

    ``optimizer``/``warmup_steps``/``total_steps`` select the update
    rule and schedule — see :func:`make_optimizer`.

    ``zero1=True`` (requires a mesh with a ``dp`` axis) shards the
    optimizer state over ``dp`` (:mod:`mpi_tpu.parallel.zero`): GSPMD
    then turns the dp gradient psum into a reduce-scatter, updates
    each device's 1/dp state shard, and all-gathers the fresh params —
    AdamW state memory drops ~dp-fold with the same step math up to
    float reduction order.

    ``fsdp=True`` (ZeRO-3: requires a mesh with a ``dp`` axis) shards
    the PARAMETERS themselves over ``dp`` on top of any tp layout
    (:func:`mpi_tpu.parallel.zero.fsdp_specs`) — parameter AND
    optimizer memory drop ~dp-fold; GSPMD inserts just-in-time weight
    all-gathers per layer (re-run in the backward under ``cfg.remat``)
    and reduce-scatters the gradients straight into the shard. Same
    step math as plain dp up to float reduction order. Subsumes
    ``zero1`` (the optimizer state follows the sharded parameters);
    combining both flags is an error."""
    import optax

    if grad_accum < 1:
        raise ValueError(f"mpi_tpu: grad_accum must be >= 1, got "
                         f"{grad_accum}")
    if zero1 and (mesh is None or "dp" not in mesh.axis_names):
        raise ValueError(
            "mpi_tpu: zero1=True needs a mesh with a 'dp' axis")
    if fsdp and (mesh is None or "dp" not in mesh.axis_names):
        raise ValueError(
            "mpi_tpu: fsdp=True needs a mesh with a 'dp' axis")
    if fsdp and zero1:
        raise ValueError(
            "mpi_tpu: fsdp subsumes zero1 (optimizer state follows the "
            "dp-sharded parameters); pass only fsdp=True")
    if mesh is not None and "tp" in mesh.axis_names:
        tp = mesh.shape["tp"]
        if cfg.n_heads % tp or cfg.kv_heads % tp:
            raise ValueError(
                f"mpi_tpu: tp={tp} must divide n_heads={cfg.n_heads} and "
                f"kv_heads={cfg.kv_heads} (GQA shards kv heads over tp "
                f"too)")
    opt = make_optimizer(optimizer, learning_rate, warmup_steps,
                         total_steps)

    def _sane_param_specs(params):
        return sane_param_specs(cfg, params, mesh)

    def _fsdp_specs(params):
        from ..parallel.zero import fsdp_specs

        return fsdp_specs(params, _sane_param_specs(params), mesh)

    def init_state(key: jax.Array):
        if mesh is not None:
            params = init_sharded_params(key, cfg, mesh)
            if fsdp:
                from ..parallel.zero import (shard_opt_state,
                                             zero1_specs)

                fspecs = _fsdp_specs(params)
                params = jax.tree.map(
                    lambda x, s: jax.device_put(
                        x, NamedSharding(mesh, s)), params, fspecs)
                opt_state = jax.jit(opt.init)(params)
                # State leaves match param shapes, and _leaf_spec is a
                # no-op when dp is already claimed — so this commits
                # the moments to the SAME fully-sharded layouts.
                zspecs = zero1_specs(params, fspecs, opt_state, mesh)
                opt_state = shard_opt_state(opt_state, zspecs, mesh)
                return {"params": params, "opt": opt_state}
            opt_state = jax.jit(opt.init)(params)
            if zero1:
                from ..parallel.zero import shard_opt_state, zero1_specs

                zspecs = zero1_specs(params, _sane_param_specs(params),
                                     opt_state, mesh)
                opt_state = shard_opt_state(opt_state, zspecs, mesh)
        else:
            params = init_params(key, cfg)
            opt_state = opt.init(params)
        return {"params": params, "opt": opt_state}

    def accumulate(params, tokens):
        """(mean loss, mean grads) over grad_accum microbatches."""
        if grad_accum == 1:
            return jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)
        b = tokens.shape[0]
        if b % grad_accum:
            raise ValueError(
                f"mpi_tpu: batch {b} not divisible by grad_accum="
                f"{grad_accum}")
        micro = tokens.reshape(grad_accum, b // grad_accum,
                               *tokens.shape[1:])

        def body(carry, mtok):
            loss_sum, gsum = carry
            l, g = jax.value_and_grad(loss_fn)(params, mtok, cfg, mesh)
            return (loss_sum + l, jax.tree.map(jnp.add, gsum, g)), None

        zero = (jnp.zeros((), jnp.float32),
                jax.tree.map(jnp.zeros_like, params))
        (loss_sum, gsum), _ = lax.scan(body, zero, micro)
        inv = 1.0 / grad_accum
        return loss_sum * inv, jax.tree.map(lambda g: g * inv, gsum)

    def step(state, tokens):
        if fsdp:
            from ..parallel.zero import (constrain_opt_state,
                                         constrain_params, zero1_specs)

            # Pin weights/grads/state to the fully-sharded layouts at
            # the step boundary so GSPMD keeps the JIT-gather +
            # grad-reduce-scatter plan instead of replicating between
            # steps (specs derive from the state itself, so restored
            # checkpoints behave identically).
            fspecs = _fsdp_specs(state["params"])
            params0 = constrain_params(state["params"], fspecs, mesh)
            loss, grads = accumulate(params0, tokens)
            grads = constrain_params(grads, fspecs, mesh)
            updates, new_opt = opt.update(grads, state["opt"], params0)
            new_params = constrain_params(
                optax.apply_updates(params0, updates), fspecs, mesh)
            zspecs = zero1_specs(state["params"], fspecs, new_opt, mesh)
            new_opt = constrain_opt_state(new_opt, zspecs, mesh)
            return {"params": new_params, "opt": new_opt}, loss
        loss, grads = accumulate(state["params"], tokens)
        updates, new_opt = opt.update(grads, state["opt"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        if zero1:
            from ..parallel.zero import constrain_opt_state, zero1_specs

            # Specs are derived at trace time from the state itself, so
            # the constraint holds even for states that bypassed
            # init_state (checkpoint restores); pinning the updated
            # state to the dp-sharded layouts keeps GSPMD on the
            # reduce-scatter/all-gather plan instead of replicating
            # state between steps.
            zspecs = zero1_specs(state["params"],
                                 _sane_param_specs(state["params"]),
                                 new_opt, mesh)
            new_opt = constrain_opt_state(new_opt, zspecs, mesh)
        return {"params": new_params, "opt": new_opt}, loss

    return init_state, step


def make_train_step(cfg: TransformerConfig, mesh: Optional[Mesh] = None,
                    learning_rate: float = 1e-3, grad_accum: int = 1,
                    optimizer: str = "adamw", warmup_steps: int = 0,
                    total_steps: Optional[int] = None,
                    zero1: bool = False, fsdp: bool = False):
    """Build (init_state, step). ``step(state, tokens) -> (state, loss)``
    is one fully jitted optimizer step; with a mesh, params/opt-state are
    committed to :func:`param_specs` shardings and the batch to
    ``P('dp', 'sp')`` so GSPMD inserts the dp grad-psum and tp
    reductions. See :func:`make_train_parts` for ``grad_accum`` and the
    optimizer/schedule options."""
    init_state, step = make_train_parts(cfg, mesh=mesh,
                                        learning_rate=learning_rate,
                                        grad_accum=grad_accum,
                                        optimizer=optimizer,
                                        warmup_steps=warmup_steps,
                                        total_steps=total_steps,
                                        zero1=zero1, fsdp=fsdp)
    # Donate the incoming state: params + optimizer state alias their
    # output buffers, halving peak HBM for the largest tensors in the
    # step (the standard TPU training setup; callers rebind
    # ``state = step(state, ...)[0]`` so the consumed input is never
    # reused). XLA ignores donation where unsupported (CPU) with a
    # warning, so tests on the virtual mesh are unaffected.
    return init_state, jax.jit(step, donate_argnums=(0,))


def make_mesh_nd(n_devices: int,
                 axes: Tuple[str, ...] = ("dp", "sp", "tp"),
                 devices=None) -> Mesh:
    """Factor ``n_devices`` into a mesh over ``axes``: smallest prime
    factors are peeled off and dealt round-robin starting at the leftmost
    axis, e.g. 8 → (2, 2, 2), 4 → (2, 2, 1), 6 → (2, 3, 1), 12 → (2, 2, 3),
    1 → (1, 1, 1)."""
    if devices is None:
        devices = jax.devices()[:n_devices]
    dims = [1] * len(axes)
    rem = n_devices
    i = 0
    while rem > 1:
        # peel the smallest prime factor
        f = next((p for p in range(2, rem + 1) if rem % p == 0), rem)
        dims[i % len(axes)] *= f
        rem //= f
        i += 1
    return Mesh(np.asarray(devices).reshape(tuple(dims)), axes)

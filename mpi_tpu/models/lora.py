"""LoRA — low-rank adaptation fine-tuning for the flagship model.

Full fine-tuning updates (and stores optimizer moments for) every
parameter; LoRA freezes the base weights and learns a rank-``r`` delta
``ΔW = (alpha / r) · A @ B`` per targeted projection, shrinking
trainable state by orders of magnitude — the standard
parameter-efficient recipe, and a natural fit for TPU training: the
base params stay committed to their tp shardings untouched while the
tiny adapters replicate.

Design (tpu-first, no module system needed): adapters are just a
pytree next to the frozen params, and one jitted train step computes
``merged = base + ΔW`` *inside* the step — two small matmuls per
target that XLA fuses into the existing forward — then differentiates
the loss **with respect to the adapters only**: the base enters the
loss as a closure, and ``jax.value_and_grad`` differentiates argument
0 alone, which IS the freeze (the ``stop_gradient`` wrap is
belt-and-braces, not the mechanism). No optimizer masking machinery is
required: the optimizer state simply IS the adapter tree. For serving,
:func:`merge_lora` folds the deltas into a plain parameter tree once,
making inference cost identical to the unadapted model (quantization
and speculative decoding compose on top).

Targets default to the attention q/v projections (the classic LoRA
choice); any of ``wq``/``wk``/``wv``/``wo``/``w1``/``w2`` may be
named. Projection weights here are (d, h, hd) / (h, hd, d) / (d, ff) /
(ff, d) shaped; each is treated as a matrix by flattening all
non-first axes into the B factor. No reference analogue (btracey/mpi
has no models).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from .transformer import TransformerConfig

__all__ = ["lora_init", "lora_delta", "merge_lora",
           "make_lora_train_parts", "make_lora_train_step",
           "count_params"]

_TARGETS = ("wq", "wk", "wv", "wo", "w1", "w2")


def _check_targets(targets: Sequence[str]) -> Tuple[str, ...]:
    bad = [t for t in targets if t not in _TARGETS]
    if bad:
        raise ValueError(
            f"mpi_tpu: unknown LoRA targets {bad}; choose from "
            f"{_TARGETS}")
    if not targets:
        raise ValueError("mpi_tpu: LoRA needs at least one target")
    return tuple(targets)


def lora_init(key: jax.Array, params: Any, rank: int,
              targets: Sequence[str] = ("wq", "wv"),
              dtype: Any = jnp.float32) -> Dict[str, Any]:
    """Zero-initialised adapters for every targeted projection in every
    block. A is gaussian (fan-in scaled), B is zeros — so the adapted
    model starts EXACTLY at the base model (ΔW = 0), the standard LoRA
    init that makes step 0 a no-op."""
    targets = _check_targets(targets)
    if rank < 1:
        raise ValueError(f"mpi_tpu: LoRA rank must be >= 1, got {rank}")
    blocks = []
    keys = jax.random.split(key, len(params["blocks"]) * len(targets))
    ki = 0
    for blk in params["blocks"]:
        entry: Dict[str, Dict[str, jax.Array]] = {}
        for t in targets:
            if t not in blk:
                continue  # e.g. w1/w2 absent in MoE blocks
            w = blk[t]
            d_in = w.shape[0]
            d_out = int(math.prod(w.shape[1:]))
            a = (jax.random.normal(keys[ki], (d_in, rank), dtype)
                 / math.sqrt(d_in))
            entry[t] = {"a": a, "b": jnp.zeros((rank, d_out), dtype)}
            ki += 1
        blocks.append(entry)
    return {"blocks": blocks, "rank": rank}


def lora_delta(w: jax.Array, ab: Dict[str, jax.Array],
               alpha: float, rank: int) -> jax.Array:
    """ΔW reshaped to ``w``'s layout, scaled by alpha / rank."""
    delta = (ab["a"] @ ab["b"]) * (alpha / rank)
    return delta.reshape(w.shape).astype(w.dtype)


def merge_lora(params: Any, lora: Dict[str, Any],
               alpha: float = 16.0) -> Any:
    """Base params with every adapter folded in (``W + ΔW``) — the
    serving-time merge; the returned tree has the exact structure and
    shardings-by-construction of ``params``."""
    rank = lora["rank"]
    merged_blocks = []
    for blk, entry in zip(params["blocks"], lora["blocks"]):
        new = dict(blk)
        for t, ab in entry.items():
            new[t] = blk[t] + lora_delta(blk[t], ab, alpha, rank)
        merged_blocks.append(new)
    out = dict(params)
    out["blocks"] = merged_blocks
    return out


def count_params(tree: Any) -> int:
    return sum(int(math.prod(x.shape))
               for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "shape"))


def make_lora_train_parts(cfg: TransformerConfig, base_params: Any,
                          rank: int = 8, alpha: float = 16.0,
                          targets: Sequence[str] = ("wq", "wv"),
                          mesh: Any = None, learning_rate: float = 1e-3,
                          optimizer: str = "adamw"):
    """(init_state, step_body): ``step_body(state, tokens)`` is one
    un-jitted adapter-only optimizer step (jit it, or scan it — same
    split as :func:`make_train_parts`). ``state`` holds ONLY the
    adapters and their optimizer state; ``base_params`` is closed over
    and never differentiated (grad is taken wrt the adapter argument
    only), so AdamW moments exist for the adapters alone."""
    from .transformer import loss_fn, make_optimizer

    _check_targets(targets)
    opt = make_optimizer(optimizer, learning_rate)
    frozen = jax.tree.map(jax.lax.stop_gradient, base_params)

    def init_state(key: jax.Array):
        lora = lora_init(key, base_params, rank, targets)
        return {"lora": lora, "opt": opt.init(_trainable(lora))}

    def _trainable(lora):
        return lora["blocks"]

    def lora_loss(blocks, tokens):
        merged = merge_lora(frozen, {"blocks": blocks, "rank": rank},
                            alpha=alpha)
        return loss_fn(merged, tokens, cfg, mesh)

    def step(state, tokens):
        import optax

        blocks = _trainable(state["lora"])
        loss, grads = jax.value_and_grad(lora_loss)(blocks, tokens)
        updates, new_opt = opt.update(grads, state["opt"], blocks)
        new_blocks = optax.apply_updates(blocks, updates)
        return ({"lora": {"blocks": new_blocks, "rank": rank},
                 "opt": new_opt}, loss)

    return init_state, step


def make_lora_train_step(cfg: TransformerConfig, base_params: Any,
                         **kw):
    """Jitted variant of :func:`make_lora_train_parts` (state donated:
    the adapter tree is small, but the habit is free)."""
    init_state, step = make_lora_train_parts(cfg, base_params, **kw)
    return init_state, jax.jit(step, donate_argnums=(0,))

"""Model zoo — the framework's flagship SPMD showcase.

The reference (btracey/mpi) contains no ML code at all (SURVEY.md §2: "no
tensors, no models, no attention anywhere in the repo"), so everything here
is *new* tpu-native work, not parity work: a decoder-only Transformer LM
whose parameters, activations and optimizer states are sharded over a
:class:`jax.sharding.Mesh` with data- (dp), tensor- (tp) and sequence-
(sp) parallel axes, exercising the collective layer
(:mod:`mpi_tpu.parallel`) the way real workloads do.
"""

from .transformer import (
    TransformerConfig,
    init_params,
    init_sharded_params,
    forward,
    forward_with_aux,
    param_specs,
    sane_param_specs,
    sanitize_spec,
    apply_rope,
    make_optimizer,
    make_train_parts,
    make_train_step,
    make_mesh_nd,
)
from .moe import init_moe_params, moe_ffn, moe_specs
from .generate import decode_step, generate, prefill
from .quant import QTensor, dequantize, quantize, quantize_params
from .lora import (lora_init, make_lora_train_parts, make_lora_train_step,
                   merge_lora)
from .vit import (ViTConfig, forward_vit, init_vit_params,
                  make_vit_train_step)
from .speculative import generate_lookahead
from .ssm import (SsmConfig, init_ssm_params, init_ssm_state,
                  make_ssm_train_step, ssm_decode, ssm_forward,
                  ssm_forward_sp, ssm_prefill, ssm_step)
from .pipeline_lm import (
    forward_pipelined,
    init_pipelined_params,
    make_pipelined_train_step,
    stack_block_params,
)

__all__ = [
    "TransformerConfig",
    "SsmConfig",
    "init_ssm_params",
    "init_ssm_state",
    "make_ssm_train_step",
    "ssm_decode",
    "ssm_forward",
    "ssm_forward_sp",
    "ssm_prefill",
    "ssm_step",
    "QTensor",
    "quantize",
    "quantize_params",
    "dequantize",
    "init_params",
    "init_sharded_params",
    "sane_param_specs",
    "forward",
    "forward_with_aux",
    "param_specs",
    "sanitize_spec",
    "apply_rope",
    "make_optimizer",
    "make_train_parts",
    "make_train_step",
    "ViTConfig",
    "forward_vit",
    "init_vit_params",
    "make_vit_train_step",
    "make_mesh_nd",
    "init_moe_params",
    "moe_ffn",
    "moe_specs",
    "prefill",
    "decode_step",
    "generate",
    "generate_lookahead",
    "lora_init",
    "merge_lora",
    "make_lora_train_parts",
    "make_lora_train_step",
    "forward_pipelined",
    "init_pipelined_params",
    "make_pipelined_train_step",
    "stack_block_params",
]

"""Model zoo — the framework's flagship SPMD showcase.

The reference (btracey/mpi) contains no ML code at all (SURVEY.md §2: "no
tensors, no models, no attention anywhere in the repo"), so everything here
is *new* tpu-native work, not parity work: a decoder-only Transformer LM
whose parameters, activations and optimizer states are sharded over a
:class:`jax.sharding.Mesh` with data- (dp), tensor- (tp) and sequence-
(sp) parallel axes, exercising the collective layer
(:mod:`mpi_tpu.parallel`) the way real workloads do.
"""

from .transformer import (
    TransformerConfig,
    init_params,
    forward,
    param_specs,
    make_train_step,
    make_mesh_nd,
)

__all__ = [
    "TransformerConfig",
    "init_params",
    "forward",
    "param_specs",
    "make_train_step",
    "make_mesh_nd",
]

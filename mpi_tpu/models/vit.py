"""Vision Transformer — the encoder (bidirectional) model family.

Same blocks, same shardings, same kernels as the flagship LM
(:func:`mpi_tpu.models.transformer.block_body` with
``TransformerConfig(causal=False)`` — the flash kernel runs its
non-causal grid), with the image-side pieces on top: patchify + linear
projection in, learned position table, mean-pool + linear
classification head out. Proves the framework's model layer is a
family, not a single decoder: dp/tp sharding, bf16 compute, remat,
and the autotuned flash blocks all apply unchanged.

No reference analogue (btracey/mpi has no models; SURVEY.md §2) —
beyond-parity breadth like the MoE/LoRA/quant variants.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .transformer import (TransformerConfig, _act_constraint, _dense_init,
                          _layernorm, block_body, init_params,
                          make_optimizer, param_specs, sanitize_spec,
                          token_xent)

__all__ = ["ViTConfig", "init_vit_params", "forward_vit",
           "make_vit_train_step"]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    n_classes: int = 10
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention_impl: str = "dense"      # dense | flash | blockwise
    remat: bool = False
    n_kv_heads: Optional[int] = None

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(
                f"mpi_tpu: image_size {self.image_size} not divisible "
                f"by patch_size {self.patch_size}")

    @property
    def n_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side

    @property
    def inner(self) -> TransformerConfig:
        """The encoder-stack config the shared blocks run under."""
        return TransformerConfig(
            vocab=1,                       # unused (no token embedding)
            d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff,
            max_seq=self.n_patches, dtype=self.dtype,
            param_dtype=self.param_dtype,
            attention_impl=self.attention_impl, remat=self.remat,
            n_kv_heads=self.n_kv_heads, causal=False)


def init_vit_params(key: jax.Array, cfg: ViTConfig) -> Dict[str, Any]:
    """Parameter pytree: shared encoder blocks + final_ln from the
    transformer init (its token embedding is dropped; its position
    table, sized ``n_patches``, becomes the patch-position table), plus
    the patch projection and the classification head."""
    k_inner, k_patch, k_head = jax.random.split(key, 3)
    params = init_params(k_inner, cfg.inner)
    del params["embed"]                 # images enter via the patch proj
    pd = cfg.param_dtype
    pdim = cfg.patch_size * cfg.patch_size * cfg.channels
    params["patch"] = _dense_init(k_patch, (pdim, cfg.d_model), pd, pdim)
    params["head"] = {
        "w": _dense_init(k_head, (cfg.d_model, cfg.n_classes), pd,
                         cfg.d_model),
        "b": jnp.zeros((cfg.n_classes,), pd),
    }
    return params


def _patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """(b, H, W, C) -> (b, n_patches, p*p*C), row-major patch order."""
    b, H, W, C = images.shape
    if (H, W, C) != (cfg.image_size, cfg.image_size, cfg.channels):
        raise ValueError(
            f"mpi_tpu: expected {cfg.image_size}x{cfg.image_size}x"
            f"{cfg.channels} images, got {H}x{W}x{C}")
    p = cfg.patch_size
    x = images.reshape(b, H // p, p, W // p, p, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, cfg.n_patches, p * p * C)


def forward_vit(params: Dict[str, Any], images: jax.Array,
                cfg: ViTConfig, mesh: Optional[Mesh] = None) -> jax.Array:
    """Class logits ``(b, n_classes)`` for ``(b, H, W, C)`` images."""
    inner = cfg.inner
    dt = cfg.dtype
    x = _patchify(images.astype(dt), cfg) @ params["patch"].astype(dt)
    x = x + params["pos"].astype(dt)[None]
    x = _act_constraint(x, mesh)
    body = functools.partial(block_body, cfg=inner, mesh=mesh)
    if cfg.remat:
        body = jax.checkpoint(body)
    for blk in params["blocks"]:
        x, _ = body(x, blk)
    x = _layernorm(x, params["final_ln"]["scale"].astype(dt),
                   params["final_ln"]["bias"].astype(dt))
    pooled = jnp.mean(x, axis=1)        # mean-pool over patches
    logits = pooled @ params["head"]["w"].astype(dt) \
        + params["head"]["b"].astype(dt)
    return logits.astype(jnp.float32)


def vit_loss_fn(params, batch: Tuple[jax.Array, jax.Array],
                cfg: ViTConfig, mesh: Optional[Mesh] = None):
    """Mean softmax cross-entropy over (images, int labels)."""
    images, labels = batch
    logits = forward_vit(params, images, cfg, mesh)
    return token_xent(logits, labels.astype(jnp.int32))


def make_vit_train_step(cfg: ViTConfig, mesh: Optional[Mesh] = None,
                        learning_rate: float = 1e-3,
                        optimizer: str = "adamw"):
    """(init_state, step) for classifier training; with a mesh, params
    follow the transformer specs (tp on heads/ffn; patch/head
    replicated) and the batch shards over ``dp``."""
    import optax

    opt = make_optimizer(optimizer, learning_rate)

    def _specs(params):
        # Shared blocks reuse the LM's canonical specs (tp on heads and
        # d_ff); the ViT-only leaves (patch proj, head) replicate.
        specs = param_specs(cfg.inner)
        specs.pop("embed", None)
        specs["patch"] = P()
        specs["head"] = {"w": P(), "b": P()}
        sane = jax.tree.map(lambda s: sanitize_spec(s, mesh), specs,
                            is_leaf=lambda s: isinstance(s, P))
        # Structural agreement with the params tree is load-bearing —
        # fail loudly if the trees ever drift.
        jax.tree.map(lambda *_: None, params, sane)
        return sane

    def init_state(key: jax.Array):
        params = init_vit_params(key, cfg)
        if mesh is not None:
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                params, _specs(params))
        opt_state = (jax.jit(opt.init)(params) if mesh is not None
                     else opt.init(params))
        return {"params": params, "opt": opt_state}

    def step_body(state, batch):
        if mesh is not None:
            images, labels = batch
            sb = NamedSharding(
                mesh, P(*(("dp",) + (None,) * (images.ndim - 1))))
            images = jax.lax.with_sharding_constraint(images, sb)
            labels = jax.lax.with_sharding_constraint(
                labels, NamedSharding(mesh, P("dp")))
            batch = (images, labels)
        loss, grads = jax.value_and_grad(vit_loss_fn)(
            state["params"], batch, cfg, mesh)
        updates, new_opt = opt.update(grads, state["opt"],
                                      state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt": new_opt}, loss

    return init_state, jax.jit(step_body)

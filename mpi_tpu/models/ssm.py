"""State-space sequence model (LRU family) — the non-attention LM.

A diagonal complex linear recurrence (Linear Recurrent Unit, the
S4/S5-family member with the simplest exact math) interleaved with
gated MLPs: where the Transformer mixes time with attention's O(s²)
matmuls, this mixes time with an O(s) scan that XLA lowers to an
O(log s)-depth ``lax.associative_scan`` — the TPU-native way to run a
recurrence (no serial loop, no dynamic shapes), with the MXU fed by
the surrounding projections and MLP. Training is full-sequence
parallel like the Transformer; decoding carries an O(1)-per-token
recurrent state instead of a KV cache that grows with context.

Per layer, over hidden size ``d`` and state size ``h``::

    lam = exp(-exp(nu_log) + i * exp(theta_log))     # |lam| < 1
    gam = sqrt(1 - |lam|^2)                          # input normalizer
    x_t = lam * x_{t-1} + gam * (u_t @ B)            # complex diagonal
    y_t = Re(x_t @ C) + D * u_t                      # read-out + skip

The recurrence runs in complex64 (f32 pairs — stability), everything
matmul-shaped runs in ``cfg.dtype`` (bf16 on TPU). No reference
analogue (the reference has no ML code at all; SURVEY.md §2); this is
model-zoo breadth on the shared training stack (same optimizer,
token_xent loss, and checkpoint format as the Transformer).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import (_dense_init, _layernorm, make_optimizer,
                          token_xent)

__all__ = ["SsmConfig", "init_ssm_params", "ssm_forward",
           "ssm_forward_sp", "make_ssm_train_step", "ssm_decode",
           "ssm_prefill", "init_ssm_state", "ssm_step"]


@dataclass(frozen=True)
class SsmConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    d_state: int = 64          # per-layer complex state size
    d_ff: int = 512
    dtype: Any = jnp.float32   # matmul compute dtype (bf16 on TPU)
    # |lam| initialized uniform in [r_min, r_max) — long memories near 1.
    r_min: float = 0.4
    r_max: float = 0.99


def _uniform(key, shape, lo, hi):
    return jax.random.uniform(key, shape, jnp.float32, lo, hi)


def init_ssm_params(cfg: SsmConfig, key: jax.Array) -> Dict[str, Any]:
    """Parameter pytree (float32 masters, like the Transformer's)."""
    keys = jax.random.split(key, 2 + cfg.n_layers)
    d, h, f = cfg.d_model, cfg.d_state, cfg.d_ff

    def glorot(k, shape):
        return _dense_init(k, shape, jnp.float32, shape[0])

    blocks = []
    for i in range(cfg.n_layers):
        ks = jax.random.split(keys[2 + i], 8)
        # LRU ring init: lam = exp(-exp(nu) + i exp(theta)), |lam| in
        # [r_min, r_max), phase uniform over the circle's first half.
        u1 = _uniform(ks[0], (h,), 0.0, 1.0)
        mod = jnp.sqrt(u1 * (cfg.r_max ** 2 - cfg.r_min ** 2)
                       + cfg.r_min ** 2)
        nu_log = jnp.log(-jnp.log(mod))
        # Lower bound keeps log() finite: uniform's minval is
        # INCLUSIVE, and a 0.0 draw would put -inf in theta_log, which
        # AdamW's weight decay turns into nan on the first update.
        theta_log = jnp.log(_uniform(ks[1], (h,), 1e-6, math.pi))
        blocks.append({
            "nu_log": nu_log,
            "theta_log": theta_log,
            "b_re": glorot(ks[2], (d, h)),
            "b_im": glorot(ks[3], (d, h)),
            "c_re": glorot(ks[4], (h, d)),
            "c_im": glorot(ks[5], (h, d)),
            "d_skip": jnp.zeros((d,), jnp.float32),
            "ln1": {"scale": jnp.ones((d,), jnp.float32),
                    "bias": jnp.zeros((d,), jnp.float32)},
            "w1": glorot(ks[6], (d, f)),
            "w2": glorot(ks[7], (f, d)),
            "ln2": {"scale": jnp.ones((d,), jnp.float32),
                    "bias": jnp.zeros((d,), jnp.float32)},
        })
    return {
        "embed": (jax.random.normal(keys[0], (cfg.vocab, d))
                  / math.sqrt(d)).astype(jnp.float32),
        "blocks": blocks,
        "ln_f": {"scale": jnp.ones((d,), jnp.float32),
                 "bias": jnp.zeros((d,), jnp.float32)},
        "head": glorot(keys[1], (d, cfg.vocab)),
    }


def _lam_gam(blk) -> Tuple[jax.Array, jax.Array]:
    lam = jnp.exp(-jnp.exp(blk["nu_log"])
                  + 1j * jnp.exp(blk["theta_log"])).astype(jnp.complex64)
    gam = jnp.sqrt(jnp.maximum(1.0 - jnp.abs(lam) ** 2, 1e-8)
                   ).astype(jnp.complex64)
    return lam, gam


def _lru_scan(blk, u: jax.Array, scan_fn=None, with_state=False):
    """The recurrence over a full sequence: u (b, s, d) -> y (b, s, d).

    ``scan_fn(a, b)`` computes the inclusive linear scan along axis 1
    (default: the single-device ``parallel.scan.linear_scan``; the
    sequence-parallel forward passes ``sharded_linear_scan`` instead —
    same monoid, sequence sharded over a mesh axis). ``with_state``
    additionally returns the final recurrent state x_{s-1} (b, h) —
    what a decode loop continues from."""
    from ..parallel.scan import linear_scan

    lam, gam = _lam_gam(blk)
    # Drive term in complex64: (b, s, h)
    u32 = u.astype(jnp.float32)
    drive = (jnp.einsum("bsd,dh->bsh", u32, blk["b_re"])
             + 1j * jnp.einsum("bsd,dh->bsh", u32, blk["b_im"]))
    drive = gam[None, None] * drive.astype(jnp.complex64)
    a = jnp.broadcast_to(lam[None, None], drive.shape)
    if scan_fn is None:
        x = linear_scan(a, drive, axis=1)
    else:
        x = scan_fn(a, drive)
    y = (jnp.einsum("bsh,hd->bsd", x.real, blk["c_re"])
         - jnp.einsum("bsh,hd->bsd", x.imag, blk["c_im"]))
    y = y.astype(u.dtype) + blk["d_skip"].astype(u.dtype) * u
    if with_state:
        return y, x[:, -1]
    return y


def _block(blk, x: jax.Array, scan_fn=None, with_state=False):
    h = _layernorm(x, blk["ln1"]["scale"].astype(x.dtype),
                   blk["ln1"]["bias"].astype(x.dtype))
    if with_state:
        y, s_last = _lru_scan(blk, h, scan_fn, with_state=True)
    else:
        y, s_last = _lru_scan(blk, h, scan_fn), None
    x = x + y
    h = _layernorm(x, blk["ln2"]["scale"].astype(x.dtype),
                   blk["ln2"]["bias"].astype(x.dtype))
    h = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h,
                               blk["w1"].astype(x.dtype)))
    x = x + jnp.einsum("bsf,fd->bsd", h, blk["w2"].astype(x.dtype))
    return (x, s_last) if with_state else x


def _forward_impl(cfg: SsmConfig, params: Dict[str, Any],
                  tokens: jax.Array, scan_fn=None) -> jax.Array:
    x = params["embed"].astype(cfg.dtype)[tokens]
    for blk in params["blocks"]:
        x = _block(blk, x, scan_fn)
    x = _layernorm(x, params["ln_f"]["scale"].astype(x.dtype),
                   params["ln_f"]["bias"].astype(x.dtype))
    return jnp.einsum("bsd,dv->bsv", x, params["head"].astype(x.dtype))


def ssm_forward(cfg: SsmConfig, params: Dict[str, Any],
                tokens: jax.Array) -> jax.Array:
    """tokens (b, s) int32 -> logits (b, s, vocab). Strictly causal:
    position t sees tokens[:, :t+1] only (the recurrence is the proof)."""
    return _forward_impl(cfg, params, tokens)


def ssm_forward_sp(cfg: SsmConfig, params: Dict[str, Any],
                   tokens: jax.Array,
                   axis_name: str = "sp") -> jax.Array:
    """Sequence-parallel forward — call inside ``shard_map`` with
    ``tokens`` (b, s_local) holding this rank's contiguous chunk of
    the sequence (sharded over ``axis_name``) and params replicated.
    Every per-position op stays local; only the recurrence crosses
    devices, via :func:`mpi_tpu.parallel.sharded_linear_scan`'s
    O(log n) carry exchange — the SSM's ring-attention analogue, for
    sequences longer than one device's memory."""
    from ..parallel.scan import sharded_linear_scan

    return _forward_impl(
        cfg, params, tokens,
        scan_fn=lambda a, b: sharded_linear_scan(a, b, axis_name,
                                                 axis=1))


# -- recurrent decode (O(1) per token; the KV-cache-free serving story) --

def init_ssm_state(cfg: SsmConfig, batch: int) -> list:
    """Per-layer recurrent state, all zeros (no context yet)."""
    return [jnp.zeros((batch, cfg.d_state), jnp.complex64)
            for _ in range(cfg.n_layers)]


def ssm_step(cfg: SsmConfig, params: Dict[str, Any], state: list,
             tokens: jax.Array) -> Tuple[list, jax.Array]:
    """One token step: tokens (b,) int32 -> (new_state, logits (b, v)).
    Bitwise the same recurrence the scan runs, carried explicitly."""
    x = params["embed"].astype(cfg.dtype)[tokens]  # (b, d)
    new_state = []
    for blk, s in zip(params["blocks"], state):
        h = _layernorm(x, blk["ln1"]["scale"].astype(x.dtype),
                       blk["ln1"]["bias"].astype(x.dtype))
        lam, gam = _lam_gam(blk)
        h32 = h.astype(jnp.float32)
        drive = (jnp.einsum("bd,dh->bh", h32, blk["b_re"])
                 + 1j * jnp.einsum("bd,dh->bh", h32, blk["b_im"]))
        s = lam[None] * s + gam[None] * drive.astype(jnp.complex64)
        new_state.append(s)
        y = (jnp.einsum("bh,hd->bd", s.real, blk["c_re"])
             - jnp.einsum("bh,hd->bd", s.imag, blk["c_im"])
             ).astype(x.dtype) + blk["d_skip"].astype(x.dtype) * h
        x = x + y
        h2 = _layernorm(x, blk["ln2"]["scale"].astype(x.dtype),
                        blk["ln2"]["bias"].astype(x.dtype))
        h2 = jax.nn.gelu(jnp.einsum("bd,df->bf", h2,
                                    blk["w1"].astype(x.dtype)))
        x = x + jnp.einsum("bf,fd->bd", h2, blk["w2"].astype(x.dtype))
    x = _layernorm(x, params["ln_f"]["scale"].astype(x.dtype),
                   params["ln_f"]["bias"].astype(x.dtype))
    return new_state, jnp.einsum("bd,dv->bv", x,
                                 params["head"].astype(x.dtype))


def ssm_prefill(cfg: SsmConfig, params: Dict[str, Any],
                prompt: jax.Array):
    """(per-layer recurrent state after the last prompt token,
    last-position logits (b, vocab)) in ONE parallel-scan forward —
    O(log p) depth instead of p serial steps, and no (p, vocab) logits
    ever materialize (only the last position projects to the head)."""
    x = params["embed"].astype(cfg.dtype)[prompt]
    states = []
    for blk in params["blocks"]:
        x, s_last = _block(blk, x, with_state=True)
        states.append(s_last)
    xl = _layernorm(x[:, -1], params["ln_f"]["scale"].astype(x.dtype),
                    params["ln_f"]["bias"].astype(x.dtype))
    return states, jnp.einsum("bd,dv->bv", xl,
                              params["head"].astype(x.dtype))


@partial(jax.jit, static_argnums=(0, 3))
def ssm_decode(cfg: SsmConfig, params: Dict[str, Any],
               prompt: jax.Array, n_new: int) -> jax.Array:
    """Greedy decode: prompt (b, p) int32 -> (b, p + n_new), one jitted
    program (parallel prefill + generate scan) carrying the O(1)
    recurrent state — decode cost per token is independent of how much
    context came before (the structural advantage over KV-cache
    attention), and the prefill is the O(log p) scan, not p serial
    steps."""
    b, p = prompt.shape
    if n_new <= 0 or p == 0:
        # p == 0 would make the prefill's last-logits read undefined;
        # unconditional generation starts from a BOS-style prompt of
        # at least one token.
        return prompt

    state, logits_last = ssm_prefill(cfg, params, prompt)
    first = jnp.argmax(logits_last, axis=-1).astype(prompt.dtype)

    def step(carry, _):
        st, tok = carry
        st, lg = ssm_step(cfg, params, st, tok)
        nxt = jnp.argmax(lg, axis=-1).astype(prompt.dtype)
        # Emit the token we just CONSUMED: the scan's outputs are then
        # exactly the n_new generated tokens in order.
        return (st, nxt), tok

    _, toks = lax.scan(step, (state, first), None, length=n_new)
    return jnp.concatenate([prompt, jnp.transpose(toks, (1, 0))],
                           axis=1)


def make_ssm_train_step(cfg: SsmConfig, learning_rate: float = 1e-3,
                        optimizer: str = "adamw",
                        mesh: Optional[Any] = None):
    """(init_state, jitted step). ``step(state, tokens)`` consumes
    (b, s+1) int32 — inputs ``tokens[:, :-1]``, targets
    ``tokens[:, 1:]`` — and returns (state, loss), same shape contract
    as the Transformer's trainer. With ``mesh`` (a ``dp`` axis), the
    batch shards over dp and GSPMD inserts the gradient psum."""
    import optax

    opt = make_optimizer(optimizer, learning_rate)

    def init_state(key):
        params = init_ssm_params(cfg, key)
        return {"params": params, "opt": opt.init(params),
                "step": jnp.zeros((), jnp.int32)}

    def loss_fn(params, tokens):
        logits = ssm_forward(cfg, params, tokens[:, :-1])
        return token_xent(logits, tokens[:, 1:])

    def step_body(state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"],
                                                  tokens)
        updates, new_opt = opt.update(grads, state["opt"],
                                      state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": new_opt,
                "step": state["step"] + 1}, loss

    if mesh is None:
        return init_state, jax.jit(step_body)

    from jax.sharding import NamedSharding, PartitionSpec as P

    tok_sharding = NamedSharding(mesh, P("dp", None))
    repl = NamedSharding(mesh, P())

    init_sharded = jax.jit(init_state, out_shardings=repl)

    step = jax.jit(step_body,
                   in_shardings=(repl, tok_sharding),
                   out_shardings=(repl, repl))
    return init_sharded, step

"""Mixture-of-Experts FFN — expert parallelism over the ``ep`` mesh axis.

GShard-style top-1 routed MoE with static shapes (XLA needs them): each
token picks its highest-probability expert, experts process fixed-capacity
token buffers, and overflow tokens fall through the residual connection.
Expert weights carry a leading expert axis sharded ``P('ep', ...)``; the
dispatched token buffers are constrained to the same axis, so GSPMD
inserts the all-to-all exchanges that carry tokens to their experts over
ICI — the standard tpu-native MoE dataflow (no reference analogue:
btracey/mpi has no ML code, SURVEY.md §2).

Everything here is einsum/one-hot arithmetic — MXU-friendly, fully
differentiable, no data-dependent shapes.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["init_moe_params", "moe_specs", "moe_ffn"]


def init_moe_params(key: jax.Array, d_model: int, d_ff: int,
                    n_experts: int, dtype: Any) -> Dict[str, Any]:
    k1, k2, k3 = jax.random.split(key, 3)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": dense(k1, (d_model, n_experts), d_model),
        "w1e": dense(k2, (n_experts, d_model, d_ff), d_model),
        "w2e": dense(k3, (n_experts, d_ff, d_model), d_ff),
    }


def moe_specs() -> Dict[str, P]:
    """PartitionSpecs for :func:`init_moe_params`'s tree: experts over
    ``ep``, the FFN hidden dim over ``tp`` (Megatron split inside each
    expert); the router is small and replicated."""
    return {
        "router": P(),
        "w1e": P("ep", None, "tp"),
        "w2e": P("ep", "tp", None),
    }


def moe_ffn(x: jax.Array, params: Dict[str, Any], n_experts: int,
            capacity_factor: float = 1.25,
            mesh: Optional[Mesh] = None,
            top_k: int = 1) -> Tuple[jax.Array, jax.Array]:
    """Top-k routed expert FFN (GShard-style; ``top_k=1`` is Switch).

    ``x``: (batch, seq, d_model). Returns ``(y, aux)`` where ``y`` has
    x's shape (fully-overflowed tokens produce zeros — the caller's
    residual stream carries them through) and ``aux`` is the
    load-balancing loss (Shazeer et al.:
    ``E * sum_e fraction_first_choice_e * mean_prob_e``, minimised at
    uniform routing; computed on first choices for any k).

    Tokens are routed within *groups* (one group per batch row, the
    GShard/Switch recipe): the dispatch one-hots are (groups, seq, E, C)
    with per-group capacity, so memory stays linear in the global token
    count instead of quadratic, and group = batch row keeps routing
    aligned with the dp sharding (no cross-device cumsum).

    Capacity handling for ``k > 1`` follows GShard: per-expert buffers
    hold ``ceil(k * seq / E * capacity_factor)`` tokens, and slots are
    claimed choice-major — every token's first choice outranks any
    token's second choice — so congestion drops k-th choices first.
    Gates are the raw router probabilities of the surviving choices
    (matching the k=1 behavior; a dropped choice contributes zero and
    its share rides the residual).
    """
    b, s, d = x.shape
    e = n_experts
    if not 1 <= top_k <= e:
        raise ValueError(
            f"mpi_tpu: moe top_k={top_k} must be in [1, n_experts={e}]")
    capacity = max(1, int(math.ceil(top_k * s / e * capacity_factor)))

    logits = jnp.einsum("gnd,de->gne", x, params["router"].astype(x.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topk_probs, topk_idx = lax.top_k(probs, top_k)       # (G, N, K)
    onehot_k = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)  # (G, N, K, E)

    # Slot positions, choice-major priority: order all first choices in
    # token order, then all second choices, ... (exclusive int cumsum —
    # deterministic, exact). pos[(g, n, k)] = slot index within the
    # chosen expert's group-g buffer.
    ordered = onehot_k.transpose(0, 2, 1, 3).reshape(b, top_k * s, e)
    pos_flat = jnp.cumsum(ordered, axis=1) - ordered
    pos = jnp.einsum("gme,gme->gm", pos_flat, ordered)
    pos = pos.reshape(b, top_k, s).transpose(0, 2, 1)    # (G, N, K)
    kept = pos < capacity                                # (G, N, K)
    gates = jnp.where(kept, topk_probs, 0.0)

    # dispatch[g, n, e', c] = 1 iff token (g, n) sits in slot c of
    # expert e''s group-g buffer (via any of its k choices — top_k gives
    # distinct experts, so slots never collide).
    slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # (G, N, K, C)
    sel = (onehot_k * kept[..., None]).astype(jnp.float32)   # (G, N, K, E)
    dispatch = jnp.einsum("gnke,gnkc->gnec", sel, slot)
    combine = jnp.einsum("gnke,gnkc,gnk->gnec", sel, slot, gates)

    xin = jnp.einsum("gnec,gnd->gecd", dispatch.astype(x.dtype), x)
    buf_sharding = None
    if mesh is not None and "ep" in mesh.axis_names:
        from .transformer import sanitize_spec

        # Commit the expert buffers to the ep axis: GSPMD materialises the
        # token all-to-all here (tokens travel to their expert's device).
        buf_sharding = NamedSharding(
            mesh, sanitize_spec(P("dp", "ep", None, None), mesh))
        xin = lax.with_sharding_constraint(xin, buf_sharding)
    h = jax.nn.gelu(jnp.einsum("gecd,edf->gecf", xin,
                               params["w1e"].astype(x.dtype)))
    y_e = jnp.einsum("gecf,efd->gecd", h, params["w2e"].astype(x.dtype))
    if buf_sharding is not None:
        y_e = lax.with_sharding_constraint(y_e, buf_sharding)
    y = jnp.einsum("gnec,gecd->gnd", combine.astype(x.dtype), y_e)

    # Load-balance aux: fraction of first-choice tokens per expert x mean
    # router prob (first choices for any k — the standard GShard form).
    frac = jnp.mean(onehot_k[:, :, 0, :].astype(jnp.float32), axis=(0, 1))
    mean_prob = jnp.mean(probs, axis=(0, 1))
    aux = e * jnp.sum(frac * mean_prob)
    return y, aux.astype(jnp.float32)

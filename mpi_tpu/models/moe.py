"""Mixture-of-Experts FFN — expert parallelism over the ``ep`` mesh axis.

GShard-style top-1 routed MoE with static shapes (XLA needs them): each
token picks its highest-probability expert, experts process fixed-capacity
token buffers, and overflow tokens fall through the residual connection.
Expert weights carry a leading expert axis sharded ``P('ep', ...)``; the
dispatched token buffers are constrained to the same axis, so GSPMD
inserts the all-to-all exchanges that carry tokens to their experts over
ICI — the standard tpu-native MoE dataflow (no reference analogue:
btracey/mpi has no ML code, SURVEY.md §2).

Everything here is einsum/one-hot arithmetic — MXU-friendly, fully
differentiable, no data-dependent shapes.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["init_moe_params", "moe_specs", "moe_ffn"]


def init_moe_params(key: jax.Array, d_model: int, d_ff: int,
                    n_experts: int, dtype: Any) -> Dict[str, Any]:
    k1, k2, k3 = jax.random.split(key, 3)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": dense(k1, (d_model, n_experts), d_model),
        "w1e": dense(k2, (n_experts, d_model, d_ff), d_model),
        "w2e": dense(k3, (n_experts, d_ff, d_model), d_ff),
    }


def moe_specs() -> Dict[str, P]:
    """PartitionSpecs for :func:`init_moe_params`'s tree: experts over
    ``ep``, the FFN hidden dim over ``tp`` (Megatron split inside each
    expert); the router is small and replicated."""
    return {
        "router": P(),
        "w1e": P("ep", None, "tp"),
        "w2e": P("ep", "tp", None),
    }


def moe_ffn(x: jax.Array, params: Dict[str, Any], n_experts: int,
            capacity_factor: float = 1.25,
            mesh: Optional[Mesh] = None) -> Tuple[jax.Array, jax.Array]:
    """Top-1 routed expert FFN.

    ``x``: (batch, seq, d_model). Returns ``(y, aux)`` where ``y`` has
    x's shape (overflowed tokens produce zeros — the caller's residual
    stream carries them through) and ``aux`` is the load-balancing loss
    (Shazeer et al.: ``E * sum_e fraction_tokens_e * mean_prob_e``,
    minimised at uniform routing).

    Tokens are routed within *groups* (one group per batch row, the
    GShard/Switch recipe): the dispatch one-hots are (groups, seq, E, C)
    with per-group capacity, so memory stays linear in the global token
    count instead of quadratic, and group = batch row keeps routing
    aligned with the dp sharding (no cross-device cumsum).
    """
    b, s, d = x.shape
    e = n_experts
    capacity = max(1, int(math.ceil(s / e * capacity_factor)))

    logits = jnp.einsum("gnd,de->gne", x, params["router"].astype(x.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate = jnp.max(probs, axis=-1)                  # (G, N)
    expert = jnp.argmax(probs, axis=-1)             # (G, N)
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.int32)  # (G, N, E)

    # Position of each token within its expert's per-group buffer
    # (exclusive int cumsum in token order — deterministic priority, and
    # exact for any token count, unlike a float32 cumsum).
    pos = jnp.cumsum(onehot, axis=1) - onehot       # (G, N, E)
    pos = jnp.einsum("gne,gne->gn", pos, onehot)    # (G, N) int32
    kept = pos < capacity
    gate = jnp.where(kept, gate, 0.0)

    # dispatch[g, n, e', c] = 1 iff token (g, n) sits in slot c of
    # expert e''s group-g buffer.
    dispatch = (onehot * kept[..., None]).astype(jnp.float32)[..., None] \
        * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[..., None, :]
    combine = dispatch * gate[..., None, None]      # (G, N, E, C)

    xin = jnp.einsum("gnec,gnd->gecd", dispatch.astype(x.dtype), x)
    buf_sharding = None
    if mesh is not None and "ep" in mesh.axis_names:
        from .transformer import sanitize_spec

        # Commit the expert buffers to the ep axis: GSPMD materialises the
        # token all-to-all here (tokens travel to their expert's device).
        buf_sharding = NamedSharding(
            mesh, sanitize_spec(P("dp", "ep", None, None), mesh))
        xin = lax.with_sharding_constraint(xin, buf_sharding)
    h = jax.nn.gelu(jnp.einsum("gecd,edf->gecf", xin,
                               params["w1e"].astype(x.dtype)))
    y_e = jnp.einsum("gecf,efd->gecd", h, params["w2e"].astype(x.dtype))
    if buf_sharding is not None:
        y_e = lax.with_sharding_constraint(y_e, buf_sharding)
    y = jnp.einsum("gnec,gecd->gnd", combine.astype(x.dtype), y_e)

    # Load-balance aux: fraction of tokens routed to e x mean router prob.
    frac = jnp.mean(onehot.astype(jnp.float32), axis=(0, 1))
    mean_prob = jnp.mean(probs, axis=(0, 1))
    aux = e * jnp.sum(frac * mean_prob)
    return y, aux.astype(jnp.float32)

"""Autoregressive generation with a KV cache — the flagship's inference path.

tpu-first decode: the cache is a preallocated ``(layers, batch, max_seq,
heads, head_dim)`` pair updated in place with ``dynamic_update_slice`` (no
shape growth — one compiled step serves every position), the per-step
attention is one masked dot against the full cache (MXU-shaped, masked by
position), and the whole generation loop is a single ``lax.scan`` under
``jit`` — no host round-trips per token. Prefill computes the prompt's
cache in one batched forward pass.

No reference analogue (btracey/mpi has no models, SURVEY.md §2) — this is
framework-completeness work: train (`make_train_step`) and serve
(`generate`) cover the model lifecycle.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .quant import embed_lookup, logits_matmul
from .transformer import TransformerConfig, _ffn, _layernorm, apply_rope

__all__ = ["prefill", "decode_step", "generate"]


def _proj_qkv(x, blk, cfg, n_valid):
    """q/k/v projections for tokens starting at absolute position
    ``n_valid``; under rope, q and k are rotated by their positions HERE
    — k enters the cache already rotated, so cached entries never need
    re-rotation as decode advances."""
    dtype = x.dtype
    q = jnp.einsum("bsd,dhk->bshk", x, blk["wq"].astype(dtype))
    k = jnp.einsum("bsd,dhk->bshk", x, blk["wk"].astype(dtype))
    v = jnp.einsum("bsd,dhk->bshk", x, blk["wv"].astype(dtype))
    if cfg.rope:
        pos = n_valid + jnp.arange(x.shape[1], dtype=jnp.int32)
        q = apply_rope(q, pos, cfg.rope_theta)
        k = apply_rope(k, pos, cfg.rope_theta)
    return q, k, v


def _attend_cached(q, k_cache, v_cache, n_valid, cfg):
    """q: (b, s_q, h, hd) attends to cache positions [0, n_valid + s_q)
    with causal offsets; cache: (b, max_seq, kv_heads, hd).

    GQA stays *grouped* through the contraction — queries reshape to
    (b, s, kv, group, hd) and each kv head is read once per step rather
    than materialised group x larger, so decode keeps GQA's bandwidth
    and peak-memory win (the point of the smaller cache)."""
    b, s_q, h, hd = q.shape
    if cfg.decode_attention not in ("dense", "flash"):
        # Same loud-unknown stance as attention_impl: silently falling
        # back would hide a misconfiguration on the hot path.
        raise ValueError(
            f"mpi_tpu: unknown decode_attention "
            f"{cfg.decode_attention!r}: expected dense|flash")
    if s_q == 1 and cfg.decode_attention == "flash":
        # One-query steps take the fused Pallas path: a single VMEM
        # pass over the cache with online softmax, GQA-native.
        from ..ops.decode_attention import flash_decode_attention

        out = flash_decode_attention(q[:, 0], k_cache, v_cache,
                                     jnp.asarray(n_valid, jnp.int32))
        return out[:, None]
    kv = cfg.kv_heads
    group = h // kv
    qg = q.reshape(b, s_q, kv, group, hd)
    scale = 1.0 / math.sqrt(cfg.head_dim)
    logits = jnp.einsum("bsKgk,btKk->bKgst", qg, k_cache) * scale
    t = k_cache.shape[1]
    # query i sits at absolute position n_valid + i; it may see cache
    # columns 0 .. n_valid + i.
    rows = n_valid + lax.broadcasted_iota(jnp.int32, (s_q, t), 0)
    cols = lax.broadcasted_iota(jnp.int32, (s_q, t), 1)
    logits = jnp.where((cols <= rows)[None, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    ctx = jnp.einsum("bKgst,btKk->bsKgk", probs.astype(q.dtype), v_cache)
    return ctx.reshape(b, s_q, h, hd)


def _forward_cached(params, tokens, cache, n_valid, cfg: TransformerConfig):
    """Run ``tokens`` (b, s) starting at absolute position ``n_valid``,
    writing their k/v into the cache. Returns (logits, new_cache)."""
    b, s = tokens.shape
    x = embed_lookup(params["embed"], tokens, cfg.dtype)
    if not cfg.rope:
        pos_emb = lax.dynamic_slice_in_dim(
            params["pos"].astype(cfg.dtype), n_valid, s, axis=0)
        x = x + pos_emb[None]
    new_cache = []
    for i, blk in enumerate(params["blocks"]):
        h = _layernorm(x, blk["ln1"]["scale"].astype(x.dtype),
                       blk["ln1"]["bias"].astype(x.dtype))
        q, k, v = _proj_qkv(h, blk, cfg, n_valid)
        k_cache = lax.dynamic_update_slice_in_dim(
            cache[i][0], k, n_valid, axis=1)
        v_cache = lax.dynamic_update_slice_in_dim(
            cache[i][1], v, n_valid, axis=1)
        new_cache.append((k_cache, v_cache))
        ctx = _attend_cached(q, k_cache, v_cache, n_valid, cfg)
        x = x + jnp.einsum("bshk,hkd->bsd", ctx, blk["wo"].astype(x.dtype))
        h = _layernorm(x, blk["ln2"]["scale"].astype(x.dtype),
                       blk["ln2"]["bias"].astype(x.dtype))
        y, _ = _ffn(h, blk, cfg, mesh=None)  # aux loss is a train concern
        x = x + y
    x = _layernorm(x, params["final_ln"]["scale"].astype(x.dtype),
                   params["final_ln"]["bias"].astype(x.dtype))
    logits = logits_matmul(x, params["embed"])
    return logits, new_cache


def _empty_cache(cfg: TransformerConfig, batch: int):
    # kv_heads, not n_heads: GQA shrinks the cache by the group factor.
    shape = (batch, cfg.max_seq, cfg.kv_heads, cfg.head_dim)
    return [(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
            for _ in range(cfg.n_layers)]


def prefill(params, prompt: jax.Array, cfg: TransformerConfig):
    """Batched prompt pass. Returns (last_logits (b, vocab), cache)."""
    cache = _empty_cache(cfg, prompt.shape[0])
    logits, cache = _forward_cached(params, prompt, cache, 0, cfg)
    return logits[:, -1], cache


def decode_step(params, token: jax.Array, cache, n_valid,
                cfg: TransformerConfig):
    """One incremental step: ``token`` (b,) at absolute position
    ``n_valid``. Returns (logits (b, vocab), new_cache)."""
    logits, cache = _forward_cached(params, token[:, None], cache,
                                    n_valid, cfg)
    return logits[:, 0], cache


def generate(params, prompt: jax.Array, cfg: TransformerConfig,
             max_new_tokens: int, temperature: float = 0.0,
             key: Optional[jax.Array] = None) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` (b, s).

    ``temperature == 0`` is greedy argmax; otherwise samples from the
    tempered softmax (requires ``key``). The decode loop is one
    ``lax.scan`` — jit-compatible end to end. Returns (b, max_new_tokens).
    """
    if prompt.shape[1] + max_new_tokens > cfg.max_seq:
        raise ValueError(
            f"mpi_tpu: prompt {prompt.shape[1]} + {max_new_tokens} new "
            f"tokens exceeds max_seq {cfg.max_seq}")
    if temperature > 0 and key is None:
        raise ValueError("mpi_tpu: sampling (temperature > 0) needs a key")
    last_logits, cache = prefill(params, prompt, cfg)
    if key is None:
        key = jax.random.PRNGKey(0)  # unused in greedy mode

    def pick(logits, k):
        if temperature > 0:
            return jax.random.categorical(k, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    def step(carry, k):
        logits, cache, n_valid = carry
        tok = pick(logits, k)
        new_logits, cache = decode_step(params, tok, cache, n_valid, cfg)
        return (new_logits, cache, n_valid + 1), tok

    keys = jax.random.split(key, max_new_tokens)
    (_, _, _), toks = lax.scan(
        step, (last_logits, cache, jnp.int32(prompt.shape[1])), keys)
    return toks.T  # (b, max_new_tokens)

"""The flagship LM trained under pipeline parallelism (pp).

Completes the parallelism matrix *in the flagship*: dp/sp/tp/ep run
through ``TransformerConfig`` shardings; this module runs the same
blocks over a ``pp`` mesh axis using :func:`mpi_tpu.parallel.pipeline.
pipeline_sharded` — each device owns a contiguous *stage* of
``n_layers/pp`` blocks, microbatches stream around the ICI ring, and
the whole schedule (embed → pipeline scan → logits → loss) is one
differentiable jitted program.

Design constraints (and why they're fine):

  * stage activations must keep one shape, which transformer blocks
    satisfy by construction ((b, s, d) → (b, s, d));
  * the embedding/unembedding and final layernorm run replicated on
    every device (they are O(vocab·d) FLOPs vs the stages' O(L·d²) —
    negligible at depth, and it keeps stage 0 / stage n-1 from needing
    special param placement);
  * attention inside a stage must be a per-device impl (dense / flash /
    blockwise) — the sp family reshards globally and MoE routes over
    ``ep``, both of which belong to the sharded (non-pp) path;
    combinations are rejected loudly.

The reference has no model execution at all (SURVEY.md §2); like the
rest of ``models/``, this is new tpu-native capability.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.pipeline import pipeline_sharded
from .transformer import (TransformerConfig, _layernorm, block_body,
                          init_params, token_xent)

__all__ = ["stack_block_params", "init_pipelined_params",
           "forward_pipelined", "pipeline_loss_fn",
           "make_pipelined_train_step"]


def _pp_size(mesh: Mesh, axis_name: str) -> int:
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mpi_tpu: mesh {mesh.axis_names} has no {axis_name!r} axis "
            f"for the pipelined flagship")
    return mesh.shape[axis_name]


def _check_cfg(cfg: TransformerConfig, pp: int) -> None:
    if cfg.n_layers % pp:
        raise ValueError(
            f"mpi_tpu: n_layers={cfg.n_layers} must divide into pp={pp} "
            f"stages")
    if cfg.n_experts > 0:
        raise ValueError(
            "mpi_tpu: MoE routes over the 'ep' axis — use the sharded "
            "(non-pp) path for expert parallelism")
    if cfg.attention_impl not in ("dense", "flash", "blockwise"):
        raise ValueError(
            f"mpi_tpu: pipeline stages need a per-device attention impl "
            f"(dense|flash|blockwise), got {cfg.attention_impl!r}")


def stack_block_params(params: Dict[str, Any], pp: int) -> Dict[str, Any]:
    """Restack ``init_params``'s per-block list into pipeline layout:
    every leaf of ``blocks`` gains leading axes ``(pp, layers_per_stage)``
    — stage i's slice lands on pipeline device i. embed/pos/final_ln
    stay as-is (replicated)."""
    blocks = params["blocks"]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    lps = len(blocks) // pp
    stacked = jax.tree.map(
        lambda x: x.reshape(pp, lps, *x.shape[1:]), stacked)
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["stages"] = stacked
    return out


def init_pipelined_params(key: jax.Array, cfg: TransformerConfig,
                          mesh: Mesh, axis_name: str = "pp"
                          ) -> Dict[str, Any]:
    """Initialise and commit: stages sharded ``P('pp')`` on their leading
    axis (one stage per pipeline device), everything else replicated."""
    pp = _pp_size(mesh, axis_name)
    _check_cfg(cfg, pp)
    params = stack_block_params(init_params(key, cfg), pp)

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    params["stages"] = jax.tree.map(
        lambda x: put(x, P(axis_name)), params["stages"])
    for k in ("embed", "pos", "final_ln"):
        if k in params:
            params[k] = jax.tree.map(lambda x: put(x, P()), params[k])
    return params


def forward_pipelined(params: Dict[str, Any], tokens: jax.Array,
                      cfg: TransformerConfig, mesh: Mesh,
                      microbatches: int = 4, axis_name: str = "pp",
                      remat_stage: bool = False) -> jax.Array:
    """tokens (batch, seq) int32 → logits (batch, seq, vocab), with the
    block stack executed as a ``pp``-stage pipeline over ``microbatches``
    microbatches (batch must divide)."""
    pp = _pp_size(mesh, axis_name)
    _check_cfg(cfg, pp)
    b, s = tokens.shape
    if b % microbatches:
        raise ValueError(
            f"mpi_tpu: batch {b} not divisible by microbatches="
            f"{microbatches}")

    x = params["embed"].astype(cfg.dtype)[tokens]
    if not cfg.rope:
        x = x + params["pos"].astype(cfg.dtype)[:s][None]
    xs = x.reshape(microbatches, b // microbatches, s, -1)

    def stage_fn(stage_params, mx):
        # One stage = layers_per_stage blocks, scanned over the stacked
        # leading axis; the block math is transformer.block_body — ONE
        # definition shared with the sequential stack (aux dropped:
        # _check_cfg rejects MoE on the pp path).
        def block(h, blk):
            h, _ = block_body(h, blk, cfg, None)
            return h, None

        out, _ = lax.scan(block, mx, stage_params)
        return out

    ys = pipeline_sharded(stage_fn, params["stages"], xs, mesh,
                          axis_name=axis_name,
                          remat_stage=remat_stage or cfg.remat)
    x = ys.reshape(b, s, -1)
    x = _layernorm(x, params["final_ln"]["scale"].astype(x.dtype),
                   params["final_ln"]["bias"].astype(x.dtype))
    return jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(x.dtype))


def pipeline_loss_fn(params, tokens, cfg: TransformerConfig, mesh: Mesh,
                     microbatches: int = 4,
                     remat_stage: bool = False) -> jax.Array:
    """Next-token cross-entropy through the pipelined forward — the same
    logsumexp-minus-target form as :func:`transformer.loss_fn`."""
    logits = forward_pipelined(params, tokens[:, :-1], cfg, mesh,
                               microbatches=microbatches,
                               remat_stage=remat_stage)
    return token_xent(logits, tokens[:, 1:])


def make_pipelined_train_step(cfg: TransformerConfig, mesh: Mesh,
                              microbatches: int = 4,
                              learning_rate: float = 1e-3,
                              optimizer: str = "adamw",
                              axis_name: str = "pp",
                              remat_stage: bool = False
                              ) -> Tuple[Any, Any]:
    """(init_state, step) for the pp flagship; same shape as
    :func:`transformer.make_train_step` (one jitted optimizer step)."""
    from .transformer import make_optimizer

    opt = make_optimizer(optimizer, learning_rate)

    def init_state(key: jax.Array):
        params = init_pipelined_params(key, cfg, mesh, axis_name)
        return {"params": params, "opt": jax.jit(opt.init)(params)}

    def step(state, tokens):
        loss, grads = jax.value_and_grad(pipeline_loss_fn)(
            state["params"], tokens, cfg, mesh,
            microbatches=microbatches, remat_stage=remat_stage)
        import optax

        updates, new_opt = opt.update(grads, state["opt"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt": new_opt}, loss

    # Donate the incoming state (params + opt alias their outputs — see
    # make_train_step); callers rebind state each step.
    return init_state, jax.jit(step, donate_argnums=(0,))

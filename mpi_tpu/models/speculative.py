"""Prompt-lookup speculative decoding — lossless greedy acceleration.

Autoregressive decode is one forward per token; speculative decoding
feeds several *drafted* tokens through one forward and keeps the prefix
the model agrees with, so the cost per accepted token drops while the
output stays EXACTLY the greedy decode (acceptance compares the draft
against the model's own argmax — a mismatch truncates the round, so no
approximation enters). The draft here is **prompt lookup** (n-gram
retrieval): find the most recent earlier occurrence of the last
``ngram`` tokens and propose whatever followed it — free to compute, no
draft model, and highly effective on inputs with repetition
(summarisation, code, chat history).

Exactness caveat: acceptance compares against THIS path's greedy
argmaxes, so the output is self-consistently greedy by construction;
it equals ``generate()``'s output whenever argmax is stable across the
two paths' forward shapes (s_q = draft_len+1 here vs 1 there). That
always holds in the f32 test regime; under bf16 TPU matmuls a
near-exact logit tie could reduce in a different order and flip — the
usual caveat for any batched-verification speculative decoder.

tpu-first shape discipline: the whole loop is ``lax.while_loop`` under
``jit`` with static shapes — the token buffer is preallocated, the
n-gram search is a vectorized window match over the buffer (no host
round trips), every round feeds exactly ``draft_len + 1`` tokens, and
variable acceptance is a masked buffer blend rather than a dynamic
shape. Batched inputs vmap the single-row engine; rows finish at their
own pace under a ``produced`` freeze mask (the standard vmap-of-while
treatment).

Verification math: with ``pending`` = the committed-but-not-yet-fed
token for position ``n_valid``, each round feeds ``[pending, d_1..d_m]``
at ``n_valid``, yielding logits whose argmaxes ``g_1..g_{m+1}`` are the
greedy continuations. ``k`` = length of the longest prefix with
``d_i == g_i``; the round commits ``pending, d_1..d_k`` and the new
pending becomes ``g_{k+1}`` (the "bonus" token — even a fully rejected
draft still nets one token, so progress ≥ 1 per round and worst case
equals plain decode with ``m`` wasted lanes of an already-launched
matmul). Rejected cache rows beyond the new ``n_valid`` are never
attended (the causal position mask) and are overwritten by the next
round's write at the same offsets.

No reference analogue (btracey/mpi has no models).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from .generate import _forward_cached, prefill
from .transformer import TransformerConfig

__all__ = ["generate_lookahead"]


def _find_draft(buf: jax.Array, n_valid: jax.Array, ngram: int,
                draft_len: int) -> jax.Array:
    """Prompt-lookup draft: the ``draft_len`` tokens that followed the
    most recent earlier occurrence of the last ``ngram`` committed
    tokens. ``buf`` is the (L,) token buffer, positions >= n_valid are
    garbage. Returns (draft_len,) int32 — possibly garbage when no
    match exists or the match runs past ``n_valid``; verification
    rejects garbage for free, so no validity flag is needed."""
    L = buf.shape[0]
    # key = buf[n_valid-ngram : n_valid], gathered at dynamic offsets
    key = jax.vmap(
        lambda j: buf[(n_valid - ngram + j) % L])(jnp.arange(ngram))
    # window t matches iff buf[t + j] == key[j] for all j; the window
    # START must sit strictly before the key's start (n_valid - ngram),
    # which excludes the trivial self-match while still admitting
    # key-overlapping matches (standard prompt-lookup behavior).
    idx = jnp.arange(L)

    def win_eq(j):
        shifted = jnp.roll(buf, -j)          # shifted[t] = buf[t + j]
        return shifted == key[j]

    eq = jnp.all(jnp.stack([win_eq(j) for j in range(ngram)]), axis=0)
    valid = idx < jnp.maximum(n_valid - ngram, 0)
    cand = jnp.where(eq & valid, idx, -1)
    p = jnp.max(cand)                         # most recent match start
    start = jnp.where(p >= 0, p + ngram, 0)   # draft follows the match
    return jax.vmap(
        lambda j: buf[(start + j) % L])(jnp.arange(draft_len))


def generate_lookahead(params: Any, prompt: jax.Array,
                       cfg: TransformerConfig, max_new_tokens: int,
                       draft_len: int = 4, ngram: int = 2) -> jax.Array:
    """Greedy generation, bit-identical to
    :func:`mpi_tpu.models.generate` at ``temperature=0``, accelerated
    by prompt-lookup speculation. ``prompt`` is (b, s); returns
    (b, max_new_tokens). ``draft_len`` tokens are verified per forward;
    ``ngram`` is the lookup key length."""
    b, s = prompt.shape
    if ngram < 1 or draft_len < 1:
        raise ValueError("mpi_tpu: ngram and draft_len must be >= 1")
    if ngram > s:
        raise ValueError(
            f"mpi_tpu: ngram {ngram} longer than the prompt ({s})")
    # Every round may write draft_len + 1 positions starting at most at
    # prompt + max_new - 1; the cache/buffer must hold the overhang.
    need = s + max_new_tokens + draft_len + 1
    if need > cfg.max_seq:
        raise ValueError(
            f"mpi_tpu: prompt {s} + {max_new_tokens} new + draft "
            f"overhang {draft_len + 1} needs max_seq >= {need}, have "
            f"{cfg.max_seq}")

    L = cfg.max_seq
    m = draft_len

    def row(prompt_row: jax.Array) -> jax.Array:
        last_logits, cache = prefill(params, prompt_row[None], cfg)
        pending = jnp.argmax(last_logits[0], axis=-1).astype(jnp.int32)
        buf = jnp.zeros((L,), jnp.int32).at[:s].set(prompt_row)

        def cond(state):
            _, _, _, _, produced = state
            return produced < max_new_tokens

        def body(state):
            buf, cache, n_valid, pending, produced = state
            # The pending token is committed: place it so the n-gram
            # key (which includes it) reads from the buffer.
            buf = lax.dynamic_update_slice(buf, pending[None], (n_valid,))
            draft = _find_draft(buf, n_valid + 1, ngram, m)
            fed = jnp.concatenate([pending[None], draft])     # (m+1,)
            logits, new_cache = _forward_cached(
                params, fed[None], cache, n_valid, cfg)
            greedy = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
            # greedy[i] continues after seq[i]; accept draft[i] while it
            # equals greedy[i] (exactly the greedy rule).
            match = draft == greedy[:m]
            k = jnp.argmin(jnp.concatenate(
                [match, jnp.zeros((1,), bool)]).astype(jnp.int32)
            ).astype(jnp.int32)
            # Commit pending + accepted drafts, but never past the
            # requested token count: freeze the surplus. int32 pinned:
            # under x64 the index arithmetic would widen the carry.
            take = jnp.minimum(k + 1, max_new_tokens - produced
                               ).astype(jnp.int32)
            seg = lax.dynamic_slice(buf, (n_valid,), (m + 1,))
            # The committed tokens ARE the fed sequence's accepted prefix.
            write = jnp.where(jnp.arange(m + 1) < take, fed, seg)
            buf = lax.dynamic_update_slice(buf, write, (n_valid,))
            new_pending = greedy[k]
            return (buf, new_cache, n_valid + take, new_pending,
                    produced + take)

        state = (buf, cache, jnp.int32(s), pending, jnp.int32(0))
        buf, _, _, _, _ = lax.while_loop(cond, body, state)
        return lax.dynamic_slice(buf, (s,), (max_new_tokens,))

    return jax.vmap(row)(prompt.astype(jnp.int32))
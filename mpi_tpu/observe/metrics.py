"""Live metrics + straggler detection.

Three data sources, one renderer:

  * **flight recorder** (:mod:`.flight`) — per-op duration samples →
    op p50/p99 and counts;
  * **trace counters** (:mod:`mpi_tpu.utils.trace`) — per-peer wire
    byte counters (``wire.*.bytes.peer*``) → bytes/s per peer;
  * **collective arrivals** — every facade collective records its
    local entry wall time here (``note_collective_entry``); in-process
    drivers (xla/hybrid rank threads share one clock) additionally
    report exact per-collective arrival skew (``note_session_skew``),
    and the trace-collection merge (:mod:`.collect`) computes
    cross-process skew from clock-aligned entries.

``summary_text()`` renders the ``mpi_tpu observe top``-style report —
printed on SIGUSR1 (installed at init) or at finalize; ``write()``
emits the machine-readable ``--mpi-metrics-out`` JSON artifact that
``bench.py`` folds into BENCH rounds (schema in docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import flight

__all__ = ["note_collective_entry", "note_session_skew",
           "collective_entries", "session_skews", "snapshot", "write",
           "summary_text", "install_sigusr1", "reset_for_testing"]

SCHEMA_VERSION = 1

_ENTRIES_CAP = 16384
_SKEWS_CAP = 4096

_lock = threading.Lock()
_entries: deque = deque(maxlen=_ENTRIES_CAP)  # (name, seq, wall_ns)
_entry_seq: Dict[str, int] = {}
_skews: deque = deque(maxlen=_SKEWS_CAP)      # (name, skew_us, slowest)
_t_start = time.time()


def note_collective_entry(name: str) -> None:
    """Record this rank's arrival at a collective. Per-name sequence
    numbers align across ranks because collectives are invoked in the
    same order on every rank (the standard MPI requirement)."""
    with _lock:
        seq = _entry_seq.get(name, 0)
        _entry_seq[name] = seq + 1
        _entries.append((name, seq, time.time_ns()))


def note_session_skew(name: str, skew_us: float, slowest: int) -> None:
    """Exact arrival skew for one in-process collective session
    (xla/hybrid rank threads — one clock, no alignment needed)."""
    with _lock:
        _skews.append((name, float(skew_us), int(slowest)))


def collective_entries() -> List[Tuple[str, int, int]]:
    with _lock:
        return list(_entries)


def session_skews() -> List[Tuple[str, float, int]]:
    with _lock:
        return list(_skews)


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _op_stats() -> Dict[str, Dict[str, float]]:
    snap = flight.snapshot()
    counts = snap["op_counts"]
    out: Dict[str, Dict[str, float]] = {}
    for op, samples in flight.op_durations().items():
        s = sorted(samples)
        out[op] = {
            "count": counts.get(op, len(s)),
            "p50_us": _percentile(s, 0.50),
            "p99_us": _percentile(s, 0.99),
        }
    return out


def _peer_bytes() -> Dict[str, Dict[str, float]]:
    """Per-peer tx/rx byte totals from the wire counters."""
    from ..utils import trace

    peers: Dict[str, Dict[str, float]] = {}
    for name, val in trace.counters().items():
        # wire.<proto>.{tx,rx}.bytes.peer<r>
        if ".bytes.peer" not in name:
            continue
        head, _, peer = name.rpartition(".peer")
        direction = "tx" if ".tx." in head else "rx"
        rec = peers.setdefault(peer, {"tx_bytes": 0.0, "rx_bytes": 0.0})
        rec[f"{direction}_bytes"] += val
    return peers


def _worst_session_skews(k: int = 8) -> List[Dict[str, Any]]:
    worst: Dict[str, Tuple[float, int]] = {}
    for name, skew_us, slowest in session_skews():
        if name not in worst or skew_us > worst[name][0]:
            worst[name] = (skew_us, slowest)
    rows = [{"collective": n, "max_skew_us": s, "slowest_rank": r}
            for n, (s, r) in worst.items()]
    rows.sort(key=lambda r: -r["max_skew_us"])
    return rows[:k]


def snapshot(rank: Optional[int] = None,
             size: Optional[int] = None) -> Dict[str, Any]:
    """The metrics-out artifact body (one per rank)."""
    from ..utils import trace

    elapsed = max(1e-9, time.time() - _t_start)
    peers = _peer_bytes()
    for rec in peers.values():
        rec["tx_bytes_per_s"] = rec["tx_bytes"] / elapsed
        rec["rx_bytes_per_s"] = rec["rx_bytes"] / elapsed
    return {
        "schema_version": SCHEMA_VERSION,
        "rank": rank,
        "size": size,
        "pid": os.getpid(),
        "elapsed_s": elapsed,
        "ops": _op_stats(),
        "peers": peers,
        "counters": trace.counters(),
        "trace_dropped_events": trace.dropped(),
        "stragglers": _worst_session_skews(),
        "collective_entries": len(collective_entries()),
    }


def validate(doc: Dict[str, Any]) -> None:
    """Raise ValueError unless ``doc`` is a well-formed metrics artifact
    (the schema contract bench.py and the observe CLI rely on)."""
    if not isinstance(doc, dict):
        raise ValueError("metrics artifact is not an object")
    if doc.get("schema_version") != SCHEMA_VERSION:
        raise ValueError(
            f"unsupported metrics schema_version {doc.get('schema_version')}")
    for key, typ in (("ops", dict), ("peers", dict), ("counters", dict),
                     ("stragglers", list), ("elapsed_s", (int, float))):
        if not isinstance(doc.get(key), typ):
            raise ValueError(f"metrics artifact field {key!r} malformed")
    for op, st in doc["ops"].items():
        for f in ("count", "p50_us", "p99_us"):
            if f not in st:
                raise ValueError(f"metrics op {op!r} missing {f!r}")


def write(path: str, rank: Optional[int] = None,
          size: Optional[int] = None) -> str:
    """Write this rank's metrics artifact. ``{rank}`` in the path is
    substituted; otherwise multi-rank jobs get a ``.rank<r>`` suffix so
    ranks never clobber each other."""
    if "{rank}" in path:
        path = path.replace("{rank}", str(rank if rank is not None else 0))
    elif size is not None and size > 1:
        path = f"{path}.rank{rank}"
    doc = snapshot(rank=rank, size=size)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return path


def summary_text(rank: Optional[int] = None,
                 size: Optional[int] = None) -> str:
    """The ``observe top`` report: bytes/s per peer, op p50/p99,
    slowest rank per collective."""
    doc = snapshot(rank=rank, size=size)
    lines = [f"mpi_tpu observe top — rank "
             f"{doc['rank'] if doc['rank'] is not None else '?'} "
             f"(pid {doc['pid']}, {doc['elapsed_s']:.1f}s)"]
    if doc["ops"]:
        lines.append(f"  {'op':<18} {'count':>8} {'p50 µs':>10} "
                     f"{'p99 µs':>10}")
        for op in sorted(doc["ops"]):
            st = doc["ops"][op]
            lines.append(f"  {op:<18} {int(st['count']):>8} "
                         f"{st['p50_us']:>10.1f} {st['p99_us']:>10.1f}")
    else:
        lines.append("  (no completed operations recorded)")
    if doc["peers"]:
        lines.append(f"  {'peer':<6} {'tx MB/s':>10} {'rx MB/s':>10} "
                     f"{'tx MB':>10} {'rx MB':>10}")
        for peer in sorted(doc["peers"], key=lambda p: int(p)):
            rec = doc["peers"][peer]
            lines.append(
                f"  {peer:<6} {rec['tx_bytes_per_s'] / 1e6:>10.2f} "
                f"{rec['rx_bytes_per_s'] / 1e6:>10.2f} "
                f"{rec['tx_bytes'] / 1e6:>10.2f} "
                f"{rec['rx_bytes'] / 1e6:>10.2f}")
    for row in doc["stragglers"]:
        lines.append(
            f"  straggler: {row['collective']:<12} max skew "
            f"{row['max_skew_us']:.1f} µs, slowest rank "
            f"{row['slowest_rank']}")
    return "\n".join(lines)


_sig_installed = False


def install_sigusr1(rank_fn=None) -> bool:
    """Print the top summary on SIGUSR1. Only possible from the main
    thread (signal module contract) — rank threads (xla driver) skip
    silently — and only when the application has not installed its own
    SIGUSR1 handler (observability must not steal a user's signal).
    Returns True when installed."""
    global _sig_installed
    if _sig_installed:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        current = signal.getsignal(signal.SIGUSR1)
    except (ValueError, AttributeError):
        return False
    if current not in (signal.SIG_DFL, signal.SIG_IGN, None):
        return False  # the application owns SIGUSR1 — leave it

    def _handler(signum, frame):  # pragma: no cover - signal timing
        try:
            r = rank_fn() if rank_fn is not None else None
        except Exception:  # noqa: BLE001
            r = None
        print(summary_text(rank=r), file=sys.stderr, flush=True)

    try:
        signal.signal(signal.SIGUSR1, _handler)
    except (ValueError, OSError, AttributeError):
        return False
    _sig_installed = True
    return True


def reset_for_testing() -> None:
    global _t_start, _sig_installed
    with _lock:
        _entries.clear()
        _entry_seq.clear()
        _skews.clear()
    _t_start = time.time()
    _sig_installed = False

"""Distributed trace collection — per-rank buffers → one aligned trace.

The process-local tracer (:mod:`mpi_tpu.utils.trace`) records spans
into a buffer that dies with its rank. This module is the job-wide
half: at Finalize (or on demand), **rank 0 gathers every rank's buffer
over the existing transport**, estimates each rank's clock offset with
a ping-style exchange, and merges everything into ONE Perfetto /
chrome://tracing JSON with one track (pid) per rank, send/receive span
pairs clock-aligned to rank 0's timeline.

Protocol (tags in the user band, chosen < 2**32 - 2**21 so the hybrid
driver's composed cross-host tags carry them; active only inside
finalize, after user traffic has drained):

  1. ping × 3 per rank: rank 0 records ``t0``, sends an empty frame,
     the peer replies with its ``time.time_ns()``, rank 0 records
     ``t1``. The minimum-RTT sample gives
     ``offset = t_peer - (t0 + t1) / 2`` (NTP's symmetric-path
     estimate; on localhost |offset| is bounded by the RTT).
  2. bundle: the peer sends its JSON bundle — span events, counters,
     the tracer's wall anchor, collective-entry records, and a flight
     summary.

Rank 0 shifts every event by ``anchor - offset`` onto its own
timeline, emits per-rank process-name metadata tracks, and computes
**cross-process straggler skew** from the clock-aligned collective
entries. Every receive is bounded (default 60 s,
``MPI_TPU_OBSERVE_TIMEOUT``) so a crashed rank stalls collection, not
the job: missing ranks are noted in the merged metadata and skipped.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils import trace
from . import flight, metrics

__all__ = ["OBS_TAG_BASE", "collect_and_merge", "local_bundle",
           "merge_bundles", "estimate_offsets"]

# 0xB5E00000 < 2**32 - 2**21: legal as a hybrid cross-host composed tag.
OBS_TAG_BASE = 0xB5E00000
_T_PING = OBS_TAG_BASE + 1
_T_PONG = OBS_TAG_BASE + 2
_T_BUNDLE = OBS_TAG_BASE + 3
_PINGS = 3


def _timeout() -> float:
    try:
        return float(os.environ.get("MPI_TPU_OBSERVE_TIMEOUT", "60"))
    except ValueError:
        return 60.0


def _bounded(fn: Callable[[], Any], timeout: float, what: str) -> Any:
    """Run a blocking transport call with a hard deadline: a crashed
    peer must stall trace collection, not finalize. The worker is a
    daemon thread (xla rank bindings inherit while run_spmd is active);
    on timeout it is abandoned — the transport teardown that follows
    finalize unblocks it."""
    box: List[Any] = [None]
    err: List[Optional[BaseException]] = [None]
    done = threading.Event()

    def run() -> None:
        try:
            box[0] = fn()
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            err[0] = exc
        done.set()

    t = threading.Thread(target=run, daemon=True, name="mpi-observe")
    t.start()
    if not done.wait(timeout):
        raise TimeoutError(f"mpi_tpu: observe {what} timed out "
                           f"after {timeout:g}s")
    if err[0] is not None:
        raise err[0]
    return box[0]


def local_bundle(rank: int) -> Dict[str, Any]:
    """This rank's contribution to the merged trace."""
    return {
        "rank": rank,
        "pid": os.getpid(),
        "anchor_ns": trace.wall_anchor_ns(),
        "events": trace.events(),
        "counters": trace.counters(),
        "dropped": trace.dropped(),
        "collective_entries": metrics.collective_entries(),
        "flight": {"op_counts": flight.snapshot()["op_counts"]},
    }


def estimate_offsets(samples: List[Dict[str, float]]) -> Dict[str, float]:
    """min-RTT offset estimate from ping samples
    [{t0_ns, t1_ns, peer_ns}, ...] → {offset_ns, rtt_ns}."""
    best = min(samples, key=lambda s: s["t1_ns"] - s["t0_ns"])
    rtt = best["t1_ns"] - best["t0_ns"]
    offset = best["peer_ns"] - (best["t0_ns"] + best["t1_ns"]) / 2.0
    return {"offset_ns": offset, "rtt_ns": rtt}


def _aligned_entries(bundles: Dict[int, Dict[str, Any]],
                     offsets: Dict[int, Dict[str, float]]
                     ) -> List[Dict[str, Any]]:
    """Cross-process straggler skew: group collective-entry records by
    (name, seq) and compare clock-aligned arrival times across ranks."""
    by_key: Dict[tuple, List[tuple]] = {}
    for r, b in bundles.items():
        off = offsets.get(r, {}).get("offset_ns", 0.0)
        for name, seq, wall_ns in b.get("collective_entries", []):
            by_key.setdefault((name, seq), []).append((r, wall_ns - off))
    nranks = len(bundles)
    rows = []
    for (name, seq), arrivals in by_key.items():
        if len(arrivals) < max(2, nranks):
            continue  # a rank missed it (crash/cap) — skew undefined
        ts = [t for _, t in arrivals]
        skew_us = (max(ts) - min(ts)) / 1e3
        slowest = max(arrivals, key=lambda a: a[1])[0]
        rows.append({"collective": name, "seq": seq,
                     "skew_us": skew_us, "slowest_rank": slowest})
    rows.sort(key=lambda r: -r["skew_us"])
    return rows


def merge_bundles(bundles: Dict[int, Dict[str, Any]],
                  offsets: Dict[int, Dict[str, float]],
                  missing: Optional[List[int]] = None) -> Dict[str, Any]:
    """Merge per-rank bundles into one chrome-trace document: pid =
    rank (one track per rank), timestamps clock-aligned to rank 0."""
    base = None
    events: List[Dict[str, Any]] = []
    for r in sorted(bundles):
        b = bundles[r]
        off = offsets.get(r, {}).get("offset_ns", 0.0)
        events.append({"name": "process_name", "ph": "M", "pid": r,
                       "args": {"name": f"rank {r} (pid {b['pid']})"}})
        for e in b["events"]:
            abs_us = e["ts_us"] + (b["anchor_ns"] - off) / 1e3
            if base is None or abs_us < base:
                base = abs_us
            events.append({
                "name": e["name"],
                "ph": "X",
                "ts": abs_us,
                "dur": e["dur_us"],
                "pid": r,
                "tid": e.get("thread", "main"),
                "args": {k: v for k, v in e.items()
                         if k not in ("name", "ts_us", "dur_us", "thread")},
            })
    # Rebase to the earliest event so viewers don't render epoch offsets.
    base = base or 0.0
    for e in events:
        if e["ph"] == "X":
            e["ts"] -= base
    stragglers = _aligned_entries(bundles, offsets)
    return {
        "traceEvents": events,
        "metadata": {
            "ranks": sorted(bundles),
            "missing_ranks": sorted(missing or []),
            "clock_offsets_us": {str(r): o["offset_ns"] / 1e3
                                 for r, o in offsets.items()},
            "clock_rtt_us": {str(r): o["rtt_ns"] / 1e3
                             for r, o in offsets.items()},
            "counters_by_rank": {str(r): b["counters"]
                                 for r, b in bundles.items()},
            "dropped_by_rank": {str(r): b["dropped"]
                                for r, b in bundles.items()},
            "stragglers": stragglers[:64],
        },
    }


def collect_and_merge(impl: Any, out_path: str) -> Optional[str]:
    """The Finalize-time gather. COLLECTIVE: every rank must call this
    (the facade's finalize does, when ``--mpi-trace-out`` is set on all
    ranks). Rank 0 writes the merged JSON and returns its path; other
    ranks return None.

    Drivers whose ranks are THREADS of one process (xla; hybrid's
    local tier) share one tracer buffer — a per-rank gather would
    duplicate every span into every track and fabricate straggler
    rows. Such drivers declare ``SHARED_PROCESS_TRACER`` and rank 0
    writes the shared buffer once, one process track with per-rank
    thread lanes (tid = rank-thread name); cross-rank skew for them
    comes from the exact in-process session stamps instead
    (:func:`mpi_tpu.observe.metrics.note_session_skew`)."""
    rank, size = impl.rank(), impl.size()
    timeout = _timeout()
    if size == 1 or getattr(impl, "SHARED_PROCESS_TRACER", False):
        if rank != 0:
            return None
        doc = merge_bundles({0: local_bundle(0)},
                            {0: {"offset_ns": 0.0, "rtt_ns": 0.0}})
        if size > 1:
            doc["metadata"]["shared_process_tracer"] = True
            doc["metadata"]["ranks"] = list(range(size))
        _write(out_path, doc)
        return out_path

    # The gather's own waits are bounded by _bounded; the transport's
    # per-op deadline (--mpi-optimeout, often a few seconds) must not
    # preempt them — a rank legitimately waits through earlier ranks'
    # turns far longer than any op deadline. Suspend it for the
    # collection and restore on the way out.
    had_optimeout = hasattr(impl, "optimeout")
    saved_optimeout = getattr(impl, "optimeout", None)
    if had_optimeout:
        impl.optimeout = None
    try:
        return _gather(impl, rank, size, timeout, out_path)
    finally:
        if had_optimeout:
            impl.optimeout = saved_optimeout


def _gather(impl: Any, rank: int, size: int, timeout: float,
            out_path: str) -> Optional[str]:
    if rank != 0:
        # The gather is serial from rank 0's side: rank k may
        # legitimately wait through k-1 earlier ranks' turns before
        # its ping arrives, so the first wait scales with world size
        # (rank 0's own per-step waits stay at one `timeout`, which is
        # what bounds the cost of a dead rank).
        first_wait = timeout * max(1, size - 1)
        _bounded(lambda: impl.receive(0, _T_PING), first_wait,
                 "ping wait")
        _bounded(lambda: impl.send(
            str(time.time_ns()).encode("ascii"), 0, _T_PONG),
            timeout, "pong send")
        for _ in range(_PINGS - 1):
            _bounded(lambda: impl.receive(0, _T_PING), timeout, "ping wait")
            _bounded(lambda: impl.send(
                str(time.time_ns()).encode("ascii"), 0, _T_PONG),
                timeout, "pong send")
        payload = json.dumps(local_bundle(rank)).encode("utf-8")
        _bounded(lambda: impl.send(payload, 0, _T_BUNDLE), timeout,
                 "bundle send")
        return None

    bundles = {0: local_bundle(0)}
    offsets: Dict[int, Dict[str, float]] = {
        0: {"offset_ns": 0.0, "rtt_ns": 0.0}}
    missing: List[int] = []
    for src in range(1, size):
        try:
            samples = []
            for _ in range(_PINGS):
                t0 = time.time_ns()
                # Bounded like the receives: a dead rank-thread on the
                # in-process drivers would park a blocking rendezvous
                # send forever.
                _bounded(lambda: impl.send(b"", src, _T_PING), timeout,
                         "ping send")
                peer_ns = int(bytes(_bounded(
                    lambda: impl.receive(src, _T_PONG), timeout,
                    "pong")).decode("ascii"))
                t1 = time.time_ns()
                samples.append({"t0_ns": t0, "t1_ns": t1,
                                "peer_ns": peer_ns})
            offsets[src] = estimate_offsets(samples)
            raw = _bounded(lambda: impl.receive(src, _T_BUNDLE), timeout,
                           "bundle")
            bundles[src] = json.loads(bytes(raw).decode("utf-8"))
        except Exception as exc:  # noqa: BLE001 - skip dead ranks
            import sys as _sys

            print(f"mpi_tpu: observe: skipping rank {src} in trace "
                  f"collection: {exc}", file=_sys.stderr)
            missing.append(src)
    doc = merge_bundles(bundles, offsets, missing=missing)
    _write(out_path, doc)
    return out_path


def _write(path: str, doc: Dict[str, Any]) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)

"""Distributed trace collection — per-rank buffers → one aligned trace.

The process-local tracer (:mod:`mpi_tpu.utils.trace`) records spans
into a buffer that dies with its rank. This module is the job-wide
half: at Finalize (or on demand), **rank 0 gathers every rank's buffer
over the existing transport**, estimates each rank's clock offset with
a ping-style exchange, and merges everything into ONE Perfetto /
chrome://tracing JSON with one track (pid) per rank, send/receive span
pairs clock-aligned to rank 0's timeline.

Protocol (tags in the user band, chosen < 2**32 - 2**21 so the hybrid
driver's composed cross-host tags carry them; active only inside
finalize, after user traffic has drained):

  1. ping × 3 per rank: rank 0 records ``t0``, sends an empty frame,
     the peer replies with its ``time.time_ns()``, rank 0 records
     ``t1``. The minimum-RTT sample gives
     ``offset = t_peer - (t0 + t1) / 2`` (NTP's symmetric-path
     estimate; on localhost |offset| is bounded by the RTT).
  2. bundle: the peer sends its JSON bundle — span events, counters,
     the tracer's wall anchor, collective-entry records, and a flight
     summary.

Rank 0 shifts every event by ``anchor - offset`` onto its own
timeline, emits per-rank process-name metadata tracks, and computes
**cross-process straggler skew** from the clock-aligned collective
entries. Every receive is bounded (default 60 s,
``MPI_TPU_OBSERVE_TIMEOUT``) so a crashed rank stalls collection, not
the job: missing ranks are noted in the merged metadata and skipped —
unless streaming spooling (``--mpi-trace-stream``) is active, in which
case rank 0 reconstructs a dead rank's track from its spool file
(:mod:`.stream`), so even a SIGKILL'd rank appears in the merged trace
up to its last flushed chunk.

Hybrid cross-host merge: the hybrid driver's ranks are threads sharing
one process tracer per host, so the per-rank gather above would ship
the same buffer N times. Instead one leader thread per host (local
rank 0) runs the same ping/bundle protocol over the DCN/tcp tier
(:func:`_gather_hosts`), and host 0 merges one track per host with
per-host clock alignment — the merged trace carries wire spans from
every host, not just rank 0's.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils import trace
from . import flight, metrics

__all__ = ["OBS_TAG_BASE", "collect_and_merge", "local_bundle",
           "merge_bundles", "estimate_offsets"]

# 0xB5E00000 < 2**32 - 2**21: legal as a hybrid cross-host composed tag.
OBS_TAG_BASE = 0xB5E00000
_T_PING = OBS_TAG_BASE + 1
_T_PONG = OBS_TAG_BASE + 2
_T_BUNDLE = OBS_TAG_BASE + 3
_PINGS = 3


def _timeout() -> float:
    try:
        return float(os.environ.get("MPI_TPU_OBSERVE_TIMEOUT", "60"))
    except ValueError:
        return 60.0


def _bounded(fn: Callable[[], Any], timeout: float, what: str) -> Any:
    """Run a blocking transport call with a hard deadline: a crashed
    peer must stall trace collection, not finalize. The worker is a
    daemon thread (xla rank bindings inherit while run_spmd is active);
    on timeout it is abandoned — the transport teardown that follows
    finalize unblocks it."""
    box: List[Any] = [None]
    err: List[Optional[BaseException]] = [None]
    done = threading.Event()

    def run() -> None:
        try:
            box[0] = fn()
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            err[0] = exc
        done.set()

    t = threading.Thread(target=run, daemon=True, name="mpi-observe")
    t.start()
    if not done.wait(timeout):
        raise TimeoutError(f"mpi_tpu: observe {what} timed out "
                           f"after {timeout:g}s")
    if err[0] is not None:
        raise err[0]
    return box[0]


def local_bundle(rank: int) -> Dict[str, Any]:
    """This rank's contribution to the merged trace. Under streaming
    spooling the resident buffer holds only the unflushed tail; the
    already-spooled chunks are read back and prepended so the gathered
    bundle is complete either way."""
    bundle = {
        "rank": rank,
        "pid": os.getpid(),
        "anchor_ns": trace.wall_anchor_ns(),
        "events": trace.events(),
        "counters": trace.counters(),
        "dropped": trace.dropped(),
        "collective_entries": metrics.collective_entries(),
        "flight": {"op_counts": flight.snapshot()["op_counts"]},
    }
    st = trace.stream()
    if st is not None and st.path is not None:
        try:
            spooled = st.read_back_events()
        except Exception:  # noqa: BLE001 - spool is best-effort
            spooled = []
        if spooled:
            bundle["events"] = spooled + bundle["events"]
        bundle["spool"] = st.path
        bundle["spool_chunks"] = st.chunks_written
    return bundle


def estimate_offsets(samples: List[Dict[str, float]]) -> Dict[str, float]:
    """min-RTT offset estimate from ping samples
    [{t0_ns, t1_ns, peer_ns}, ...] → {offset_ns, rtt_ns}."""
    best = min(samples, key=lambda s: s["t1_ns"] - s["t0_ns"])
    rtt = best["t1_ns"] - best["t0_ns"]
    offset = best["peer_ns"] - (best["t0_ns"] + best["t1_ns"]) / 2.0
    return {"offset_ns": offset, "rtt_ns": rtt}


def _aligned_entries(bundles: Dict[int, Dict[str, Any]],
                     offsets: Dict[int, Dict[str, float]]
                     ) -> List[Dict[str, Any]]:
    """Cross-process straggler skew: group collective-entry records by
    (name, seq) and compare clock-aligned arrival times across ranks."""
    by_key: Dict[tuple, List[tuple]] = {}
    for r, b in bundles.items():
        off = offsets.get(r, {}).get("offset_ns", 0.0)
        for name, seq, wall_ns in b.get("collective_entries", []):
            by_key.setdefault((name, seq), []).append((r, wall_ns - off))
    nranks = len(bundles)
    rows = []
    for (name, seq), arrivals in by_key.items():
        if len(arrivals) < max(2, nranks):
            continue  # a rank missed it (crash/cap) — skew undefined
        ts = [t for _, t in arrivals]
        skew_us = (max(ts) - min(ts)) / 1e3
        slowest = max(arrivals, key=lambda a: a[1])[0]
        rows.append({"collective": name, "seq": seq,
                     "skew_us": skew_us, "slowest_rank": slowest})
    rows.sort(key=lambda r: -r["skew_us"])
    return rows


def merge_bundles(bundles: Dict[int, Dict[str, Any]],
                  offsets: Dict[int, Dict[str, float]],
                  missing: Optional[List[int]] = None,
                  labels: Optional[Dict[int, str]] = None
                  ) -> Dict[str, Any]:
    """Merge per-rank bundles into one chrome-trace document: pid =
    rank (one track per rank), timestamps clock-aligned to rank 0.
    ``labels`` overrides a track's process-name metadata (the hybrid
    cross-host merge labels tracks by host + rank range)."""
    base = None
    events: List[Dict[str, Any]] = []
    for r in sorted(bundles):
        b = bundles[r]
        off = offsets.get(r, {}).get("offset_ns", 0.0)
        label = (labels or {}).get(r) or f"rank {r} (pid {b['pid']})"
        events.append({"name": "process_name", "ph": "M", "pid": r,
                       "args": {"name": label}})
        for e in b["events"]:
            abs_us = e["ts_us"] + (b["anchor_ns"] - off) / 1e3
            if base is None or abs_us < base:
                base = abs_us
            events.append({
                "name": e["name"],
                "ph": "X",
                "ts": abs_us,
                "dur": e["dur_us"],
                "pid": r,
                "tid": e.get("thread", "main"),
                "args": {k: v for k, v in e.items()
                         if k not in ("name", "ts_us", "dur_us", "thread")},
            })
    # Rebase to the earliest event so viewers don't render epoch offsets.
    base = base or 0.0
    for e in events:
        if e["ph"] == "X":
            e["ts"] -= base
    stragglers = _aligned_entries(bundles, offsets)
    return {
        "traceEvents": events,
        "metadata": {
            "ranks": sorted(bundles),
            "missing_ranks": sorted(missing or []),
            "clock_offsets_us": {str(r): o["offset_ns"] / 1e3
                                 for r, o in offsets.items()},
            "clock_rtt_us": {str(r): o["rtt_ns"] / 1e3
                             for r, o in offsets.items()},
            "counters_by_rank": {str(r): b["counters"]
                                 for r, b in bundles.items()},
            "dropped_by_rank": {str(r): b["dropped"]
                                for r, b in bundles.items()},
            "stragglers": stragglers[:64],
        },
    }


def collect_and_merge(impl: Any, out_path: str) -> Optional[str]:
    """The Finalize-time gather. COLLECTIVE: every rank must call this
    (the facade's finalize does, when ``--mpi-trace-out`` is set on all
    ranks). Rank 0 writes the merged JSON and returns its path; other
    ranks return None.

    Drivers whose ranks are THREADS of one process (xla; hybrid's
    local tier) share one tracer buffer — a per-rank gather would
    duplicate every span into every track and fabricate straggler
    rows. Such drivers declare ``SHARED_PROCESS_TRACER`` and rank 0
    writes the shared buffer once, one process track with per-rank
    thread lanes (tid = rank-thread name); cross-rank skew for them
    comes from the exact in-process session stamps instead
    (:func:`mpi_tpu.observe.metrics.note_session_skew`)."""
    rank, size = impl.rank(), impl.size()
    timeout = _timeout()
    if size == 1 or getattr(impl, "SHARED_PROCESS_TRACER", False):
        # Hybrid: ranks are threads per host, but hosts are separate
        # processes linked by the tcp tier — gather per HOST over it so
        # the merged trace carries every host's buffer, not just rank
        # 0's (tentpole 3). Degrades to the single-host document when
        # the driver has no multi-host tcp tier (xla) or the cross-host
        # gather fails.
        tcp = getattr(impl, "_tcp", None)
        try:
            nhosts = tcp.size() if tcp is not None else 1
        except Exception:  # noqa: BLE001
            nhosts = 1
        if nhosts > 1:
            try:
                return _gather_hosts(impl, tcp, nhosts, size, timeout,
                                     out_path)
            except Exception as exc:  # noqa: BLE001
                print(f"mpi_tpu: observe: cross-host trace merge failed "
                      f"({exc}); falling back to rank 0's host",
                      file=sys.stderr)
        if rank != 0:
            return None
        doc = merge_bundles({0: local_bundle(0)},
                            {0: {"offset_ns": 0.0, "rtt_ns": 0.0}})
        if size > 1:
            doc["metadata"]["shared_process_tracer"] = True
            doc["metadata"]["ranks"] = list(range(size))
        _write(out_path, doc)
        return out_path

    # The gather's own waits are bounded by _bounded; the transport's
    # per-op deadline (--mpi-optimeout, often a few seconds) must not
    # preempt them — a rank legitimately waits through earlier ranks'
    # turns far longer than any op deadline. Suspend it for the
    # collection and restore on the way out.
    had_optimeout = hasattr(impl, "optimeout")
    saved_optimeout = getattr(impl, "optimeout", None)
    if had_optimeout:
        impl.optimeout = None
    try:
        return _gather(impl, rank, size, timeout, out_path)
    finally:
        if had_optimeout:
            impl.optimeout = saved_optimeout


def _gather(impl: Any, rank: int, size: int, timeout: float,
            out_path: str) -> Optional[str]:
    if rank != 0:
        # The gather is serial from rank 0's side: rank k may
        # legitimately wait through k-1 earlier ranks' turns before
        # its ping arrives, so the first wait scales with world size
        # (rank 0's own per-step waits stay at one `timeout`, which is
        # what bounds the cost of a dead rank).
        first_wait = timeout * max(1, size - 1)
        _bounded(lambda: impl.receive(0, _T_PING), first_wait,
                 "ping wait")
        _bounded(lambda: impl.send(
            str(time.time_ns()).encode("ascii"), 0, _T_PONG),
            timeout, "pong send")
        for _ in range(_PINGS - 1):
            _bounded(lambda: impl.receive(0, _T_PING), timeout, "ping wait")
            _bounded(lambda: impl.send(
                str(time.time_ns()).encode("ascii"), 0, _T_PONG),
                timeout, "pong send")
        payload = json.dumps(local_bundle(rank)).encode("utf-8")
        _bounded(lambda: impl.send(payload, 0, _T_BUNDLE), timeout,
                 "bundle send")
        return None

    bundles = {0: local_bundle(0)}
    offsets: Dict[int, Dict[str, float]] = {
        0: {"offset_ns": 0.0, "rtt_ns": 0.0}}
    missing: List[int] = []
    for src in range(1, size):
        try:
            samples = []
            for _ in range(_PINGS):
                t0 = time.time_ns()
                # Bounded like the receives: a dead rank-thread on the
                # in-process drivers would park a blocking rendezvous
                # send forever.
                _bounded(lambda: impl.send(b"", src, _T_PING), timeout,
                         "ping send")
                peer_ns = int(bytes(_bounded(
                    lambda: impl.receive(src, _T_PONG), timeout,
                    "pong")).decode("ascii"))
                t1 = time.time_ns()
                samples.append({"t0_ns": t0, "t1_ns": t1,
                                "peer_ns": peer_ns})
            offsets[src] = estimate_offsets(samples)
            raw = _bounded(lambda: impl.receive(src, _T_BUNDLE), timeout,
                           "bundle")
            bundles[src] = json.loads(bytes(raw).decode("utf-8"))
        except Exception as exc:  # noqa: BLE001 - skip dead ranks
            print(f"mpi_tpu: observe: skipping rank {src} in trace "
                  f"collection: {exc}", file=sys.stderr)
            missing.append(src)
    recovered = _recover_from_spools(bundles, offsets, missing)
    doc = merge_bundles(bundles, offsets, missing=missing)
    if recovered:
        doc["metadata"]["spool_reconstructed_ranks"] = sorted(recovered)
    _write(out_path, doc)
    return out_path


def _recover_from_spools(bundles: Dict[int, Dict[str, Any]],
                         offsets: Dict[int, Dict[str, float]],
                         missing: List[int]) -> List[int]:
    """Rebuild dead ranks' tracks from their spool files. A rank that
    died (SIGKILL, chaos crash, hang) never answered the gather, but
    under ``--mpi-trace-stream`` everything it flushed survives on
    disk; fold it back in so the merged trace shows what the dead rank
    was doing. The rank stays in ``missing_ranks`` — it IS dead — and
    is additionally listed in ``spool_reconstructed_ranks``."""
    if not missing:
        return []
    recovered: List[int] = []
    try:
        from .. import observe as _observe
        from . import stream as _stream

        spool_dir = _observe.trace_stream_dir()
        if not spool_dir:
            return []
        found = _stream.scan_spools(spool_dir)
        for src in missing:
            b = found.get(src)
            if b is None:
                continue
            bundles[src] = b
            # Same-machine launch (mpirun): spool anchors share rank
            # 0's wall clock, so a zero offset is the right estimate.
            offsets.setdefault(src, {"offset_ns": 0.0, "rtt_ns": 0.0})
            recovered.append(src)
        if recovered:
            print(f"mpi_tpu: observe: reconstructed rank(s) "
                  f"{sorted(recovered)} from trace spool(s) in "
                  f"{spool_dir}", file=sys.stderr)
    except Exception as exc:  # noqa: BLE001 - recovery is best-effort
        print(f"mpi_tpu: observe: spool reconstruction failed: {exc}",
              file=sys.stderr)
    return recovered


def _gather_hosts(impl: Any, tcp: Any, nhosts: int, size: int,
                  timeout: float, out_path: str) -> Optional[str]:
    """Hybrid cross-host merge. One leader thread per host — the thread
    whose local rank is 0 — ships the host's shared process tracer
    buffer to host 0 over the DCN/tcp tier with the same ping/pong
    clock-offset exchange as the per-rank gather; every other rank
    thread returns immediately. Host 0 merges one chrome-trace track
    per host (pid = the host's first global rank), labelled with the
    host index and its global-rank range."""
    local = impl._local()
    my_host = tcp.rank()
    host_offsets = list(impl._offsets)
    host_counts = list(impl._counts)
    if local != 0:
        return None

    def host_bundle() -> Dict[str, Any]:
        b = local_bundle(host_offsets[my_host])
        b["host"] = my_host
        b["ranks"] = list(range(
            host_offsets[my_host],
            host_offsets[my_host] + host_counts[my_host]))
        return b

    # The tcp tier's per-op deadline must not preempt the gather's own
    # bounded waits (same reasoning as collect_and_merge's suspension,
    # which does not reach this inner network).
    saved_optimeout = getattr(tcp, "optimeout", None)
    if hasattr(tcp, "optimeout"):
        tcp.optimeout = None
    try:
        if my_host != 0:
            first_wait = timeout * max(1, nhosts - 1)
            _bounded(lambda: tcp.receive(0, _T_PING), first_wait,
                     "host ping wait")
            _bounded(lambda: tcp.send(
                str(time.time_ns()).encode("ascii"), 0, _T_PONG),
                timeout, "host pong send")
            for _ in range(_PINGS - 1):
                _bounded(lambda: tcp.receive(0, _T_PING), timeout,
                         "host ping wait")
                _bounded(lambda: tcp.send(
                    str(time.time_ns()).encode("ascii"), 0, _T_PONG),
                    timeout, "host pong send")
            payload = json.dumps(host_bundle()).encode("utf-8")
            _bounded(lambda: tcp.send(payload, 0, _T_BUNDLE), timeout,
                     "host bundle send")
            return None

        host_bundles: Dict[int, Dict[str, Any]] = {0: host_bundle()}
        host_clock: Dict[int, Dict[str, float]] = {
            0: {"offset_ns": 0.0, "rtt_ns": 0.0}}
        missing_hosts: List[int] = []
        shared_hosts: List[int] = []
        for h in range(1, nhosts):
            try:
                samples = []
                for _ in range(_PINGS):
                    t0 = time.time_ns()
                    _bounded(lambda: tcp.send(b"", h, _T_PING), timeout,
                             "host ping send")
                    peer_ns = int(bytes(_bounded(
                        lambda: tcp.receive(h, _T_PONG), timeout,
                        "host pong")).decode("ascii"))
                    t1 = time.time_ns()
                    samples.append({"t0_ns": t0, "t1_ns": t1,
                                    "peer_ns": peer_ns})
                raw = _bounded(lambda: tcp.receive(h, _T_BUNDLE), timeout,
                               "host bundle")
                b = json.loads(bytes(raw).decode("utf-8"))
                if b.get("pid") == os.getpid():
                    # Multi-host-in-one-process worlds (tests, bench)
                    # share ONE tracer: this "remote" host's buffer is
                    # the same buffer host 0 already contributed, so
                    # keeping it would duplicate every span. Its spans
                    # are present via host 0's track.
                    shared_hosts.append(h)
                    continue
                host_bundles[h] = b
                host_clock[h] = estimate_offsets(samples)
            except Exception as exc:  # noqa: BLE001 - skip dead hosts
                print(f"mpi_tpu: observe: skipping host {h} in "
                      f"cross-host trace merge: {exc}", file=sys.stderr)
                missing_hosts.append(h)

        # Track key = the host's first global rank (so tracks sort in
        # rank order in viewers); tid lanes inside a track remain the
        # per-rank thread names.
        bundles = {host_offsets[h]: b for h, b in host_bundles.items()}
        offsets = {host_offsets[h]: o for h, o in host_clock.items()}
        labels = {
            host_offsets[h]: (
                f"host {h} ranks {host_offsets[h]}.."
                f"{host_offsets[h] + host_counts[h] - 1} "
                f"(pid {b['pid']})")
            for h, b in host_bundles.items()}
        missing_ranks = [r for h in missing_hosts
                         for r in range(host_offsets[h],
                                        host_offsets[h] + host_counts[h])]
        doc = merge_bundles(bundles, offsets, missing=missing_ranks,
                            labels=labels)
        doc["metadata"].update({
            "shared_process_tracer": True,
            "ranks": list(range(size)),
            "hosts": nhosts,
            "hosts_merged": sorted(host_bundles),
            "hosts_missing": sorted(missing_hosts),
            "hosts_in_gatherer_process": sorted(shared_hosts),
            "ranks_by_host": {
                str(h): list(range(host_offsets[h],
                                   host_offsets[h] + host_counts[h]))
                for h in range(nhosts)},
        })
        _write(out_path, doc)
        return out_path
    finally:
        if hasattr(tcp, "optimeout"):
            tcp.optimeout = saved_optimeout


def _write(path: str, doc: Dict[str, Any]) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)

"""mpi_tpu.observe — job-wide observability layer.

Three pillars on top of the process-local tracer
(:mod:`mpi_tpu.utils.trace`):

  * **distributed trace collection** (:mod:`.collect`) — every rank
    records spans/counters locally (the facade and the tcp/shm/xla/
    hybrid wire paths are instrumented); at Finalize rank 0 gathers all
    buffers over the existing transport, estimates per-rank clock
    offsets with a ping exchange, and merges one Perfetto/chrome-trace
    JSON with one track per rank (``--mpi-trace-out`` /
    ``MPI_TPU_TRACE_OUT``, with ``MPI_TPU_TRACE=1``);
  * **flight recorder** (:mod:`.flight`) — a bounded ring of the last N
    operations per rank, dumped to a per-rank postmortem file on fatal
    typed errors and chaos crashes (``--mpi-postmortem`` /
    ``MPI_TPU_POSTMORTEM_DIR``); ``mpirun`` folds survivors' dumps into
    one job report;
  * **streaming trace spooling** (:mod:`.stream`) — with
    ``--mpi-trace-stream DIR`` (``MPI_TPU_TRACE_STREAM``) each rank
    flushes bounded span chunks to a per-rank spool file continuously,
    keeping tracer memory O(chunk) and making everything already
    flushed crash-durable: the Finalize gather reads spools back, rank
    0 reconstructs dead ranks' tracks from their spool files, and
    ``mpirun`` can rebuild a merged trace from spools alone;
  * **live metrics + straggler detection** (:mod:`.metrics`) —
    per-collective arrival skew, an ``observe top`` text summary on
    SIGUSR1 or at Finalize (``MPI_TPU_OBSERVE_SUMMARY=1``), and a
    machine-readable ``--mpi-metrics-out`` JSON artifact
    (``MPI_TPU_METRICS_OUT``) that bench.py folds into BENCH rounds.

The facade (:mod:`mpi_tpu.api`) calls :func:`on_init` after a
successful ``init()`` and :func:`on_finalize` at the top of
``finalize()``; both are defensive — observability must never take a
job down. See docs/OBSERVABILITY.md for the operator's guide and the
overhead budget.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Optional, Set, Tuple

from . import flight, metrics  # noqa: F401 - re-exported submodules

__all__ = ["flight", "metrics", "on_init", "on_finalize",
           "postmortem_dir", "trace_out_path", "metrics_out_path",
           "trace_stream_dir", "summary_enabled", "fatal_error_hook",
           "reset_for_testing"]

# Fatal typed failures that trigger a flight-recorder postmortem (by
# class name: the backends that define them import lazily, and a name
# match avoids the import cycle at error time).
_FATAL_NAMES = frozenset({
    "RemoteAbortError", "DeadlineError", "PeerDeadError", "ChecksumError",
})

_cfg_lock = threading.Lock()
_cfg: Optional[dict] = None
_collected: Set[Tuple[int, int]] = set()
_metrics_written: Set[Tuple[int, int]] = set()
_spooler: Optional[Any] = None


def _flag_or_env(flag: str, env: str) -> Optional[str]:
    from .. import flags as flagmod

    found = flagmod.scan_argv({flag})
    return found.get(flag) or os.environ.get(env) or None


def _config() -> dict:
    """Resolve the observe flags once per process (same precedence as
    the core ``-mpi-*`` flags: argv > env)."""
    global _cfg
    with _cfg_lock:
        if _cfg is None:
            from .. import flags as flagmod

            _cfg = {
                "trace_out": _flag_or_env(flagmod.FLAG_TRACE_OUT,
                                          flagmod.ENV_TRACE_OUT),
                "metrics_out": _flag_or_env(flagmod.FLAG_METRICS_OUT,
                                            flagmod.ENV_METRICS_OUT),
                "postmortem": _flag_or_env(flagmod.FLAG_POSTMORTEM,
                                           flagmod.ENV_POSTMORTEM),
                "trace_stream": _flag_or_env(flagmod.FLAG_TRACE_STREAM,
                                             flagmod.ENV_TRACE_STREAM),
            }
        return _cfg


def postmortem_dir() -> Optional[str]:
    return _config()["postmortem"]


def trace_out_path() -> Optional[str]:
    return _config()["trace_out"]


def metrics_out_path() -> Optional[str]:
    return _config()["metrics_out"]


def trace_stream_dir() -> Optional[str]:
    return _config()["trace_stream"]


def summary_enabled() -> bool:
    return os.environ.get("MPI_TPU_OBSERVE_SUMMARY", "").strip() not in (
        "", "0")


def on_init(impl: Any) -> None:
    """Post-``init()`` hook: bind the flight recorder to this rank,
    install the SIGUSR1 top handler (main thread only), and implicitly
    enable span recording when a trace sink is configured."""
    try:
        flight.set_rank(impl.rank())
    except Exception:  # noqa: BLE001 - never take init down
        pass
    try:
        from ..utils import trace

        if (trace_out_path() or trace_stream_dir()) and not trace.enabled():
            trace.enable()
        _install_spooler(impl)
        metrics.install_sigusr1(rank_fn=impl.rank)
    except Exception:  # noqa: BLE001
        pass


def _install_spooler(impl: Any) -> None:
    """Start streaming this process's tracer to a per-rank spool file.
    One spooler per process: under the hybrid driver every local rank
    thread shares the process tracer, so they share the spool too (the
    file is labelled with the first rank to init)."""
    global _spooler
    directory = trace_stream_dir()
    if not directory:
        return
    from ..utils import trace

    with _cfg_lock:
        if _spooler is not None:
            return
        from . import stream

        _spooler = stream.SpoolWriter(directory)
    try:
        _spooler.set_rank(impl.rank())
    except Exception:  # noqa: BLE001
        pass
    trace.set_stream(_spooler)


def on_finalize(impl: Any) -> None:
    """Pre-teardown hook, called from the facade's ``finalize()`` while
    the transport is still up. Collective when trace collection is
    configured (every rank's finalize participates in the gather); each
    step runs once per (backend, rank) even if finalize is re-entered."""
    try:
        rank, size = impl.rank(), impl.size()
    except Exception:  # noqa: BLE001 - backend already down
        return
    key = (id(impl), rank)

    cfg = _config()
    from ..utils import trace

    if cfg["trace_stream"]:
        # Push the resident tail out and stamp the footer BEFORE the
        # gather, so the spool is a complete standalone record and the
        # gather's spool read-back sees every span.
        try:
            trace.flush_stream()
            st = trace.stream()
            if st is not None:
                st.write_footer()
        except Exception:  # noqa: BLE001
            pass

    if cfg["trace_out"] and trace.enabled():
        with _cfg_lock:
            fresh = key not in _collected
            _collected.add(key)
        if fresh:
            try:
                from . import collect

                path = collect.collect_and_merge(impl, cfg["trace_out"])
                if path:
                    print(f"mpi_tpu: observe: merged trace written to "
                          f"{path}", file=sys.stderr)
            except Exception as exc:  # noqa: BLE001
                print(f"mpi_tpu: observe: trace collection failed: "
                      f"{exc}", file=sys.stderr)

    if cfg["metrics_out"]:
        with _cfg_lock:
            fresh = key not in _metrics_written
            _metrics_written.add(key)
        if fresh:
            try:
                metrics.write(cfg["metrics_out"], rank=rank, size=size)
            except Exception as exc:  # noqa: BLE001
                print(f"mpi_tpu: observe: metrics write failed: {exc}",
                      file=sys.stderr)

    if summary_enabled():
        try:
            print(metrics.summary_text(rank=rank, size=size),
                  file=sys.stderr, flush=True)
        except Exception:  # noqa: BLE001
            pass


def fatal_error_hook(exc: BaseException) -> None:
    """Called by the facade's error dispatch for every MpiError: the
    first FATAL typed failure (abort/deadline/peer-death/corruption)
    dumps this rank's flight-recorder postmortem."""
    if type(exc).__name__ not in _FATAL_NAMES:
        return
    try:
        # Make this rank's last spans durable before anything else: the
        # process may be about to die without reaching finalize.
        from ..utils import trace

        trace.flush_stream()
    except Exception:  # noqa: BLE001
        pass
    try:
        path = flight.dump(f"{type(exc).__name__}: {exc}")
        if path:
            print(f"mpi_tpu: observe: flight-recorder postmortem "
                  f"written to {path}", file=sys.stderr)
    except Exception:  # noqa: BLE001 - never mask the real error
        pass


def reset_for_testing() -> None:
    global _cfg, _spooler
    from ..utils import trace

    trace.set_stream(None)
    with _cfg_lock:
        _cfg = None
        _collected.clear()
        _metrics_written.clear()
        if _spooler is not None:
            _spooler.close()
            _spooler = None
    flight.reset_for_testing()
    metrics.reset_for_testing()

"""Flight recorder — a bounded ring of the last N operations per rank.

The chaos layer (PR 2) made failures *typed*; this module makes them
*narrated*. Every facade operation registers itself here (begin →
in-flight table, end → completed ring with duration and outcome), so
when a rank dies — a chaos ``crash@K``, an ``abort()``, or the first
fatal typed error (``RemoteAbortError``/``DeadlineError``/
``PeerDeadError``/``ChecksumError``) — a **postmortem JSON** snapshot
of "what this rank was doing" lands on disk: the in-flight operations
at the moment of death plus the completed-op ring leading up to it.
``mpirun`` then folds every rank's dump into one job report
(docs/OBSERVABILITY.md).

Cost doctrine: recording is two ``perf_counter_ns`` calls, one dict
store and one deque append per operation — noise against even the
fastest transport op (~60 µs xla bounce) — and a single module-bool
check when disabled (``MPI_TPU_FLIGHT=0``). Dumping only happens on
the way down and only when a postmortem directory is configured
(``--mpi-postmortem`` / ``MPI_TPU_POSTMORTEM_DIR``); otherwise
``dump()`` is a no-op.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["enabled", "begin", "end", "dump", "set_rank", "snapshot",
           "op_durations", "configure", "reset_for_testing"]

_DEFAULT_CAP = 256
# Per-op duration accumulators keep at most this many samples for
# p50/p99 (first-K; counts keep accumulating past the cap).
_DURATIONS_CAP = 4096


def _env_enabled() -> bool:
    return os.environ.get("MPI_TPU_FLIGHT", "1").strip().lower() not in (
        "0", "f", "false", "off", "no", "n")


def _env_cap() -> int:
    try:
        return max(8, int(os.environ.get("MPI_TPU_FLIGHT_N", _DEFAULT_CAP)))
    except ValueError:
        return _DEFAULT_CAP


class _Flight:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.cap = _env_cap()
        self.ring: deque = deque(maxlen=self.cap)
        self.inflight: Dict[int, Dict[str, Any]] = {}
        self.ids = itertools.count(1)
        self.rank: Optional[int] = None
        self.durations: Dict[str, List[float]] = {}
        self.op_counts: Dict[str, int] = {}
        self.dumped = False
        self.dump_lock = threading.Lock()


_fl = _Flight()
enabled = _env_enabled()


def configure(on: Optional[bool] = None, cap: Optional[int] = None) -> None:
    """Runtime switch (tests; programs use the env vars)."""
    global enabled
    if on is not None:
        enabled = bool(on)
    if cap is not None:
        with _fl.lock:
            _fl.cap = max(8, int(cap))
            _fl.ring = deque(_fl.ring, maxlen=_fl.cap)


def set_rank(rank: int) -> None:
    _fl.rank = int(rank)


def begin(op: str, peer: int, tag: int, nbytes: int = 0) -> int:
    """Register an operation as in-flight; returns a token for end()."""
    tok = next(_fl.ids)
    _fl.inflight[tok] = {
        "op": op,
        "peer": peer,
        "tag": tag,
        "bytes": nbytes,
        "t0_ns": time.perf_counter_ns(),
        "wall_ns": time.time_ns(),
        "thread": threading.current_thread().name,
    }
    return tok


def end(tok: int, state: str = "ok") -> None:
    """Move an in-flight operation to the completed ring."""
    ent = _fl.inflight.pop(tok, None)
    if ent is None:
        return
    dur_us = (time.perf_counter_ns() - ent["t0_ns"]) / 1e3
    ent["dur_us"] = dur_us
    ent["state"] = state
    del ent["t0_ns"]
    with _fl.lock:
        _fl.ring.append(ent)
        _fl.op_counts[ent["op"]] = _fl.op_counts.get(ent["op"], 0) + 1
        samples = _fl.durations.setdefault(ent["op"], [])
        if len(samples) < _DURATIONS_CAP:
            samples.append(dur_us)


def op_durations() -> Dict[str, List[float]]:
    """Per-op duration samples (µs) with total counts — the metrics
    layer's p50/p99 source. Returns {op: [samples...]}; counts via
    snapshot()."""
    with _fl.lock:
        return {k: list(v) for k, v in _fl.durations.items()}


def snapshot(reason: str = "") -> Dict[str, Any]:
    """The postmortem payload (also embedded in metrics artifacts)."""
    now_ns = time.perf_counter_ns()
    inflight = []
    for ent in list(_fl.inflight.values()):
        e = dict(ent)
        # A concurrent end() may have completed this op between the
        # values() snapshot and the copy (it del-s t0_ns) — treat it
        # as no-longer-in-flight rather than racing the mutation.
        t0 = e.pop("t0_ns", None)
        if t0 is None:
            continue
        e["elapsed_us"] = (now_ns - t0) / 1e3
        inflight.append(e)
    with _fl.lock:
        recent = list(_fl.ring)
        counts = dict(_fl.op_counts)
    return {
        "version": 1,
        "rank": _fl.rank,
        "pid": os.getpid(),
        "wall_ns": time.time_ns(),
        "reason": reason,
        "in_flight": inflight,
        "recent": recent,
        "op_counts": counts,
    }


def _postmortem_dir() -> Optional[str]:
    from . import postmortem_dir

    return postmortem_dir()


def dump(reason: str, path: Optional[str] = None,
         force: bool = False) -> Optional[str]:
    """Write this rank's postmortem JSON; returns the path (None when no
    postmortem directory is configured). First fatal error wins — later
    cascade failures (every op on a dead peer poisons) don't re-dump
    unless ``force``."""
    with _fl.dump_lock:
        if _fl.dumped and not force:
            return None
        if path is None:
            d = _postmortem_dir()
            if not d:
                return None
            try:
                os.makedirs(d, exist_ok=True)
            except OSError:
                return None
            rank = _fl.rank if _fl.rank is not None else "unknown"
            path = os.path.join(
                d, f"postmortem-rank{rank}-pid{os.getpid()}.json")
        snap = snapshot(reason)
        try:
            with open(path, "w") as f:
                json.dump(snap, f, indent=1)
        except OSError:
            return None
        _fl.dumped = True
        return path


def reset_for_testing() -> None:
    global enabled
    _fl.__init__()
    enabled = _env_enabled()

"""Streaming trace spooling — crash-durable per-rank chunk files.

The Finalize-batched trace collection (:mod:`.collect`) has two
structural weaknesses: a rank holds its whole span buffer in memory
(O(job) growth, capped only by dropping), and a rank that dies by
SIGKILL / chaos ``crash@K`` / hang takes its evidence with it. This
module makes the tracer *continuous*: with ``--mpi-trace-stream DIR``
(``MPI_TPU_TRACE_STREAM``) each rank's tracer flushes bounded chunks to
an append-only per-rank spool file, so resident buffer memory stays
O(chunk) and everything already flushed survives any death the OS
survives (the file's written bytes are kernel-owned after ``flush()``;
only the unflushed tail — at most one chunk — dies with the process).

Spool chunk format (newline-delimited JSON, one object per line, each
line self-describing so a reader needs no header):

    {"v": 1, "t": "chunk", "rank": R, "pid": P, "seq": N,
     "anchor_ns": A, "events": [span...]}          # flushed span batch
    {"v": 1, "t": "footer", "rank": R, "pid": P, "counters": {...},
     "dropped": D, "collective_entries": [...],
     "op_counts": {...}}                           # once, at finalize

``seq`` is the chunk sequence number (gaps reveal lost writes);
``anchor_ns`` is the tracer's perf_counter→wall-clock anchor, repeated
per chunk so any single surviving line places its spans on the wall
clock. A truncated final line (death mid-write) is skipped by the
reader; everything before it parses.

Consumers: :func:`mpi_tpu.observe.collect.local_bundle` reads a rank's
own spool back so the Finalize gather still produces a complete merged
trace; rank 0's gather and ``mpirun`` reconstruct *dead* ranks' bundles
from their spool files (:func:`scan_spools` / :func:`parse_spool`),
folding pre-crash spans into the merged chrome trace and
``job_postmortem.json`` even when the flight-recorder dump never ran.

Flush watermarks: size (``MPI_TPU_TRACE_STREAM_EVENTS``, default 512
events) or age (``MPI_TPU_TRACE_STREAM_AGE_S``, default 1.0 s, checked
when the next event arrives — a fully idle rank keeps its sub-chunk
tail buffered, which is fine: an idle rank has nothing new to lose).
Spooling I/O failures are recorded and silence the writer — streaming
observability must never take the job down.
"""

from __future__ import annotations

import glob
import json
import os
import threading
from typing import Any, Dict, List, Optional

__all__ = ["SpoolWriter", "spool_path", "parse_spool", "scan_spools",
           "reconstruct_bundles", "SPOOL_VERSION"]

SPOOL_VERSION = 1

_DEFAULT_CHUNK_EVENTS = 512
_DEFAULT_MAX_AGE_S = 1.0


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, default)))
    except ValueError:
        return default


def spool_path(directory: str, rank: Any, pid: int) -> str:
    return os.path.join(directory, f"spool-rank{rank}-pid{pid}.ndjson")


class SpoolWriter:
    """Per-process spool sink, installed into the tracer with
    :func:`mpi_tpu.utils.trace.set_stream`. The tracer calls
    :meth:`write_chunk` under its own lock whenever the resident buffer
    hits a watermark (it reads ``max_events`` / ``max_age_s`` /
    ``first_t`` directly — the watermark state lives here so the
    tracer's disabled path stays a single attribute check)."""

    def __init__(self, directory: str, rank: Optional[int] = None):
        self.directory = directory
        self.rank = rank
        self.max_events = _env_int("MPI_TPU_TRACE_STREAM_EVENTS",
                                   _DEFAULT_CHUNK_EVENTS)
        self.max_age_s = _env_float("MPI_TPU_TRACE_STREAM_AGE_S",
                                    _DEFAULT_MAX_AGE_S)
        # Monotonic time of the oldest unflushed event (None = empty
        # buffer); maintained by the tracer's add_event, reset here.
        self.first_t: Optional[float] = None
        self.path: Optional[str] = None
        self.seq = 0
        self.chunks_written = 0
        self.events_written = 0
        self.broken: Optional[str] = None
        self.footer_written = False
        self._f = None
        self._io_lock = threading.Lock()

    def set_rank(self, rank: int) -> None:
        """Bind the rank once known (init order: the spooler can be
        installed before the backend has assigned ranks)."""
        if self.path is None:
            self.rank = rank

    def _open(self):
        if self._f is None and self.broken is None:
            try:
                os.makedirs(self.directory, exist_ok=True)
                self.path = spool_path(
                    self.directory,
                    self.rank if self.rank is not None else "unknown",
                    os.getpid())
                self._f = open(self.path, "a")
            except OSError as exc:
                self.broken = str(exc)
        return self._f

    def _emit(self, record: Dict[str, Any]) -> None:
        with self._io_lock:
            f = self._open()
            if f is None:
                return
            try:
                f.write(json.dumps(record) + "\n")
                # One flush per chunk: the written bytes become
                # kernel-owned, surviving SIGKILL of this process.
                f.flush()
            except (OSError, ValueError, TypeError) as exc:
                self.broken = str(exc)

    def write_chunk(self, events: List[Dict[str, Any]]) -> None:
        """Append one chunk line. Called by the tracer with the batch it
        just detached from its resident buffer (so file I/O here never
        grows tracer memory)."""
        self.first_t = None
        if not events or self.broken is not None:
            return
        from ..utils import trace

        self._emit({"v": SPOOL_VERSION, "t": "chunk",
                    "rank": self.rank, "pid": os.getpid(),
                    "seq": self.seq, "anchor_ns": trace.wall_anchor_ns(),
                    "events": events})
        self.seq += 1
        self.chunks_written += 1
        self.events_written += len(events)

    def write_footer(self) -> None:
        """Finalize record: counters and collective entries, so a bundle
        reconstructed from the spool alone carries the same fields as a
        live-gathered one. Written once."""
        if self.footer_written or self.broken is not None:
            return
        self.footer_written = True
        from ..utils import trace
        from . import flight, metrics

        self._emit({"v": SPOOL_VERSION, "t": "footer",
                    "rank": self.rank, "pid": os.getpid(),
                    "counters": trace.counters(),
                    "dropped": trace.dropped(),
                    "collective_entries": metrics.collective_entries(),
                    "op_counts": flight.snapshot()["op_counts"]})

    def read_back_events(self) -> List[Dict[str, Any]]:
        """This rank's already-flushed spans, in flush order (the
        Finalize gather prepends them to the resident tail so the merged
        trace stays complete under streaming)."""
        if self.path is None:
            return []
        b = parse_spool(self.path)
        return b["events"] if b else []

    def close(self) -> None:
        with self._io_lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def parse_spool(path: str) -> Optional[Dict[str, Any]]:
    """Rebuild a :func:`mpi_tpu.observe.collect.local_bundle`-shaped
    dict from one spool file. Tolerant: a truncated trailing line
    (death mid-write) and unknown record types are skipped. Returns
    None when the file is unreadable or holds no parseable record."""
    bundle: Dict[str, Any] = {
        "rank": None, "pid": None, "anchor_ns": 0,
        "events": [], "counters": {}, "dropped": 0,
        "collective_entries": [],
        "flight": {"op_counts": {}},
        "spool": path, "spool_chunks": 0,
    }
    got_any = False
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # truncated tail / torn write
                if not isinstance(rec, dict):
                    continue
                kind = rec.get("t")
                if kind == "chunk":
                    got_any = True
                    bundle["rank"] = rec.get("rank", bundle["rank"])
                    bundle["pid"] = rec.get("pid", bundle["pid"])
                    bundle["anchor_ns"] = rec.get("anchor_ns",
                                                  bundle["anchor_ns"])
                    bundle["events"].extend(rec.get("events", []))
                    bundle["spool_chunks"] += 1
                elif kind == "footer":
                    got_any = True
                    bundle["rank"] = rec.get("rank", bundle["rank"])
                    bundle["pid"] = rec.get("pid", bundle["pid"])
                    bundle["counters"] = rec.get("counters", {})
                    bundle["dropped"] = rec.get("dropped", 0)
                    bundle["collective_entries"] = rec.get(
                        "collective_entries", [])
                    bundle["flight"] = {
                        "op_counts": rec.get("op_counts", {})}
    except OSError:
        return None
    return bundle if got_any else None


def scan_spools(directory: str) -> Dict[int, Dict[str, Any]]:
    """All reconstructable bundles in a spool directory, keyed by rank.
    When one rank left several spool files (restarts), the most recently
    modified wins. Files whose rank never resolved are skipped — an
    unattributable track would corrupt the merge."""
    found: Dict[int, Dict[str, Any]] = {}
    mtimes: Dict[int, float] = {}
    for path in sorted(glob.glob(os.path.join(directory,
                                              "spool-rank*.ndjson"))):
        b = parse_spool(path)
        if b is None or not isinstance(b.get("rank"), int):
            continue
        try:
            mt = os.path.getmtime(path)
        except OSError:
            mt = 0.0
        r = b["rank"]
        if r not in found or mt >= mtimes[r]:
            found[r] = b
            mtimes[r] = mt
    return found


def reconstruct_bundles(directory: str,
                        ranks: Optional[List[int]] = None
                        ) -> Dict[int, Dict[str, Any]]:
    """Bundles for the given ranks (all spooled ranks when None) — the
    ``mpirun`` post-job path: dead ranks' evidence without any
    surviving process's cooperation."""
    found = scan_spools(directory)
    if ranks is None:
        return found
    return {r: found[r] for r in ranks if r in found}

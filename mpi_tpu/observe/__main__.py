"""CLI for observe artifacts::

    python -m mpi_tpu.observe top metrics.json [...]   # render metrics
    python -m mpi_tpu.observe postmortem dir_or_file   # summarize dumps

``top`` renders one or more ``--mpi-metrics-out`` artifacts as the
same text report SIGUSR1 prints live; ``postmortem`` summarizes
per-rank flight-recorder dumps (or an ``mpirun`` job report), naming
each rank's last in-flight operation — the first thing to read after
a crashed job.
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import Any, Dict, List


def _load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _render_metrics(doc: Dict[str, Any], path: str) -> None:
    from . import metrics

    metrics.validate(doc)
    r = doc.get("rank")
    print(f"== {path} (rank {r if r is not None else '?'}, "
          f"{doc['elapsed_s']:.1f}s) ==")
    for op in sorted(doc["ops"]):
        st = doc["ops"][op]
        print(f"  {op:<18} n={int(st['count']):<8} "
              f"p50={st['p50_us']:.1f}µs p99={st['p99_us']:.1f}µs")
    for peer in sorted(doc["peers"], key=lambda p: int(p)):
        rec = doc["peers"][peer]
        print(f"  peer {peer}: tx {rec['tx_bytes_per_s'] / 1e6:.2f} MB/s"
              f"  rx {rec['rx_bytes_per_s'] / 1e6:.2f} MB/s")
    for row in doc.get("stragglers", []):
        print(f"  straggler: {row['collective']} skew "
              f"{row['max_skew_us']:.1f}µs slowest rank "
              f"{row['slowest_rank']}")


def _describe_op(ent: Dict[str, Any]) -> str:
    peer = ent.get("peer")
    tag = ent.get("tag")
    loc = "" if peer in (None, -1) else f" peer={peer} tag={tag}"
    return f"{ent.get('op', '?')}{loc} bytes={ent.get('bytes', 0)}"


def _render_postmortem(doc: Dict[str, Any], path: str) -> None:
    ranks = doc["ranks"] if "ranks" in doc else {str(doc.get("rank")): doc}
    print(f"== {path} ==")
    for r in sorted(ranks, key=lambda x: (x == "None", x)):
        snap = ranks[r]
        inflight = snap.get("in_flight", [])
        print(f"  rank {r} (pid {snap.get('pid')}): "
              f"reason: {snap.get('reason', '?')}")
        if inflight:
            for ent in inflight:
                print(f"    in flight: {_describe_op(ent)} "
                      f"({ent.get('elapsed_us', 0):.0f}µs elapsed)")
        else:
            print("    no operation in flight")
        recent = snap.get("recent", [])[-3:]
        for ent in recent:
            print(f"    recent: {_describe_op(ent)} -> "
                  f"{ent.get('state', '?')} "
                  f"({ent.get('dur_us', 0):.0f}µs)")


def main(argv: List[str]) -> int:
    if len(argv) < 2 or argv[0] not in ("top", "postmortem"):
        print(__doc__, file=sys.stderr)
        return 2
    cmd, targets = argv[0], argv[1:]
    paths: List[str] = []
    for t in targets:
        if os.path.isdir(t):
            paths += sorted(glob.glob(os.path.join(t, "*.json")))
        else:
            paths += sorted(glob.glob(t)) or [t]
    rc = 0
    for p in paths:
        try:
            doc = _load(p)
            if cmd == "top":
                _render_metrics(doc, p)
            else:
                _render_postmortem(doc, p)
        except Exception as exc:  # noqa: BLE001 - report and continue
            print(f"{p}: unreadable ({exc})", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

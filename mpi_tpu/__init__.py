"""mpi_tpu — a TPU-native message-passing framework.

A from-scratch rebuild of the capabilities of ``btracey/mpi`` (an MPI-like
point-to-point library over TCP, /root/reference) designed TPU-first:

  * the reference's full API surface — ``init``/``finalize``/``rank``/
    ``size``, blocking tagged rendezvous ``send``/``receive``, a pluggable
    backend ``Interface`` with ``register``, the ``Raw`` passthrough type,
    ``-mpi-*`` flag config, and local/SLURM launchers;
  * a faithful TCP driver (:mod:`mpi_tpu.backends.tcp`) as CPU fallback and
    bitwise-parity oracle;
  * an XLA driver (:mod:`mpi_tpu.backends.xla`) that maps ranks onto a
    ``jax.sharding.Mesh`` axis and lowers communication to XLA collectives
    over ICI/DCN;
  * **new** collectives — ``reduce``/``bcast``/``allgather``/``allreduce``/
    ``gather``/``scatter``/``alltoall``/``scan``/``exscan``/``barrier``
    (the reference stubs
    ``AllReduce`` out, mpi.go:130);
  * communicators (:mod:`mpi_tpu.comm`: split/dup/create_group, Cartesian
    topologies), distributed-graph topologies (:mod:`mpi_tpu.distgraph`),
    intercommunicators (:mod:`mpi_tpu.intercomm`), one-sided RMA windows
    (:mod:`mpi_tpu.window`), and parallel file IO (:mod:`mpi_tpu.io`);
  * a functional layer (:mod:`mpi_tpu.parallel`) for use *inside* ``jit``
    ted SPMD code — including ZeRO-1 optimizer-state sharding
    (:mod:`mpi_tpu.parallel.zero`) — plus Pallas ring/DMA kernels
    (:mod:`mpi_tpu.ops`);
  * a native runtime core (:mod:`mpi_tpu.native`): C++ socket frame
    engine, shared-memory ring transport (``-mpi-protocol shm``), and
    batch-gather data-loader kernel, all ctypes-loaded with pure-Python
    fallbacks;
  * job-wide observability (:mod:`mpi_tpu.observe`): distributed trace
    collection into one clock-aligned chrome trace, a flight recorder
    whose postmortems narrate typed failures, and live metrics with
    straggler detection (docs/OBSERVABILITY.md).
"""

from .comm import CartComm, Comm, cart_create, comm_self, comm_world
from .compressed import allreduce_compressed_wire
from .distgraph import (DistGraphComm, GraphComm,
                        dist_graph_create_adjacent, graph_create)
from .intercomm import Intercomm, create_intercomm
from .io import File, open_file
from .window import Window, win_create
from .runner import run_main, selected_backend
from .api import (
    Interface,
    MpiError,
    NotInitializedError,
    Raw,
    TagError,
    allgather,
    allreduce,
    alltoall,
    barrier,
    iallreduce,
    ireduce,
    ibcast,
    igather,
    iallgather,
    iscatter,
    ialltoall,
    ireduce_scatter,
    ibarrier,
    bcast,
    finalize,
    gather,
    init,
    rank,
    receive,
    iprobe,
    probe,
    Request,
    PersistentRequest,
    isend,
    irecv,
    send_init,
    recv_init,
    waitall,
    waitany,
    reduce,
    reduce_scatter,
    register,
    registered,
    scan,
    exscan,
    scatter,
    send,
    sendrecv,
    size,
    wtime,
    wtick,
    set_errhandler,
    get_errhandler,
    allreduce_init,
    bcast_init,
    barrier_init,
    pack,
    unpack,
    receive_any,
    abort,
)

__version__ = "0.1.0"

# Fault-surface exports resolve lazily (PEP 562): a chaos-less run
# never imports the chaos module — matching the TCP driver's init-time
# deferral and the flag layer's raw-string pass-through — and the typed
# fault errors (docs/FAULT_TOLERANCE.md) are catchable from the package
# top level without reaching into backend internals.
_CHAOS_EXPORTS = ("ChaosNetwork", "ChaosEngine", "ChaosConfig",
                  "parse_chaos")
_LAZY_EXPORTS = {
    **{name: "chaos" for name in _CHAOS_EXPORTS},
    "ChecksumError": "backends.tcp",
    "PeerDeadError": "backends.tcp",
    "RemoteAbortError": "backends.tcp",
    "DeadlineError": "backends.rendezvous",
}


def __getattr__(name):
    modname = _LAZY_EXPORTS.get(name)
    if modname is not None:
        import importlib

        return getattr(
            importlib.import_module(f".{modname}", __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))


__all__ = [
    "ChaosNetwork",
    "ChaosEngine",
    "ChaosConfig",
    "parse_chaos",
    "ChecksumError",
    "PeerDeadError",
    "RemoteAbortError",
    "DeadlineError",
    "Comm",
    "CartComm",
    "Window",
    "win_create",
    "cart_create",
    "comm_self",
    "comm_world",
    "run_main",
    "selected_backend",
    "Interface",
    "MpiError",
    "NotInitializedError",
    "Raw",
    "TagError",
    "allgather",
    "allreduce",
    "allreduce_compressed_wire",
    "alltoall",
    "barrier",
    "iallreduce",
    "ireduce",
    "ibcast",
    "igather",
    "iallgather",
    "iscatter",
    "ialltoall",
    "ireduce_scatter",
    "ibarrier",
    "bcast",
    "finalize",
    "gather",
    "init",
    "rank",
    "receive",
    "iprobe",
    "probe",
    "Request",
    "PersistentRequest",
    "isend",
    "irecv",
    "send_init",
    "recv_init",
    "waitall",
    "waitany",
    "reduce",
    "reduce_scatter",
    "register",
    "registered",
    "scan",
    "exscan",
    "scatter",
    "send",
    "sendrecv",
    "size",
    "wtime",
    "wtick",
    "set_errhandler",
    "get_errhandler",
    "allreduce_init",
    "bcast_init",
    "barrier_init",
    "pack",
    "unpack",
    "receive_any",
    "abort",
    "Intercomm",
    "create_intercomm",
    "DistGraphComm",
    "dist_graph_create_adjacent",
    "GraphComm",
    "graph_create",
    "File",
    "open_file",
    "__version__",
]

"""Parallel file IO over a communicator — the MPI-IO analogue.

No reference counterpart (btracey/mpi does no file IO at all); this is
framework-completeness work mirroring the MPI_File surface an MPI user
expects, adapted to the numpy/jax world:

* a :class:`File` is opened **collectively** over a communicator and
  reads/writes flat typed arrays at explicit element offsets — the
  MPI_File_{read,write}_at model, with the "etype" being a numpy dtype;
* ``*_at_all`` are the collective variants (every member calls;
  completion is barrier-synchronized so a reader rank can immediately
  reopen/consume what a writer rank just wrote);
* :meth:`File.set_view` installs the MPI_Type_vector-style strided view
  (displacement + block/stride in elements), after which
  :meth:`read_all`/:meth:`write_all` move each rank's interleaved
  blocks in one call — the classic row-cyclic distribution;
* :meth:`write_ordered` is MPI_File_write_ordered: variable-size
  contributions land back-to-back in rank order, with the offsets
  agreed via an exscan — no shared file pointer needed;
* independent ops use ``os.pread``/``os.pwrite`` (no seek state, safe
  under the thread-per-rank drivers where every rank shares one
  process).

tpu-first note: checkpointing sharded *device* arrays is
:mod:`mpi_tpu.utils.checkpoint`'s job (gather + atomic step dirs);
this module is the raw byte-level surface beneath such schemes and for
data interchange with non-JAX tools.

Single-writer-per-byte discipline is the caller's contract, as in
MPI-IO; overlapping writes have filesystem-order semantics.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional, Tuple, Union

import numpy as np

from .api import MpiError
from .comm import Comm

__all__ = ["File", "open_file"]


def open_file(comm: Comm, path: Union[str, os.PathLike],
              mode: str = "r") -> "File":
    """Collectively open ``path`` on every member of ``comm``.

    Modes: ``"r"`` read-only (must exist), ``"w"`` create/truncate then
    read-write, ``"a"`` create-if-missing then read-write (no
    truncation) — the MPI_MODE_RDONLY / CREATE|TRUNC / CREATE
    combinations. Creation/truncation happens exactly once (group rank
    0) before any other rank opens, so ``"w"`` is race-free within the
    group."""
    if mode not in ("r", "w", "a"):
        raise MpiError(f"mpi_tpu: open_file mode must be r|w|a, got {mode!r}")
    path = os.fspath(path)
    err: Optional[str] = None
    if comm.rank() == 0 and mode in ("w", "a"):
        try:
            flags = os.O_RDWR | os.O_CREAT | (
                os.O_TRUNC if mode == "w" else 0)
            os.close(os.open(path, flags, 0o644))
        except OSError as exc:  # propagate to every rank below
            err = f"mpi_tpu: cannot create {path!r}: {exc}"
    # Surface a creation failure everywhere (fail-loud, like the
    # dist-graph validation) and fence rank 0's create/truncate.
    err = comm.bcast(err, root=0)
    if err is not None:
        raise MpiError(err)
    try:
        fd = os.open(path, os.O_RDONLY if mode == "r" else os.O_RDWR)
    except OSError as exc:
        raise MpiError(f"mpi_tpu: cannot open {path!r}: {exc}") from exc
    return File(comm, path, fd, writable=(mode != "r"))


class File:
    """A communicator-shared file handle. Construct via
    :func:`open_file`."""

    def __init__(self, comm: Comm, path: str, fd: int, writable: bool):
        self._comm = comm
        self._path = path
        self._fd = fd
        self._writable = writable
        self._closed = False
        self._lock = threading.Lock()
        # Default view: every rank sees the whole file as contiguous
        # bytes from 0 (MPI's native default view) — index 0 for all
        # ranks, NOT rank-shifted (that would overlap byte ranges).
        self._view_disp = 0
        self._view_dtype = np.dtype(np.uint8)
        self._view_block = 1
        self._view_stride = 1
        self._view_index = 0
        self._sp_win = None  # shared-pointer window (opt-in; see below)

    # -- basics -------------------------------------------------------------

    @property
    def path(self) -> str:
        return self._path

    @property
    def comm(self) -> Comm:
        return self._comm

    def __repr__(self) -> str:
        state = "closed" if self._closed else "open"
        return f"File({self._path!r}, {state}, ctx={self._comm.context})"

    def _check_open(self, write: bool = False) -> None:
        if self._closed:
            raise MpiError(f"mpi_tpu: file {self._path!r} is closed")
        if write and not self._writable:
            raise MpiError(f"mpi_tpu: file {self._path!r} opened read-only")

    def size(self) -> int:
        """Current file size in bytes (MPI_File_get_size)."""
        self._check_open()
        return os.fstat(self._fd).st_size

    def set_size(self, nbytes: int) -> None:
        """Truncate/extend (MPI_File_set_size). Collective."""
        self._check_open(write=True)
        if self._comm.rank() == 0:
            os.ftruncate(self._fd, nbytes)
        self._comm.barrier()

    def sync(self) -> None:
        """Flush to storage (MPI_File_sync). Collective."""
        self._check_open()
        os.fsync(self._fd)
        self._comm.barrier()

    def close(self) -> None:
        """Collective close (MPI_File_close); idempotent per rank."""
        if self._closed:
            return
        self._closed = True
        if self._writable:
            try:
                os.fsync(self._fd)
            except OSError:
                pass
        os.close(self._fd)
        self._comm.barrier()
        if self._sp_win is not None:
            # After the barrier no rank has a *_shared claim in flight
            # (an op past one's own close is erroneous per MPI);
            # Window.free is purely local, so no second barrier.
            self._sp_win.free()
            self._sp_win = None

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- independent positioned IO (MPI_File_read_at / write_at) ------------

    def write_at(self, offset_bytes: int, data: Any) -> int:
        """Write ``data`` (array-like; written as its raw little-endian
        bytes, C order) at the absolute byte offset. Independent.
        Returns bytes written."""
        self._check_open(write=True)
        buf = _as_bytes(data)
        done = 0
        while done < len(buf):
            done += os.pwrite(self._fd, buf[done:], offset_bytes + done)
        return done

    def read_at(self, offset_bytes: int, count: int,
                dtype: Any = np.uint8) -> np.ndarray:
        """Read ``count`` elements of ``dtype`` at the byte offset.
        Independent. Short files raise (a read past EOF is a caller
        bug, not a quiet truncation)."""
        self._check_open()
        dt = np.dtype(dtype)
        need = count * dt.itemsize
        chunks = []
        got = 0
        while got < need:
            b = os.pread(self._fd, need - got, offset_bytes + got)
            if not b:
                raise MpiError(
                    f"mpi_tpu: short read at {offset_bytes}+{got} "
                    f"(wanted {need} bytes) from {self._path!r}")
            chunks.append(b)
            got += len(b)
        return np.frombuffer(b"".join(chunks), dtype=dt).copy()

    # -- collective variants ------------------------------------------------

    def write_at_all(self, offset_bytes: int, data: Any) -> int:
        """Collective :meth:`write_at`: every member calls (data may be
        empty); returns this rank's bytes written. On return every
        rank's data is visible to every other rank's reads."""
        n = self.write_at(offset_bytes, data) if _nbytes(data) else 0
        self._comm.barrier()
        return n

    def read_at_all(self, offset_bytes: int, count: int,
                    dtype: Any = np.uint8) -> np.ndarray:
        """Collective :meth:`read_at` (every member calls; barriers on
        entry so it sequences after the matching collective write)."""
        self._comm.barrier()
        return self.read_at(offset_bytes, count, dtype)

    # -- file views (MPI_File_set_view + MPI_Type_vector) -------------------

    def set_view(self, disp: int = 0, dtype: Any = np.uint8,
                 block: int = 1, stride: Optional[int] = None,
                 index: Optional[int] = None) -> None:
        """Install this rank's strided view: starting at byte ``disp``,
        the file is a sequence of *rounds* of ``stride`` elements of
        ``dtype``; this rank owns the ``block``-element slab at round
        offset ``index * block``. Defaults give the canonical row-cyclic
        split: ``stride = block * comm.size()``, ``index = comm.rank()``.

        Equivalent MPI: ``MPI_Type_vector(count, block, stride)`` +
        ``MPI_File_set_view(disp + rank*block*esize, etype, filetype)``."""
        self._check_open()
        dt = np.dtype(dtype)
        if block < 1:
            raise MpiError(f"mpi_tpu: view block must be >= 1, got {block}")
        idx = self._comm.rank() if index is None else int(index)
        st = block * self._comm.size() if stride is None else int(stride)
        if st < block:
            raise MpiError(
                f"mpi_tpu: view stride {st} smaller than block {block}")
        self._view_disp = int(disp)
        self._view_dtype = dt
        self._view_block = int(block)
        self._view_stride = st
        self._view_index = idx

    def _view_offsets(self, nelems: int) -> Tuple[np.ndarray, np.ndarray]:
        """(element offsets in file, element offsets in the local
        buffer) for ``nelems`` view elements, as (file_elem, length)
        runs — one entry per (partial) block."""
        block = self._view_block
        nblocks = -(-nelems // block)
        starts = (np.arange(nblocks, dtype=np.int64) * self._view_stride
                  + self._view_index * block)
        lens = np.full(nblocks, block, dtype=np.int64)
        tail = nelems - (nblocks - 1) * block
        lens[-1] = tail
        return starts, lens

    def write_all(self, data: Any) -> int:
        """Collective strided write through the view: ``data``'s
        elements land in this rank's view slots, in order. Returns
        elements written."""
        self._check_open(write=True)
        arr = np.ascontiguousarray(np.asarray(data, dtype=self._view_dtype)
                                   ).reshape(-1)
        esize = self._view_dtype.itemsize
        starts, lens = self._view_offsets(arr.size) if arr.size else ((), ())
        pos = 0
        for s, ln in zip(starts, lens):
            off = self._view_disp + int(s) * esize
            self.write_at(off, arr[pos:pos + int(ln)])
            pos += int(ln)
        self._comm.barrier()
        return arr.size

    def read_all(self, nelems: int) -> np.ndarray:
        """Collective strided read through the view: this rank's next
        ``nelems`` view elements."""
        self._check_open()
        self._comm.barrier()
        esize = self._view_dtype.itemsize
        out = np.empty(nelems, dtype=self._view_dtype)
        starts, lens = self._view_offsets(nelems) if nelems else ((), ())
        pos = 0
        for s, ln in zip(starts, lens):
            off = self._view_disp + int(s) * esize
            out[pos:pos + int(ln)] = self.read_at(off, int(ln),
                                                  self._view_dtype)
            pos += int(ln)
        return out

    # -- shared file pointer (MPI_File_write_shared family) -----------------

    def init_shared_pointer(self) -> None:
        """COLLECTIVE: create the shared file pointer — a one-element
        passive-RMA counter window owned by group rank 0 (the classic
        MPI-IO shared-pointer realization; fetch_and_op under an
        exclusive lock IS the atomic pointer claim). Opt-in because the
        window runs a per-rank service thread; call once after open,
        on every rank, before any ``*_shared`` op."""
        self._check_open()
        if self._sp_win is not None:
            raise MpiError("mpi_tpu: shared pointer already initialized")
        from .window import win_create

        size = 1 if self._comm.rank() == 0 else 0
        self._sp_win = win_create(self._comm, np.zeros(size, np.int64),
                                  locks=True)

    def _sp(self):
        win = self._sp_win
        if win is None:
            raise MpiError(
                "mpi_tpu: shared file pointer not initialized — call "
                "init_shared_pointer() (collective) after open_file")
        return win

    def _sp_claim(self, nbytes: int) -> int:
        """Atomically advance the shared pointer by ``nbytes``; returns
        the claimed start offset."""
        win = self._sp()
        win.lock(0, exclusive=True)
        try:
            start = int(win.fetch_and_op(np.int64(nbytes), 0).array[0])
        finally:
            win.unlock(0)
        return start

    def get_position_shared(self) -> int:
        """Current shared-pointer byte offset (MPI_File_get_position_
        shared): a snapshot — concurrent ``*_shared`` ops move it."""
        win = self._sp()
        win.lock(0, exclusive=False)
        try:
            return int(win.get(0, 0, 1).array[0])
        finally:
            win.unlock(0)

    def seek_shared(self, offset_bytes: int) -> None:
        """COLLECTIVE: set the shared pointer (MPI_File_seek_shared;
        every rank passes the same offset)."""
        win = self._sp()
        if self._comm.rank() == 0:
            win.lock(0, exclusive=True)
            try:
                win.put(np.int64([int(offset_bytes)]), 0, 0)
            finally:
                win.unlock(0)
        self._comm.barrier()

    def write_shared(self, data: Any) -> int:
        """Non-collective atomic append at the shared pointer
        (MPI_File_write_shared): claims ``len(data)`` bytes of the
        pointer atomically, writes there, returns the start offset.
        Ordering across ranks is arrival order (MPI leaves it
        unspecified); each write's span is exclusively its own."""
        self._check_open(write=True)
        buf = _as_bytes(data)
        start = self._sp_claim(len(buf))
        if buf:
            self.write_at(start, buf)
        return start

    def read_shared(self, count: int,
                    dtype: Any = np.uint8) -> np.ndarray:
        """Non-collective read at the shared pointer
        (MPI_File_read_shared): atomically claims up to ``count``
        elements and reads from the claimed offset. At EOF the claim
        shrinks to what the file holds (possibly zero) — a SHORT read,
        as MPI specifies, never a pointer stranded past EOF."""
        self._check_open()
        item = np.dtype(dtype).itemsize
        want = int(count) * item
        win = self._sp()
        win.lock(0, exclusive=True)
        try:
            cur = int(win.get(0, 0, 1).array[0])
            avail = max(0, min(want, self.size() - cur))
            avail -= avail % item  # whole elements only
            if avail:
                win.put(np.int64([cur + avail]), 0, 0)
        finally:
            win.unlock(0)
        if not avail:
            return np.empty(0, dtype)
        return self.read_at(cur, avail // item, dtype)

    # -- ordered write (MPI_File_write_ordered) -----------------------------

    def write_ordered(self, data: Any, offset_bytes: int = 0) -> int:
        """Collective: every rank's bytes land back-to-back in rank
        order starting at ``offset_bytes`` — variable sizes welcome
        (the offsets are agreed via an exscan of byte counts; no shared
        file pointer exists to contend on). Returns this rank's start
        offset."""
        self._check_open(write=True)
        buf = _as_bytes(data)
        before = self._comm.exscan(np.int64(len(buf)), op="sum")
        start = offset_bytes + (0 if before is None else int(before))
        if buf:
            self.write_at(start, buf)
        self._comm.barrier()
        return start


def _as_bytes(data: Any) -> bytes:
    if isinstance(data, (bytes, bytearray, memoryview)):
        return bytes(data)
    return np.ascontiguousarray(np.asarray(data)).tobytes()


def _nbytes(data: Any) -> int:
    if isinstance(data, (bytes, bytearray, memoryview)):
        return len(data)
    return np.asarray(data).nbytes

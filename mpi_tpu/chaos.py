"""Deterministic, seeded fault injection for any backend (chaos layer).

No reference analogue: the reference treats every transport failure as a
panic or an infinite hang (network.go:555,611; SURVEY.md §2). This module
is the test harness for the opposite stance — failures detected,
classified, and propagated (docs/FAULT_TOLERANCE.md) — in the spirit of
MPI Advance's "robustness below a stable API" layering (PAPERS.md).

Two injection planes, one configuration:

  * **Op plane** (any backend): :class:`ChaosNetwork` wraps an
    :class:`~mpi_tpu.api.Interface` and perturbs each ``send``/``receive``
    with seeded latency/delivery-delay sleeps and a "rank crashes at op
    k" kill switch. Delays change *timing only* — a correct transport
    must produce bit-exact results under them (tests/test_chaos.py).

  * **Wire plane** (TCP driver): the same :class:`ChaosEngine` installs
    onto ``TcpNetwork._chaos``; the driver consults it per outbound DATA
    frame and applies payload bit-corruption, frame truncation, and
    connection resets *after* CRC computation — so a negotiated CRC
    trailer (``--mpi-crc``) catches the corruption exactly as real line
    noise would, and truncation/reset exercise the peer-death and
    ``--mpi-optimeout`` deadline paths.

Configuration grammar (``--mpi-chaos`` / ``MPI_TPU_CHAOS``)::

    spec  := seed ":" rate ":" modes
    seed  := integer            # RNG seed; same spec ⇒ same fault plan
    rate  := float in [0, 1]    # per-operation fault probability
    modes := mode ("," mode)*
    mode  := "latency"          # sleep ≤ 2 ms before a matched op
           | "delay"            # sleep ≤ 20 ms before frame delivery
                                # (reorders completions across threads)
           | "corrupt"          # flip one payload bit per matched send
           | "truncate"         # cut a frame short, then drop the conn
           | "reset"            # drop the connection instead of sending
           | "crash@K"          # os._exit after K chaos-visible ops

Determinism: every fault decision derives from a BLAKE2 hash of
``(seed, op, peer, tag, per-channel sequence number)`` — independent of
thread scheduling, hash randomization, and wall clock — so a failing
seed replays exactly (``tools/chaos_soak.sh``). The one exception is
``crash@K``, which by design counts chaos-visible ops in *arrival
order* ("the rank dies K ops in, whatever they are"): with ops issued
from multiple threads, which op the death lands on can vary between
runs of the same seed.

Bootstrap frames (HELLO) never pass through the chaos planes: the fault
surface starts after ``init()`` returns, so a chaos run always reaches a
connected state first.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

from .api import Interface, MpiError

__all__ = ["ChaosConfig", "ChaosEngine", "ChaosNetwork", "WireFault",
           "parse_chaos", "CRASH_EXIT_CODE"]

# Exit code of a chaos-injected crash ("crash@K"): distinguishable from
# abort() codes and from mpirun's own kill in launcher logs.
CRASH_EXIT_CODE = 37

_MODES = ("latency", "delay", "corrupt", "truncate", "reset")
_MAX_LATENCY_S = 0.002
_MAX_DELAY_S = 0.020


@dataclass(frozen=True)
class ChaosConfig:
    """Parsed ``seed:rate:modes`` spec (immutable; shareable)."""

    seed: int
    rate: float
    modes: FrozenSet[str]
    crash_at: Optional[int] = None  # total chaos-visible ops before exit

    @property
    def wire_modes(self) -> FrozenSet[str]:
        return self.modes & {"corrupt", "truncate", "reset"}


def parse_chaos(spec: str) -> ChaosConfig:
    """Parse the flag grammar; raises :class:`MpiError` on malformed
    specs (a typo'd chaos flag must fail loudly, not silently run the
    job fault-free)."""
    parts = spec.split(":", 2)
    if len(parts) != 3:
        raise MpiError(
            f"mpi_tpu: malformed --mpi-chaos spec {spec!r}; expected "
            f"seed:rate:modes (e.g. 42:0.05:delay,corrupt)")
    seed_s, rate_s, modes_s = parts
    try:
        seed = int(seed_s)
    except ValueError:
        raise MpiError(f"mpi_tpu: --mpi-chaos seed {seed_s!r} is not an "
                       f"integer") from None
    try:
        rate = float(rate_s)
    except ValueError:
        raise MpiError(f"mpi_tpu: --mpi-chaos rate {rate_s!r} is not a "
                       f"float") from None
    if not 0.0 <= rate <= 1.0:
        raise MpiError(f"mpi_tpu: --mpi-chaos rate {rate} outside [0, 1]")
    modes: List[str] = []
    crash_at: Optional[int] = None
    for raw in modes_s.split(","):
        mode = raw.strip()
        if not mode:
            continue
        if mode.startswith("crash@"):
            try:
                crash_at = int(mode[len("crash@"):])
            except ValueError:
                raise MpiError(
                    f"mpi_tpu: --mpi-chaos mode {mode!r}: crash@K needs "
                    f"an integer K") from None
            if crash_at < 1:
                raise MpiError(
                    f"mpi_tpu: --mpi-chaos crash@{crash_at}: K must "
                    f"be >= 1")
            continue
        if mode not in _MODES:
            raise MpiError(
                f"mpi_tpu: unknown --mpi-chaos mode {mode!r}; known: "
                f"{', '.join(_MODES)}, crash@K")
        modes.append(mode)
    if not modes and crash_at is None:
        raise MpiError(
            f"mpi_tpu: --mpi-chaos spec {spec!r} names no modes")
    return ChaosConfig(seed=seed, rate=rate, modes=frozenset(modes),
                       crash_at=crash_at)


@dataclass
class WireFault:
    """A wire-plane fault plan for one outbound DATA frame, consumed by
    the TCP driver's ``_send_frame`` (applied after CRC computation)."""

    corrupt_offset: Optional[int] = None  # byte index into payload region
    corrupt_bit: int = 0                  # which bit to flip (0..7)
    truncate_at: Optional[int] = None     # send only this many frame bytes
    reset: bool = False                   # drop the conn without sending

    def any(self) -> bool:
        return (self.corrupt_offset is not None
                or self.truncate_at is not None or self.reset)


class ChaosEngine:
    """Per-rank deterministic fault decider.

    One engine serves both planes: :meth:`on_op` is called once per
    ``send``/``receive`` (sleeps for latency/delay modes, enforces
    crash@K, and — for remote sends — returns the :class:`WireFault`
    the TCP driver applies to that frame)."""

    def __init__(self, config: ChaosConfig):
        self.config = config
        self._lock = threading.Lock()
        self._seq: Dict[Tuple[str, int, int], int] = {}
        self._ops = 0

    # -- determinism core ---------------------------------------------------

    def _draw(self, op: str, peer: int, tag: int, seq: int,
              salt: str) -> float:
        """Uniform [0, 1) derived from a stable hash — thread-schedule
        and PYTHONHASHSEED independent."""
        key = f"{self.config.seed}:{op}:{peer}:{tag}:{seq}:{salt}"
        digest = hashlib.blake2b(key.encode("utf-8"),
                                 digest_size=8).digest()
        return int.from_bytes(digest, "little") / float(1 << 64)

    def _next(self, op: str, peer: int, tag: int) -> Tuple[int, int]:
        """(per-channel sequence, total op count) — both under one lock
        so crash@K counts every chaos-visible op exactly once."""
        key = (op, peer, tag)
        with self._lock:
            seq = self._seq.get(key, 0) + 1
            self._seq[key] = seq
            self._ops += 1
            return seq, self._ops

    # -- op plane -----------------------------------------------------------

    def on_op(self, op: str, peer: int, tag: int,
              wire: bool = False) -> Optional[WireFault]:
        """Account one operation: apply crash@K and delay-mode sleeps;
        return the wire fault plan for this frame (remote sends with a
        wire mode active and the dice landing under ``rate``), else
        ``None``."""
        cfg = self.config
        seq, total = self._next(op, peer, tag)
        if cfg.crash_at is not None and total >= cfg.crash_at:
            import sys as _sys

            print(f"mpi_tpu: chaos crash@{cfg.crash_at} — injected rank "
                  f"death (op {total}: {op} peer={peer} tag={tag})",
                  file=_sys.stderr)
            # Flight-recorder postmortem: the dying rank's in-flight op
            # and recent-op ring hit disk before the injected death, so
            # the launcher's job report can name what it was doing
            # (docs/OBSERVABILITY.md).
            try:
                from .observe import flight as _flight

                path = _flight.dump(
                    f"chaos crash@{cfg.crash_at} (op {total}: {op} "
                    f"peer={peer} tag={tag})")
                if path:
                    print(f"mpi_tpu: observe: flight-recorder postmortem "
                          f"written to {path}", file=_sys.stderr)
            except BaseException:  # noqa: BLE001 - dying anyway
                pass
            # Under --mpi-trace-stream, push the tracer's unflushed
            # tail to the spool so the merged trace / job postmortem
            # can show this rank's spans right up to the injected
            # death.
            try:
                from .utils import trace as _trace

                _trace.flush_stream()
            except BaseException:  # noqa: BLE001 - dying anyway
                pass
            _sys.stderr.flush()
            os._exit(CRASH_EXIT_CODE)
        if "latency" in cfg.modes and \
                self._draw(op, peer, tag, seq, "lat?") < cfg.rate:
            time.sleep(self._draw(op, peer, tag, seq, "lat") * _MAX_LATENCY_S)
        if "delay" in cfg.modes and \
                self._draw(op, peer, tag, seq, "dly?") < cfg.rate:
            time.sleep(self._draw(op, peer, tag, seq, "dly") * _MAX_DELAY_S)
        if not wire or op != "send" or not cfg.wire_modes:
            return None
        if self._draw(op, peer, tag, seq, "wire?") >= cfg.rate:
            return None
        # Pick one active wire mode deterministically.
        modes = sorted(cfg.wire_modes)
        mode = modes[int(self._draw(op, peer, tag, seq, "mode")
                         * len(modes))]
        fault = WireFault()
        if mode == "corrupt":
            fault.corrupt_offset = int(
                self._draw(op, peer, tag, seq, "off") * (1 << 30))
            fault.corrupt_bit = int(
                self._draw(op, peer, tag, seq, "bit") * 8)
        elif mode == "truncate":
            fault.truncate_at = int(
                self._draw(op, peer, tag, seq, "cut") * (1 << 30))
        elif mode == "reset":
            fault.reset = True
        return fault


class ChaosNetwork:
    """Interface wrapper running any backend under op-plane chaos.

    Wire-plane faults need frame access, so when the inner backend
    exposes a ``_chaos`` attachment point (the TCP driver) the engine is
    installed there and the driver does all injection itself — the
    wrapper then only forwards, avoiding double-counting ops. Every
    other attribute (collectives, ``iprobe``, ``host_key``, ...)
    passes through untouched, so the facade's capability probing sees
    exactly the inner backend's surface.

    ``--mpi-chaos`` / ``MPI_TPU_CHAOS`` reaches the default TCP backend
    without this wrapper (the driver self-installs from flags); wrap
    explicitly to put other backends — or a hand-built engine — under
    chaos."""

    def __init__(self, inner: Interface,
                 spec: Optional[str] = None,
                 engine: Optional[ChaosEngine] = None):
        if engine is None:
            if spec is None:
                raise MpiError("mpi_tpu: ChaosNetwork needs a chaos spec "
                               "or a prebuilt ChaosEngine")
            engine = ChaosEngine(parse_chaos(spec))
        self._inner = inner
        self._engine = engine
        self._wire_level = hasattr(inner, "_chaos")
        if self._wire_level:
            inner._chaos = engine

    # -- Interface ----------------------------------------------------------

    def init(self) -> None:
        self._inner.init()

    def finalize(self) -> None:
        self._inner.finalize()

    def rank(self) -> int:
        return self._inner.rank()

    def size(self) -> int:
        return self._inner.size()

    def send(self, data: Any, dest: int, tag: int) -> None:
        if not self._wire_level:
            self._engine.on_op("send", dest, tag)
        self._inner.send(data, dest, tag)

    def receive(self, source: int, tag: int,
                out: Optional[Any] = None) -> Any:
        if not self._wire_level:
            self._engine.on_op("receive", source, tag)
        return self._inner.receive(source, tag, out=out)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"ChaosNetwork({self._inner!r}, config={self._engine.config})"

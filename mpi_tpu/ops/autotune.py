"""Flash-attention block-size autotuner.

The Pallas flash kernel's throughput on a given chip is dominated by
its ``(block_q, block_k)`` grid shape — the shipped 256x512 default
came from a hand sweep on v5e at s=1024 (2.6x over 128x128), but the
best shape shifts with sequence length, head count, head dim, and chip
generation. :func:`tune_flash_blocks` measures the real kernel
(forward or forward+backward) over a candidate grid ON THE CURRENT
BACKEND, registers the winner for the exact tuned shape
(:func:`mpi_tpu.ops.attention.register_tuned_blocks` — consulted at
trace time before the global default, so tuning one shape never
degrades another), and returns the full timing table so benchmarks can
report the kernel-level breakdown.

No reference analogue (btracey/mpi has no kernels); the method is the
bounce harness's discipline (/root/reference/examples/bounce/
bounce.go:85-152 — warm up, repeat, report the representative time)
applied to kernel configs.
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .attention import (_pick_block, flash_attention,
                        register_tuned_blocks)

__all__ = ["tune_flash_blocks", "DEFAULT_CANDIDATES"]

# Pallas TPU wants the trailing dims MXU/VPU-tileable: multiples of 128
# in both block axes. The grid covers skinny-q (decode-ish), square,
# and wide-k (long-context) shapes.
DEFAULT_CANDIDATES: Tuple[Tuple[int, int], ...] = (
    (128, 128), (128, 256), (128, 512),
    (256, 256), (256, 512), (256, 1024),
    (512, 256), (512, 512), (512, 1024),
    (1024, 512),
)

# (shape key, backend) -> chosen (block_q, block_k); one sweep per
# distinct shape per process.
_cache: Dict[tuple, Tuple[int, int]] = {}


# Committed with the package: winners tuned on real hardware survive
# not just across processes but across checkouts/rounds, so a short
# device window spends its minutes measuring, never re-tuning.
_DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "flash_tune_cache.json")


def _disk_cache_path() -> Optional[str]:
    """Cross-process winner cache. Defaults to the committed
    ``flash_tune_cache.json`` next to this module; override with
    ``MPI_TPU_TUNE_CACHE=path`` or disable with ``MPI_TPU_TUNE_CACHE=``
    (empty). A TPU sweep costs one kernel compile per candidate —
    behind a slow or flaky device tunnel that is minutes; persisting
    winners makes every later run free."""
    if "MPI_TPU_TUNE_CACHE" in os.environ:
        return os.environ["MPI_TPU_TUNE_CACHE"] or None
    return _DEFAULT_CACHE


def _disk_cache_load(key: tuple) -> Optional[Tuple[int, int]]:
    path = _disk_cache_path()
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            rec = json.load(f).get(repr(key))
        return (int(rec[0]), int(rec[1])) if rec else None
    except (OSError, ValueError, TypeError, KeyError, IndexError,
            AttributeError):
        # Any malformed cache content — wrong JSON shape included —
        # degrades to a re-sweep, never a crash.
        return None


def _disk_cache_store(key: tuple, best: Tuple[int, int]) -> None:
    path = _disk_cache_path()
    if not path:
        return
    try:
        data = {}
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
        data[repr(key)] = list(best)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)
    except (OSError, ValueError):
        pass  # best-effort; the in-process sweep result still applies


def _time_once(fn, *args) -> float:
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    return time.perf_counter() - t0


def tune_flash_blocks(batch: int, seq: int, heads: int, head_dim: int,
                      *, kv_heads: Optional[int] = None,
                      seq_k: Optional[int] = None, causal: bool = True,
                      dtype=jnp.bfloat16,
                      candidates: Optional[Sequence[Tuple[int, int]]]
                      = None,
                      reps: int = 3, include_bwd: bool = True,
                      set_default: bool = True,
                      interpret: Optional[bool] = None):
    """Sweep flash block configs at the given attention shape; return
    ``(best_blocks, table)``.

    ``table`` is ``[{"block_q", "block_k", "ms"}, ...]`` sorted
    fastest-first (median of ``reps`` post-warmup runs of the jitted
    kernel — forward+backward when ``include_bwd``, the training
    shape). With ``set_default`` (the default) the winner is registered
    for the EXACT tuned ``(seq, seq_k)`` shape
    (:func:`mpi_tpu.ops.attention.register_tuned_blocks`), so
    default-block ``flash_attention`` calls at that shape — the
    transformer stack at the tuned sequence length — use it, while
    calls at other shapes keep the shipped global default (a winner
    shrunk to fit a short sequence must not degrade longer ones).
    Results are cached per (shape, candidates, backend): repeat calls
    are free.
    """
    kv = heads if kv_heads is None else kv_heads
    tk = seq if seq_k is None else seq_k
    cands = tuple(candidates) if candidates else DEFAULT_CANDIDATES
    # device_kind, not just the backend name: a persisted winner tuned
    # on one TPU generation must not be reused on another (the best
    # grid shifts with the chip — module doc).
    key = (batch, seq, tk, heads, kv, head_dim, causal, include_bwd,
           str(jnp.dtype(dtype)), jax.default_backend(),
           jax.devices()[0].device_kind, cands)
    if key not in _cache:
        disk = _disk_cache_load(key)
        if disk is not None:
            _cache[key] = disk
    if key in _cache:
        best = _cache[key]
        if set_default:
            register_tuned_blocks(seq, tk, *best)
        return best, []

    rng = jax.random.PRNGKey(0)
    kq, kk, kv_ = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (batch, seq, heads, head_dim), dtype)
    k = jax.random.normal(kk, (batch, tk, kv, head_dim), dtype)
    v = jax.random.normal(kv_, (batch, tk, kv, head_dim), dtype)

    # Distinct preferences can collapse onto one effective grid at
    # short sequences (_pick_block shrinks to divide s) — dedupe on the
    # effective blocks so no config is compiled twice.
    effective: List[Tuple[int, int]] = []
    seen = set()
    for bq, bk in cands:
        eff = (_pick_block(seq, bq), _pick_block(tk, bk))
        if eff not in seen:
            seen.add(eff)
            effective.append(eff)

    def build(bq: int, bk: int):
        if include_bwd:
            def loss(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, causal, bq, bk,
                                    interpret).astype(jnp.float32))
            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        return jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal, bq, bk, interpret))

    # Each candidate costs a kernel compile — through a tunnel that is
    # 20-40 s each. A sweep deadline (MPI_TPU_TUNE_DEADLINE_S, 0
    # disables) stops after the candidate in flight and takes the best
    # so far, so the caller's own budget (e.g. the bench train leg's
    # subprocess timeout) is never blown by tuning alone; the truncated
    # marker in the table records which configs went unmeasured.
    deadline_s = float(os.environ.get("MPI_TPU_TUNE_DEADLINE_S", "300"))
    t_start = time.monotonic()
    table = []
    for bq, bk in effective:
        # Truncate only once something actually TIMED — a prefix of
        # failed candidates (VMEM misfits) must not cut off the
        # still-viable rest, however long their failed compiles took.
        if deadline_s > 0 and any("ms" in t for t in table) \
                and time.monotonic() - t_start > deadline_s:
            table.append({"block_q": bq, "block_k": bk,
                          "error": "untried: tune deadline "
                                   f"({deadline_s:.0f}s) reached"})
            continue
        fn = build(bq, bk)
        try:
            _time_once(fn, q, k, v)  # compile + warm
            ms = statistics.median(
                _time_once(fn, q, k, v) for _ in range(reps)) * 1e3
        except Exception as exc:  # noqa: BLE001 - config may not fit VMEM
            table.append({"block_q": bq, "block_k": bk,
                          "error": str(exc)[:120]})
            continue
        table.append({"block_q": bq, "block_k": bk, "ms": round(ms, 3)})

    timed = [t for t in table if "ms" in t]
    if not timed:
        raise RuntimeError(
            f"mpi_tpu: flash autotune: no candidate compiled/ran "
            f"({[t.get('error') for t in table][:3]})")
    timed.sort(key=lambda t: t["ms"])
    best = (timed[0]["block_q"], timed[0]["block_k"])
    truncated = any("untried" in str(t.get("error", "")) for t in table)
    # A truncated winner serves THIS process (re-tuning now would blow
    # the same deadline again) but is never persisted: the next run —
    # with time to finish the sweep — must not inherit a
    # first-candidates-only result as if it were the full verdict.
    _cache[key] = best
    if not truncated:
        _disk_cache_store(key, best)
    if set_default:
        register_tuned_blocks(seq, tk, *best)
    return best, timed + [t for t in table if "ms" not in t]

"""Pallas ring collectives — hand-scheduled ICI neighbour DMA.

XLA's built-in collectives (``lax.psum`` et al., used by
:mod:`mpi_tpu.parallel.collectives`) are the production path; these
kernels are the framework's *native* collective implementations, written
directly against the TPU interconnect with
``pltpu.make_async_remote_copy``: each device pushes a buffer to its ring
neighbour's VMEM and signals a DMA semaphore — exactly the transfer the
reference performs with a TCP socket write + ack (network.go:518-625),
re-expressed as chip-to-chip RDMA. They exist (a) as the lowest-level
point on the framework's collective stack, (b) to support custom fusion
(compute folded into the ring step) that XLA's opaque collectives can't
express, and (c) as executable documentation of the pallas_guide.md ring
pattern.

Algorithms:
  * :func:`ring_allgather` — n-1 ring hops, double-buffered;
  * :func:`ring_allreduce` — bandwidth-optimal two-phase ring:
    reduce-scatter (n-1 hops, each folding the arriving partial into the
    resident chunk) then allgather of the reduced chunks (n-1 hops).
    2·(n-1)/n · bytes moved per device — the classic ring bound.

Both are per-device bodies to be traced inside ``shard_map`` over the
ring axis; ``*_sharded`` wrappers handle that. On non-TPU backends the
kernels run in the Pallas interpreter (exact same code path the tests
exercise on the virtual CPU mesh).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

__all__ = ["ring_allgather", "ring_allreduce",
           "ring_allgather_sharded", "ring_allreduce_sharded"]


def _combine(a, b, op: str):
    if op == "sum":
        return a + b
    if op == "max":
        return jnp.maximum(a, b)
    if op == "min":
        return jnp.minimum(a, b)
    if op == "prod":
        return a * b
    raise ValueError(f"mpi_tpu: unknown ring op {op!r}")


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# All-gather
# --------------------------------------------------------------------------

def _allgather_kernel(x_ref, out_ref, comm, send_sem, recv_sem, *,
                      axis_name: str):
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    chunk = x_ref.shape[0]
    out_ref[pl.ds(me * chunk, chunk)] = x_ref[...]
    comm[0] = x_ref[...]
    for step in range(n - 1):
        src = (me - step - 1) % n
        dst = (me + 1) % n
        s_slot, r_slot = step % 2, (step + 1) % 2
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm.at[s_slot], dst_ref=comm.at[r_slot],
            send_sem=send_sem.at[s_slot], recv_sem=recv_sem.at[r_slot],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()
        out_ref[pl.ds(src * chunk, chunk)] = comm[r_slot]


def ring_allgather(x: jax.Array, axis_name: str = "rank",
                   interpret: Optional[bool] = None) -> jax.Array:
    """Per-device body: gather every device's ``x`` (concatenated along
    axis 0 in ring order). Call inside shard_map over ``axis_name``."""
    itp = _should_interpret() if interpret is None else interpret
    n = lax.axis_size(axis_name)
    kernel = functools.partial(_allgather_kernel, axis_name=axis_name)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((x.shape[0] * n, *x.shape[1:]),
                                       x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, *x.shape), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                             collective_id=0),
        interpret=itp,
    )(x)


# --------------------------------------------------------------------------
# All-reduce (reduce-scatter ring + allgather ring)
# --------------------------------------------------------------------------

def _allreduce_kernel(x_ref, out_ref, comm, send_sem, recv_sem, *,
                      axis_name: str, op: str, n: int):
    me = lax.axis_index(axis_name)
    m = x_ref.shape[0]
    chunk = m // n
    out_ref[...] = x_ref[...]

    def hop(value, slot_step):
        """One neighbour push: send `value`, return the arriving buffer."""
        s_slot, r_slot = slot_step % 2, (slot_step + 1) % 2
        comm[s_slot] = value
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm.at[s_slot], dst_ref=comm.at[r_slot],
            send_sem=send_sem.at[s_slot], recv_sem=recv_sem.at[r_slot],
            device_id=(me + 1) % n,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()
        return comm[r_slot]

    # Phase 1 — reduce-scatter: after step t every device has folded t+1
    # partials into chunk (me - t) % n; chunk (me + 1) % n ends fully
    # reduced here.
    for step in range(n - 1):
        send_idx = (me - step) % n
        recv_idx = (me - step - 1) % n
        arrived = hop(out_ref[pl.ds(send_idx * chunk, chunk)], step)
        out_ref[pl.ds(recv_idx * chunk, chunk)] = _combine(
            out_ref[pl.ds(recv_idx * chunk, chunk)], arrived, op)

    # Phase 2 — allgather of the reduced chunks around the same ring.
    for step in range(n - 1):
        send_idx = (me + 1 - step) % n
        recv_idx = (me - step) % n
        arrived = hop(out_ref[pl.ds(send_idx * chunk, chunk)],
                      (n - 1) + step)
        out_ref[pl.ds(recv_idx * chunk, chunk)] = arrived


def ring_allreduce(x: jax.Array, axis_name: str = "rank", op: str = "sum",
                   interpret: Optional[bool] = None) -> jax.Array:
    """Per-device body: bandwidth-optimal ring allreduce of ``x`` across
    ``axis_name``. ``x.shape[0]`` must be divisible by the ring size (the
    sharded wrapper pads). Reduction order is ring order — deterministic,
    but not the binomial tree of the bitwise-parity path."""
    itp = _should_interpret() if interpret is None else interpret
    n = lax.axis_size(axis_name)
    if x.shape[0] % n:
        raise ValueError(
            f"mpi_tpu: ring_allreduce needs axis-0 divisible by ring size "
            f"{n}, got {x.shape[0]} (use ring_allreduce_sharded, which pads)")
    kernel = functools.partial(_allreduce_kernel, axis_name=axis_name,
                               op=op, n=n)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, x.shape[0] // n, *x.shape[1:]), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                             collective_id=1),
        interpret=itp,
    )(x)


# --------------------------------------------------------------------------
# shard_map wrappers
# --------------------------------------------------------------------------

def ring_allgather_sharded(x: jax.Array, mesh, axis_name: str = "rank",
                           interpret: Optional[bool] = None) -> jax.Array:
    """Global view: ``x`` sharded over ``axis_name`` on axis 0 → gathered
    (replicated) result."""
    body = functools.partial(ring_allgather, axis_name=axis_name,
                             interpret=interpret)
    fn = jax.shard_map(body, mesh=mesh, in_specs=P(axis_name),
                       out_specs=P(), check_vma=False)
    return fn(x)


def ring_allreduce_sharded(contribs: jax.Array, mesh,
                           axis_name: str = "rank", op: str = "sum",
                           interpret: Optional[bool] = None) -> jax.Array:
    """Global view: ``contribs`` is ``(n, m, ...)`` — device i's
    contribution at index i, sharded over ``axis_name`` — and the result
    is the ``(m, ...)`` reduction, replicated. Pads ``m`` to a multiple
    of the ring size internally."""
    n = mesh.shape[axis_name]
    if contribs.shape[0] != n:
        raise ValueError(
            f"mpi_tpu: contribs leading axis {contribs.shape[0]} != ring "
            f"size {n}")
    m = contribs.shape[1]
    pad = (-m) % n
    if pad:
        contribs = jnp.pad(
            contribs, ((0, 0), (0, pad)) + ((0, 0),) * (contribs.ndim - 2))

    def body(c):
        return ring_allreduce(c[0], axis_name=axis_name, op=op,
                              interpret=interpret)

    fn = jax.shard_map(body, mesh=mesh, in_specs=P(axis_name),
                       out_specs=P(), check_vma=False)
    out = fn(contribs)
    return out[:m] if pad else out

"""Pallas flash-decode kernel — fused single-query attention over the
KV cache.

Decode-time attention is the long-context serving hot op: one query
position against the whole cache, every step. The unfused path
materialises (heads, t) logits and probabilities between HBM-visible
ops; this kernel streams the cache through VMEM once per step with an
online softmax (the FlashAttention recurrence, specialised to s_q = 1),
so per-token attention cost is one read of K and V and nothing else —
the op is purely bandwidth-bound, which is exactly what the roofline
says it should be. The caches are consumed IN PLACE in their storage
layout (b, t, kv, hd) via the block index map — no transpose/reshape
copy of the full cache per step, which would have doubled the traffic
the kernel exists to minimise.

Grouped-query layout is native: the kernel's "rows" are the ``group =
n_heads / kv_heads`` queries that share one kv head, so each K/V tile
is read once per kv head (GQA's bandwidth win carries into the kernel;
rows are padded up to the TPU sublane multiple when the group is
small). The cache's dead tail — positions past ``n_valid`` — is
masked, and whole key blocks past it skip their matmuls entirely
(``pl.when``), so compute tracks the LIVE cache length even though
shapes stay static.

Used by the decode path when ``TransformerConfig.decode_attention =
"flash"`` (models/generate.py); the dense jnp path remains the default
and the correctness oracle. Off-TPU the kernel runs in interpreter
mode, so tests cover it everywhere; on builds without pallas it
degrades to an equivalent jnp fold (same numerics contract as
``attention.flash_attention``'s fallback). No reference analogue
(btracey/mpi has no models).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .attention import NEG_INF, _pick_block, _should_interpret

try:  # pallas ships with jax; guard exotic builds like attention.py does
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover - jax always ships pallas here
    _HAVE_PALLAS = False

__all__ = ["flash_decode_attention"]

_MIN_ROWS = 8  # TPU f32 sublane multiple; small GQA groups pad up


def _decode_kernel(n_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *, scale: float, block_k: int,
                   t: int):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    n_valid = n_ref[0, 0]

    # Key blocks wholly past the live cache contribute nothing: skip
    # both matmuls (the online-softmax state is untouched, which is the
    # correct skip semantics).
    @pl.when(ki * block_k <= n_valid)
    def _():
        # Stored dtype in, f32 accumulation out: bf16 dots run the MXU
        # at full rate (an f32 upcast first would quarter throughput
        # for the same f32 accumulator); softmax state stays f32.
        q = q_ref[0, 0]                            # (rows, d)
        k = k_ref[0, :, 0]                         # (block_k, d)
        v = v_ref[0, :, 0]
        logits = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = ki * block_k + lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        valid = (col <= n_valid) & (col < t)
        logits = jnp.where(valid, logits, NEG_INF)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=-1)
        m_scr[:, 0] = m_new
        acc_scr[:] = acc_scr[:] * corr[:, None] + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        l = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        # log-sum-exp rows: what cache-parallel decode needs to merge
        # shard partials exactly (parallel/cache_parallel.py). A shard
        # whose live prefix is empty reports ~-1e30, which the merge
        # weights to zero.
        lse_ref[0, 0, 0] = m_scr[:, 0] + jnp.log(l)


def _jnp_fallback(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                  n_valid: jax.Array, group: int):
    """Pallas-less equivalent (also the shape-semantics oracle).
    Returns (out, lse) like the kernel's with_lse mode. For a fully
    masked row (n_valid < 0, the cache-parallel empty-shard case) the
    ctx is an artifact of exp(-inf - -inf) but its lse is ~-1e30, so
    the shard merge weights it to zero — same contract as the kernel's
    all-blocks-skipped zero output."""
    b, h, hd = q.shape
    kv = k_cache.shape[2]
    qg = q.reshape(b, kv, group, hd)
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bKgk,btKk->bKgt", qg, k_cache) * scale
    col = lax.broadcasted_iota(jnp.int32, logits.shape, 3)
    logits = jnp.where(col <= n_valid, logits, NEG_INF).astype(jnp.float32)
    m = jnp.max(logits, axis=-1)
    l = jnp.maximum(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1),
                    1e-30)
    probs = jnp.exp(logits - m[..., None]) / l[..., None]
    ctx = jnp.einsum("bKgt,btKk->bKgk", probs.astype(q.dtype), v_cache)
    lse = (m + jnp.log(l)).reshape(b, h)
    return ctx.reshape(b, h, hd), lse


def flash_decode_attention(q: jax.Array, k_cache: jax.Array,
                           v_cache: jax.Array, n_valid: jax.Array,
                           block_k: int = 512,
                           interpret: Optional[bool] = None,
                           with_lse: bool = False):
    """Single-position attention against the cache.

    ``q``: (b, h, hd) — the one decode position's queries;
    ``k_cache``/``v_cache``: (b, t, kv, hd) with ``h % kv == 0``;
    ``n_valid``: scalar int32, the query's absolute position (it
    attends to cache columns ``0 .. n_valid`` inclusive — its own k/v
    must already be written at column ``n_valid``). Returns (b, h, hd)
    in the query dtype; with ``with_lse=True`` additionally the
    float32 (b, h) log-sum-exp rows — the sufficient statistic for
    merging shard partials in cache-parallel decode."""
    b, h, hd = q.shape
    _, t, kv, _ = k_cache.shape
    if h % kv:
        raise ValueError(f"mpi_tpu: n_heads {h} not divisible by "
                         f"kv_heads {kv}")
    group = h // kv
    if not _HAVE_PALLAS:
        out, lse = _jnp_fallback(q, k_cache, v_cache,
                                 jnp.asarray(n_valid, jnp.int32), group)
        return (out, lse) if with_lse else out
    rows = max(group, _MIN_ROWS)
    itp = _should_interpret() if interpret is None else interpret
    # A divisor block size (like the flash kernel's _pick_block) keeps
    # the cache operand un-padded — padding it would copy the whole
    # cache every step.
    bk = _pick_block(t, min(block_k, t))
    nk = t // bk
    scale = 1.0 / math.sqrt(hd)
    n_arr = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)

    # Only the tiny per-step q is re-laid-out; the caches stay in their
    # storage layout and are tiled in place by the index maps.
    qg = q.reshape(b, kv, group, hd)
    if rows != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - group), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, block_k=bk, t=t),
        grid=(b, kv, nk),
        in_specs=[
            # Scalar in SMEM: it feeds the pl.when block-skip predicate,
            # and scalar control flow is what SMEM is for (a VMEM load
            # is not a reliable predicate source under Mosaic).
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, rows, hd),
                         lambda bi, kvi, ki: (bi, kvi, 0, 0)),
            pl.BlockSpec((1, bk, 1, hd),
                         lambda bi, kvi, ki: (bi, ki, kvi, 0)),
            pl.BlockSpec((1, bk, 1, hd),
                         lambda bi, kvi, ki: (bi, ki, kvi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, rows, hd),
                         lambda bi, kvi, ki: (bi, kvi, 0, 0)),
            # lse rows live as (b, kv, 1, rows): the block's trailing
            # two dims (1, rows) fit Mosaic's tiling rule (same layout
            # trick as the flash kernel's lse output).
            pl.BlockSpec((1, 1, 1, rows),
                         lambda bi, kvi, ki: (bi, kvi, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, kv, rows, hd), q.dtype),
            jax.ShapeDtypeStruct((b, kv, 1, rows), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, hd), jnp.float32),
        ],
        interpret=itp,
    )(n_arr, qg, k_cache, v_cache)

    out, lse = out
    res = out[:, :, :group].reshape(b, h, hd)
    if not with_lse:
        return res
    return res, lse[:, :, 0, :group].reshape(b, h)

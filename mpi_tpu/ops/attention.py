"""Attention kernels: dense reference, blockwise-scan, Pallas flash.

Three implementations of the same math (softmax(q·kᵀ/√d)·v, optionally
causal), in increasing tpu-nativeness:

  * :func:`dense_attention` — the O(s²)-memory reference used by tests and
    tiny models;
  * :func:`blockwise_attention` — online-softmax over key blocks via
    ``lax.scan`` with per-step rematerialisation (``jax.checkpoint``), so
    peak memory is O(s·block) while staying a single differentiable XLA
    program. Its per-block recurrence, :func:`online_softmax_fold`, is
    shared with ring attention
    (:mod:`mpi_tpu.parallel.ring_attention`);
  * :func:`flash_attention` — the Pallas TPU kernel: q/k/v tiles staged
    through VMEM, MXU matmuls with float32 accumulation, running
    (m, l, acc) online-softmax state in VMEM scratch across the key-block
    grid dimension. Backward runs the checkpointed blockwise
    implementation under ``jax.vjp`` (recompute, no O(s²) residuals).

All take ``q, k, v`` shaped ``(batch, seq, heads, head_dim)`` — the layout
:mod:`mpi_tpu.models.transformer` uses — and return the same shape. The
reference repo has no attention anywhere (it is a transport library); these
kernels are new tpu-first work layered on it.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["dense_attention", "blockwise_attention", "flash_attention",
           "online_softmax_fold", "NEG_INF"]

NEG_INF = -1e30  # finite mask value: keeps exp() well-defined everywhere
_NEG_INF = NEG_INF


def _scale(q):
    return 1.0 / math.sqrt(q.shape[-1])


def online_softmax_fold(q32, kc, vc, m, l, acc, scale, mask=None):
    """One step of the flash-attention recurrence, shared by
    :func:`blockwise_attention` and ring attention.

    ``q32`` is ``(b, h, s, d)`` float32; ``kc``/``vc`` are the visiting
    key/value chunk ``(b, h, t, d)``; ``(m, l, acc)`` is the running
    (row-max, normaliser, unnormalised output) state with shapes
    ``(b, h, s) / (b, h, s) / (b, h, s, d)``; ``mask`` is an optional
    ``(s, t)`` bool array (True = attend). Returns the updated state."""
    logits = jnp.einsum("bhsk,bhtk->bhst", q32,
                        kc.astype(jnp.float32)) * scale
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhst,bhtk->bhsk", p, vc.astype(jnp.float32))
    return m_new, l_new, acc_new


# --------------------------------------------------------------------------
# Dense reference
# --------------------------------------------------------------------------

def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True) -> jax.Array:
    """Materialised-logits attention; the correctness oracle."""
    logits = jnp.einsum("bshk,bthk->bhst", q, k) * _scale(q)
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhst,bthk->bshk", probs.astype(q.dtype), v)


# --------------------------------------------------------------------------
# Blockwise scan (differentiable, memory-light)
# --------------------------------------------------------------------------

def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        block_k: int = 128) -> jax.Array:
    """Online-softmax attention scanning over key blocks.

    One ``lax.scan`` step attends the full query tensor to one key/value
    block and folds the result into running ``(m, l, acc)`` state — the
    standard flash-attention recurrence. Each step is wrapped in
    ``jax.checkpoint`` so the backward pass recomputes the block instead
    of storing O(s²) probabilities.
    """
    b, s, h, d = q.shape
    t = k.shape[1]
    bk = min(block_k, t)
    if t % bk:  # pad keys; padded positions are masked out below
        pad = bk - t % bk
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    nk = k.shape[1] // bk
    # (b, s, h, d) -> per-block (nk, b, h, bk, d) for the shared fold
    kb = k.reshape(b, nk, bk, h, d).transpose(1, 0, 3, 2, 4)
    vb = v.reshape(b, nk, bk, h, d).transpose(1, 0, 3, 2, 4)

    scale = _scale(q)
    q32 = q.transpose(0, 2, 1, 3).astype(jnp.float32)  # (b, h, s, d)
    row_ids = lax.broadcasted_iota(jnp.int32, (s, bk), 0)

    @jax.checkpoint
    def step(carry, blk):
        m, l, acc = carry
        kblk, vblk, start = blk
        col_ids = start + lax.broadcasted_iota(jnp.int32, (s, bk), 1)
        valid = col_ids < t
        if causal:
            valid &= row_ids >= col_ids
        return online_softmax_fold(q32, kblk, vblk, m, l, acc, scale,
                                   mask=valid), None

    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    a0 = jnp.zeros((b, h, s, d), jnp.float32)
    starts = jnp.arange(nk, dtype=jnp.int32) * bk
    (m, l, acc), _ = lax.scan(step, (m0, l0, a0), (kb, vb, starts))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas flash kernel
# --------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  causal: bool, scale: float, block_q: int, block_k: int,
                  seq_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)       # (block_q, d)
        k = k_ref[0].astype(jnp.float32)       # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        row = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = col < seq_k
        if causal:
            valid &= row >= col
        logits = jnp.where(valid, logits, _NEG_INF)

        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=-1)
        m_scr[:, 0] = m_new
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # Blocks entirely above the diagonal contribute nothing — skip the
        # two MXU matmuls (the mask math keeps skipped-state consistent).
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        l = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)


try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover - jax always ships pallas here
    _HAVE_PALLAS = False


def _pick_block(n: int, preferred: int) -> int:
    """Largest power-of-two ≤ preferred that divides n (n itself if none —
    one full block beats a degenerate 1-element grid)."""
    bsz = preferred
    while bsz > 1:
        if n % bsz == 0:
            return bsz
        bsz //= 2
    return n


def _flash_fwd_pallas(q, k, v, causal: bool, block_q: int, block_k: int,
                      interpret: bool) -> jax.Array:
    b, s, h, d = q.shape
    t = k.shape[1]
    bq = _pick_block(s, block_q)
    bk = _pick_block(t, block_k)
    # (b, s, h, d) -> (b*h, s, d): heads become the embarrassingly parallel
    # leading grid dimension.
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    grid = (b * h, s // bq, t // bk)
    kernel = functools.partial(
        _flash_kernel, causal=causal, scale=_scale(q), block_q=bq,
        block_k=bk, seq_k=t)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # running max m
            pltpu.VMEM((bq, 1), jnp.float32),   # running sum l
            pltpu.VMEM((bq, d), jnp.float32),   # accumulator
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention: Pallas TPU kernel forward, recompute backward.

    ``interpret=None`` auto-selects interpreter mode off-TPU so tests run
    on CPU against the same kernel code. Falls back to
    :func:`blockwise_attention` when Pallas is unavailable.
    """
    itp = _should_interpret() if interpret is None else interpret
    if not _HAVE_PALLAS:  # pragma: no cover
        return blockwise_attention(q, k, v, causal=causal, block_k=block_k)
    return _flash_fwd_pallas(q, k, v, causal, block_q, block_k, itp)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    # Recompute through the checkpointed blockwise scan — same math, no
    # O(s²) residuals; a dedicated Pallas backward kernel can slot in here
    # without touching callers.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, block_k=block_k), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)

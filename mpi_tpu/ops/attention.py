"""Attention kernels: dense reference, blockwise-scan, Pallas flash.

Three implementations of the same math (softmax(q·kᵀ/√d)·v, optionally
causal), in increasing tpu-nativeness:

  * :func:`dense_attention` — the O(s²)-memory reference used by tests and
    tiny models;
  * :func:`blockwise_attention` — online-softmax over key blocks via
    ``lax.scan`` with per-step rematerialisation (``jax.checkpoint``), so
    peak memory is O(s·block) while staying a single differentiable XLA
    program. Its per-block recurrence, :func:`online_softmax_fold`, is
    shared with ring attention
    (:mod:`mpi_tpu.parallel.ring_attention`);
  * :func:`flash_attention` — the Pallas TPU kernel: q/k/v tiles staged
    through VMEM, MXU matmuls with float32 accumulation, running
    (m, l, acc) online-softmax state in VMEM scratch across the key-block
    grid dimension. Backward is the FlashAttention-2 scheme in Pallas
    too: the forward saves only the log-sum-exp rows, and two kernels
    (dq over key blocks; dk/dv over query blocks) rebuild the
    probabilities on the fly — no O(s²) residuals.

All take ``q, k, v`` shaped ``(batch, seq, heads, head_dim)`` — the layout
:mod:`mpi_tpu.models.transformer` uses — and return the same shape. The
reference repo has no attention anywhere (it is a transport library); these
kernels are new tpu-first work layered on it.
"""

from __future__ import annotations

import functools
import os
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["dense_attention", "blockwise_attention", "flash_attention",
           "flash_attention_with_lse", "flash_chunk_bwd",
           "merge_attention_chunks", "online_softmax_fold", "NEG_INF"]

NEG_INF = -1e30  # finite mask value: keeps exp() well-defined everywhere
_NEG_INF = NEG_INF


def _scale(q):
    return 1.0 / math.sqrt(q.shape[-1])


def online_softmax_fold(q32, kc, vc, m, l, acc, scale, mask=None):
    """One step of the flash-attention recurrence, shared by
    :func:`blockwise_attention` and ring attention.

    ``q32`` is ``(b, h, s, d)`` float32; ``kc``/``vc`` are the visiting
    key/value chunk ``(b, h, t, d)``; ``(m, l, acc)`` is the running
    (row-max, normaliser, unnormalised output) state with shapes
    ``(b, h, s) / (b, h, s) / (b, h, s, d)``; ``mask`` is an optional
    ``(s, t)`` bool array (True = attend). Returns the updated state."""
    logits = jnp.einsum("bhsk,bhtk->bhst", q32,
                        kc.astype(jnp.float32)) * scale
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhst,bhtk->bhsk", p, vc.astype(jnp.float32))
    return m_new, l_new, acc_new


# --------------------------------------------------------------------------
# Dense reference
# --------------------------------------------------------------------------

def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True) -> jax.Array:
    """Materialised-logits attention; the correctness oracle."""
    logits = jnp.einsum("bshk,bthk->bhst", q, k) * _scale(q)
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhst,bthk->bshk", probs.astype(q.dtype), v)


# --------------------------------------------------------------------------
# Blockwise scan (differentiable, memory-light)
# --------------------------------------------------------------------------

def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        block_k: int = 128) -> jax.Array:
    """Online-softmax attention scanning over key blocks.

    One ``lax.scan`` step attends the full query tensor to one key/value
    block and folds the result into running ``(m, l, acc)`` state — the
    standard flash-attention recurrence. Each step is wrapped in
    ``jax.checkpoint`` so the backward pass recomputes the block instead
    of storing O(s²) probabilities.
    """
    b, s, h, d = q.shape
    t = k.shape[1]
    bk = min(block_k, t)
    if t % bk:  # pad keys; padded positions are masked out below
        pad = bk - t % bk
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    nk = k.shape[1] // bk
    # (b, s, h, d) -> per-block (nk, b, h, bk, d) for the shared fold
    kb = k.reshape(b, nk, bk, h, d).transpose(1, 0, 3, 2, 4)
    vb = v.reshape(b, nk, bk, h, d).transpose(1, 0, 3, 2, 4)

    scale = _scale(q)
    q32 = q.transpose(0, 2, 1, 3).astype(jnp.float32)  # (b, h, s, d)
    row_ids = lax.broadcasted_iota(jnp.int32, (s, bk), 0)

    @jax.checkpoint
    def step(carry, blk):
        m, l, acc = carry
        kblk, vblk, start = blk
        col_ids = start + lax.broadcasted_iota(jnp.int32, (s, bk), 1)
        valid = col_ids < t
        if causal:
            valid &= row_ids >= col_ids
        return online_softmax_fold(q32, kblk, vblk, m, l, acc, scale,
                                   mask=valid), None

    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    a0 = jnp.zeros((b, h, s, d), jnp.float32)
    starts = jnp.arange(nk, dtype=jnp.int32) * bk
    (m, l, acc), _ = lax.scan(step, (m0, l0, a0), (kb, vb, starts))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas flash kernel
# --------------------------------------------------------------------------

def _block_mask(qi, ki, block_q: int, block_k: int, causal: bool,
                seq_k: int):
    """The (block_q, block_k) validity mask for grid cell (qi, ki)."""
    row = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    col = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = col < seq_k
    if causal:
        valid &= row >= col
    return valid


def _block_probs(q_ref, k_ref, lse_ref, qi, ki, *, causal: bool,
                 scale: float, block_q: int, block_k: int, seq_k: int):
    """Backward-pass helper: rebuild this block's softmax probabilities
    from (q, k, lse) — the FlashAttention-2 trick that replaces O(s²)
    stored residuals. Returns (q, k) in their stored dtype (bf16 dots
    run the MXU at full rate; f32 casts would quarter it) and p in
    float32 (the exp must match the forward's f32 softmax state)."""
    q = q_ref[0]
    k = k_ref[0]
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    valid = _block_mask(qi, ki, block_q, block_k, causal, seq_k)
    p = jnp.where(valid, jnp.exp(logits - lse_ref[0, 0][:, None]), 0.0)
    return q, k, p


def _flash_kernel_fwd_res(q_ref, k_ref, v_ref, o_ref, lse_ref,
                          m_scr, l_scr, acc_scr, *, causal: bool,
                          scale: float, block_q: int, block_k: int,
                          seq_k: int):
    """Forward kernel that also emits the log-sum-exp rows — the only
    residual the backward kernels need (FlashAttention-2 scheme: softmax
    is reconstructed from (q, k, lse), never stored)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        # Inputs stay in their STORED dtype (bf16 on the flagship) so
        # the MXU runs at full bf16 rate; preferred_element_type keeps
        # the accumulation f32 — softmax state is always f32. Casting
        # to f32 first would quarter the matmul throughput on v5e for
        # identical accumulator precision.
        q = q_ref[0]                           # (block_q, d)
        k = k_ref[0]                           # (block_k, d)
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        valid = _block_mask(qi, ki, block_q, block_k, causal, seq_k)
        logits = jnp.where(valid, logits, _NEG_INF)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * corr + jnp.sum(p, axis=-1)
        m_scr[:, 0] = m_new
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # Blocks entirely above the diagonal contribute nothing — skip the
        # two MXU matmuls (the mask math keeps skipped-state consistent).
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        l = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, 0] + jnp.log(l)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, causal: bool, scale: float,
                         block_q: int, block_k: int, seq_k: int):
    """dq = Σ_k  ds·K  with ds = P ∘ (dP − δ), P rebuilt from (q, k, lse).
    Grid (bh, nq, nk): each (bh, qi) accumulates over the key blocks."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute():
        v = v_ref[0]
        g = g_ref[0]
        _, k, p = _block_probs(q_ref, k_ref, lse_ref, qi, ki,
                               causal=causal, scale=scale, block_q=block_q,
                               block_k=block_k, seq_k=seq_k)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                          scale: float, block_q: int, block_k: int,
                          seq_k: int, nq: int):
    """dv = Σ_q Pᵀ·dO and dk = Σ_q dsᵀ·Q. Grid (b·kv_heads, nk, G·nq):
    each (bh, ki) accumulates over the query blocks of EVERY query head
    in the kv head's group (G = n_heads / kv_heads; 1 for MHA) — the
    third grid axis enumerates (g, qi) pairs g-major, and the index
    maps point q/g/lse/delta at query head g of the group."""
    ki = pl.program_id(1)
    t = pl.program_id(2)
    qi = t % nq  # query-block index within the current group member
    nt = pl.num_programs(2)

    @pl.when(t == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute():
        v = v_ref[0]
        g = g_ref[0]
        q, _, p = _block_probs(q_ref, k_ref, lse_ref, qi, ki,
                               causal=causal, scale=scale, block_q=block_q,
                               block_k=block_k, seq_k=seq_k)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # Query blocks entirely above the diagonal see nothing of this
        # key block — skip all four MXU matmuls.
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(t == nt - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover - jax always ships pallas here
    _HAVE_PALLAS = False


def _pick_block(n: int, preferred: int) -> int:
    """Largest power-of-two ≤ preferred that divides n (n itself if none —
    one full block beats a degenerate 1-element grid)."""
    bsz = preferred
    while bsz > 1:
        if n % bsz == 0:
            return bsz
        bsz //= 2
    return n


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _env_flash_blocks():
    env = os.environ.get("MPI_TPU_FLASH_BLOCKS", "")
    if env:
        try:
            bq, sep, bk = env.partition(",")
            if not sep:
                raise ValueError("expected 'BQ,BK'")
            return [int(bq), int(bk)]
        except ValueError:
            import warnings

            # A bad env var must not kill every `import mpi_tpu`: warn
            # and fall back to the shipped default.
            warnings.warn(
                f"mpi_tpu: ignoring malformed MPI_TPU_FLASH_BLOCKS="
                f"{env!r} (expected 'BQ,BK', e.g. '256,512')",
                stacklevel=2)
    return [256, 512]


# Default (block_q, block_k) used when flash_attention is called with
# block sizes of None (every internal caller — transformer.py, ring
# attention chunks). The shipped 256x512 comes from a v5e sweep
# (128x128 keeps the MXU only ~30% as busy at s=1024); override per
# device/shape with :func:`set_flash_block_defaults` (the
# ops.autotune sweep does this) or MPI_TPU_FLASH_BLOCKS="bq,bk".
_flash_block_default = _env_flash_blocks()


def set_flash_block_defaults(block_q: int, block_k: int) -> None:
    """Set the process-wide default flash block sizes (autotuner
    output). Takes effect on the next trace; do not call between a
    step's forward and backward."""
    _flash_block_default[0] = int(block_q)
    _flash_block_default[1] = int(block_k)


def flash_block_defaults():
    """Current process-wide default ``(block_q, block_k)``."""
    return tuple(_flash_block_default)


# (seq_q, seq_k) -> (block_q, block_k): shape-exact winners from the
# autotune sweep, consulted at trace time BEFORE the global default —
# so tuning at one shape can never degrade flash calls at another
# (the sweep's winner at a short sequence is shrunk to divide it and
# would be a bad global choice).
_tuned_blocks: dict = {}


def register_tuned_blocks(seq_q: int, seq_k: int, block_q: int,
                          block_k: int) -> None:
    """Record the autotuned block grid for an exact (seq_q, seq_k)
    attention shape; default-block flash calls at that shape use it."""
    _tuned_blocks[(int(seq_q), int(seq_k))] = (int(block_q),
                                               int(block_k))


def _resolve_blocks(block_q, block_k, seq_q=None, seq_k=None):
    if block_q is None and block_k is None and seq_q is not None:
        hit = _tuned_blocks.get((seq_q, seq_k))
        if hit is not None:
            return hit
    return (_flash_block_default[0] if block_q is None else block_q,
            _flash_block_default[1] if block_k is None else block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention: Pallas TPU kernels, forward and backward.

    ``interpret=None`` auto-selects interpreter mode off-TPU so tests run
    on CPU against the same kernel code. Falls back to
    :func:`blockwise_attention` when Pallas is unavailable. Block sizes
    of ``None`` take the process-wide defaults
    (:func:`flash_block_defaults` — 256x512 from a v5e sweep unless the
    :mod:`mpi_tpu.ops.autotune` sweep picked better for this shape);
    :func:`_pick_block` shrinks them to fit short sequences.
    """
    itp = _should_interpret() if interpret is None else interpret
    if not _HAVE_PALLAS:  # pragma: no cover
        _, bk = _resolve_blocks(block_q, block_k)
        k, v = _expand_grouped_kv(q, k, v)
        return blockwise_attention(q, k, v, causal=causal, block_k=bk)
    # Same kernel as the residual-saving forward; the (b*h, 1, s) lse
    # output is dead here and DCE'd by XLA.
    return _flash_fwd_res_pallas(q, k, v, causal, block_q, block_k,
                                 itp)[0]


def _expand_grouped_kv(q, k, v):
    """Repeat grouped (GQA) kv heads for paths without native grouped
    support (the no-Pallas blockwise fallback only). Enforces the same
    divisibility contract as :func:`_gqa_layout` so all builds raise
    the same error."""
    h, hk = q.shape[2], k.shape[2]
    if h % hk or v.shape[2] != hk:
        raise ValueError(
            f"mpi_tpu: flash attention kv heads ({hk}/{v.shape[2]}) must "
            f"divide query heads ({h})")
    group = h // hk
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    return k, v


def _gqa_layout(q, k, v):
    """Flattened-head layout shared by the kernels: queries as
    ``(b*h, s, d)``, k/v as ``(b*kv_heads, t, d)``, plus the index-map
    taking a flat query-head grid index to its kv head's flat index
    (query head i reads kv head ``i // group`` — the GQA convention;
    the map is the identity for MHA)."""
    b, s, h, d = q.shape
    t = k.shape[1]
    hk = k.shape[2]
    if h % hk or v.shape[2] != hk:
        raise ValueError(
            f"mpi_tpu: flash attention kv heads ({hk}/{v.shape[2]}) must "
            f"divide query heads ({h})")
    group = h // hk
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hk, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hk, t, d)

    def kv_index(bh):
        return (bh // h) * hk + (bh % h) // group

    return qf, kf, vf, kv_index, group


def _flash_fwd_res_pallas(q, k, v, causal, block_q, block_k, interpret):
    """Forward + log-sum-exp residuals: (out, lse).

    ``out`` is ``(b, s, h, d)``; ``lse`` stays in the kernels'
    ``(b*h, 1, s)`` row layout (the singleton middle dim satisfies
    Mosaic's trailing-two-dims tiling rule) — exactly what the backward
    row specs consume. k/v may carry fewer (grouped/GQA) heads; the
    kernel reads each kv head once per query head via the index map —
    nothing is materialised group-times larger."""
    b, s, h, d = q.shape
    t = k.shape[1]
    block_q, block_k = _resolve_blocks(block_q, block_k, s, t)
    bq = _pick_block(s, block_q)
    bk = _pick_block(t, block_k)
    qf, kf, vf, kv_index, _ = _gqa_layout(q, k, v)
    grid = (b * h, s // bq, t // bk)
    kernel = functools.partial(
        _flash_kernel_fwd_res, causal=causal, scale=_scale(q), block_q=bq,
        block_k=bk, seq_k=t)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda bh, qi, ki: (kv_index(bh), ki, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda bh, qi, ki: (kv_index(bh), ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            # Rows live as (bh, 1, s) so the block's trailing two dims are
            # (1, bq) with the middle dim equal to the array's — the shape
            # Mosaic's (8, 128) tiling rule accepts for per-row vectors.
            pl.BlockSpec((1, 1, bq), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3), lse


def _flash_bwd_pallas(q, k, v, out, lse, g, causal, block_q, block_k,
                      interpret):
    """FlashAttention-2 backward: two Pallas passes (dq over key blocks;
    dk/dv over query blocks), probabilities rebuilt from lse — no O(s²)
    residuals, float32 accumulation throughout. Grouped (GQA) k/v are
    handled natively: dq reads each kv head through the group index
    map, and the dk/dv grid enumerates every (group member, query
    block) pair so the per-kv-head scratch accumulates the whole
    group's contributions before one write."""
    b, s, h, d = q.shape
    t = k.shape[1]
    hk = k.shape[2]
    block_q, block_k = _resolve_blocks(block_q, block_k, s, t)
    bq = _pick_block(s, block_q)
    bk = _pick_block(t, block_k)
    qf, kf, vf, kv_index, group = _gqa_layout(q, k, v)
    gf = g.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    of = out.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    # δ_i = Σ_d dO_i·O_i — cheap elementwise reduction; XLA fuses it.
    # Same (bh, 1, s) row layout as lse (see _flash_fwd_res_pallas).
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                    -1)[:, None, :]

    common = dict(causal=causal, scale=_scale(q), block_q=bq, block_k=bk,
                  seq_k=t)
    qspec = pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0))
    kspec = pl.BlockSpec((1, bk, d),
                         lambda bh, qi, ki: (kv_index(bh), ki, 0))
    rowspec = pl.BlockSpec((1, 1, bq), lambda bh, qi, ki: (bh, 0, qi))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(b * h, s // bq, t // bk),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    # dk/dv: grid (b*hk, nk, group*nq) — ki owns the accumulation, the
    # third axis walks the group's query heads g-major so the scratch
    # gathers all of them; index maps send q/g/lse/delta at group
    # member g's flat query head.
    nq = s // bq

    def q_head(bh, gq):
        return (bh // hk) * h + (bh % hk) * group + gq // nq

    qspec2 = pl.BlockSpec(
        (1, bq, d), lambda bh, ki, gq: (q_head(bh, gq), gq % nq, 0))
    kspec2 = pl.BlockSpec((1, bk, d), lambda bh, ki, gq: (bh, ki, 0))
    rowspec2 = pl.BlockSpec(
        (1, 1, bq), lambda bh, ki, gq: (q_head(bh, gq), 0, gq % nq))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, nq=nq, **common),
        grid=(b * hk, t // bk, group * nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((b * hk, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * hk, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    unflat_q = lambda x: x.reshape(b, h, s, d).transpose(0, 2, 1, 3)  # noqa: E731
    unflat_kv = lambda x: x.reshape(b, hk, t, d).transpose(0, 2, 1, 3)  # noqa: E731
    return unflat_q(dq), unflat_kv(dk), unflat_kv(dv)


def flash_attention_with_lse(q, k, v, causal: bool = True,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             interpret: Optional[bool] = None):
    """Forward flash attention that also returns the per-row log-sum-exp.

    ``(out, lse)`` with ``out`` shaped like ``q`` and ``lse`` ``(b, h, s)``
    float32. Attention over a *subset* of keys composes exactly from
    (out, lse) pairs (:func:`merge_attention_chunks`) — the primitive ring
    attention builds on: each ring step runs this kernel on the visiting
    kv chunk and merges. Forward-only (no vjp is registered here); ring
    attention supplies its own backward via :func:`flash_chunk_bwd`."""
    itp = _should_interpret() if interpret is None else interpret
    b, s, h, d = q.shape
    out, lse = _flash_fwd_res_pallas(q, k, v, causal, block_q, block_k, itp)
    return out, lse.reshape(b, h, s)


def merge_attention_chunks(o1, lse1, o2, lse2):
    """Combine two attention results over disjoint key sets.

    ``o``: (b, s, h, d) normalized outputs; ``lse``: (b, h, s) float32.
    Returns the merged (o, lse). Rows that attended nothing anywhere
    (lse ~ NEG_INF on both sides) stay zero, matching the masked-fold
    convention."""
    lse_m = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse_m).transpose(0, 2, 1)[..., None]
    w2 = jnp.exp(lse2 - lse_m).transpose(0, 2, 1)[..., None]
    o = o1.astype(jnp.float32) * w1 + o2.astype(jnp.float32) * w2
    return o.astype(o1.dtype), lse_m


def flash_chunk_bwd(q, k, v, out, lse, g, causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """FA-2 backward for ONE (query-chunk, kv-chunk) pair against the
    *global* softmax: ``out``/``lse`` are the full-attention result rows
    (after every chunk was merged), so the rebuilt probabilities
    ``exp(qk - lse)`` are the true global ones and the returned
    ``(dq, dk, dv)`` are this pair's exact additive contributions. Ring
    attention calls this once per ring step."""
    itp = _should_interpret() if interpret is None else interpret
    b, s, h, _ = q.shape
    return _flash_bwd_pallas(q, k, v, out, lse.reshape(b * h, 1, s), g,
                             causal, block_q, block_k, itp)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    itp = _should_interpret() if interpret is None else interpret
    if not _HAVE_PALLAS:  # pragma: no cover
        ke, ve = _expand_grouped_kv(q, k, v)
        out = blockwise_attention(q, ke, ve, causal=causal,
                                  block_k=_resolve_blocks(None, block_k)[1])
        return out, (q, k, v, None, None)
    out, lse = _flash_fwd_res_pallas(q, k, v, causal, block_q, block_k, itp)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if out is None:  # pragma: no cover - pallas-less fallback
        def ref(q_, k_, v_):
            ke, ve = _expand_grouped_kv(q_, k_, v_)
            return blockwise_attention(
                q_, ke, ve, causal=causal,
                block_k=_resolve_blocks(None, block_k)[1])

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)
    itp = _should_interpret() if interpret is None else interpret
    return _flash_bwd_pallas(q, k, v, out, lse, g, causal, block_q,
                             block_k, itp)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)

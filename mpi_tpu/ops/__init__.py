"""Hot-op kernels (Pallas on TPU, interpreter fallback elsewhere).

The reference has no compute ops at all (SURVEY.md §2 — it is a pure
communication runtime); this package is the rebuild's tpu-native ops
library, supplying the kernels the flagship workloads sit on. Kernels are
written with ``jax.experimental.pallas`` against the TPU backend and run
in interpreter mode on CPU so the whole suite is testable without chips.
"""

from .attention import (
    blockwise_attention,
    dense_attention,
    flash_attention,
    flash_attention_with_lse,
    flash_block_defaults,
    flash_chunk_bwd,
    merge_attention_chunks,
    set_flash_block_defaults,
)
from .autotune import tune_flash_blocks
from .decode_attention import flash_decode_attention
from .ring_collectives import (
    ring_allgather,
    ring_allgather_sharded,
    ring_allreduce,
    ring_allreduce_sharded,
)

__all__ = [
    "dense_attention",
    "blockwise_attention",
    "flash_attention",
    "flash_attention_with_lse",
    "flash_block_defaults",
    "flash_decode_attention",
    "set_flash_block_defaults",
    "tune_flash_blocks",
    "flash_chunk_bwd",
    "merge_attention_chunks",
    "ring_allgather",
    "ring_allgather_sharded",
    "ring_allreduce",
    "ring_allreduce_sharded",
]

"""Checkpoint / resume for sharded training state.

The reference has no persistence at all (SURVEY.md §5 "checkpoint/resume:
absent entirely"); this is new tpu-native work supporting the flagship
training workloads: save any pytree of (possibly sharded) jax/numpy arrays
to a step-numbered directory and restore it — onto the same shardings —
later or elsewhere.

Format: one ``step_N/`` directory per checkpoint containing

  * ``arrays.npz``   — every array leaf, key = flattened tree path;
  * ``meta.json``    — step number, leaf order, scalar/aux metadata.

Writes are atomic (temp dir + rename), so a crash mid-save never corrupts
the latest complete checkpoint. Sharded arrays are gathered to host before
writing (fine for single-controller meshes — every shard is addressable);
on restore, pass ``shardings`` (a matching pytree of
:class:`jax.sharding.NamedSharding` / PartitionSpec-applied shardings) to
place leaves directly back onto the mesh.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "save_checkpoint",
    "restore_checkpoint",
    "latest_step",
    "all_steps",
]

_STEP_RE = re.compile(r"^step_(\d+)$")


def _flatten(state: Any):
    import jax

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(state)
    keys = ["/".join(str(k) for k in path) for path, _ in leaves_with_path]
    leaves = [leaf for _, leaf in leaves_with_path]
    return keys, leaves, treedef


def save_checkpoint(directory: str, state: Any, step: int,
                    max_to_keep: Optional[int] = None) -> str:
    """Write ``state`` (pytree of arrays/scalars) as ``step_{step}``.
    Returns the checkpoint path. ``max_to_keep`` prunes oldest steps."""
    import jax

    os.makedirs(directory, exist_ok=True)
    keys, leaves, _ = _flatten(state)
    arrays: Dict[str, np.ndarray] = {}
    for key, leaf in zip(keys, leaves):
        # Sharded device arrays gather to host; everything numeric becomes
        # an ndarray (0-d for scalars) so the npz round-trip is lossless.
        arrays[key] = np.asarray(jax.device_get(leaf))
    final = os.path.join(directory, f"step_{step}")
    tmp = tempfile.mkdtemp(prefix=f".step_{step}.tmp.", dir=directory)
    try:
        with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
            np.savez(f, **arrays)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"step": step, "keys": keys,
                       "format": "mpi_tpu.checkpoint.v1"}, f)
        # Overwrite near-atomically: park the old step under a
        # ``.step_N.old.*`` name before renaming the new one in. A crash
        # between the two renames leaves no ``step_N`` but an intact
        # parked copy — ``all_steps`` recovers it (see ``_recover_old``),
        # so either the old or the new checkpoint is always reachable.
        # A concurrent reader's recovery can resurrect the parked copy
        # in that same window, making our rename land on a non-empty
        # dir — park-and-rename retries until it wins (the resurrector
        # acts at most once per parked dir, so this converges).
        old = None
        for attempt in range(10):
            if os.path.exists(final):
                old = tempfile.mkdtemp(prefix=f".step_{step}.old.",
                                       dir=directory)
                os.rmdir(old)
                os.rename(final, old)
            try:
                os.rename(tmp, final)
                break
            except OSError:
                if attempt == 9:
                    if old is not None and not os.path.exists(final):
                        os.rename(old, final)  # put the old one back
                        old = None
                    raise
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if max_to_keep is not None:
        steps = all_steps(directory)
        for old in steps[:-max_to_keep]:
            shutil.rmtree(os.path.join(directory, f"step_{old}"),
                          ignore_errors=True)
    return final


_OLD_RE = re.compile(r"^\.step_(\d+)\.old\.")


def _recover_old(directory: str) -> None:
    """Restore checkpoints orphaned by a crash mid-overwrite.

    ``save_checkpoint`` parks the previous ``step_N`` as ``.step_N.old.*``
    before renaming the replacement in; a crash between the renames leaves
    only the parked copy. Rename it back so the step stays visible."""
    for name in os.listdir(directory):
        m = _OLD_RE.match(name)
        if not m:
            continue
        final = os.path.join(directory, f"step_{m.group(1)}")
        parked = os.path.join(directory, name)
        if os.path.exists(final):
            # The replacement landed; the parked copy is leftover debris.
            shutil.rmtree(parked, ignore_errors=True)
        elif os.path.exists(os.path.join(parked, "meta.json")):
            try:
                os.rename(parked, final)
            except OSError:
                pass  # concurrent writer raced us; next scan cleans up


def all_steps(directory: str) -> List[int]:
    """Complete checkpoint steps present, ascending (recovering any step
    orphaned by a crash mid-overwrite first)."""
    if not os.path.isdir(directory):
        return []
    _recover_old(directory)
    steps = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(directory, name, "meta.json")):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    steps = all_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, template: Any,
                       step: Optional[int] = None,
                       shardings: Optional[Any] = None) -> Any:
    """Load ``step`` (default: latest) into the structure of ``template``.

    ``template`` supplies the tree structure and leaf dtypes/kinds (its
    array *values* are ignored). ``shardings``, if given, is a matching
    pytree whose leaves are shardings (or None for host placement); each
    restored leaf is ``jax.device_put`` onto its sharding — the restore
    path for tp/dp-sharded train state.
    """
    import jax

    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"mpi_tpu: no checkpoints under {directory!r}")
    path = os.path.join(directory, f"step_{step}")
    if not os.path.exists(path) and os.path.isdir(directory):
        # The explicit-step path must see crash-orphaned steps too.
        _recover_old(directory)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    with np.load(os.path.join(path, "arrays.npz")) as npz:
        arrays = {k: npz[k] for k in npz.files}

    keys, leaves, treedef = _flatten(template)
    if sorted(keys) != sorted(meta["keys"]):
        missing = set(meta["keys"]) - set(keys)
        extra = set(keys) - set(meta["keys"])
        raise ValueError(
            f"mpi_tpu: checkpoint/template tree mismatch "
            f"(missing from template: {sorted(missing)[:5]}, "
            f"not in checkpoint: {sorted(extra)[:5]})")

    shard_leaves: List[Any] = [None] * len(leaves)
    if shardings is not None:
        s_keys, s_leaves, _ = _flatten(shardings)
        by_key = dict(zip(s_keys, s_leaves))
        shard_leaves = [by_key.get(k) for k in keys]

    out_leaves = []
    for key, tmpl, shard in zip(keys, leaves, shard_leaves):
        val = arrays[key]
        if isinstance(tmpl, (int, float, bool, complex)) and val.ndim == 0:
            out_leaves.append(type(tmpl)(val[()]))
            continue
        if shard is not None:
            out_leaves.append(jax.device_put(val, shard))
        elif isinstance(tmpl, jax.Array):
            from jax.sharding import NamedSharding

            sh = getattr(tmpl, "sharding", None)
            if isinstance(sh, NamedSharding):
                out_leaves.append(jax.device_put(val, sh))
            else:
                # Single-device jit outputs (e.g. optimizer step counters)
                # must stay *uncommitted* so the next jitted step can place
                # them beside mesh-sharded leaves without a device clash.
                out_leaves.append(jax.numpy.asarray(val))
        else:
            out_leaves.append(val)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)

"""Checkpoint / resume for sharded training state.

The reference has no persistence at all (SURVEY.md §5 "checkpoint/resume:
absent entirely"); this is new tpu-native work supporting the flagship
training workloads: save any pytree of (possibly sharded) jax/numpy arrays
to a step-numbered directory and restore it — onto the same shardings —
later or elsewhere.

Format: one ``step_N/`` directory per checkpoint containing

  * ``arrays.npz``   — every array leaf, key = flattened tree path;
  * ``meta.json``    — step number, leaf order, scalar/aux metadata.

Writes are atomic (temp dir + rename), so a crash mid-save never corrupts
the latest complete checkpoint. Sharded arrays are gathered to host before
writing (fine for single-controller meshes — every shard is addressable);
on restore, pass ``shardings`` (a matching pytree of
:class:`jax.sharding.NamedSharding` / PartitionSpec-applied shardings) to
place leaves directly back onto the mesh.
"""

from __future__ import annotations

import json
import os
import queue
import re
import shutil
import tempfile
import threading
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "save_checkpoint",
    "restore_checkpoint",
    "latest_step",
    "all_steps",
    "AsyncCheckpointer",
]

_STEP_RE = re.compile(r"^step_(\d+)$")


def _flatten(state: Any):
    import jax

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(state)
    keys = ["/".join(str(k) for k in path) for path, _ in leaves_with_path]
    leaves = [leaf for _, leaf in leaves_with_path]
    return keys, leaves, treedef


def _snapshot(state: Any, copy: bool = False):
    """Gather ``state`` to host: (keys, {key: ndarray}).

    ``copy=True`` forces owned copies — required when the write happens
    later (async): ``device_get`` of a numpy leaf returns the caller's
    own array, and on the CPU backend even a jax.Array can alias the
    live buffer, so without a copy the training loop's next in-place
    update (or donation) would tear the checkpoint."""
    import jax

    keys, leaves, _ = _flatten(state)
    arrays: Dict[str, np.ndarray] = {}
    for key, leaf in zip(keys, leaves):
        # Sharded device arrays gather to host; everything numeric becomes
        # an ndarray (0-d for scalars) so the npz round-trip is lossless.
        arr = np.asarray(jax.device_get(leaf))
        arrays[key] = arr.copy() if copy else arr
    return keys, arrays


def save_checkpoint(directory: str, state: Any, step: int,
                    max_to_keep: Optional[int] = None) -> str:
    """Write ``state`` (pytree of arrays/scalars) as ``step_{step}``.
    Returns the checkpoint path. ``max_to_keep`` prunes oldest steps."""
    keys, arrays = _snapshot(state)
    return _write_checkpoint(directory, keys, arrays, step, max_to_keep)


def _write_checkpoint(directory: str, keys: List[str],
                      arrays: Dict[str, np.ndarray], step: int,
                      max_to_keep: Optional[int] = None) -> str:
    """Disk half of a save: npz + meta into a temp dir, then the
    park-and-rename overwrite dance. Host-only (no jax)."""
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"step_{step}")
    tmp = tempfile.mkdtemp(prefix=f".step_{step}.tmp.", dir=directory)
    try:
        with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
            np.savez(f, **arrays)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"step": step, "keys": keys,
                       "format": "mpi_tpu.checkpoint.v1"}, f)
        # Overwrite near-atomically: park the old step under a
        # ``.step_N.old.*`` name before renaming the new one in. A crash
        # between the two renames leaves no ``step_N`` but an intact
        # parked copy — ``all_steps`` recovers it (see ``_recover_old``),
        # so either the old or the new checkpoint is always reachable.
        # A concurrent reader's recovery can resurrect the parked copy
        # in that same window, making our rename land on a non-empty
        # dir — park-and-rename retries until it wins (the resurrector
        # acts at most once per parked dir, so this converges).
        old = None
        for attempt in range(10):
            if os.path.exists(final):
                old = tempfile.mkdtemp(prefix=f".step_{step}.old.",
                                       dir=directory)
                os.rmdir(old)
                os.rename(final, old)
            try:
                os.rename(tmp, final)
                break
            except OSError:
                if attempt == 9:
                    if old is not None and not os.path.exists(final):
                        os.rename(old, final)  # put the old one back
                        old = None
                    raise
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if max_to_keep is not None:
        steps = all_steps(directory)
        for old in steps[:-max_to_keep]:
            shutil.rmtree(os.path.join(directory, f"step_{old}"),
                          ignore_errors=True)
    return final


class _SaveHandle:
    """Completion handle for one async save (a tiny future)."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self._path: Optional[str] = None
        self._exc: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> str:
        """Block until the write lands; return the checkpoint path or
        re-raise the write error."""
        if not self._done.wait(timeout):
            raise TimeoutError("mpi_tpu: async checkpoint still writing")
        if self._exc is not None:
            raise self._exc
        assert self._path is not None
        return self._path


class AsyncCheckpointer:
    """Background checkpoint writer: training resumes while bytes hit disk.

    The device→host gather happens **synchronously** on the caller thread
    (a snapshot — so the train loop may immediately donate/overwrite its
    buffers), and the disk half (npz encode, fsync-free writes, the
    park-and-rename overwrite) runs on a single worker thread, which also
    keeps concurrent saves step-ordered. This is the standard TPU
    checkpointing shape (compute waits only for HBM→host, not for disk).

    Use as a context manager or call :meth:`wait` /:meth:`close`; both
    re-raise the first background write error.
    """

    def __init__(self, max_pending: int = 2) -> None:
        # Bounded: each queued job holds a full host copy of the state,
        # so when disk is slower than the checkpoint cadence, save()
        # BLOCKS once ``max_pending`` snapshots are in flight instead of
        # accumulating model-sized copies until the host OOMs. (This
        # backpressure is why the worker is hand-rolled rather than a
        # ThreadPoolExecutor, whose work queue is unbounded.)
        self._jobs: "queue.Queue[Optional[tuple]]" = queue.Queue(
            maxsize=max(1, max_pending))
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # _first_exc has its own lock: save() may block in a full-queue
        # put() while holding _lock, and the worker's error path must
        # stay able to record its failure (and task_done) meanwhile.
        self._exc_lock = threading.Lock()
        self._first_exc: Optional[BaseException] = None
        self._closed = False

    def save(self, directory: str, state: Any, step: int,
             max_to_keep: Optional[int] = None) -> _SaveHandle:
        """Snapshot ``state`` now; write ``step_{step}`` in the background.
        Returns a handle whose ``result()`` blocks for this save only."""
        with self._exc_lock:
            if self._first_exc is not None:
                exc, self._first_exc = self._first_exc, None
                raise exc
        with self._lock:
            if self._closed:
                raise RuntimeError("mpi_tpu: AsyncCheckpointer is closed")
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._run, name="mpi-ckpt-writer", daemon=True)
                self._worker.start()
        keys, arrays = _snapshot(state, copy=True)
        handle = _SaveHandle()
        # Enqueue under the lock: the snapshot above can take seconds, and
        # a concurrent close() must either see this job (queued before the
        # shutdown sentinel) or make this call raise — never strand the
        # job on a dead queue with a forever-pending handle.
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    "mpi_tpu: AsyncCheckpointer closed during save()")
            self._jobs.put((directory, keys, arrays, step, max_to_keep,
                            handle))
        return handle

    def _run(self) -> None:
        while True:
            job = self._jobs.get()
            try:
                if job is None:
                    return
                directory, keys, arrays, step, max_to_keep, handle = job
                try:
                    handle._path = _write_checkpoint(
                        directory, keys, arrays, step, max_to_keep)
                except BaseException as exc:  # noqa: BLE001 — reported
                    handle._exc = exc         # via handle and wait()
                    with self._exc_lock:
                        if self._first_exc is None:
                            self._first_exc = exc
                finally:
                    handle._done.set()
            finally:
                self._jobs.task_done()

    def wait(self) -> None:
        """Block until every queued save has landed; re-raise the first
        background error (also surfaced by the failing save's handle)."""
        self._jobs.join()
        with self._exc_lock:
            exc, self._first_exc = self._first_exc, None
        if exc is not None:
            raise exc

    def close(self) -> None:
        """Drain pending saves and stop the worker (idempotent)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            worker = self._worker
        if worker is not None:
            self._jobs.put(None)
            worker.join()
        with self._exc_lock:
            exc, self._first_exc = self._first_exc, None
        if exc is not None:
            raise exc

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


_OLD_RE = re.compile(r"^\.step_(\d+)\.old\.")


def _recover_old(directory: str) -> None:
    """Restore checkpoints orphaned by a crash mid-overwrite.

    ``save_checkpoint`` parks the previous ``step_N`` as ``.step_N.old.*``
    before renaming the replacement in; a crash between the renames leaves
    only the parked copy. Rename it back so the step stays visible."""
    for name in os.listdir(directory):
        m = _OLD_RE.match(name)
        if not m:
            continue
        final = os.path.join(directory, f"step_{m.group(1)}")
        parked = os.path.join(directory, name)
        if os.path.exists(final):
            # The replacement landed; the parked copy is leftover debris.
            shutil.rmtree(parked, ignore_errors=True)
        elif os.path.exists(os.path.join(parked, "meta.json")):
            try:
                os.rename(parked, final)
            except OSError:
                pass  # concurrent writer raced us; next scan cleans up


def all_steps(directory: str) -> List[int]:
    """Complete checkpoint steps present, ascending (recovering any step
    orphaned by a crash mid-overwrite first)."""
    if not os.path.isdir(directory):
        return []
    _recover_old(directory)
    steps = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(directory, name, "meta.json")):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    steps = all_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, template: Any,
                       step: Optional[int] = None,
                       shardings: Optional[Any] = None) -> Any:
    """Load ``step`` (default: latest) into the structure of ``template``.

    ``template`` supplies the tree structure and leaf dtypes/kinds (its
    array *values* are ignored). ``shardings``, if given, is a matching
    pytree whose leaves are shardings (or None for host placement); each
    restored leaf is ``jax.device_put`` onto its sharding — the restore
    path for tp/dp-sharded train state.
    """
    import jax

    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"mpi_tpu: no checkpoints under {directory!r}")
    path = os.path.join(directory, f"step_{step}")
    if not os.path.exists(path) and os.path.isdir(directory):
        # The explicit-step path must see crash-orphaned steps too.
        _recover_old(directory)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    with np.load(os.path.join(path, "arrays.npz")) as npz:
        arrays = {k: npz[k] for k in npz.files}

    keys, leaves, treedef = _flatten(template)
    if sorted(keys) != sorted(meta["keys"]):
        missing = set(meta["keys"]) - set(keys)
        extra = set(keys) - set(meta["keys"])
        raise ValueError(
            f"mpi_tpu: checkpoint/template tree mismatch "
            f"(missing from template: {sorted(missing)[:5]}, "
            f"not in checkpoint: {sorted(extra)[:5]})")

    shard_leaves: List[Any] = [None] * len(leaves)
    if shardings is not None:
        s_keys, s_leaves, _ = _flatten(shardings)
        by_key = dict(zip(s_keys, s_leaves))
        shard_leaves = [by_key.get(k) for k in keys]

    out_leaves = []
    for key, tmpl, shard in zip(keys, leaves, shard_leaves):
        val = arrays[key]
        if isinstance(tmpl, (int, float, bool, complex)) and val.ndim == 0:
            out_leaves.append(type(tmpl)(val[()]))
            continue
        if shard is not None:
            out_leaves.append(jax.device_put(val, shard))
        elif isinstance(tmpl, jax.Array):
            from jax.sharding import NamedSharding

            sh = getattr(tmpl, "sharding", None)
            if isinstance(sh, NamedSharding):
                out_leaves.append(jax.device_put(val, sh))
            else:
                # Single-device jit outputs (e.g. optimizer step counters)
                # must stay *uncommitted* so the next jitted step can place
                # them beside mesh-sharded leaves without a device clash.
                out_leaves.append(jax.numpy.asarray(val))
        else:
            out_leaves.append(val)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)

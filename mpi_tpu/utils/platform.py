"""JAX platform selection helper shared by the driver entry points.

Pinning the platform via :func:`jax.config.update` must happen before the
first device query; env-var selection (``JAX_PLATFORMS``) alone is
unreliable when a TPU PJRT plugin was pre-registered at interpreter
startup. Centralized here so ``bench.py`` and ``__graft_entry__`` apply
the identical workaround.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["force_platform"]


def force_platform(name: str, num_cpu_devices: Optional[int] = None) -> bool:
    """Pin the JAX platform (and optionally the virtual CPU device count).

    Returns False (instead of raising) if a backend is already live —
    then the existing devices must suffice.
    """
    import jax

    try:
        if num_cpu_devices is not None:
            try:
                jax.config.update("jax_num_cpu_devices",
                                  num_cpu_devices)
            except AttributeError:
                # Older jax has no virtual-CPU-count option; the
                # platform pin below still applies and callers that
                # oversubscribe rank threads work on 1 device.
                pass
        jax.config.update("jax_platforms", name)
    except RuntimeError:
        return False
    return True

"""Tracing / profiling — spans, comm counters, and jax.profiler hooks.

The reference has no tracing subsystem at all — its only instrument is the
bounce example's manual ``time.Now()`` deltas (SURVEY.md §5; bounce.go:
90-101). This module supplies the idiomatic tpu equivalents:

  * **spans** — wall-clock regions (``with span("allreduce", bytes=n)``)
    recorded into a bounded process-local buffer (events beyond the cap
    are dropped and counted — see :func:`dropped`) and exportable as a
    chrome://tracing / Perfetto JSON trace (``dump_chrome_trace``);
  * **counters** — monotonically accumulated values (bytes sent/received
    per peer, collective invocations), queryable for bench harnesses;
  * **device profiling** — :func:`profile` wraps ``jax.profiler.trace``
    so a region's XLA/TPU activity lands in TensorBoard-compatible
    traces alongside the host spans.

Off by default and cheap when off (one attribute check per call site);
enable with ``MPI_TPU_TRACE=1`` or :func:`enable`. The facade
(:mod:`mpi_tpu.api`) instruments send/receive/collectives through this
module, so any backend gets comm accounting for free.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

__all__ = [
    "enable",
    "disable",
    "enabled",
    "span",
    "count",
    "counters",
    "events",
    "dropped",
    "clear",
    "dump_chrome_trace",
    "wall_anchor_ns",
    "add_span",
    "set_stream",
    "stream",
    "flush_stream",
    "profile",
]

_MAX_EVENTS = 100_000


class _Tracer:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.events: List[Dict[str, Any]] = []
        self.counters: Dict[str, float] = {}
        self.enabled = bool(os.environ.get("MPI_TPU_TRACE"))
        self.dropped = 0
        # Span timestamps are perf_counter_ns (monotonic, arbitrary
        # origin). This anchor maps them onto the wall clock —
        # wall_ns ≈ ts_ns + anchor — which is what the job-wide merge
        # (mpi_tpu.observe.collect) aligns across ranks.
        self.wall_anchor_ns = time.time_ns() - time.perf_counter_ns()
        # Optional streaming sink (mpi_tpu.observe.stream.SpoolWriter).
        # When set, the resident buffer is bounded by the sink's chunk
        # watermarks instead of _MAX_EVENTS: full batches are detached
        # and handed to the sink, keeping memory O(chunk) over any job
        # length and making flushed spans crash-durable.
        self.stream: Optional[Any] = None

    def add_event(self, ev: Dict[str, Any]) -> None:
        with self.lock:
            st = self.stream
            if st is None:
                if len(self.events) >= _MAX_EVENTS:
                    self.dropped += 1
                    return
                self.events.append(ev)
                return
            self.events.append(ev)
            now = time.monotonic()
            if st.first_t is None:
                st.first_t = now
            if (len(self.events) >= st.max_events
                    or now - st.first_t >= st.max_age_s):
                batch = self.events
                self.events = []
                st.write_chunk(batch)

    def add_count(self, name: str, value: float) -> None:
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + value


_tracer = _Tracer()


def enable() -> None:
    """Turn span/counter recording on for this process."""
    _tracer.enabled = True


def disable() -> None:
    _tracer.enabled = False


def enabled() -> bool:
    return _tracer.enabled


@contextmanager
def span(name: str, **attrs: Any) -> Iterator[None]:
    """Record a wall-clock region. No-op (one bool check) when disabled."""
    if not _tracer.enabled:
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        t1 = time.perf_counter_ns()
        _tracer.add_event({
            "name": name,
            "ts_us": t0 / 1e3,
            "dur_us": (t1 - t0) / 1e3,
            "thread": threading.current_thread().name,
            **attrs,
        })


def add_span(name: str, ts_us: float, dur_us: float, **attrs: Any) -> None:
    """Record a completed span with explicit perf_counter timestamps
    (µs). For sub-op stages measured outside Python's control flow —
    e.g. the native wirecore stage scratch read back after the call —
    where a ``with span(...)`` block cannot bracket the work."""
    if not _tracer.enabled:
        return
    _tracer.add_event({
        "name": name,
        "ts_us": ts_us,
        "dur_us": dur_us,
        "thread": threading.current_thread().name,
        **attrs,
    })


def set_stream(writer: Optional[Any]) -> None:
    """Install (or remove, with None) a streaming sink — an object with
    ``max_events`` / ``max_age_s`` / ``first_t`` attributes and a
    ``write_chunk(events)`` method (see
    :class:`mpi_tpu.observe.stream.SpoolWriter`). While installed, full
    event batches are flushed to it instead of accumulating."""
    with _tracer.lock:
        _tracer.stream = writer


def stream() -> Optional[Any]:
    """The installed streaming sink, or None."""
    return _tracer.stream


def flush_stream() -> int:
    """Force the resident tail out to the streaming sink (finalize /
    fatal-error path). Returns the number of events flushed; no-op
    without a sink."""
    with _tracer.lock:
        st = _tracer.stream
        if st is None:
            return 0
        batch = _tracer.events
        _tracer.events = []
        st.write_chunk(batch)
        return len(batch)


def count(name: str, value: float = 1) -> None:
    """Accumulate a counter (e.g. ``comm.send.bytes``). No-op when
    disabled."""
    if _tracer.enabled:
        _tracer.add_count(name, value)


def counters() -> Dict[str, float]:
    with _tracer.lock:
        return dict(_tracer.counters)


def events() -> List[Dict[str, Any]]:
    with _tracer.lock:
        return list(_tracer.events)


def dropped() -> int:
    """Events discarded because the buffer cap was hit."""
    with _tracer.lock:
        return _tracer.dropped


def wall_anchor_ns() -> int:
    """This process's perf_counter→wall-clock anchor: add it to a
    span's ``ts_us * 1e3`` to place the span on the wall clock (the
    cross-rank merge substrate; see :mod:`mpi_tpu.observe.collect`)."""
    return _tracer.wall_anchor_ns


def clear() -> None:
    with _tracer.lock:
        _tracer.events.clear()
        _tracer.counters.clear()
        _tracer.dropped = 0
        if _tracer.stream is not None:
            _tracer.stream.first_t = None


def dump_chrome_trace(path: str) -> int:
    """Write recorded spans as a chrome://tracing / Perfetto JSON file.
    Returns the number of events written."""
    with _tracer.lock:
        evs = list(_tracer.events)
        cts = dict(_tracer.counters)
        ndropped = _tracer.dropped
    trace = {
        "traceEvents": [
            {
                "name": e["name"],
                "ph": "X",
                "ts": e["ts_us"],
                "dur": e["dur_us"],
                "pid": os.getpid(),
                "tid": e.get("thread", "main"),
                "args": {k: v for k, v in e.items()
                         if k not in ("name", "ts_us", "dur_us", "thread")},
            }
            for e in evs
        ],
        "metadata": {"counters": cts, "dropped_events": ndropped},
    }
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(evs)


@contextmanager
def profile(logdir: str, host_spans: bool = True) -> Iterator[None]:
    """Capture a jax.profiler device trace (TensorBoard format) for the
    region, optionally enabling host span recording too."""
    import jax

    prev = _tracer.enabled
    if host_spans:
        _tracer.enabled = True
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        _tracer.enabled = prev

"""Utility layer: wire codec, structured logging."""

from .serialize import CodecError, Raw, decode, encode

__all__ = ["CodecError", "Raw", "decode", "encode"]

"""Utility layer: wire codec, platform pinning, tracing, checkpointing."""

from .serialize import CodecError, Raw, decode, encode
from . import trace
from .checkpoint import (
    AsyncCheckpointer,
    all_steps,
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)

__all__ = [
    "CodecError", "Raw", "decode", "encode", "trace",
    "save_checkpoint", "restore_checkpoint", "latest_step", "all_steps",
    "AsyncCheckpointer",
]

"""Typed wire codec — the rebuild's replacement for ``encoding/gob``.

The reference serializes every payload with Go's gob (network.go:537-541,
594-601) and special-cases a ``Raw []byte`` passthrough that skips
re-encoding and reuses the caller's buffer on decode when it is large enough
(mpi.go:75-91). gob is Go-specific, so the rebuild defines an explicit,
documented, language-neutral encoding with the same two properties:

  * **typed round-trip** — the receiver gets back the same logical type the
    sender passed (ndarray with dtype+shape, scalar, bytes, arbitrary
    object), like gob's self-describing streams;
  * **zero-copy raw path** — ``bytes``/``bytearray``/``memoryview`` payloads
    are transported verbatim with a 2-byte header, and ndarray payloads are
    a header + raw C-order buffer (a memcpy, not an element loop — this is
    where we beat gob's per-element float64 encode on the bounce benchmark,
    bounce.go:114-136).

Wire grammar (all integers little-endian)::

    payload   := kind:u8 body
    kind      := 0 RAW      body = raw bytes (verbatim)
                 1 NDARRAY  body = u8 dtype_len, dtype_str(ascii),
                                   u8 ndim, ndim * u32 dims, C-order data
                 2 PICKLE   body = pickle bytes (arbitrary objects)
                 3 STR      body = utf-8 bytes
                 4 NONE     body = empty

Scalars (int/float/bool/complex) ride the NDARRAY path as 0-d arrays so
numeric fidelity is exact and language-neutral. Framing (length prefix, tag,
message kind) is the transport's job — see ``backends/tcp.py``.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Optional

import numpy as np

__all__ = ["Raw", "encode", "encode_parts", "decode", "CodecError"]

# Below this, the two-part path's extra bookkeeping outweighs the copy
# it saves; above it, skipping tobytes() + join is a measured ~2x on
# the 64 MiB one-way send (encode was 81 ms of a 155 ms transfer).
PARTS_MIN_BYTES = 32 << 10

KIND_RAW = 0
KIND_NDARRAY = 1
KIND_PICKLE = 2
KIND_STR = 3
KIND_NONE = 4


class CodecError(ValueError):
    """Raised on malformed wire payloads or undecodable inputs."""


class Raw(bytes):
    """Marker type for verbatim byte transport, mirroring ``mpi.Raw``
    (mpi.go:75-91). Any bytes-like payload already takes the raw path;
    ``Raw`` exists so user code can be explicit about it (and so decoded
    raw payloads round-trip as the same type they were sent as)."""


def _is_jax_array(obj: Any) -> bool:
    mod = type(obj).__module__
    return mod.startswith("jax") or type(obj).__name__ == "ArrayImpl"


def encode(data: Any) -> bytes:
    """Encode one payload to the wire format."""
    if data is None:
        return bytes([KIND_NONE])
    if isinstance(data, (bytes, bytearray, memoryview)):
        return bytes([KIND_RAW]) + bytes(data)
    if isinstance(data, str):
        return bytes([KIND_STR]) + data.encode("utf-8")
    if _is_jax_array(data):
        data = np.asarray(data)
    if isinstance(data, (int, float, bool, complex, np.generic)):
        data = np.asarray(data)
    if isinstance(data, np.ndarray):
        if data.dtype.hasobject or data.dtype.kind == "V":
            # Object arrays hold pointers and structured/void arrays lose
            # their field layout through the raw-buffer path — both must
            # ride the pickle fallback.
            return bytes([KIND_PICKLE]) + pickle.dumps(
                data, protocol=pickle.HIGHEST_PROTOCOL)
        # NB: np.ascontiguousarray promotes 0-d to 1-d — avoid it for 0-d.
        arr = data if data.ndim == 0 or data.flags.c_contiguous \
            else np.ascontiguousarray(data)
        dt = arr.dtype.str.encode("ascii")  # e.g. b'<f4'
        if len(dt) > 255 or arr.ndim > 255:
            raise CodecError("unsupported ndarray dtype/rank")
        header = struct.pack(f"<B{arr.ndim}I", arr.ndim, *arr.shape)
        return b"".join(
            (bytes([KIND_NDARRAY, len(dt)]), dt, header, arr.tobytes())
        )
    # Arbitrary python objects: the gob-for-anything fallback.
    try:
        return bytes([KIND_PICKLE]) + pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception as exc:  # pragma: no cover - exotic unpicklables
        raise CodecError(f"cannot encode {type(data)!r}: {exc}") from exc


def encode_parts(data: Any):
    """``(prefix, view)`` — the zero-copy form of :func:`encode`.

    For large C-contiguous ndarrays (and bytes-likes) the wire bytes
    are ``prefix + view`` with ``view`` aliasing the caller's buffer:
    no ``tobytes()``, no join — the transport scatter-gathers both
    segments into one frame (wc_send_frame2 / shm_send_frame2 /
    sendmsg). Every other payload returns ``(encode(data), None)``.
    ``prefix + bytes(view)`` is byte-identical to ``encode(data)`` —
    the receiver cannot tell which form the sender used. The caller
    must not mutate ``data`` until the send completes (the same
    aliasing contract Raw's decode reuse documents)."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        mv = memoryview(data)
        # cast("B") demands C-contiguity specifically — an
        # F-contiguous view would raise where encode() succeeds.
        if mv.nbytes >= PARTS_MIN_BYTES and mv.c_contiguous:
            return bytes([KIND_RAW]), mv.cast("B")
        return encode(data), None
    arr = data
    if _is_jax_array(arr):
        arr = np.asarray(arr)
    if (isinstance(arr, np.ndarray)
            and arr.flags.c_contiguous
            and arr.nbytes >= PARTS_MIN_BYTES
            and not arr.dtype.hasobject and arr.dtype.kind != "V"):
        dt = arr.dtype.str.encode("ascii")
        if len(dt) <= 255 and arr.ndim <= 255:
            header = struct.pack(f"<B{arr.ndim}I", arr.ndim, *arr.shape)
            prefix = bytes([KIND_NDARRAY, len(dt)]) + dt + header
            return prefix, memoryview(arr).cast("B")
    return encode(data), None


def decode(payload: bytes, out: Optional[Any] = None) -> Any:
    """Decode one wire payload.

    ``out`` mirrors the reference's receive-into-pointer semantics
    (mpi.go:157-159) and ``Raw``'s buffer reuse (mpi.go:84-90): pass a
    ``bytearray``/``memoryview`` for RAW payloads or an ``np.ndarray`` for
    NDARRAY payloads and the data is written in place when dtype and size
    match (the filled ``out`` is also returned). Otherwise a fresh object
    is returned.
    """
    if not payload:
        raise CodecError("empty payload")
    kind = payload[0]
    body = memoryview(payload)[1:]

    if kind == KIND_NONE:
        return None
    if kind == KIND_RAW:
        if out is not None and isinstance(out, (bytearray, memoryview)) \
                and len(out) >= len(body):
            mv = memoryview(out)
            mv[: len(body)] = body
            return out if len(out) == len(body) else out[: len(body)]
        return Raw(body)
    if kind == KIND_STR:
        return bytes(body).decode("utf-8")
    if kind == KIND_NDARRAY:
        try:
            dt_len = body[0]
            dt = bytes(body[1 : 1 + dt_len]).decode("ascii")
            pos = 1 + dt_len
            ndim = body[pos]
            pos += 1
            shape = struct.unpack_from(f"<{ndim}I", body, pos)
            pos += 4 * ndim
            dtype = np.dtype(dt)
            arr_bytes = body[pos:]
        except (IndexError, struct.error, TypeError, ValueError) as exc:
            raise CodecError(f"malformed ndarray payload: {exc}") from exc
        count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
        if len(arr_bytes) != count * dtype.itemsize:
            raise CodecError(
                f"ndarray payload size mismatch: header says "
                f"{count * dtype.itemsize} bytes, got {len(arr_bytes)}"
            )
        if (
            out is not None
            and isinstance(out, np.ndarray)
            and out.dtype == dtype
            and out.shape == tuple(shape)
            and out.flags.c_contiguous
        ):
            out.view(np.uint8).reshape(-1)[:] = np.frombuffer(arr_bytes, np.uint8)
            return out
        arr = np.frombuffer(arr_bytes, dtype=dtype).reshape(shape)
        if not arr.flags.writeable:
            # Source buffer is immutable (bytes) — copy so callers get a
            # normal writable array. Transport hands us its own bytearray,
            # in which case the zero-copy view is safe to return as-is.
            arr = arr.copy()
        if ndim == 0:
            return arr[()]  # scalars round-trip as numpy scalars
        return arr
    if kind == KIND_PICKLE:
        try:
            return pickle.loads(bytes(body))
        except Exception as exc:
            raise CodecError(f"malformed pickle payload: {exc}") from exc
    raise CodecError(f"unknown payload kind {kind}")

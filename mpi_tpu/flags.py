"""Flag/environment configuration system.

The reference registers five command-line flags at import time
(/root/reference/flags.go:44-50) which double as the launcher<->program ABI:
the launchers (/root/reference/mpirun/gompirun/gompirun.go:77,
/root/reference/mpirun/gompirunslurm/slurm.go:103) synthesize ``-mpi-addr``
and ``-mpi-alladdr`` flags for every spawned rank, and ``Network.useFlags``
(/root/reference/network.go:69-90) resolves unset struct fields from them.

This module keeps the exact same flag names (so launcher-injected argv is
wire-compatible with the reference's UX) and layers an environment-variable
fallback (``MPI_TPU_*``) on top, which is the idiomatic transport for cluster
launchers (SLURM, GKE, TPU pods) that prefer env to argv.

Resolution precedence, mirroring network.go:69-90:
  explicitly-set backend attribute  >  CLI flag  >  environment  >  default.

Unlike Go's ``flag`` package, parsing here is *tolerant*: unknown argv
entries are ignored so user programs keep their own CLI space without
coordinating with us (the reference instead requires the program to call
``flag.Parse()`` itself, mpi.go:43).
"""

from __future__ import annotations

import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

__all__ = [
    "MpiFlags",
    "parse_duration",
    "format_duration",
    "parse_bool",
    "parse_flags",
    "get_flags",
    "set_argv_for_testing",
    "FLAG_ADDR",
    "FLAG_ALLADDR",
    "FLAG_INITTIMEOUT",
    "FLAG_PROTOCOL",
    "FLAG_PASSWORD",
    "FLAG_OPTIMEOUT",
    "FLAG_CRC",
    "FLAG_CHAOS",
    "FLAG_TRACE_OUT",
    "FLAG_METRICS_OUT",
    "FLAG_POSTMORTEM",
    "FLAG_TRACE_STREAM",
    "DEFAULT_PROTOCOL",
    "DEFAULT_INIT_TIMEOUT",
]

# Flag names — identical spelling to flags.go:44-50 so launcher-injected
# argv runs unmodified. Both single- and double-dash forms are accepted.
FLAG_ADDR = "mpi-addr"
FLAG_ALLADDR = "mpi-alladdr"
FLAG_INITTIMEOUT = "mpi-inittimeout"
FLAG_PROTOCOL = "mpi-protocol"
FLAG_PASSWORD = "mpi-password"
# Robustness extensions beyond the reference's five (docs/FAULT_TOLERANCE.md):
# per-operation deadline, per-frame CRC trailer, chaos fault injection.
FLAG_OPTIMEOUT = "mpi-optimeout"
FLAG_CRC = "mpi-crc"
FLAG_CHAOS = "mpi-chaos"
# Observability extensions (docs/OBSERVABILITY.md): merged-trace sink,
# per-rank metrics artifact, flight-recorder postmortem directory.
FLAG_TRACE_OUT = "mpi-trace-out"
FLAG_METRICS_OUT = "mpi-metrics-out"
FLAG_POSTMORTEM = "mpi-postmortem"
# Streaming trace spool directory: ranks flush bounded span chunks there
# continuously, making traces crash-durable (docs/OBSERVABILITY.md).
FLAG_TRACE_STREAM = "mpi-trace-stream"

ENV_PREFIX = "MPI_TPU_"
ENV_ADDR = ENV_PREFIX + "ADDR"
ENV_ALLADDR = ENV_PREFIX + "ALLADDR"
ENV_INITTIMEOUT = ENV_PREFIX + "INITTIMEOUT"
ENV_PROTOCOL = ENV_PREFIX + "PROTOCOL"
ENV_PASSWORD = ENV_PREFIX + "PASSWORD"
ENV_OPTIMEOUT = ENV_PREFIX + "OPTIMEOUT"
ENV_CRC = ENV_PREFIX + "CRC"
ENV_CHAOS = ENV_PREFIX + "CHAOS"
ENV_TRACE_OUT = ENV_PREFIX + "TRACE_OUT"
ENV_METRICS_OUT = ENV_PREFIX + "METRICS_OUT"
ENV_POSTMORTEM = ENV_PREFIX + "POSTMORTEM_DIR"
ENV_TRACE_STREAM = ENV_PREFIX + "TRACE_STREAM"

DEFAULT_PROTOCOL = "tcp"  # flags.go:48 default
# The reference's DurationFlag has no default (zero value); Network.Init then
# treats zero as "no timeout" for the listen side but the dial side polls
# until Timeout elapses (network.go:297-312). A finite default is safer.
DEFAULT_INIT_TIMEOUT = 60.0  # seconds

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}


def parse_duration(text: str) -> float:
    """Parse a Go-style duration string ("300ms", "1m30s", "10s") to seconds.

    Mirrors the reference's ``DurationFlag`` (flags.go:29-42), which wraps
    Go's ``time.ParseDuration``. Bare numbers are treated as seconds.
    """
    text = text.strip()
    if not text:
        raise ValueError("empty duration")
    try:
        return float(text)  # bare number → seconds
    except ValueError:
        pass
    pos = 0
    total = 0.0
    for m in _DURATION_RE.finditer(text):
        if m.start() != pos:
            raise ValueError(f"invalid duration {text!r}")
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(text):
        raise ValueError(f"invalid duration {text!r}")
    return total


def parse_bool(text: str) -> bool:
    """Parse a boolean flag value (``--mpi-crc on``). Accepts Go's
    strconv.ParseBool set plus on/off; anything else raises."""
    low = text.strip().lower()
    if low in ("1", "t", "true", "on", "y", "yes"):
        return True
    if low in ("0", "f", "false", "off", "n", "no"):
        return False
    raise ValueError(f"invalid boolean {text!r}")


def format_duration(seconds: float) -> str:
    """Inverse of :func:`parse_duration`, used when re-injecting flags.

    Falls back to the bare-seconds form for awkward values so the
    round-trip is always exact (a "0.0004" stays 400 µs instead of
    truncating to "0ms")."""
    if seconds >= 1 and float(seconds).is_integer():
        return f"{int(seconds)}s"
    return repr(float(seconds))


@dataclass
class MpiFlags:
    """Resolved values of the reference's five ``-mpi-*`` flags
    (flags.go:10-14) plus the three robustness extensions."""

    addr: Optional[str] = None
    alladdr: List[str] = field(default_factory=list)
    inittimeout: Optional[float] = None  # seconds
    protocol: Optional[str] = None
    password: Optional[str] = None
    optimeout: Optional[float] = None  # seconds; None = no op deadline
    crc: Optional[bool] = None         # per-frame CRC32 trailer wanted
    chaos: Optional[str] = None        # raw seed:rate:modes spec
    trace_out: Optional[str] = None    # merged chrome-trace sink (rank 0)
    metrics_out: Optional[str] = None  # per-rank metrics JSON artifact
    postmortem: Optional[str] = None   # flight-recorder dump directory
    trace_stream: Optional[str] = None  # streaming trace spool directory

    def as_argv(self) -> List[str]:
        """Render back to launcher-injectable argv (gompirun.go:77 ABI)."""
        out: List[str] = []
        if self.addr is not None:
            out += [f"--{FLAG_ADDR}", self.addr]
        if self.alladdr:
            out += [f"--{FLAG_ALLADDR}", ",".join(self.alladdr)]
        if self.inittimeout is not None:
            out += [f"--{FLAG_INITTIMEOUT}", format_duration(self.inittimeout)]
        if self.protocol is not None:
            out += [f"--{FLAG_PROTOCOL}", self.protocol]
        if self.password is not None:
            out += [f"--{FLAG_PASSWORD}", self.password]
        if self.optimeout is not None:
            out += [f"--{FLAG_OPTIMEOUT}", format_duration(self.optimeout)]
        if self.crc is not None:
            out += [f"--{FLAG_CRC}", "on" if self.crc else "off"]
        if self.chaos is not None:
            out += [f"--{FLAG_CHAOS}", self.chaos]
        if self.trace_out is not None:
            out += [f"--{FLAG_TRACE_OUT}", self.trace_out]
        if self.metrics_out is not None:
            out += [f"--{FLAG_METRICS_OUT}", self.metrics_out]
        if self.postmortem is not None:
            out += [f"--{FLAG_POSTMORTEM}", self.postmortem]
        if self.trace_stream is not None:
            out += [f"--{FLAG_TRACE_STREAM}", self.trace_stream]
        return out


_FLAG_NAMES = {FLAG_ADDR, FLAG_ALLADDR, FLAG_INITTIMEOUT, FLAG_PROTOCOL,
               FLAG_PASSWORD, FLAG_OPTIMEOUT, FLAG_CRC, FLAG_CHAOS,
               FLAG_TRACE_OUT, FLAG_METRICS_OUT, FLAG_POSTMORTEM,
               FLAG_TRACE_STREAM}

# Overridable argv source for tests (instead of mutating sys.argv).
_argv_override: Optional[Sequence[str]] = None


def set_argv_for_testing(argv: Optional[Sequence[str]]) -> None:
    global _argv_override
    _argv_override = argv


def _scan_argv(argv: Sequence[str],
               names: Optional[set] = None) -> Dict[str, str]:
    """Extract the given flags from argv, ignoring everything else.

    Accepts ``-name value``, ``--name value``, ``-name=value``,
    ``--name=value``. ``names`` defaults to the core ``-mpi-*`` flags;
    the runner passes its own set (``mpi-backend``/``mpi-ranks``) so there
    is exactly one argv grammar in the package.
    """
    if names is None:
        names = _FLAG_NAMES
    found: Dict[str, str] = {}
    i = 0
    while i < len(argv):
        tok = argv[i]
        if tok.startswith("-"):
            body = tok.lstrip("-")
            if "=" in body:
                name, _, value = body.partition("=")
                if name in names:
                    found[name] = value
            elif body in names:
                if i + 1 < len(argv):
                    found[body] = argv[i + 1]
                    i += 1
        i += 1
    return found


def scan_argv(names: set, argv: Optional[Sequence[str]] = None) -> Dict[str, str]:
    """Public scanner for extension flags, honoring the same argv source
    override (:func:`set_argv_for_testing`) as the core five."""
    if argv is None:
        argv = _argv_override if _argv_override is not None else sys.argv[1:]
    return _scan_argv(argv, names)


def parse_flags(argv: Optional[Sequence[str]] = None,
                environ: Optional[Dict[str, str]] = None) -> MpiFlags:
    """Resolve the ``-mpi-*`` flags from argv then environment.

    argv wins over env for each individual flag, matching the reference's
    "flags are the source of truth the launcher controls" design.
    """
    if argv is None:
        argv = _argv_override if _argv_override is not None else sys.argv[1:]
    env = os.environ if environ is None else environ

    raw = _scan_argv(argv)
    flags = MpiFlags()

    addr = raw.get(FLAG_ADDR, env.get(ENV_ADDR))
    if addr:
        flags.addr = addr

    alladdr = raw.get(FLAG_ALLADDR, env.get(ENV_ALLADDR))
    if alladdr:
        # Comma-separated list, as AddrsFlag (flags.go:16-27).
        flags.alladdr = [a for a in (s.strip() for s in alladdr.split(",")) if a]

    timeout = raw.get(FLAG_INITTIMEOUT, env.get(ENV_INITTIMEOUT))
    if timeout:
        flags.inittimeout = parse_duration(timeout)

    proto = raw.get(FLAG_PROTOCOL, env.get(ENV_PROTOCOL))
    if proto:
        flags.protocol = proto

    password = raw.get(FLAG_PASSWORD, env.get(ENV_PASSWORD))
    if password is not None:
        flags.password = password

    optimeout = raw.get(FLAG_OPTIMEOUT, env.get(ENV_OPTIMEOUT))
    if optimeout:
        flags.optimeout = parse_duration(optimeout)

    crc = raw.get(FLAG_CRC, env.get(ENV_CRC))
    if crc:
        flags.crc = parse_bool(crc)

    chaos = raw.get(FLAG_CHAOS, env.get(ENV_CHAOS))
    if chaos:
        flags.chaos = chaos

    trace_out = raw.get(FLAG_TRACE_OUT, env.get(ENV_TRACE_OUT))
    if trace_out:
        flags.trace_out = trace_out

    metrics_out = raw.get(FLAG_METRICS_OUT, env.get(ENV_METRICS_OUT))
    if metrics_out:
        flags.metrics_out = metrics_out

    postmortem = raw.get(FLAG_POSTMORTEM, env.get(ENV_POSTMORTEM))
    if postmortem:
        flags.postmortem = postmortem

    trace_stream = raw.get(FLAG_TRACE_STREAM, env.get(ENV_TRACE_STREAM))
    if trace_stream:
        flags.trace_stream = trace_stream

    return flags


def get_flags() -> MpiFlags:
    """Parse flags from the live process argv/env (used by backend init)."""
    return parse_flags()

"""Dynamic process management — MPI_Comm_spawn / MPI_Comm_get_parent.

No reference analogue: btracey/mpi fixes the world at init (rank =
index in the sorted ``--mpi-alladdr`` list, network.go:94-118) and has
no way to add processes. This module is mpi4py-parity work (the one
commonly used dynamic-process facility), built entirely from existing
subsystems — no core changes:

* **Children run in their own private TCP world.** ``spawn`` launches
  them through the standard flag ABI (``--mpi-addr``/``--mpi-alladdr``,
  the launcher protocol of :mod:`mpi_tpu.launch.mpirun`), so a spawned
  child's ``init()`` — and therefore its ``COMM_WORLD`` — contains
  exactly the children, correct by construction.
* **A second, private bridge network spans parents + children.** Each
  parent and each child contributes one extra TCP endpoint; addresses
  travel to the children in environment variables. Ranks on the bridge
  follow the driver's sorted-address rule, so both sides derive the
  same parent/child rank sets with no negotiation.
* **The intercomm rides the existing machinery** over the bridge's
  union world: ``create_group`` (collective among each side only,
  disjoint tags) + :func:`mpi_tpu.intercomm.create_intercomm`
  (leaders = group rank 0 of each side).

Scope: local-host spawn (like the local launcher); children must reach
:func:`get_parent` — directly, or via ``mpi_tpu.compat``'s ``MPI.Init``
/ first ``COMM_WORLD`` access, which call it automatically for spawned
processes — or the parents' ``spawn`` times out (the parents' bridge
init blocks until every child connects).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from typing import List, Optional, Sequence, Tuple

from .api import MpiError
from .comm import Comm
from .intercomm import Intercomm, create_intercomm

__all__ = ["spawn", "get_parent", "is_spawned", "disconnect",
           "open_port", "close_port", "accept", "connect",
           "publish_name", "unpublish_name", "lookup_name"]

# Flag-protocol env overrides (flags.py ENV_*) that must NOT leak from
# the parent's environment into a spawned child: the child's world is
# fully specified by the argv spawn builds, and an inherited
# MPI_TPU_PROTOCOL / MPI_TPU_ADDR / ... would reinterpret or override
# it (e.g. TCP addresses read as unix-socket paths).
_FLAG_ENV = ("MPI_TPU_ADDR", "MPI_TPU_ALLADDR", "MPI_TPU_INITTIMEOUT",
             "MPI_TPU_PROTOCOL", "MPI_TPU_PASSWORD")

ENV_BRIDGE_ADDR = "MPI_TPU_SPAWN_BRIDGE_ADDR"
ENV_BRIDGE_ALL = "MPI_TPU_SPAWN_BRIDGE_ALL"
ENV_PARENT_ADDRS = "MPI_TPU_SPAWN_PARENT_ADDRS"
ENV_CHILD_ADDRS = "MPI_TPU_SPAWN_CHILD_ADDRS"
ENV_PASSWORD_VAR = "MPI_TPU_SPAWN_PASSWORD"
ENV_TIMEOUT = "MPI_TPU_SPAWN_TIMEOUT"
_SPAWN_ENV = (ENV_BRIDGE_ADDR, ENV_BRIDGE_ALL, ENV_PARENT_ADDRS,
              ENV_CHILD_ADDRS, ENV_PASSWORD_VAR, ENV_TIMEOUT)

# create_group / create_intercomm bootstrap tags on the bridge's union
# world (disjoint groups may share a tag, but distinct ones cost
# nothing and read unambiguously).
_TAG_PARENT_GROUP = 0
_TAG_CHILD_GROUP = 1
_TAG_INTERCOMM = 2


def _alloc_addrs(n: int) -> List[str]:
    """n free loopback endpoints (bind-and-release, the in-repo port
    allocation idiom; zero-padded so string sort == numeric sort)."""
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    addrs = [f"127.0.0.1:{s.getsockname()[1]:05d}" for s in socks]
    for s in socks:
        s.close()
    return addrs


def _build_intercomm(bridge, bridge_all: List[str],
                     parent_addrs: Sequence[str],
                     child_addrs: Sequence[str],
                     is_parent: bool) -> Intercomm:
    """Both sides: union world over the bridge network -> own-side
    group -> intercomm. ``bridge_all`` must be the sorted address list
    (bridge rank = index, the driver's rule); ``parent_addrs`` /
    ``child_addrs`` must be in LOGICAL order — parent comm rank and
    child world rank respectively — so intercomm group rank i IS
    logical rank i on both sides (ephemeral bridge ports sort
    arbitrarily; deriving group order from the sorted addresses would
    scramble which process is 'remote rank 0')."""
    parent_ranks = tuple(bridge_all.index(a) for a in parent_addrs)
    child_ranks = tuple(bridge_all.index(a) for a in child_addrs)
    union = Comm(bridge, tuple(range(len(bridge_all))), 0)
    if is_parent:
        local = union.create_group(parent_ranks, tag=_TAG_PARENT_GROUP)
        remote_leader = child_ranks[0]
    else:
        local = union.create_group(child_ranks, tag=_TAG_CHILD_GROUP)
        remote_leader = parent_ranks[0]
    return create_intercomm(local, 0, union, remote_leader,
                            tag=_TAG_INTERCOMM)


def spawn(comm: Comm, command: str, args: Sequence[str] = (),
          maxprocs: int = 1, *, root: int = 0,
          python: Optional[str] = None,
          timeout: float = 60.0) -> Intercomm:
    """Parent side (MPI_Comm_spawn): launch ``maxprocs`` copies of
    ``python command *args`` on this host and return the
    intercommunicator (local group = ``comm``'s members in bridge
    order, remote group = the children). Collective over ``comm``.

    The children see the standard flag ABI for their own world plus
    the spawn environment for the bridge; the root's process handles
    are attached to the returned intercomm as ``_spawned_procs`` so a
    caller that wants to reap exit codes can. Blocks until every child
    reaches :func:`get_parent` (compat's ``MPI.Init`` does so
    automatically) or ``timeout`` expires."""
    from .backends.tcp import TcpNetwork

    if maxprocs < 1:
        raise MpiError(f"mpi_tpu: spawn maxprocs must be >= 1, got "
                       f"{maxprocs}")
    me = comm.rank()
    if me == root:
        import secrets

        nparents = comm.size()
        # ONE allocation batch (all sockets held open together): three
        # sequential bind-and-release batches could hand a freed port
        # straight back and self-collide across the lists.
        ports = _alloc_addrs(nparents + 2 * maxprocs)
        parent_bridge = ports[:nparents]
        child_world = ports[nparents:nparents + maxprocs]
        child_bridge = ports[nparents + maxprocs:]
        # Private handshake token for the bridge AND the child world:
        # explicit on every endpoint, so neither inherits whatever
        # --mpi-password the PARENT world was launched with (children
        # don't know it) nor the ambient flag defaults.
        password = secrets.token_hex(8)
        payload = (parent_bridge, child_world, child_bridge, password)
    else:
        payload = None
    parent_bridge, child_world, child_bridge, password = comm.bcast(
        payload, root=root)
    my_bridge_addr = parent_bridge[me]
    bridge_all = sorted(parent_bridge + child_bridge)
    # Child i's WORLD rank is its world addr's position in the sorted
    # alladdr list (the driver's rule) — order the bridge addrs the
    # same way so intercomm remote rank i is child world rank i.
    order = sorted(range(maxprocs), key=lambda i: child_world[i])
    child_bridge_ordered = [child_bridge[i] for i in order]

    procs: List[subprocess.Popen] = []
    if me == root:
        # Child env: strip spawn vars inherited from OUR spawn (a
        # nested spawn's grandchildren must not try to join the old
        # bridge) and the flag-protocol env overrides (the child's
        # world is fully specified by argv below); prepend the package
        # root (launcher parity — the child program's cwd need not see
        # mpi_tpu).
        env = {k: v for k, v in os.environ.items()
               if k not in _SPAWN_ENV and k not in _FLAG_ENV}
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (pkg_root + os.pathsep + existing
                                 if existing else pkg_root)
        env[ENV_BRIDGE_ALL] = ",".join(bridge_all)
        env[ENV_PARENT_ADDRS] = ",".join(parent_bridge)
        env[ENV_CHILD_ADDRS] = ",".join(child_bridge_ordered)
        env[ENV_PASSWORD_VAR] = password
        env[ENV_TIMEOUT] = f"{timeout:.1f}"
        # mpi4py's canonical form is Spawn(sys.executable,
        # args=[script]); the in-repo form is Spawn(script). Don't
        # stack an interpreter on top of an interpreter.
        if python is None and os.path.basename(command).startswith(
                "python"):
            base = [command, *args]
        else:
            base = [python or sys.executable, command, *args]
        for waddr, baddr in zip(child_world, child_bridge):
            argv = [*base,
                    "--mpi-addr", waddr,
                    "--mpi-alladdr", ",".join(sorted(child_world)),
                    "--mpi-protocol", "tcp",
                    "--mpi-inittimeout", f"{max(1, round(timeout))}s"]
            # The child-world password travels via env (flags.py
            # resolves MPI_TPU_PASSWORD when the flag is absent), NOT
            # argv: /proc/<pid>/cmdline is world-readable, and a
            # secret there would let any local user join the child
            # world's loopback ports. _FLAG_ENV stripping above
            # removed any inherited value, so this set is the only
            # one the child sees.
            procs.append(subprocess.Popen(
                argv, env={**env, ENV_BRIDGE_ADDR: baddr,
                           "MPI_TPU_PASSWORD": password}))

    # Every parent joins the bridge; init blocks until the children
    # connect (their get_parent side of this same all-to-all).
    bridge = TcpNetwork(addr=my_bridge_addr, addrs=list(bridge_all),
                        timeout=timeout, proto="tcp", password=password)
    try:
        bridge.init()
    except Exception:
        for p in procs:  # don't leave half-spawned children behind
            p.kill()
        raise
    inter = _build_intercomm(bridge, bridge_all, parent_bridge,
                             child_bridge_ordered, is_parent=True)
    inter._spawned_procs = procs   # root: handles for reaping
    inter._bridge_net = bridge     # Disconnect() tears this down
    return inter


_parent_lock = threading.Lock()
_parent_cache: Optional[Intercomm] = None


def is_spawned() -> bool:
    """True when this process was launched by :func:`spawn`."""
    return ENV_BRIDGE_ADDR in os.environ


def get_parent() -> Optional[Intercomm]:
    """Child side (MPI_Comm_get_parent): the intercommunicator to the
    spawning group (local = this child world, remote = the parents),
    or ``None`` when this process was not spawned. The first call
    joins the bridge network — collective with the parents' ``spawn``
    and the sibling children — then caches; later calls are free."""
    global _parent_cache
    if not is_spawned():
        return None
    with _parent_lock:
        if _parent_cache is None:
            from .backends.tcp import TcpNetwork

            bridge_all = os.environ[ENV_BRIDGE_ALL].split(",")
            bridge = TcpNetwork(
                addr=os.environ[ENV_BRIDGE_ADDR],
                addrs=list(bridge_all),
                timeout=float(os.environ.get(ENV_TIMEOUT, "60")),
                proto="tcp",
                password=os.environ.get(ENV_PASSWORD_VAR))
            bridge.init()
            _parent_cache = _build_intercomm(
                bridge, sorted(bridge_all),
                os.environ[ENV_PARENT_ADDRS].split(","),
                os.environ[ENV_CHILD_ADDRS].split(","),
                is_parent=False)
            _parent_cache._bridge_net = bridge
    return _parent_cache


def disconnect(inter: Intercomm) -> None:
    """Tear down a spawn intercommunicator (MPI_Comm_disconnect):
    free the communicator AND shut down its private bridge network —
    sockets and reader threads that would otherwise accumulate one
    mesh per spawn in a long-running master. After this the intercomm
    is unusable; in a child, :func:`get_parent` thereafter returns
    ``None`` (COMM_NULL — a disconnected child looks non-spawned, as
    after mpi4py's ``Disconnect``) instead of rebuilding a bridge
    whose far side is gone."""
    global _parent_cache
    net = getattr(inter, "_bridge_net", None)
    inter.free()
    if net is not None:
        net.finalize()
    # Reap the Popen children (root side): without a wait() each
    # exited child lingers as a zombie until GC/interpreter exit, so a
    # long-running master accumulates one per spawn — the exact leak
    # this teardown exists to prevent. Disconnect is NOT child exit
    # (MPI lets a disconnected child keep computing), so never block:
    # poll() reaps the already-exited; a daemon waiter collects each
    # straggler whenever it does exit.
    for proc in getattr(inter, "_spawned_procs", ()):
        if proc.poll() is None:
            threading.Thread(target=proc.wait, daemon=True,
                             name="mpi-tpu-spawn-reaper").start()
    with _parent_lock:
        if _parent_cache is inter:
            _parent_cache = None
            os.environ.pop(ENV_BRIDGE_ADDR, None)  # is_spawned -> False


# --------------------------------------------------------------------------
# Client/server connection (MPI_Open_port / MPI_Comm_accept /
# MPI_Comm_connect): two INDEPENDENT, already-running worlds join
# through a rendezvous address instead of a parent launching children.
# The handshake socket carries one JSON line each way (group sizes +
# bridge addresses + a fresh token); the intercomm then rides the same
# private-bridge construction spawn uses.
# --------------------------------------------------------------------------

def open_port() -> str:
    """MPI_Open_port: a rendezvous address ("host:port") a server
    passes to :func:`accept` and advertises to clients out of band
    (a file, a nameserver, argv). The address is allocated now but
    only listened on inside ``accept`` — clients retry their dial
    until the server is there (or their timeout expires)."""
    return _alloc_addrs(1)[0]


def close_port(port_name: str) -> None:
    """MPI_Close_port: nothing is held between calls here — the
    listener lives only inside :func:`accept` — so this is a no-op
    kept for surface parity."""


def _recv_json_line(sock: socket.socket, limit: int = 1 << 20) -> dict:
    import json as _json

    buf = bytearray()
    while not buf.endswith(b"\n"):
        if len(buf) > limit:
            raise MpiError("mpi_tpu: accept/connect handshake line "
                           "too long")
        chunk = sock.recv(4096)
        if not chunk:
            raise MpiError("mpi_tpu: accept/connect handshake closed "
                           "early")
        buf += chunk
    return _json.loads(buf.decode())


def _send_json_line(sock: socket.socket, obj: dict) -> None:
    import json as _json

    sock.sendall((_json.dumps(obj) + "\n").encode())


def _bcast_or_raise(comm: Comm, payload, err: Optional[str], root: int):
    """Root's handshake outcome travels to every rank — success
    payload or error string — so a failed rendezvous raises the SAME
    error on the whole collective instead of stranding non-root ranks
    in a bcast no one will ever feed."""
    payload, err = comm.bcast((payload, err), root=root)
    if err is not None:
        raise MpiError(err)
    return payload


def _handshake_timeout(deadline: Optional[float],
                       cap: float = 60.0) -> float:
    """Per-socket-op timeout: bounded by the caller's deadline when
    one exists, by ``cap`` when blocking indefinitely (a dead peer
    mid-handshake must not wedge an unbounded accept forever)."""
    import time as _time

    if deadline is None:
        return cap
    return max(0.1, min(cap, deadline - _time.monotonic()))


def accept(comm: Comm, port_name: str, *, root: int = 0,
           timeout: Optional[float] = 60.0) -> Intercomm:
    """Server side (MPI_Comm_accept): block until one client group
    :func:`connect`\\ s to ``port_name``, then return the
    intercommunicator (local = this comm's members, remote = the
    client's). Collective over ``comm``; ANY root-side failure —
    timeout, malformed port name, bind error — raises the same
    MpiError on every rank (the outcome travels in a bcast; a raise
    that skipped it would strand the non-roots). ``timeout=None``
    blocks indefinitely, MPI's own semantics (the compat ``Accept``
    default). A malformed peer (stale dialer from an earlier
    timed-out connect, port-reuse traffic) is dropped and the
    listener keeps waiting for a real client."""
    import time as _time

    me = comm.rank()
    payload, err = None, None
    if me == root:
        try:
            import secrets

            n = comm.size()
            server_bridge = _alloc_addrs(n)
            password = secrets.token_hex(8)
            host, _, port = port_name.rpartition(":")
            port_num = int(port)   # malformed port_name raises here
            deadline = (None if timeout is None
                        else _time.monotonic() + timeout)
            srv = socket.socket()
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            client_bridge: Optional[List[str]] = None
            try:
                srv.bind((host or "127.0.0.1", port_num))
                srv.listen(4)
                while client_bridge is None and err is None:
                    if deadline is None:
                        srv.settimeout(None)
                    else:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            err = (f"mpi_tpu: accept on {port_name}: "
                                   f"no client connected within "
                                   f"{timeout:.0f}s")
                            break
                        srv.settimeout(remaining)
                    try:
                        conn, _addr = srv.accept()
                    except socket.timeout:
                        continue
                    try:
                        conn.settimeout(_handshake_timeout(deadline))
                        hello = _recv_json_line(conn)
                        bridge = list(hello["bridge"])
                        dup = set(server_bridge) & set(bridge)
                        if dup:
                            # Independent bind-and-release batches in
                            # two processes CAN collide (spawn's
                            # single batch prevents the SELF-collision
                            # only). Checked BEFORE the success reply,
                            # and the client is told too — otherwise
                            # it would burn its timeout in a doomed
                            # bridge init while the server reports
                            # the actionable message.
                            msg = (f"mpi_tpu: accept/connect bridge "
                                   f"port collision {sorted(dup)}; "
                                   f"retry the rendezvous")
                            _send_json_line(conn, {"error": msg})
                            err = msg
                        else:
                            _send_json_line(
                                conn, {"bridge": server_bridge,
                                       "password": password})
                            client_bridge = bridge
                    except Exception:  # noqa: BLE001 - one bad peer
                        continue       # keep listening for a client
                    finally:
                        conn.close()
            finally:
                srv.close()
            if err is None:
                if client_bridge is not None:
                    payload = (server_bridge, client_bridge, password)
                else:
                    err = f"mpi_tpu: accept on {port_name}: no client"
        except Exception as exc:  # noqa: BLE001 - whole-comm raise
            if err is None:
                err = (f"mpi_tpu: accept on {port_name}: "
                       f"{type(exc).__name__}: {exc}")
    server_bridge, client_bridge, password = _bcast_or_raise(
        comm, payload, err, root)
    return _join_bridge(comm, server_bridge, client_bridge, password,
                        accepting=True, timeout=timeout)


def connect(comm: Comm, port_name: str, *, root: int = 0,
            timeout: Optional[float] = 60.0) -> Intercomm:
    """Client side (MPI_Comm_connect): rendezvous with the server
    group accepting on ``port_name``; returns the intercomm
    (local = this comm's members, remote = the server's). Collective
    over ``comm``; any root-side failure raises on every rank (same
    outcome-bcast as :func:`accept`). The dial retries until the
    server reaches ``accept``; ``timeout=None`` retries
    indefinitely."""
    import time as _time

    me = comm.rank()
    n = comm.size()
    payload, err = None, None
    if me == root:
        try:
            client_bridge = _alloc_addrs(n)
            host, _, port = port_name.rpartition(":")
            port_num = int(port)   # malformed port_name raises here
            deadline = (None if timeout is None
                        else _time.monotonic() + timeout)
            conn: Optional[socket.socket] = None
            while conn is None and err is None:
                try:
                    conn = socket.create_connection(
                        (host or "127.0.0.1", port_num),
                        timeout=_handshake_timeout(deadline, cap=10.0))
                except OSError:
                    if deadline is not None \
                            and _time.monotonic() >= deadline:
                        err = (f"mpi_tpu: connect to {port_name}: no "
                               f"server accepted within "
                               f"{timeout:.0f}s")
                        break
                    _time.sleep(0.1)  # server not in accept(); retry
            if err is None:
                try:
                    conn.settimeout(_handshake_timeout(deadline))
                    _send_json_line(conn, {"bridge": client_bridge})
                    reply = _recv_json_line(conn)
                    if "error" in reply:
                        # The server detected a problem (e.g. a bridge
                        # port collision) and told us the actionable
                        # message instead of letting us burn the
                        # timeout in a doomed bridge init.
                        err = str(reply["error"])
                    else:
                        payload = (list(reply["bridge"]),
                                   client_bridge,
                                   str(reply["password"]))
                except Exception as exc:  # noqa: BLE001
                    err = (f"mpi_tpu: connect to {port_name}: "
                           f"handshake failed: {exc}")
                finally:
                    conn.close()
        except Exception as exc:  # noqa: BLE001 - whole-comm raise
            if err is None:
                err = (f"mpi_tpu: connect to {port_name}: "
                       f"{type(exc).__name__}: {exc}")
    server_bridge, client_bridge, password = _bcast_or_raise(
        comm, payload, err, root)
    return _join_bridge(comm, server_bridge, client_bridge, password,
                        accepting=False, timeout=timeout)


def _join_bridge(comm: Comm, server_bridge: List[str],
                 client_bridge: List[str], password: str,
                 accepting: bool,
                 timeout: Optional[float]) -> Intercomm:
    """Shared tail of accept/connect: every member joins the bridge
    network on its side's addr (indexed by ITS comm rank — both lists
    are in comm-rank order, so intercomm group rank i is comm rank i
    on both sides, exactly like spawn) and builds the intercomm. An
    unbounded rendezvous still gets a BOUNDED bridge init: once the
    handshake succeeded both sides are live, so a peer that dies now
    should fail the init, not hang it forever."""
    from .backends.tcp import TcpNetwork

    my_addr = (server_bridge if accepting else client_bridge)[comm.rank()]
    bridge_all = sorted(server_bridge + client_bridge)
    bridge = TcpNetwork(addr=my_addr, addrs=list(bridge_all),
                        timeout=120.0 if timeout is None else timeout,
                        proto="tcp", password=password)
    bridge.init()
    inter = _build_intercomm(bridge, bridge_all, server_bridge,
                             client_bridge, is_parent=accepting)
    inter._bridge_net = bridge     # disconnect() tears this down
    return inter


# --------------------------------------------------------------------------
# Name service (MPI_Publish_name / MPI_Lookup_name / MPI_Unpublish_name):
# the out-of-band channel the standard pairs with open_port — a server
# publishes its port under a service name, clients look it up instead
# of receiving the address through argv/files themselves.
# --------------------------------------------------------------------------

def _nameserver_dir() -> str:
    """Single-host registry directory (one file per service name).

    The default is PER-USER: ``$XDG_RUNTIME_DIR/mpi_tpu_nameserver``
    (the runtime dir is 0700 by contract) or
    ``<tmp>/mpi_tpu_nameserver-<uid>``, created 0700 and verified to
    be owned by this uid. A fixed world-writable default would be
    squattable — another local user pre-creates it (the old chmod
    failure was tolerated) or replaces service-hash records, silently
    redirecting a connecting client's rendezvous to a port they
    control. Cross-user registries are therefore an EXPLICIT opt-in:
    point MPI_TPU_NAMESERVER_DIR at a shared directory whose trust
    the operator vouches for (that override is used as-is)."""
    import tempfile

    d = os.environ.get("MPI_TPU_NAMESERVER_DIR")
    if d:
        os.makedirs(d, exist_ok=True)
        return d
    runtime = os.environ.get("XDG_RUNTIME_DIR")
    if runtime and os.path.isdir(runtime):
        d = os.path.join(runtime, "mpi_tpu_nameserver")
    else:
        d = os.path.join(tempfile.gettempdir(),
                         f"mpi_tpu_nameserver-{os.getuid()}")
    os.makedirs(d, mode=0o700, exist_ok=True)
    st = os.lstat(d)
    import stat as _stat

    if not _stat.S_ISDIR(st.st_mode) or st.st_uid != os.getuid():
        # Symlink swap or a squatter's pre-created dir: refuse loudly
        # (the OpenSSH agent-dir rule) instead of publishing
        # rendezvous addresses into a directory another user controls.
        raise MpiError(
            f"mpi_tpu: name-service dir {d!r} is not a directory "
            f"owned by uid {os.getuid()} — refusing to use it; set "
            f"MPI_TPU_NAMESERVER_DIR to a trusted location")
    if st.st_mode & 0o077:
        try:
            os.chmod(d, 0o700)
        except OSError:
            pass  # ours but unfixable perms: records are still ours
    return d


def _service_path(service_name: str) -> str:
    import hashlib

    digest = hashlib.sha256(service_name.encode()).hexdigest()[:24]
    return os.path.join(_nameserver_dir(), f"{digest}.json")


def publish_name(service_name: str, port_name: str) -> None:
    """MPI_Publish_name: make ``port_name`` discoverable under
    ``service_name``. Re-publishing an ALREADY published name is an
    error, per the standard (unpublish first)."""
    import json as _json

    path = _service_path(service_name)
    # Write the full record to a private temp file, then hard-link it
    # into place: link() is atomic AND exclusive, so concurrent
    # publishers cannot both win, and no reader/duplicate-checker can
    # ever observe a half-written registry file (an O_EXCL create
    # followed by a separate write would wedge the name if the
    # publisher died between the two: 'already published' to
    # publishers, 'not found' to lookups).
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        # start: the publisher pid's kernel start time — lets
        # _reclaim_if_stale tell a live publisher from an unrelated
        # process that recycled the pid after a crash.
        _json.dump({"service": service_name, "port": port_name,
                    "pid": os.getpid(),
                    "start": _pid_start_time(os.getpid())}, f)
    try:
        for attempt in (0, 1):
            try:
                os.link(tmp, path)
                return
            except FileExistsError:
                if attempt == 0 and _reclaim_if_stale(path):
                    continue  # dead publisher's entry removed: retry
                raise MpiError(
                    f"mpi_tpu: service {service_name!r} is already "
                    f"published (MPI_ERR_SERVICE); unpublish_name it "
                    f"first")
    finally:
        os.unlink(tmp)


def _pid_start_time(pid: int) -> Optional[int]:
    """Kernel start time (clock ticks since boot) of ``pid``, or None
    off-Linux / on read failure. Field 22 of /proc/<pid>/stat, parsed
    after the last ')' so a comm containing spaces or parens cannot
    shift the split."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read().decode("ascii", "replace")
        return int(raw.rsplit(")", 1)[1].split()[19])
    except (OSError, ValueError, IndexError):
        return None


def _reclaim_if_stale(path: str) -> bool:
    """True when ``path`` held a publisher that no longer exists and
    was removed — a server that crashed without unpublishing must not
    wedge its service name forever (its restart is the normal caller
    here). Liveness = the recorded pid still exists on this host AND
    (when recorded) its kernel start time matches — a recycled pid
    does not keep a dead publisher's name alive.

    An exclusive reclaim lock serializes concurrent reclaimers: a
    read-then-remove without it could delete a RIVAL's freshly linked
    record (both restarted publishers judging the same stale entry)
    and let two publishes both 'succeed'. Losers simply report
    already-published; inside the lock the only concurrent writers
    are unpublish (remove -> our remove just misses) and publish
    (link-only — cannot replace the file we judged).

    The lock is ``flock``-based, NOT existence-based: the kernel
    releases an flock when its holder dies, so a reclaimer killed
    between acquire and release cannot orphan the lock and wedge the
    name (the O_EXCL design's failure mode, ADVICE r4), and breaking
    a stale lock needs no TTL heuristics or unlink-by-path races.
    The fstat/stat inode check closes the classic flock+unlink race:
    a lock acquired on an inode that a finishing rival already
    unlinked is discarded and the open retried."""
    import fcntl
    import json as _json

    lock = f"{path}.reclaim"
    fd = None
    for _ in range(8):  # bounded: pathological churn -> report False
        try:
            cand = os.open(lock, os.O_WRONLY | os.O_CREAT, 0o644)
        except OSError:
            return False
        try:
            fcntl.flock(cand, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(cand)
            return False   # a LIVE reclaimer owns the verdict
        try:
            if os.fstat(cand).st_ino == os.stat(lock).st_ino:
                fd = cand
                break
        except OSError:
            pass           # path vanished under us: retry the open
        os.close(cand)     # locked a rival's unlinked inode: retry
    if fd is None:
        return False
    try:
        try:
            with open(path) as f:
                rec = _json.load(f)
                pid = int(rec["pid"])
        except (OSError, ValueError, KeyError, TypeError):
            # Unreadable/half-gone: a VANISHED file counts as
            # reclaimed (the owner just unpublished); anything else
            # as live — never delete what we can't attribute.
            return not os.path.exists(path)
        try:
            os.kill(pid, 0)
            alive = True
        except ProcessLookupError:
            alive = False         # dead: reclaim below
        except PermissionError:
            alive = True          # exists, owned by another user
        if alive:
            # /proc start time is readable regardless of uid, so the
            # recycled-pid check runs for the PermissionError case
            # too — pids are host-global, and a crashed publisher's
            # pid recycled by ANOTHER user's daemon must not wedge
            # the name forever.
            rec_start = rec.get("start")
            cur_start = _pid_start_time(pid)
            if (rec_start is None or cur_start is None
                    or cur_start == rec_start):
                return False      # genuinely the live publisher
            # pid exists but is a DIFFERENT process: reclaim below.
        try:
            os.remove(path)
            return True
        except OSError:
            return False
    finally:
        # Unlink BEFORE close: close releases the flock, and a rival
        # must never acquire an flock on an inode that is still the
        # live path (the inode-identity loop above assumes unlinked
        # means released).
        try:
            os.unlink(lock)
        except OSError:
            pass
        os.close(fd)


def unpublish_name(service_name: str, port_name: Optional[str] = None
                   ) -> None:
    """MPI_Unpublish_name: withdraw a published service. Unpublishing
    a name that is not published is an error, per the standard."""
    try:
        os.remove(_service_path(service_name))
    except FileNotFoundError:
        raise MpiError(
            f"mpi_tpu: service {service_name!r} is not published "
            f"(MPI_ERR_SERVICE)")


def lookup_name(service_name: str, *,
                timeout: float = 0.0) -> str:
    """MPI_Lookup_name: the port published under ``service_name``.
    Unpublished -> MpiError immediately (MPI_ERR_NAME), or after
    ``timeout`` seconds of 100 ms polls when one is given (a client
    racing its server's publish is the normal pattern)."""
    import json as _json
    import time as _time

    path = _service_path(service_name)
    deadline = _time.monotonic() + timeout
    while True:
        try:
            with open(path) as f:
                rec = _json.load(f)
            if rec.get("service") == service_name:
                return str(rec["port"])
            # Hash-prefix collision with a different name: treat as
            # not found (astronomically unlikely at 96 bits).
        except (OSError, ValueError):
            pass
        if _time.monotonic() >= deadline:
            raise MpiError(
                f"mpi_tpu: no port published under {service_name!r} "
                f"(MPI_ERR_NAME)")
        _time.sleep(0.1)

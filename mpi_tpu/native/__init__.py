"""Native runtime core — build-on-demand C++ loaded via ctypes.

The reference's runtime is compiled Go; the rebuild's equivalent native
layer lives in ``native/*.cpp`` and is compiled lazily with the system
toolchain into a per-user cache, then loaded with :mod:`ctypes` (no
pybind11 needed — the ABI is plain C). Everything degrades gracefully:
if no compiler is present or ``MPI_TPU_NO_NATIVE=1`` is set, callers get
``None`` and use their pure-Python fallbacks, with identical semantics
(tests cover both paths).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile
import threading
from typing import Optional

__all__ = ["wirecore", "available", "build_error"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_error: Optional[str] = None

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "wirecore.cpp")

PEER_CLOSED = 1000


def _cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "mpi_tpu")


def _build() -> ctypes.CDLL:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out_dir = _cache_dir()
    os.makedirs(out_dir, exist_ok=True)
    so_path = os.path.join(out_dir, f"wirecore-{digest}.so")
    if not os.path.exists(so_path):
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
        os.close(fd)
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)  # atomic publish; races converge
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    lib = ctypes.CDLL(so_path)
    lib.wc_send_frame.restype = ctypes.c_int
    lib.wc_send_frame.argtypes = [
        ctypes.c_int, ctypes.c_uint8, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint64)]
    lib.wc_recv_exact.restype = ctypes.c_int
    lib.wc_recv_exact.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.wc_version.restype = ctypes.c_int
    if lib.wc_version() != 2:
        raise RuntimeError("wirecore version mismatch")
    return lib


def wirecore() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if
    unavailable (non-linux, no compiler, or MPI_TPU_NO_NATIVE=1)."""
    global _lib, _tried, _error
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        if os.environ.get("MPI_TPU_NO_NATIVE") \
                or not sys.platform.startswith("linux") \
                or sys.byteorder != "little":
            # The wire format is explicit little-endian; wirecore.cpp
            # memcpys host-order ints, so big-endian hosts must not load.
            _error = "disabled"
        else:
            try:
                _lib = _build()
            except BaseException as exc:  # noqa: BLE001 - fall back to python
                _error = f"{type(exc).__name__}: {exc}"
        _tried = True
        return _lib


def available() -> bool:
    return wirecore() is not None


def build_error() -> Optional[str]:
    """Why the native core is unavailable (None if loaded or untried)."""
    wirecore()
    return _error


def _reset_for_testing() -> None:
    global _lib, _tried, _error
    with _lock:
        _lib, _tried, _error = None, False, None

"""Native runtime core — build-on-demand C++ loaded via ctypes.

The reference's runtime is compiled Go; the rebuild's equivalent native
layer lives in ``native/*.cpp`` and is compiled lazily with the system
toolchain into a per-user cache, then loaded with :mod:`ctypes` (no
pybind11 needed — the ABI is plain C). Everything degrades gracefully:
if no compiler is present or ``MPI_TPU_NO_NATIVE=1`` is set, callers get
``None`` and use their pure-Python fallbacks, with identical semantics
(tests cover both paths).

Libraries:

* ``wirecore`` (native/wirecore.cpp) — framed send/receive on blocking
  sockets for the TCP driver's hot data path (writev, GIL-free).
* ``shmcore`` (native/shmcore.cpp) — shared-memory SPSC ring transport
  for the ``shm`` protocol (futex-blocked, spin fast path).
* ``dataloader`` (native/dataloader.cpp) — GIL-free gather+widen of
  training batches out of a memory-mapped token corpus.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile
import threading
from typing import Callable, Dict, Optional

__all__ = ["wirecore", "shmcore", "dataloader", "quantcore",
           "available", "build_error"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

PEER_CLOSED = 1000


def _cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "mpi_tpu")


def _configure_wirecore(lib: ctypes.CDLL) -> None:
    # v4: every entry point grew a trailing nullable uint64_t *stages
    # scratch — per-call stage nanoseconds/counts for the tracer's
    # wire.* child spans (pass None on the untraced hot path).
    stages_t = ctypes.POINTER(ctypes.c_uint64)
    lib.wc_send_frame.restype = ctypes.c_int
    lib.wc_send_frame.argtypes = [
        ctypes.c_int, ctypes.c_uint8, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_uint32, stages_t, stages_t]
    lib.wc_send_frame2.restype = ctypes.c_int
    lib.wc_send_frame2.argtypes = [
        ctypes.c_int, ctypes.c_uint8, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_uint32,
        stages_t, stages_t]
    lib.wc_recv_exact.restype = ctypes.c_int
    lib.wc_recv_exact.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64,
        stages_t, stages_t]
    lib.wc_version.restype = ctypes.c_int
    if lib.wc_version() != 4:
        raise RuntimeError("wirecore version mismatch")


def _configure_dataloader(lib: ctypes.CDLL) -> None:
    lib.dl_gather.restype = ctypes.c_int
    lib.dl_gather.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_int]
    lib.dl_version.restype = ctypes.c_int
    if lib.dl_version() != 1:
        raise RuntimeError("dataloader version mismatch")


def _configure_shmcore(lib: ctypes.CDLL) -> None:
    lib.shm_ring_create.restype = ctypes.c_int
    lib.shm_ring_create.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.POINTER(ctypes.c_void_p)]
    lib.shm_ring_attach.restype = ctypes.c_int
    lib.shm_ring_attach.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.shm_ring_unlink.restype = ctypes.c_int
    lib.shm_ring_unlink.argtypes = [ctypes.c_char_p]
    lib.shm_ring_mark_closed.restype = None
    lib.shm_ring_mark_closed.argtypes = [ctypes.c_void_p]
    lib.shm_ring_close.restype = None
    lib.shm_ring_close.argtypes = [ctypes.c_void_p]
    lib.shm_send_frame.restype = ctypes.c_int
    lib.shm_send_frame.argtypes = [
        ctypes.c_void_p, ctypes.c_uint8, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int]
    lib.shm_send_frame2.restype = ctypes.c_int
    lib.shm_send_frame2.argtypes = [
        ctypes.c_void_p, ctypes.c_uint8, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int]
    lib.shm_recv_hdr.restype = ctypes.c_int
    lib.shm_recv_hdr.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int]
    lib.shm_recv_payload.restype = ctypes.c_int
    lib.shm_recv_payload.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int]
    lib.shm_abandon.restype = ctypes.c_int
    lib.shm_abandon.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.shm_version.restype = ctypes.c_int
    if lib.shm_version() != 2:
        raise RuntimeError("shmcore version mismatch")


def _cpu_tag() -> str:
    """Short stable tag for this machine's ISA (model + feature
    flags), for caching -march=native artifacts per CPU type."""
    try:
        with open("/proc/cpuinfo") as f:
            text = f.read()
        lines = [ln for ln in text.splitlines()
                 if ln.startswith(("model name", "flags"))][:2]
        basis = "|".join(lines)
    except OSError:
        import platform

        basis = platform.processor() or platform.machine()
    return hashlib.sha256(basis.encode()).hexdigest()[:10]


def _configure_quantcore(lib: ctypes.CDLL) -> None:
    for name in ("qc_quantize", "qc_accumulate", "qc_dequantize"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
    lib.qc_quantize.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.qc_accumulate.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_uint32, ctypes.c_void_p]
    lib.qc_dequantize.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_uint32, ctypes.c_void_p]
    lib.qc_version.restype = ctypes.c_int
    if lib.qc_version() != 1:
        raise RuntimeError("quantcore version mismatch")


class _Lib:
    """Lazy build+load state for one native library."""

    def __init__(self, stem: str,
                 configure: Callable[[ctypes.CDLL], None]):
        self.stem = stem
        self.src = os.path.join(_NATIVE_DIR, f"{stem}.cpp")
        self.configure = configure
        self.lock = threading.Lock()
        self.lib: Optional[ctypes.CDLL] = None
        self.tried = False
        self.error: Optional[str] = None

    def _build(self) -> ctypes.CDLL:
        with open(self.src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        if self.stem == "quantcore":
            # -march=native builds are CPU-specific, and the cache dir
            # can live on a $HOME shared across heterogeneous nodes
            # (the norm on HPC clusters): key the artifact by this
            # machine's ISA too, or an AVX-512 build loaded on an
            # older node dies with SIGILL inside the kernel.
            digest += "-" + _cpu_tag()
        out_dir = _cache_dir()
        os.makedirs(out_dir, exist_ok=True)
        so_path = os.path.join(out_dir, f"{self.stem}-{digest}.so")
        if not os.path.exists(so_path):
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
            os.close(fd)
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   self.src, "-o", tmp, "-pthread"]
            if self.stem == "quantcore":
                # Streaming arithmetic kernels: let the compiler
                # vectorize for THIS machine (the cache is per-user,
                # per-source-hash, built where it runs — never
                # shipped). NOT -ffast-math: the NaN-poisoning
                # semantics are contractual.
                cmd[1:2] = ["-O3", "-march=native", "-funroll-loops"]
            try:
                try:
                    subprocess.run(cmd, check=True, capture_output=True,
                                   timeout=120)
                except subprocess.CalledProcessError as exc:
                    # Older glibc keeps shm_open in librt; retry with
                    # -lrt ONLY for that link failure — a blanket retry
                    # would mask real compile errors and double their
                    # cost.
                    stderr = (exc.stderr or b"").decode("utf-8", "replace")
                    if "shm_open" not in stderr and "shm_unlink" \
                            not in stderr:
                        raise
                    subprocess.run(cmd + ["-lrt"], check=True,
                                   capture_output=True, timeout=120)
                os.replace(tmp, so_path)  # atomic publish; races converge
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        lib = ctypes.CDLL(so_path)
        self.configure(lib)
        return lib

    def load(self) -> Optional[ctypes.CDLL]:
        if self.tried:
            return self.lib
        with self.lock:
            if self.tried:
                return self.lib
            if os.environ.get("MPI_TPU_NO_NATIVE") \
                    or not sys.platform.startswith("linux") \
                    or sys.byteorder != "little":
                # The wire format is explicit little-endian; the engines
                # memcpy host-order ints, so big-endian hosts must not load.
                self.error = "disabled"
            else:
                try:
                    self.lib = self._build()
                except BaseException as exc:  # noqa: BLE001 - fall back
                    self.error = f"{type(exc).__name__}: {exc}"
            self.tried = True
            return self.lib


_LIBS: Dict[str, _Lib] = {
    "wirecore": _Lib("wirecore", _configure_wirecore),
    "shmcore": _Lib("shmcore", _configure_shmcore),
    "dataloader": _Lib("dataloader", _configure_dataloader),
    "quantcore": _Lib("quantcore", _configure_quantcore),
}


def wirecore() -> Optional[ctypes.CDLL]:
    """The loaded socket frame engine, building on first use; None if
    unavailable (non-linux, no compiler, or MPI_TPU_NO_NATIVE=1)."""
    return _LIBS["wirecore"].load()


def shmcore() -> Optional[ctypes.CDLL]:
    """The loaded shared-memory ring engine; None if unavailable."""
    return _LIBS["shmcore"].load()


def dataloader() -> Optional[ctypes.CDLL]:
    """The loaded batch-gather kernel; None if unavailable."""
    return _LIBS["dataloader"].load()


def quantcore() -> Optional[ctypes.CDLL]:
    """The loaded int8 quantization kernels (compressed wire
    allreduce); None if unavailable."""
    return _LIBS["quantcore"].load()


def available(stem: str = "wirecore") -> bool:
    return _LIBS[stem].load() is not None


def build_error(stem: str = "wirecore") -> Optional[str]:
    """Why the native core is unavailable (None if loaded or untried)."""
    _LIBS[stem].load()
    return _LIBS[stem].error


def _reset_for_testing() -> None:
    for entry in _LIBS.values():
        with entry.lock:
            entry.lib, entry.tried, entry.error = None, False, None

"""Int8-compressed allreduce over the socket drivers — the wire twin
of :func:`mpi_tpu.parallel.quantized_allreduce`.

Round-5 decomposition (docs/PERF_NOTES.md): on the socket fabric the
exact float allreduce is wire-bound at >= 64 MiB, and an int8 path
(4x fewer wire bytes + per-block float32 scales) beats it **iff**
quantization costs ~one memory pass. numpy's ~7 full-array passes
erase the margin, so the hot loops live in ``native/quantcore.cpp``
(fused single-pass kernels, GIL released); the numpy fallback keeps
the path correct — just not profitable — under ``MPI_TPU_NO_NATIVE``.

Algorithm (EQuARX-style two-phase, one quantization per phase, so the
elementwise error is bounded by TWO roundings regardless of rank
count — the same contract as the XLA version, quantized.py:17-32):

1. **reduce-scatter**: every rank splits its vector into ``n`` rank
   shards and quantizes each (including its own); shard ``d`` travels
   to rank ``d`` in ``n-1`` rotation rounds (send to ``me+r``,
   receive from ``me-r`` — the deadlock-free pairwise schedule the
   ring phases use); the receiver dequant-accumulates in float32 **in
   rank order** (deterministic).
2. **allgather**: the reduced shard is quantized once more and
   rotated to every rank; each shard dequantizes into its slot.

Error bound: ``|err| <= 0.5 * (sum_i s1_i + s2)`` with ``s1_i`` rank
i's phase-1 scale for the element's block and ``s2`` the phase-2
scale — asserted exactly by the unit tests. A block containing
NaN/inf quantizes to scale NaN, so divergence propagates loudly.

This is LOSSY and therefore **never** dispatched by the exact
:func:`~mpi_tpu.collectives_generic.allreduce`; callers opt in, and
:func:`wire_compressed_eligible` records the measured crossover the
same way ``ring_eligible``/``quantized_eligible`` do.

No reference analogue (btracey/mpi stubs collectives, mpi.go:130).
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Tuple

import numpy as np

from .api import Interface, MpiError, exchange as _exchange
from .collectives_generic import reserve_tag_blocks

__all__ = ["allreduce_compressed_wire", "wire_compressed_eligible",
           "WIRE_QUANTIZED_MIN_BYTES", "quantize_np", "dequantize_np"]

_BLOCK = 1024

# Measured crossover for the SOCKET fabric. None = never: on the
# 1-core loopback box the REAL path loses at every size (4 ranks,
# vectorized kernels, interleaved A/B: 0.47x @ 16 MiB, 0.69x @
# 64 MiB, 0.21x @ 256 MiB — all four ranks' quantize/accumulate
# passes serialize onto the one core, while on a real deployment each
# rank owns its core and the wire is the shared resource). The
# decomposition bound (PERF_NOTES.md) shows the win appears exactly
# when per-rank compute runs concurrently: enable on such a fabric
# with MPI_TPU_WIRE_QUANTIZED_MIN=<bytes> after an on-fabric A/B —
# the same experimental-DCN discipline as the pipeline lever.
WIRE_QUANTIZED_MIN_BYTES = None


def wire_compressed_eligible(nbytes: int) -> bool:
    """True when the compressed path is expected to beat the exact
    float allreduce on the socket fabric (measured gate; same
    never-lose discipline as ``ring_eligible``)."""
    env = os.environ.get("MPI_TPU_WIRE_QUANTIZED_MIN")
    threshold = WIRE_QUANTIZED_MIN_BYTES
    if env is not None:
        try:
            threshold = int(env)
        except ValueError:
            import warnings

            warnings.warn(
                f"mpi_tpu: MPI_TPU_WIRE_QUANTIZED_MIN={env!r} is not "
                f"an integer byte count — compressed wire allreduce "
                f"stays OFF", RuntimeWarning, stacklevel=2)
    return threshold is not None and nbytes >= threshold


def _qc():
    from . import native as _native

    return _native.quantcore()


def _ptr(arr: np.ndarray):
    return ctypes.c_void_p(arr.ctypes.data)


def _check_f32_blocked(x: np.ndarray, block: int,
                       what: str) -> np.ndarray:
    """The kernels reinterpret raw memory: a float64 buffer or a
    strided view would silently produce garbage on the native path
    that the numpy fallback rejects — validate identically on both."""
    x = np.asarray(x)
    if x.dtype != np.float32:
        raise MpiError(
            f"mpi_tpu: {what} operates on float32 vectors; got "
            f"{x.dtype} (cast explicitly — the quantization grid "
            f"depends on the dtype)")
    if x.size % block:
        raise MpiError(
            f"mpi_tpu: {what} needs size ({x.size}) divisible by "
            f"block ({block}); pad the vector")
    return np.ascontiguousarray(x.reshape(-1))


def quantize_np(x: np.ndarray, block: int = _BLOCK
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Blockwise symmetric int8 quantization of a float32 vector whose
    size divides ``block`` — native kernel when available, numpy
    otherwise; bit-identical semantics to
    ``parallel.quantized.quantize_blocks``."""
    x = _check_f32_blocked(x, block, "quantize_np")
    lib = _qc()
    if lib is not None:
        q = np.empty(x.size, np.int8)
        s = np.empty(x.size // block, np.float32)
        lib.qc_quantize(_ptr(x), x.size, block, _ptr(q), _ptr(s))
        return q, s
    xb = x.reshape(-1, block)
    amax = np.max(np.abs(xb), axis=1)
    finite = np.isfinite(amax)
    safe = np.where(finite & (amax > 0), amax, np.float32(127.0))
    s = (safe / 127.0).astype(np.float32)
    q = np.clip(np.round(xb / s[:, None]), -127, 127)
    q = np.where(np.isnan(q), 0, q).astype(np.int8).reshape(-1)
    s = np.where(finite, s, np.float32(np.nan)).astype(np.float32)
    return q, s


def _check_qs(q: np.ndarray, s: np.ndarray, block: int,
              what: str) -> Tuple[np.ndarray, np.ndarray]:
    q = np.ascontiguousarray(np.asarray(q).reshape(-1))
    s = np.ascontiguousarray(np.asarray(s).reshape(-1))
    if q.dtype != np.int8 or s.dtype != np.float32 \
            or q.size != s.size * block:
        raise MpiError(
            f"mpi_tpu: {what} expects (int8[{block}*nblk], "
            f"float32[nblk]); got {q.dtype}[{q.size}], "
            f"{s.dtype}[{s.size}]")
    return q, s


def _accumulate(q: np.ndarray, s: np.ndarray, acc: np.ndarray,
                block: int) -> None:
    q, s = _check_qs(q, s, block, "accumulate")
    lib = _qc()
    if lib is not None:
        lib.qc_accumulate(_ptr(q), _ptr(s), q.size, block, _ptr(acc))
        return
    acc += (q.astype(np.float32).reshape(-1, block)
            * s[:, None]).reshape(-1)


def dequantize_np(q: np.ndarray, s: np.ndarray, block: int = _BLOCK
                  ) -> np.ndarray:
    """Inverse of :func:`quantize_np` (float32)."""
    q, s = _check_qs(q, s, block, "dequantize_np")
    lib = _qc()
    if lib is not None:
        out = np.empty(q.size, np.float32)
        lib.qc_dequantize(_ptr(q), _ptr(s), q.size, block, _ptr(out))
        return out
    return (q.astype(np.float32).reshape(-1, block)
            * s[:, None]).reshape(-1)


def allreduce_compressed_wire(impl: Interface, data: Any,
                              block: int = _BLOCK) -> np.ndarray:
    """Sum-allreduce with int8-compressed wire traffic over any socket
    driver (module doc). Float payloads only; accumulation in float32;
    returns ``data``'s shape and dtype. LOSSY — two int8 roundings."""
    arr = np.asarray(data)
    if not np.issubdtype(arr.dtype, np.floating):
        raise MpiError(
            f"mpi_tpu: allreduce_compressed_wire compresses float "
            f"payloads; got {arr.dtype} (integer reductions must be "
            f"exact — use allreduce)")
    n, me = impl.size(), impl.rank()
    flat = arr.reshape(-1).astype(np.float32, copy=False)
    if n == 1:
        return flat.astype(arr.dtype, copy=True).reshape(arr.shape)
    m = flat.size
    chunk = -(-m // (n * block)) * block       # elements per rank shard
    padded = np.zeros(n * chunk, np.float32)
    padded[:m] = flat
    # The two rotation phases use 4n tags (phase 1: tag..tag+2n-1,
    # phase 2: tag+2n..tag+4n-1) — claim the TRUE span, not one 4096
    # block, so world sizes > 1024 cannot spill into the next
    # collective's tag block (ADVICE.md round 5).
    tag = reserve_tag_blocks(impl, 4 * n)

    # Phase 1: quantize all n shards once, rotate each to its owner,
    # dequant-accumulate IN RANK ORDER (round order is timing-fixed,
    # but the sum must fold 0..n-1 deterministically — stage arrivals
    # and fold after the exchanges).
    q, s = quantize_np(padded, block)
    sblk = chunk // block
    q_shards = q.reshape(n, chunk)
    s_shards = s.reshape(n, sblk)
    arrived: dict = {me: (q_shards[me], s_shards[me])}
    for r in range(1, n):
        dst, src = (me + r) % n, (me - r) % n
        got_q = _exchange(impl, np.ascontiguousarray(q_shards[dst]),
                          dst, src, tag + 2 * r)
        got_s = _exchange(impl, np.ascontiguousarray(s_shards[dst]),
                          dst, src, tag + 2 * r + 1)
        arrived[src] = (np.asarray(got_q), np.asarray(got_s))
    acc = np.zeros(chunk, np.float32)
    for r in range(n):                          # canonical rank order
        _accumulate(*arrived[r], acc, block)

    # Phase 2: one more quantization, rotate the reduced shard to
    # every rank, dequantize into place.
    q2, s2 = quantize_np(acc, block)
    out = np.empty(n * chunk, np.float32)
    out[me * chunk:(me + 1) * chunk] = dequantize_np(q2, s2, block)
    base2 = tag + 2 * n
    for r in range(1, n):
        dst, src = (me + r) % n, (me - r) % n
        got_q = _exchange(impl, q2, dst, src, base2 + 2 * r)
        got_s = _exchange(impl, s2, dst, src, base2 + 2 * r + 1)
        out[src * chunk:(src + 1) * chunk] = dequantize_np(
            np.asarray(got_q), np.asarray(got_s), block)
    return out[:m].astype(arr.dtype, copy=False).reshape(arr.shape)

"""One-sided communication — RMA windows (MPI_Win, active target).

The last MPI pillar the facade lacked: every rank exposes a local array
(the *window*), and peers read/write it with :meth:`Window.put` /
:meth:`Window.get` / :meth:`Window.accumulate` without the target
issuing a matching call. Synchronization is **active-target fence
epochs** (MPI_Win_fence): RMA calls issued between two fences are
queued locally and complete collectively at the closing fence —
exactly MPI's "all operations complete at the fence" contract.
(Passive-target lock/unlock is intentionally not provided; fences are
the model the collective transports realize faithfully.)

tpu-first realization: a fence is two ``alltoall`` rounds over the
window's communicator — one delivering queued put/accumulate records,
one exchanging get requests and their replies — so on the xla driver
the data movement rides the compiled sub-mesh engines (single XLA
programs over ICI), on hybrid the hierarchical engines, and on TCP the
generic algorithms. The target side participates only through the
collective fence, never per-operation: true one-sided semantics without
per-driver progress threads or new wire frames.

Determinism where MPI leaves behavior undefined: overlapping puts (and
accumulate ordering) apply in ``(source rank, issue order)``, and
within an epoch all puts/accumulates land before any get is served —
so every rank computes the same window contents from the same ops.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Tuple

import numpy as np

from .api import MpiError
from .collectives_generic import OpLike, combine
from .comm import Comm

__all__ = ["Window", "win_create"]


class RmaHandle:
    """Result handle for :meth:`Window.get`: the data is defined once
    the closing :meth:`Window.fence` has run."""

    __slots__ = ("_value", "_ready")

    def __init__(self) -> None:
        self._value: Optional[np.ndarray] = None
        self._ready = False

    @property
    def array(self) -> np.ndarray:
        if not self._ready:
            raise MpiError(
                "mpi_tpu: RMA get result read before the closing fence()")
        return self._value


class Window:
    """An exposed local array plus the epoch machinery (MPI_Win).

    Create collectively with :func:`win_create`. ``win.local`` is this
    rank's exposed array — direct loads/stores to it are legal between
    fences (they are 'local accesses' in MPI terms); remote access goes
    through put/get/accumulate and completes at the closing fence.
    """

    def __init__(self, comm: Comm, local: np.ndarray):
        self._comm = comm
        self._local = local
        self._lock = threading.Lock()
        self._puts: List[Tuple[int, int, np.ndarray, Optional[OpLike]]] = []
        self._gets: List[Tuple[int, int, int, RmaHandle]] = []
        self._epoch = 0
        # Collective sanity: every member must expose the same dtype (and
        # learn each peer's extent so origin-side bounds checks work).
        metas = comm.allgather((int(local.shape[0]), str(local.dtype)))
        self._extents = [int(m[0]) for m in metas]
        dtypes = {m[1] for m in metas}
        if len(dtypes) != 1:
            raise MpiError(
                f"mpi_tpu: window dtype must agree across ranks, got "
                f"{sorted(dtypes)}")

    # -- identity ----------------------------------------------------------

    @property
    def comm(self) -> Comm:
        return self._comm

    @property
    def local(self) -> np.ndarray:
        """This rank's exposed window memory."""
        return self._local

    @property
    def epoch(self) -> int:
        """Completed fence count (0 = window just created)."""
        return self._epoch

    def _check_span(self, target: int, offset: int, count: int) -> None:
        self._comm._check_peer(target)
        extent = self._extents[target]
        if offset < 0 or count < 0 or offset + count > extent:
            raise MpiError(
                f"mpi_tpu: RMA span [{offset}, {offset + count}) outside "
                f"rank {target}'s window extent {extent}")

    # -- origin-side operations (queued until the closing fence) -----------

    def _queue(self, data: Any, target: int, offset: int,
               op: Optional[OpLike]) -> None:
        """Shared put/accumulate path: snapshot the payload ONCE (the
        caller may reuse its buffer immediately), validate the span,
        queue the record for the closing fence."""
        arr = np.array(data, dtype=self._local.dtype, copy=True).reshape(-1)
        self._check_span(target, offset, arr.shape[0])
        with self._lock:
            self._puts.append((target, int(offset), arr, op))

    def put(self, data: Any, target: int, offset: int = 0) -> None:
        """Write ``data`` into ``target``'s window at ``offset``
        (MPI_Put). Completes at the closing fence; the origin buffer is
        snapshotted now, so the caller may reuse it immediately."""
        self._queue(data, target, offset, None)

    def accumulate(self, data: Any, target: int, offset: int = 0,
                   op: OpLike = "sum") -> None:
        """Combine ``data`` into ``target``'s window (MPI_Accumulate):
        ``window[span] = op(window[span], data)``, applied in
        (source rank, issue order) at the closing fence. Callable ops
        must be picklable (module-level functions, not lambdas): the
        record crosses process boundaries on the tcp/hybrid drivers, and
        the check runs here — identically on every driver — so a bad op
        fails at issue time instead of desyncing the collective fence."""
        from .collectives_generic import check_op

        check_op(op)
        if callable(op):
            import pickle

            try:
                pickle.dumps(op)
            except Exception as exc:
                raise MpiError(
                    "mpi_tpu: callable accumulate ops must be picklable "
                    "(a module-level function, not a lambda/closure) — "
                    f"they cross process boundaries at fence(): {exc}"
                ) from exc
        self._queue(data, target, offset, op)

    def get(self, target: int, offset: int = 0,
            count: Optional[int] = None) -> RmaHandle:
        """Read ``count`` elements from ``target``'s window at
        ``offset`` (MPI_Get). Returns a handle whose ``.array`` is
        defined after the closing fence; it observes the epoch's
        puts/accumulates (deterministic ordering, see module doc)."""
        self._comm._check_peer(target)
        if count is None:
            count = self._extents[target] - offset
        self._check_span(target, offset, count)
        handle = RmaHandle()
        with self._lock:
            self._gets.append((target, int(offset), int(count), handle))
        return handle

    # -- synchronization ---------------------------------------------------

    def fence(self) -> None:
        """Close the current epoch (MPI_Win_fence): collective; applies
        every member's queued puts/accumulates to the targets' windows
        in (source rank, issue order), then serves every queued get from
        the updated windows. On return all RMA issued before the fence
        is complete everywhere."""
        n = self._comm.size()
        with self._lock:
            puts, self._puts = self._puts, []
            gets, self._gets = self._gets, []

        # Round 1: deliver put/accumulate records to their targets.
        outbound: List[List[Tuple]] = [[] for _ in range(n)]
        for target, offset, arr, op in puts:
            outbound[target].append((offset, arr, op))
        inbound = self._comm.alltoall(outbound)
        for records in inbound:  # source-rank order; issue order within
            for offset, arr, op in records:
                span = slice(offset, offset + arr.shape[0])
                if op is None:
                    self._local[span] = arr
                else:
                    self._local[span] = np.asarray(
                        combine(self._local[span], arr, op),
                        dtype=self._local.dtype)

        # Round 2: exchange get requests, then serve them from the
        # post-put window state.
        requests: List[List[Tuple[int, int]]] = [[] for _ in range(n)]
        for target, offset, count, _ in gets:
            requests[target].append((offset, count))
        incoming = self._comm.alltoall(requests)
        replies = [
            [self._local[o:o + c].copy() for (o, c) in reqs]
            for reqs in incoming
        ]
        answered = self._comm.alltoall(replies)
        cursor = [0] * n
        for target, _, _, handle in gets:  # issue order per target
            handle._value = np.asarray(answered[target][cursor[target]])
            handle._ready = True
            cursor[target] += 1
        self._epoch += 1

    def free(self) -> None:
        """Release the window (MPI_Win_free). Collective by convention;
        pending (un-fenced) RMA is an error."""
        with self._lock:
            if self._puts or self._gets:
                raise MpiError(
                    "mpi_tpu: Window.free() with un-fenced RMA pending")


def win_create(comm: Comm, local: Any) -> Window:
    """Create an RMA window over ``comm`` (MPI_Win_create): collective;
    ``local`` is this rank's exposed 1-D array (its dtype must agree
    across ranks; extents may differ). Mutating ``local`` directly is
    legal between fences; remote access completes at fences."""
    arr = np.asarray(local)
    if arr.ndim != 1:
        raise MpiError(
            f"mpi_tpu: window memory must be a 1-D array, got shape "
            f"{arr.shape}")
    return Window(comm, arr)

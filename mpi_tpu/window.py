"""One-sided communication — RMA windows (MPI_Win).

The last MPI pillar the facade lacked: every rank exposes a local array
(the *window*), and peers read/write it with :meth:`Window.put` /
:meth:`Window.get` / :meth:`Window.accumulate` /
:meth:`Window.get_accumulate` / :meth:`Window.fetch_and_op` without the
target issuing a matching call. Synchronization is **active-target fence
epochs** (MPI_Win_fence): RMA calls issued between two fences are
queued locally and complete collectively at the closing fence —
exactly MPI's "all operations complete at the fence" contract.

**Passive target** (MPI_Win_lock/unlock) is available on windows
created with ``win_create(..., locks=True)``: each rank then runs a
window *service thread* that serves lock requests and applies RMA
operations the moment they arrive — true one-sided progress without
the target calling anything (the software progress engine every
socket-transport MPI uses). Inside a lock epoch, put/get/accumulate/
get_accumulate/fetch_and_op execute synchronously at the target (so
``flush`` is a completed-by-construction ordering point), exclusive
locks serialize read-modify-write sequences, and shared locks admit
concurrent readers; waiters queue strictly FIFO (consecutive shared
requests grant as a batch). The same service engine carries **PSCW**
(:meth:`Window.post` / :meth:`Window.start` /
:meth:`Window.complete` / :meth:`Window.wait` — generalized active
target), completing all three MPI RMA synchronization modes.
``locks`` defaults to False because the
service thread polls the driver's ANY_SOURCE probe — the same
latency/CPU tradeoff MPI implementations expose inverted via the
``no_locks`` info hint.

tpu-first realization: a fence is two ``alltoall`` rounds over the
window's communicator — one delivering queued put/accumulate records,
one exchanging get requests and their replies — so on the xla driver
the data movement rides the compiled sub-mesh engines (single XLA
programs over ICI), on hybrid the hierarchical engines, and on TCP the
generic algorithms. The target side participates only through the
collective fence, never per-operation: true one-sided semantics without
per-driver progress threads or new wire frames.

Determinism where MPI leaves behavior undefined: overlapping puts (and
accumulate ordering) apply in ``(source rank, issue order)``, and
within an epoch all puts/accumulates land before any get is served —
so every rank computes the same window contents from the same ops.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .api import MpiError
from .collectives_generic import OpLike, combine
from .comm import Comm, _WIN_SLICE, _win_tag_base

__all__ = ["Window", "win_create"]

_win_alloc_lock = threading.Lock()


def _svc_tags(comm: Comm, wid: int) -> Tuple[int, int]:
    """(request, reply) tags for window ``wid``'s passive-target
    service, carved from the reserved window slice directly below the
    neighborhood slice (comm.py tag layout; the hybrid driver's
    cross-host remap shares the same _win_tag_base)."""
    if wid * 2 + 1 >= _WIN_SLICE:
        raise MpiError(
            f"mpi_tpu: window id space exhausted (wid={wid})")
    base = _win_tag_base() + wid * 2
    return base, base + 1


def _alloc_wid(comm: Comm) -> int:
    """Collectively-consistent window id: window creation is collective
    on ``comm``, so a per-``(rank, context)`` counter on the driver
    yields the same id on every member (keyed like the comm's
    _CollState — by rank too, because thread-per-rank drivers share one
    impl object)."""
    key = (comm._impl.rank(), comm.context)
    with _win_alloc_lock:
        seqs = comm._impl.__dict__.setdefault("_win_seqs", {})
        wid = seqs.get(key, 0)
        seqs[key] = wid + 1
    return wid


class RmaHandle:
    """Result handle for :meth:`Window.get` / :meth:`Window.get_accumulate`
    / :meth:`Window.fetch_and_op`: the data (fetched span or pre-value)
    is defined once the closing :meth:`Window.fence` has run."""

    __slots__ = ("_value", "_ready")

    def __init__(self) -> None:
        self._value: Optional[np.ndarray] = None
        self._ready = False

    @property
    def ready(self) -> bool:
        """True once the result is defined — immediately for passive
        (lock-epoch) operations, at the closing fence otherwise."""
        return self._ready

    @property
    def array(self) -> np.ndarray:
        if not self._ready:
            raise MpiError(
                "mpi_tpu: RMA result read before the closing fence()")
        return self._value


class Window:
    """An exposed local array plus the epoch machinery (MPI_Win).

    Create collectively with :func:`win_create`. ``win.local`` is this
    rank's exposed array — direct loads/stores to it are legal between
    fences (they are 'local accesses' in MPI terms); remote access goes
    through put/get/accumulate and completes at the closing fence.
    """

    def __init__(self, comm: Comm, local: np.ndarray,
                 locks: bool = False):
        self._comm = comm
        self._local = local
        self._lock = threading.Lock()
        # Passive target (set up at the end of __init__, after the
        # collective metadata round, so every member's service thread
        # starts only on fully-constructed windows).
        self._locks_enabled = bool(locks)
        self._held: Dict[int, str] = {}      # target -> "excl"/"shared"
        self._access: set = set()            # PSCW access epoch targets
        self._access_open = False
        self._exposure_open = False
        self._origin_lock = threading.Lock()  # serialize my requests
        self._svc_thread: Optional[threading.Thread] = None
        self._svc_stop = False
        # (target, offset, payload, op, fetch_handle): op None = put;
        # a non-None handle makes it a get_accumulate (pre-value read).
        self._puts: List[Tuple[int, int, np.ndarray, Optional[OpLike],
                               Optional[RmaHandle]]] = []
        self._gets: List[Tuple[int, int, int, RmaHandle]] = []
        self._epoch = 0
        # Collective sanity: every member must expose the same dtype (and
        # learn each peer's extent so origin-side bounds checks work).
        # One collective allgather carries extent/dtype metadata — and,
        # on drivers whose members share ONE address space (the xla
        # driver's thread-per-rank model; MPI's "unified" memory model),
        # the actual array object: the object-payload allgather passes
        # references in-process, so shared_query() hands out the peer's
        # real buffer, zero-copy (MPI_Win_allocate_shared semantics).
        # Cross-process drivers ship None instead of copying the window
        # contents over the wire.
        self._freed = False
        shared_ok = bool(getattr(comm._impl, "SUPPORTS_SHARED_WINDOWS",
                                 False))
        metas = comm.allgather((int(local.shape[0]), str(local.dtype),
                                local if shared_ok else None))
        self._extents = [int(m[0]) for m in metas]
        dtypes = {m[1] for m in metas}
        if len(dtypes) != 1:
            raise MpiError(
                f"mpi_tpu: window dtype must agree across ranks, got "
                f"{sorted(dtypes)}")
        entries = [m[2] for m in metas]
        # The zero-copy contract is verified by IDENTITY: if the driver
        # delivered a copy of our own buffer (or anything else), shared
        # windows are silently broken — disable them instead.
        if shared_ok and entries[comm.rank()] is local \
                and all(isinstance(e, np.ndarray) for e in entries):
            self._shared: Optional[List[np.ndarray]] = entries
        else:
            self._shared = None
        if self._locks_enabled:
            wid = _alloc_wid(comm)
            self._svc_tag, self._reply_tag = _svc_tags(comm, wid)
            # Lock state lives on (and is only touched by) the service
            # thread — no extra synchronization needed.
            self._lk_excl: Optional[int] = None
            self._lk_shared: set = set()
            self._lk_waiters: deque = deque()
            # PSCW state. _exposure/_completed are shared between the
            # service thread (post notifications, completes) and
            # wait(); _posted_from between the service thread and
            # start() — both under _pscw_cv.
            self._pscw_cv = threading.Condition()
            self._exposure: set = set()
            self._completed: set = set()
            self._posted_from: set = set()
            self._svc_thread = threading.Thread(
                target=self._serve, daemon=True,
                name=f"mpi-win-svc-{wid}")
            self._svc_thread.start()

    # -- identity ----------------------------------------------------------

    @property
    def comm(self) -> Comm:
        return self._comm

    @property
    def local(self) -> np.ndarray:
        """This rank's exposed window memory."""
        return self._local

    @property
    def epoch(self) -> int:
        """Completed fence count (0 = window just created)."""
        return self._epoch

    def _check_span(self, target: int, offset: int, count: int) -> None:
        self._comm._check_peer(target)
        extent = self._extents[target]
        if offset < 0 or count < 0 or offset + count > extent:
            raise MpiError(
                f"mpi_tpu: RMA span [{offset}, {offset + count}) outside "
                f"rank {target}'s window extent {extent}")

    # -- origin-side operations (queued until the closing fence) -----------

    @staticmethod
    def _check_acc_op(op: OpLike) -> None:
        """Shared accumulate/get_accumulate op validation. Callable ops
        must additionally be picklable (module-level functions, not
        lambdas): the record crosses process boundaries on the
        tcp/hybrid drivers, and the check runs at issue time —
        identically on every driver — so a bad op fails here instead of
        desyncing the collective fence."""
        from .collectives_generic import check_op

        check_op(op)
        if callable(op):
            import pickle

            try:
                pickle.dumps(op)
            except Exception as exc:
                raise MpiError(
                    "mpi_tpu: callable accumulate ops must be picklable "
                    "(a module-level function, not a lambda/closure) — "
                    f"they cross process boundaries at fence(): {exc}"
                ) from exc

    def _queue(self, data: Any, target: int, offset: int,
               op: Optional[OpLike],
               handle: Optional[RmaHandle] = None) -> None:
        """Shared put/accumulate/get_accumulate path: snapshot the
        payload ONCE (the caller may reuse its buffer immediately),
        validate the span, queue the record for the closing fence."""
        arr = np.array(data, dtype=self._local.dtype, copy=True).reshape(-1)
        self._check_span(target, offset, arr.shape[0])
        if target in self._held or target in self._access:
            # Passive or PSCW epoch: execute synchronously at the
            # target's service thread (completed on return; flush is
            # trivially satisfied). The pre-value rides the reply for
            # get_accumulate/fetch_and_op.
            pre = self._svc_request(
                target, ("apply", int(offset), arr, op,
                         handle is not None))
            if handle is not None:
                handle._value = np.asarray(pre)
                handle._ready = True
            return
        with self._lock:
            self._puts.append((target, int(offset), arr, op, handle))

    def put(self, data: Any, target: int, offset: int = 0) -> None:
        """Write ``data`` into ``target``'s window at ``offset``
        (MPI_Put). Completes at the closing fence; the origin buffer is
        snapshotted now, so the caller may reuse it immediately."""
        self._queue(data, target, offset, None)

    def accumulate(self, data: Any, target: int, offset: int = 0,
                   op: OpLike = "sum") -> None:
        """Combine ``data`` into ``target``'s window (MPI_Accumulate):
        ``window[span] = op(window[span], data)``, applied in
        (source rank, issue order) at the closing fence."""
        self._check_acc_op(op)
        self._queue(data, target, offset, op)

    def get_accumulate(self, data: Any, target: int, offset: int = 0,
                       op: OpLike = "sum") -> RmaHandle:
        """Atomically read-then-combine (MPI_Get_accumulate): at the
        closing fence the target span's PRE-combination value is
        captured for this origin, then ``op(window[span], data)`` is
        applied — all in the deterministic (source rank, issue order),
        so e.g. a fetch-and-add counter hands every rank a distinct
        ticket. Returns a handle whose ``.array`` (the pre-value) is
        defined after the fence."""
        self._check_acc_op(op)
        handle = RmaHandle()
        self._queue(data, target, offset, op, handle)
        return handle

    def fetch_and_op(self, value: Any, target: int, offset: int = 0,
                     op: OpLike = "sum") -> RmaHandle:
        """Single-element :meth:`get_accumulate` (MPI_Fetch_and_op) —
        the distributed-counter primitive; ``handle.array[0]`` is this
        rank's pre-value after the fence."""
        arr = np.asarray(value, dtype=self._local.dtype)
        if arr.size != 1:
            raise MpiError(
                f"mpi_tpu: fetch_and_op takes a single element, got "
                f"shape {arr.shape}; use get_accumulate for spans")
        return self.get_accumulate(arr.reshape(1), target, offset, op=op)

    def get(self, target: int, offset: int = 0,
            count: Optional[int] = None) -> RmaHandle:
        """Read ``count`` elements from ``target``'s window at
        ``offset`` (MPI_Get). Returns a handle whose ``.array`` is
        defined after the closing fence; it observes the epoch's
        puts/accumulates (deterministic ordering, see module doc)."""
        self._comm._check_peer(target)
        if count is None:
            count = self._extents[target] - offset
        self._check_span(target, offset, count)
        handle = RmaHandle()
        if target in self._held or target in self._access:
            handle._value = np.asarray(
                self._svc_request(target, ("get", int(offset),
                                           int(count))))
            handle._ready = True
            return handle
        with self._lock:
            self._gets.append((target, int(offset), int(count), handle))
        return handle

    # -- passive target (lock/unlock epochs) -------------------------------

    def _require_locks(self, what: str) -> None:
        if not self._locks_enabled:
            raise MpiError(
                f"mpi_tpu: Window.{what} needs a passive-target window "
                f"— create it with win_create(comm, local, locks=True) "
                f"(runs a per-rank service thread; see module doc)")

    def _svc_request(self, target: int, msg: Tuple) -> Any:
        """One request/reply round-trip to ``target``'s service thread.
        Serialized per window (the reply tag is a single slot); a lock
        request may legitimately block here until the current holder
        unlocks."""
        with self._origin_lock:
            self._comm.send(msg, target, self._svc_tag)
            kind, payload = self._comm.receive(target, self._reply_tag)
        if kind == "err":
            raise MpiError(payload)
        return payload

    def lock(self, target: int, exclusive: bool = True) -> None:
        """Open a passive-target epoch at ``target`` (MPI_Win_lock):
        blocks until the lock is granted. ``exclusive=False`` is
        MPI_LOCK_SHARED (concurrent holders allowed); waiters are
        served strictly FIFO with consecutive shared requests granted
        as a batch. RMA issued before :meth:`unlock` executes
        synchronously at the target."""
        self._require_locks("lock")
        self._comm._check_peer(target)
        if target in self._held:
            raise MpiError(
                f"mpi_tpu: Window.lock({target}) while already holding "
                f"a lock on that rank")
        with self._lock:
            if self._puts or self._gets:
                raise MpiError(
                    "mpi_tpu: Window.lock with un-fenced active-target "
                    "RMA pending — close the fence epoch first")
        self._svc_request(target, ("lock", bool(exclusive)))
        self._held[target] = "excl" if exclusive else "shared"

    def unlock(self, target: int) -> None:
        """Close the passive epoch at ``target`` (MPI_Win_unlock). All
        RMA issued under the lock is already complete (operations are
        synchronous); this releases the lock and wakes FIFO waiters."""
        self._require_locks("unlock")
        if target not in self._held:
            raise MpiError(
                f"mpi_tpu: Window.unlock({target}) without holding a "
                f"lock on that rank")
        self._svc_request(target, ("unlock",))
        del self._held[target]

    def lock_all(self) -> None:
        """Shared lock on every rank (MPI_Win_lock_all), in rank order."""
        self._require_locks("lock_all")
        for r in range(self._comm.size()):
            self.lock(r, exclusive=False)

    def unlock_all(self) -> None:
        """Release every lock taken by :meth:`lock_all`."""
        self._require_locks("unlock_all")
        for r in range(self._comm.size()):
            self.unlock(r)

    def flush(self, target: int) -> None:
        """Complete all my RMA at ``target`` (MPI_Win_flush). Passive
        operations execute synchronously here, so this is an ordering
        ping: it round-trips the service thread, proving every earlier
        operation from this origin has been applied."""
        self._require_locks("flush")
        if target not in self._held:
            raise MpiError(
                f"mpi_tpu: Window.flush({target}) outside a lock epoch")
        self._svc_request(target, ("flush",))

    def flush_all(self) -> None:
        """:meth:`flush` every locked target (MPI_Win_flush_all)."""
        self._require_locks("flush_all")
        for r in sorted(self._held):
            self.flush(r)

    # -- PSCW (generalized active target: MPI_Win_post/start/complete/wait)

    def _pscw_group(self, group, what: str) -> set:
        ranks = {int(r) for r in group}
        for r in ranks:
            self._comm._check_peer(r)
        return ranks  # empty is a valid MPI no-op epoch

    @staticmethod
    def _pscw_timeout() -> Optional[float]:
        """PSCW epochs block indefinitely by default (matching the
        lock path); MPI_TPU_PSCW_TIMEOUT_S sets a debug deadline so a
        mismatched post/start pairing fails loudly instead of hanging
        a test run."""
        import os

        t = float(os.environ.get("MPI_TPU_PSCW_TIMEOUT_S", "0"))
        return t if t > 0 else None

    def post(self, group) -> None:
        """Expose this window to the origin ``group`` (MPI_Win_post,
        nonblocking): their PSCW epoch ops may arrive from now on;
        :meth:`wait` closes the epoch (an empty group is a valid
        no-op epoch). Needs ``locks=True`` (the same service engine
        applies the ops)."""
        self._require_locks("post")
        ranks = self._pscw_group(group, "post")
        with self._pscw_cv:
            if self._exposure_open:
                raise MpiError(
                    "mpi_tpu: Window.post while an exposure epoch is "
                    "already open (wait() first)")
            self._exposure_open = True
            self._exposure = ranks
            self._completed = set()
        me = self._comm.rank()
        for r in sorted(ranks):
            # One-way notification; the origin's start() collects it.
            self._comm.send(("posted", me), r, self._svc_tag)

    def start(self, group) -> None:
        """Open an access epoch to the target ``group`` (MPI_Win_start):
        blocks until every target has :meth:`post`-ed; RMA to those
        targets then executes synchronously until :meth:`complete`.
        An empty group opens a valid no-op epoch."""
        self._require_locks("start")
        ranks = self._pscw_group(group, "start")
        if self._access_open:
            raise MpiError(
                "mpi_tpu: Window.start while an access epoch is "
                "already open (complete() first)")
        with self._pscw_cv:
            if not self._pscw_cv.wait_for(
                    lambda: ranks <= self._posted_from,
                    timeout=self._pscw_timeout()):
                raise MpiError(
                    f"mpi_tpu: Window.start timed out waiting for "
                    f"post() from {sorted(ranks - self._posted_from)}")
            self._posted_from -= ranks
        self._access_open = True
        self._access = ranks

    def complete(self) -> None:
        """Close the access epoch (MPI_Win_complete): every op issued
        since :meth:`start` is already applied (synchronous service);
        notify each target so its :meth:`wait` can return."""
        self._require_locks("complete")
        if not self._access_open:
            raise MpiError(
                "mpi_tpu: Window.complete without an open access epoch")
        for r in sorted(self._access):
            self._svc_request(r, ("complete",))
        self._access_open = False
        self._access = set()

    def wait(self) -> None:
        """Close the exposure epoch (MPI_Win_wait): blocks until every
        origin in the posted group has :meth:`complete`-d."""
        self._require_locks("wait")
        with self._pscw_cv:
            if not self._exposure_open:
                raise MpiError(
                    "mpi_tpu: Window.wait without an open exposure "
                    "epoch (post() first)")
            if not self._pscw_cv.wait_for(
                    lambda: self._completed >= self._exposure,
                    timeout=self._pscw_timeout()):
                raise MpiError(
                    f"mpi_tpu: Window.wait timed out; missing "
                    f"complete() from "
                    f"{sorted(self._exposure - self._completed)}")
            self._exposure_open = False
            self._exposure = set()
            self._completed = set()

    # -- passive-target service thread (the software progress engine) ------

    def _serve(self) -> None:
        """Probe-serve loop. Hand-rolled rather than ``receive_any``:
        during teardown a finalized peer's closed sockets make that
        peer's PROBE raise, which would kill the thread mid-sweep and
        leave live peers (and free()) hanging — here a raising source
        just counts as nothing-to-serve. Shutdown is flag-based
        (free() sets ``_svc_stop``), not a message: a message to an
        already-dead thread would rendezvous forever."""
        import sys as _sys
        import time as _time

        me = self._comm.rank()
        n = self._comm.size()
        probe_errs: set = set()
        while not self._svc_stop:
            got = None
            for off in range(n):
                src = (me + off) % n
                try:
                    if self._comm.iprobe(src, self._svc_tag):
                        got = (src,
                               self._comm.receive(src, self._svc_tag))
                        break
                except (ConnectionError, OSError):
                    # A finalized/dead peer (normal teardown order:
                    # some ranks finalize while others still hold
                    # their windows) — nothing to serve from it.
                    continue
                except Exception as exc:  # noqa: BLE001 — anything
                    # else is a real defect (driver without iprobe,
                    # transport bug); logged ONCE per (source, type)
                    # so it is never silently indistinguishable from
                    # nothing-to-serve while origins hang.
                    sig = (src, type(exc).__name__)
                    if sig not in probe_errs:
                        probe_errs.add(sig)
                        print(f"mpi_tpu: window service (rank {me}): "
                              f"probe of rank {src} raised "
                              f"{type(exc).__name__}: {exc} — treating "
                              f"that source as unavailable",
                              file=_sys.stderr)
                    continue
            if got is None:
                _time.sleep(0.0005)
                continue
            src, msg = got
            try:
                reply = self._svc_handle(src, msg)
            except Exception as exc:  # noqa: BLE001 — a user accumulate
                # op may raise ANYTHING; the thread dying silently would
                # turn that error into a permanent distributed hang
                # (origin blocked in _svc_request). Reply the error.
                reply = ("err", f"{type(exc).__name__}: {exc}")
            if reply is not None:  # None = deferred (queued lock waiter)
                try:
                    self._comm.send(reply, src, self._reply_tag)
                except Exception:  # noqa: BLE001 — origin died mid-
                    # request (erroneous program); keep serving others.
                    pass

    def _svc_handle(self, src: int, msg: Tuple) -> Optional[Tuple]:
        kind = msg[0]
        if kind == "lock":
            exclusive = msg[1]
            if self._lk_waiters or self._lk_conflicts(exclusive):
                self._lk_waiters.append((src, exclusive))
                return None  # granted later, strictly FIFO
            self._lk_grant(src, exclusive)
            return ("ok", None)
        if kind == "unlock":
            if self._lk_excl == src:
                self._lk_excl = None
            elif src in self._lk_shared:
                self._lk_shared.discard(src)
            else:
                return ("err",
                        f"mpi_tpu: rank {src} unlocked a window lock "
                        f"it does not hold")
            # Wake the FIFO waiters that can now hold (grant state is
            # already applied inside _lk_take_grantable), then answer
            # the unlocker; the order is unobservable to it.
            for waiter, _excl in self._lk_take_grantable():
                self._comm.send(("ok", None), waiter, self._reply_tag)
            return ("ok", None)
        if kind == "posted":
            with self._pscw_cv:
                self._posted_from.add(msg[1])
                self._pscw_cv.notify_all()
            return None  # one-way: start() is the consumer
        if kind == "complete":
            with self._pscw_cv:
                if src not in self._exposure:
                    return ("err",
                            f"mpi_tpu: complete() from rank {src} "
                            f"outside the posted group")
                self._completed.add(src)
                self._pscw_cv.notify_all()
            return ("ok", None)
        if kind == "flush":
            self._lk_check_holder(src, "flush")
            return ("ok", None)
        if kind == "apply":
            _, offset, arr, op, fetch = msg
            self._lk_check_holder(src, "RMA")
            span = slice(offset, offset + arr.shape[0])
            with self._lock:
                pre = self._local[span].copy() if fetch else None
                if op is None:
                    self._local[span] = arr
                else:
                    self._local[span] = np.asarray(
                        combine(self._local[span], arr, op),
                        dtype=self._local.dtype)
            return ("ok", pre)
        if kind == "get":
            _, offset, count = msg
            self._lk_check_holder(src, "RMA")
            with self._lock:
                return ("ok", self._local[offset:offset + count].copy())
        return ("err", f"mpi_tpu: unknown window service request "
                       f"{kind!r}")

    def _lk_conflicts(self, exclusive: bool) -> bool:
        if exclusive:
            return self._lk_excl is not None or bool(self._lk_shared)
        return self._lk_excl is not None

    def _lk_grant(self, src: int, exclusive: bool) -> None:
        if exclusive:
            self._lk_excl = src
        else:
            self._lk_shared.add(src)

    def _lk_take_grantable(self) -> List[Tuple[int, bool]]:
        """Pop the FIFO prefix of waiters that can hold simultaneously:
        one exclusive, or a run of consecutive shared requests."""
        out: List[Tuple[int, bool]] = []
        while self._lk_waiters:
            src, excl = self._lk_waiters[0]
            if self._lk_conflicts(excl) or (excl and out):
                break
            self._lk_waiters.popleft()
            self._lk_grant(src, excl)  # mark held NOW so conflicts see it
            out.append((src, excl))
        # _lk_grant already applied; callers must not re-grant.
        return out

    def _lk_check_holder(self, src: int, what: str) -> None:
        if self._lk_excl == src or src in self._lk_shared:
            return
        with self._pscw_cv:
            if src in self._exposure:  # PSCW access epoch
                return
        raise MpiError(
            f"mpi_tpu: passive {what} from rank {src} outside a "
            f"lock or PSCW epoch (MPI_Win_lock or post/start first)")

    # -- synchronization ---------------------------------------------------

    def fence(self) -> None:
        """Close the current epoch (MPI_Win_fence): collective; applies
        every member's queued puts/accumulates to the targets' windows
        in (source rank, issue order), then serves every queued get from
        the updated windows. On return all RMA issued before the fence
        is complete everywhere."""
        if self._held:
            raise MpiError(
                f"mpi_tpu: Window.fence while holding passive locks on "
                f"ranks {sorted(self._held)} — unlock first (MPI forbids "
                f"mixing synchronization modes in one epoch)")
        if self._access_open or self._exposure_open:
            raise MpiError(
                "mpi_tpu: Window.fence inside a PSCW epoch — "
                "complete()/wait() first (MPI forbids mixing "
                "synchronization modes in one epoch)")
        n = self._comm.size()
        with self._lock:
            puts, self._puts = self._puts, []
            gets, self._gets = self._gets, []

        # Round 1: deliver put/accumulate records to their targets (the
        # fetch flag asks the target to capture the span's PRE-value for
        # this origin before combining — MPI_Get_accumulate).
        outbound: List[List[Tuple]] = [[] for _ in range(n)]
        fetch_handles: List[List[RmaHandle]] = [[] for _ in range(n)]
        for target, offset, arr, op, handle in puts:
            outbound[target].append((offset, arr, op, handle is not None))
            if handle is not None:
                fetch_handles[target].append(handle)
        inbound = self._comm.alltoall(outbound)
        pres: List[List[np.ndarray]] = [[] for _ in range(n)]
        for source, records in enumerate(inbound):
            # source-rank order; issue order within — the deterministic
            # application order the module doc promises.
            for offset, arr, op, fetch in records:
                span = slice(offset, offset + arr.shape[0])
                if fetch:
                    pres[source].append(self._local[span].copy())
                if op is None:
                    self._local[span] = arr
                else:
                    self._local[span] = np.asarray(
                        combine(self._local[span], arr, op),
                        dtype=self._local.dtype)

        # Round 2: exchange get requests; serve them (and return the
        # captured pre-values) from the post-put window state.
        requests: List[List[Tuple[int, int]]] = [[] for _ in range(n)]
        for target, offset, count, _ in gets:
            requests[target].append((offset, count))
        incoming = self._comm.alltoall(requests)
        replies = [
            (pres[peer], [self._local[o:o + c].copy()
                          for (o, c) in reqs])
            for peer, reqs in enumerate(incoming)
        ]
        answered = self._comm.alltoall(replies)
        for target, (pre_vals, _) in enumerate(answered):
            for handle, pre in zip(fetch_handles[target], pre_vals):
                handle._value = np.asarray(pre)
                handle._ready = True
        cursor = [0] * n
        for target, _, _, handle in gets:  # issue order per target
            handle._value = np.asarray(answered[target][1][cursor[target]])
            handle._ready = True
            cursor[target] += 1
        self._epoch += 1

    def shared_query(self, rank: int) -> np.ndarray:
        """Direct reference to ``rank``'s window memory
        (MPI_Win_shared_query) — only when the communicator's members
        share one address space (the xla driver's thread-per-rank
        model). Loads/stores through it are immediately visible to the
        owner with no fence (MPI's unified-memory model within a
        process); the caller owns the data-race discipline, exactly as
        with MPI shared windows. Raises on cross-process drivers."""
        self._comm._check_peer(rank)
        if self._freed:
            raise MpiError("mpi_tpu: shared_query() on a freed window")
        if self._shared is None:
            raise MpiError(
                "mpi_tpu: window memory is not in a shared address space "
                "on this driver; use put/get/accumulate with fences")
        return self._shared[rank]

    def free(self) -> None:
        """Release the window (MPI_Win_free). Collective by convention;
        pending (un-fenced) RMA or a held passive lock is an error."""
        if self._held:
            raise MpiError(
                f"mpi_tpu: Window.free() while holding passive locks "
                f"on ranks {sorted(self._held)}")
        if self._access_open:
            raise MpiError(
                f"mpi_tpu: Window.free() inside a PSCW access epoch "
                f"to ranks {sorted(self._access)} (complete() first)")
        if self._exposure_open:
            raise MpiError(
                "mpi_tpu: Window.free() inside a PSCW exposure epoch "
                "(wait() first)")
        with self._lock:
            if self._puts or self._gets:
                raise MpiError(
                    "mpi_tpu: Window.free() with un-fenced RMA pending")
            # Release peers' buffers and invalidate shared_query: a
            # freed window must not pin (or keep handing out) memory.
            self._shared = None
            self._freed = True
        if self._svc_thread is not None:
            # Stop my service thread (each rank stops its own; free is
            # collective, so peers do the same). Flag-based: the serve
            # loop polls it every sweep, so the join is bounded. A peer
            # request racing the shutdown is erroneous per MPI and may
            # hang that peer.
            self._svc_stop = True
            self._svc_thread.join(timeout=30.0)
            self._svc_thread = None


def win_create(comm: Comm, local: Any, locks: bool = False) -> Window:
    """Create an RMA window over ``comm`` (MPI_Win_create): collective;
    ``local`` is this rank's exposed 1-D array (its dtype must agree
    across ranks; extents may differ). Mutating ``local`` directly is
    legal between fences; remote access completes at fences.
    ``locks=True`` (collective — every member must agree) additionally
    enables passive-target lock/unlock epochs, running a per-rank
    service thread (see the module doc for the tradeoff)."""
    arr = np.asarray(local)
    if arr.ndim != 1:
        raise MpiError(
            f"mpi_tpu: window memory must be a 1-D array, got shape "
            f"{arr.shape}")
    return Window(comm, arr, locks=locks)

"""Generic collectives built on the backend's blocking ``send``/``receive``.

The reference has **no** collectives — ``AllReduce`` is a commented-out stub
(mpi.go:130) with an unused ``isAllReducer`` capability probe (mpi.go:69-71).
This module supplies the missing layer for *any* backend that only speaks
point-to-point (notably the TCP driver, the CPU parity oracle). The XLA
driver overrides these with native ``jax.lax`` collectives over ICI; these
implementations define the **canonical deterministic reduction order** that
the XLA driver's ``deterministic=True`` path reproduces, which is what makes
"bitwise-identical results to the TCP backend" (BASELINE.json north_star)
achievable for floating-point reductions.

Canonical reduction order (used by ``reduce``/``allreduce`` here and by
``parallel.collectives.tree_allreduce``): binomial-tree recursive halving.
In round ``k`` (distance ``d = 2**k``), every rank ``r`` with
``r % (2d) == 0`` and ``r + d < n`` combines ``acc[r] = op(acc[r],
acc[r+d])`` — lower-rank partial always on the left. This is well defined
for any ``n`` and fixes the float summation tree exactly.

Requirements inherited from MPI semantics: all ranks must invoke the same
collectives in the same order (tags for collective traffic are drawn from a
reserved tag space ``>= COLL_TAG_BASE`` using a per-backend sequence number,
so collective traffic can never collide with user point-to-point tags).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, List, Optional, Union

import numpy as np

from .api import Interface, MpiError
from .api import exchange as _sendrecv  # shared concurrent-exchange engine

__all__ = [
    "COLL_TAG_BASE",
    "OpLike",
    "combine",
    "tree_combine",
    "reduce",
    "allreduce",
    "ring_allreduce",
    "ring_reduce_scatter",
    "ring_combine",
    "canonical_combine",
    "ring_eligible",
    "RING_MIN_BYTES",
    "reduce_scatter",
    "bcast",
    "gather",
    "scatter",
    "allgather",
    "alltoall",
    "scan",
    "exscan",
    "barrier",
]

# A reduction op: a built-in name or an associative user callable
# (the MPI_Op_create analogue; see check_op).
OpLike = Union[str, Callable[[Any, Any], Any]]

# User tags live below this; collective rounds allocate from above it.
COLL_TAG_BASE = 1 << 48
_TAGS_PER_COLLECTIVE = 4096


def _next_tag_base(impl: Interface) -> int:
    """Per-backend monotone sequence → disjoint tag block per collective.

    Correct because collectives must be invoked in the same order on every
    rank (standard MPI requirement, documented in module doc)."""
    return reserve_tag_blocks(impl, _TAGS_PER_COLLECTIVE)


def reserve_tag_blocks(impl: Interface, tags_needed: int) -> int:
    """Claim enough CONSECUTIVE collective tag blocks to cover
    ``tags_needed`` tags; returns the base of the first block.

    The standard block is ``_TAGS_PER_COLLECTIVE`` (4096) tags; a
    collective whose schedule uses more (``allreduce_compressed_wire``
    needs 4n tags, which overflows at world sizes > 1024 — ADVICE.md
    round 5) must claim its true span or its tail tags would spill
    into the NEXT collective's block and cross-collective traffic
    could collide with no diagnostic. Consistent across ranks because
    every rank invokes collectives in the same order with the same
    world size."""
    nblocks = max(1, -(-int(tags_needed) // _TAGS_PER_COLLECTIVE))
    lock = getattr(impl, "_coll_lock", None)
    if lock is None:
        lock = threading.Lock()
        setattr(impl, "_coll_lock", lock)
    with lock:
        seq = getattr(impl, "_coll_seq", 0)
        setattr(impl, "_coll_seq", seq + nblocks)
    return COLL_TAG_BASE + seq * _TAGS_PER_COLLECTIVE


_OPS: dict[str, Callable[[np.ndarray, np.ndarray], np.ndarray]] = {
    "sum": np.add,
    "prod": np.multiply,
    "min": np.minimum,
    "max": np.maximum,
}


def check_op(op) -> None:
    """Validate a reduction op — a built-in name or a user callable (the
    MPI_Op_create analogue: ``op(a, b) -> combined``, associative; the
    canonical binomial tree preserves rank order, so non-commutative
    ops are well-defined). Called on *every* rank before any
    communication so a bad op fails everywhere instead of deadlocking
    the ranks whose partner errored."""
    if callable(op):
        return
    if op not in _OPS:
        raise MpiError(f"mpi_tpu: unknown reduction op {op!r}; "
                       f"expected one of {sorted(_OPS)} or a callable "
                       f"op(a, b) -> combined")


def combine(a: Any, b: Any, op) -> Any:
    """``op(a, b)`` elementwise, preserving dtype. Shared by every backend
    so the arithmetic (not just the order) is identical across drivers.
    ``op`` may be a built-in name or a user callable (check_op)."""
    check_op(op)
    fn = op if callable(op) else _OPS[op]
    an, bn = np.asarray(a), np.asarray(b)
    if an.shape != bn.shape:
        raise MpiError(
            f"mpi_tpu: reduction shape mismatch across ranks: {an.shape} vs {bn.shape}")
    from .utils import trace

    if trace.enabled():
        # The reduce step of every generic collective funnels through
        # here — the per-stage counter the observe layer reads next to
        # the wire spans (element count, not wall time: combine is
        # memory-bound and the span machinery would dominate small
        # payloads).
        trace.count("coll.reduce.steps")
        trace.count("coll.reduce.elems", float(an.size))
    out = np.asarray(fn(an, bn))
    if out.shape != an.shape:
        raise MpiError(
            f"mpi_tpu: user reduction op changed the payload shape: "
            f"{an.shape} -> {out.shape}")
    if np.isscalar(a) or an.ndim == 0:
        return out[()] if isinstance(out, np.ndarray) else out
    return out




def tree_combine(slots: List[Any], op: OpLike) -> np.ndarray:
    """Fold ``slots`` (rank-ordered payloads) in the canonical binomial-tree
    order — the single host-side definition of the combination order that
    ``reduce`` executes over the wire, ``parallel.collectives.
    tree_allreduce`` replays with ppermute rounds, and the XLA driver's
    oversubscribed path uses directly. One source of truth → bitwise
    parity across all drivers."""
    check_op(op)
    acc = [np.asarray(s) for s in slots]
    n, d = len(acc), 1
    while d < n:
        for r in range(0, n, 2 * d):
            if r + d < n:
                acc[r] = np.asarray(combine(acc[r], acc[r + d], op))
        d *= 2
    return acc[0]


def reduce(impl: Interface, data: Any, root: int = 0, op: OpLike = "sum",
           _tag_base: Optional[int] = None) -> Optional[Any]:
    """Binomial-tree reduce in the canonical order; result on ``root``.

    The tree is rooted at rank 0; a final point-to-point hop moves the
    result to ``root`` when ``root != 0`` so the combination order is
    *independent of root* (simplifies bitwise-parity guarantees)."""
    check_op(op)
    tag = _next_tag_base(impl) if _tag_base is None else _tag_base
    me, n = impl.rank(), impl.size()
    acc = np.asarray(data)
    d = 1
    rnd = 0
    while d < n:
        if me % (2 * d) == 0:
            if me + d < n:
                other = impl.receive(me + d, tag + rnd)
                acc = combine(acc, other, op)
        elif me % (2 * d) == d:
            impl.send(acc, me - d, tag + rnd)
            acc = None  # handed off
        d *= 2
        rnd += 1
    if root != 0:
        if me == 0:
            impl.send(acc, root, tag + rnd)
            acc = None
        elif me == root:
            acc = impl.receive(0, tag + rnd)
    return acc if me == root else None


def bcast(impl: Interface, data: Any, root: int = 0,
          _tag_base: Optional[int] = None) -> Any:
    """Binomial-tree broadcast (inverse shape of ``reduce``'s tree)."""
    tag = _next_tag_base(impl) if _tag_base is None else _tag_base
    me, n = impl.rank(), impl.size()
    rel = (me - root) % n  # relabel so the tree is rooted at `root`
    # Highest power of two <= n-1 determines the first round distance.
    d = 1
    while d < n:
        d *= 2
    d //= 2
    rnd = 0
    payload = data if me == root else None
    have = me == root
    while d >= 1:
        if rel % (2 * d) == 0 and have:
            if rel + d < n:
                impl.send(payload, (root + rel + d) % n, tag + rnd)
        elif rel % (2 * d) == d and not have:
            payload = impl.receive((root + rel - d) % n, tag + rnd)
            have = True
        d //= 2
        rnd += 1
    return payload


# Large numeric payloads CAN switch from the binomial tree to the
# bandwidth-optimal ring (the size-based algorithm selection
# MPICH/OpenMPI apply) — but the switch is a measured gate, and on
# every fabric this layer has been measured on, the ring loses:
#
# * Pre round 5 (copy-heavy wire path) the crossover measured 32 MiB
#   (ring 2.23x tree at 64 MiB / 8 ranks) and that was the default.
# * Round 5's zero-copy send path (encode_parts + writev) cut
#   per-byte cost ~2.5x, which helps the tree's full-buffer hops
#   most: remeasured on loopback TCP, tree wins at EVERY size
#   (4 ranks: 64 MiB ring 950 ms vs tree 455 ms; 256 MiB ring
#   39.8 s vs tree 7.4 s; 8 ranks the same shape). On a shared-core
#   loopback fabric the ring's 2(n-1) strictly sequential rounds —
#   each a full rendezvous — dominate its per-byte advantage.
#
# So the default is NEVER (same never-lose discipline as
# QUANTIZED_MIN_BYTES). On a real multi-host fabric, where each ring
# hop rides its own link concurrently and bandwidth genuinely
# dominates, set MPI_TPU_RING_MIN_BYTES to the measured crossover;
# every driver reads the same constant, so the cross-driver bitwise
# contract (identical algorithm per payload) holds at any setting.
# NB: every rank must see the SAME value (export it uniformly —
# launchers propagate the environment; a per-host divergence would
# have ranks disagree on the algorithm and hang), and a malformed
# value is a LOUD no-op: silently ignoring it would defeat the
# explicit opt-in.
_RING_MIN_NEVER = 1 << 62
try:
    RING_MIN_BYTES = int(os.environ.get("MPI_TPU_RING_MIN_BYTES",
                                        str(_RING_MIN_NEVER)))
except ValueError:
    import warnings

    warnings.warn(
        f"mpi_tpu: MPI_TPU_RING_MIN_BYTES="
        f"{os.environ['MPI_TPU_RING_MIN_BYTES']!r} is not an integer "
        f"byte count — ring dispatch stays OFF",
        RuntimeWarning, stacklevel=1)
    RING_MIN_BYTES = _RING_MIN_NEVER


def _ring_dtype_ok(dtype) -> bool:
    """Real/integer/bool dtypes including bfloat16 — the flagship's
    gradient dtype registers with numpy as kind 'V' (ml_dtypes), which
    a bare kind check would silently exclude from the ring path."""
    d = np.dtype(dtype)
    if d.kind in "fiub":
        return True
    try:
        import ml_dtypes

        return d == np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover - ml_dtypes ships with jax
        return False


def ring_eligible(nbytes: int, dtype, n: int, op) -> bool:
    """The ONE algorithm-selection rule, shared verbatim by this
    module, the XLA driver's deterministic path
    (``parallel.collectives.allreduce``), and the oversubscribed
    host-side fold — all three must switch together or the cross-driver
    bitwise contract breaks at the threshold. User-callable ops stay on
    the tree (its rank-ordered fold is the documented contract for
    non-commutative ops); complex dtypes stay on the tree (min/max are
    undefined and uniformity is simpler than op-dependent rules)."""
    return (isinstance(op, str) and n >= 3
            and _ring_dtype_ok(dtype)
            and nbytes >= RING_MIN_BYTES)


def allreduce(impl: Interface, data: Any, op: OpLike = "sum") -> Any:
    """Allreduce in a canonical, size-selected combination order.

    Small/non-numeric payloads: reduce-to-0 + bcast in the binomial
    tree order. Large numeric arrays (``ring_eligible``): ring
    reduce-scatter + allgather (:func:`ring_allreduce`). Both orders
    are deterministic, and the XLA driver's deterministic path applies
    the identical switch — the bitwise contract holds at every size."""
    check_op(op)
    n = impl.size()
    if isinstance(op, str):
        arr = np.asarray(data)
        if ring_eligible(arr.nbytes, arr.dtype, n, op):
            out = ring_allreduce(impl, arr, op=op)
            return out[()] if arr.ndim == 0 else out
    tag = _next_tag_base(impl)
    result = reduce(impl, data, root=0, op=op, _tag_base=tag)
    return bcast(impl, result, root=0, _tag_base=tag + 64)


def ring_allreduce(impl: Interface, data: Any, op: OpLike = "sum") -> Any:
    """Bandwidth-optimal allreduce: ring reduce-scatter + ring
    allgather over blocking point-to-point (the algorithm the
    reference's dead ``AllReduce`` stub, mpi.go:130, never got).

    Each rank moves ``2(n-1)/n`` of the buffer instead of the tree's
    ``~2·log2(n)`` full-buffer hops — for 8 ranks that is ~3.4x less
    wire traffic. **Canonical ring order**: block ``b`` folds rank
    contributions left-to-right in ring order starting at rank ``b``:
    ``((x_b ⊕ x_{b+1}) ⊕ ...) ⊕ x_{b+n-1 mod n}`` — deterministic (the
    order is topology-fixed, never timing-dependent), but a *different*
    canonical order than the binomial tree, which is why the algorithm
    switch must be identical in every driver (``ring_eligible``).
    ``parallel.collectives.ring_allreduce`` replays exactly this order
    with ``ppermute`` hops; :func:`ring_combine` replays it on the host
    for the oversubscribed XLA path."""
    check_op(op)
    arr = np.asarray(data)
    n, me = impl.size(), impl.rank()
    if n == 1:
        return arr.copy()
    tag = _next_tag_base(impl)
    right, left = (me + 1) % n, (me - 1) % n
    flat = arr.reshape(-1)
    m = -(-flat.size // n)  # ceil: pad so n equal blocks tile the buffer
    padded = np.zeros(n * m, dtype=arr.dtype)
    padded[:flat.size] = flat
    carry = _ring_fold_phase(impl, padded.reshape(n, m), op, tag)
    # Allgather: rotate the completed blocks the rest of the way round.
    out = np.empty((n, m), dtype=carry.dtype)
    out[(me + 1) % n] = carry
    cur = carry
    for u in range(n - 1):
        cur = np.asarray(
            _sendrecv(impl, cur, right, left, tag + (n - 1) + u))
        out[(me - u) % n] = cur
    return out.reshape(-1)[:flat.size].reshape(arr.shape)


def _ring_fold_phase(impl: Interface, blocks: np.ndarray, op: OpLike,
                     tag: int) -> np.ndarray:
    """The n-1 fold rounds of the canonical ring order — THE single
    wire-side definition (ring_allreduce and ring_reduce_scatter both
    run it; ring_combine and the parallel module replay it). After
    round t this rank holds the running partial for block
    ``(me - t - 1) % n``, covering ranks b..me in ring order; the
    return value is the completed block ``(me + 1) % n``. Uses tags
    ``tag .. tag + n - 2``."""
    n, me = impl.size(), impl.rank()
    right, left = (me + 1) % n, (me - 1) % n
    carry = blocks[me].copy()
    for t in range(n - 1):
        incoming = np.asarray(
            _sendrecv(impl, carry, right, left, tag + t))
        carry = np.asarray(combine(incoming, blocks[(me - t - 1) % n],
                                   op))
    return carry


def canonical_combine(slots: List[Any], op: OpLike) -> np.ndarray:
    """Host-side fold of every rank's payload in the SAME canonical
    order the wire algorithms use — ring for ``ring_eligible``
    payloads, binomial tree otherwise. The oversubscribed XLA driver
    folds with this so it stays bitwise-equal to the socket drivers on
    both sides of the algorithm threshold."""
    first = np.asarray(slots[0])
    if ring_eligible(first.nbytes, first.dtype, len(slots), op):
        return ring_combine(slots, op)
    return tree_combine(slots, op)


def ring_combine(slots: List[Any], op: OpLike) -> np.ndarray:
    """Host-side replay of :func:`ring_allreduce`'s canonical order
    (block ``b`` folds ranks ``b, b+1, ...`` left-to-right), for code
    that holds every rank's payload in one process (the XLA driver's
    oversubscribed leader). Bitwise-identical to the wire version."""
    check_op(op)
    arrs = [np.asarray(s) for s in slots]
    n = len(arrs)
    if n == 1:
        return arrs[0].copy()
    shape, size = arrs[0].shape, arrs[0].size
    m = -(-size // n)
    padded = np.zeros((n, n * m), dtype=arrs[0].dtype)
    for r, a in enumerate(arrs):
        padded[r, :size] = a.reshape(-1)
    blocks = padded.reshape(n, n, m)  # [rank, block, elem]
    out = np.empty((n, m), dtype=arrs[0].dtype)
    for b in range(n):
        acc = blocks[b, b]
        for k in range(1, n):
            acc = np.asarray(combine(acc, blocks[(b + k) % n, b], op))
        out[b] = acc
    return out.reshape(-1)[:size].reshape(shape)


def reduce_scatter(impl: Interface, data: Any, op: OpLike = "sum") -> Any:
    """Reduce across ranks, then keep this rank's block: the payload's
    leading axis splits into ``size`` equal blocks and rank ``i`` returns
    reduced block ``i``. Combination order is the canonical
    size-selected order (:func:`allreduce`): binomial tree
    reduce-then-slice below the ring threshold; above it, the DIRECT
    ring reduce-scatter phase — bitwise-identical to ring-allreduce-
    then-slice (the block split and per-block fold coincide exactly
    when the leading axis divides) while moving half the data."""
    check_op(op)
    arr = np.asarray(data)
    n = impl.size()
    if arr.ndim < 1 or arr.shape[0] % n:
        raise MpiError(
            f"mpi_tpu: reduce_scatter payload leading axis "
            f"{arr.shape if arr.ndim else 'scalar'} must divide into {n} "
            f"equal blocks")
    if ring_eligible(arr.nbytes, arr.dtype, n, op):
        return ring_reduce_scatter(impl, arr, op=op)
    total = np.asarray(allreduce(impl, data, op=op))
    m = arr.shape[0] // n
    me = impl.rank()
    return total[me * m:(me + 1) * m]


def ring_reduce_scatter(impl: Interface, data: Any,
                        op: OpLike = "sum") -> Any:
    """The reduce-scatter PHASE of :func:`ring_allreduce` plus one
    block rotation: after n-1 fold rounds rank ``r`` holds reduced
    block ``(r+1) % n`` in the canonical ring order; one neighbor hop
    lands block ``r`` at rank ``r``. Moves ``n/(n-1) ≈ 1`` buffer per
    rank versus the full ring allreduce's 2 — and stays bitwise-equal
    to allreduce-then-slice because the fold order per block is the
    same (``parallel.collectives.ring_reduce_scatter`` replays this
    with ppermute for the XLA deterministic path)."""
    check_op(op)
    arr = np.asarray(data)
    n, me = impl.size(), impl.rank()
    if arr.ndim < 1 or arr.shape[0] % n:
        raise MpiError(
            f"mpi_tpu: reduce_scatter payload leading axis "
            f"{arr.shape if arr.ndim else 'scalar'} must divide into {n} "
            f"equal blocks")
    if n == 1:
        return arr.copy()
    k = arr.shape[0] // n
    tag = _next_tag_base(impl)
    right, left = (me + 1) % n, (me - 1) % n
    # leading-axis blocks == flat blocks (divisible, so no padding)
    carry = _ring_fold_phase(impl, arr.reshape(n, -1), op, tag)
    # Rotation: my left neighbor finished block me; swap along the ring.
    mine = np.asarray(_sendrecv(impl, carry, right, left, tag + n - 1))
    return mine.reshape((k,) + arr.shape[1:])


def gather(impl: Interface, data: Any, root: int = 0) -> Optional[List[Any]]:
    """Direct gather: each rank sends to root; root returns rank-ordered list."""
    tag = _next_tag_base(impl)
    me, n = impl.rank(), impl.size()
    if me == root:
        out: List[Any] = [None] * n
        out[me] = data
        # Receives run concurrently so sender blocking order can't deadlock
        # (each non-root send rendezvouses with its own receive).
        threads = []
        errs: List[Optional[BaseException]] = [None] * n
        for src in range(n):
            if src == root:
                continue

            def _recv(src: int = src) -> None:
                try:
                    out[src] = impl.receive(src, tag + src)
                except BaseException as exc:  # noqa: BLE001
                    errs[src] = exc

            t = threading.Thread(target=_recv, name=f"mpi-gather-{src}", daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        for e in errs:
            if e is not None:
                raise e
        return out
    impl.send(data, root, tag + me)
    return None


def scatter(impl: Interface, data: Optional[List[Any]], root: int = 0) -> Any:
    """Root distributes ``data[i]`` to rank ``i``; returns this rank's item."""
    tag = _next_tag_base(impl)
    me, n = impl.rank(), impl.size()
    if me == root:
        if data is None or len(data) != n:
            raise MpiError(
                f"mpi_tpu: scatter root needs a list of exactly {n} payloads")
        threads = []
        errs: List[Optional[BaseException]] = [None] * n
        for dst in range(n):
            if dst == root:
                continue

            def _send(dst: int = dst) -> None:
                try:
                    impl.send(data[dst], dst, tag + dst)
                except BaseException as exc:  # noqa: BLE001
                    errs[dst] = exc

            t = threading.Thread(target=_send, name=f"mpi-scatter-{dst}", daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        for e in errs:
            if e is not None:
                raise e
        return data[root]
    return impl.receive(root, tag + me)


def allgather(impl: Interface, data: Any) -> List[Any]:
    """Ring allgather: n-1 rotations; each rank forwards the chunk it
    received last round. Rank-ordered result everywhere."""
    tag = _next_tag_base(impl)
    me, n = impl.rank(), impl.size()
    out: List[Any] = [None] * n
    out[me] = data
    right, left = (me + 1) % n, (me - 1) % n
    current = data
    for step in range(n - 1):
        current = _sendrecv(impl, current, right, left, tag + step)
        out[(me - step - 1) % n] = current
    return out


def alltoall(impl: Interface, data: List[Any]) -> List[Any]:
    """Personalized all-to-all via n-1 rotation rounds of pairwise
    exchanges (deadlock-free: send/receive run concurrently per round)."""
    me, n = impl.rank(), impl.size()
    if len(data) != n:
        raise MpiError(f"mpi_tpu: alltoall needs exactly {n} payloads, got {len(data)}")
    tag = _next_tag_base(impl)
    out: List[Any] = [None] * n
    out[me] = data[me]
    for offset in range(1, n):
        dst = (me + offset) % n
        src = (me - offset) % n
        out[src] = _sendrecv(impl, data[dst], dst, src, tag + offset)
    return out


def _allgather_best(impl: Interface, data: Any) -> List[Any]:
    """The backend's native allgather when it has one (the xla driver's
    is a single compiled XLA program), else the generic ring."""
    native = getattr(impl, "allgather", None)
    return native(data) if native is not None else allgather(impl, data)


def _prefix_fold(items: List[Any], count: int, op: OpLike) -> Any:
    """Left fold of ``items[:count]`` in rank order — the combination
    order shared by scan/exscan here and ``parallel.collectives.
    prefix_reduce`` (bitwise contract across backends)."""
    acc = items[0]
    for i in range(1, count):
        acc = combine(acc, items[i], op)
    return acc


def scan(impl: Interface, data: Any, op: OpLike = "sum") -> Any:
    """Inclusive prefix reduction: rank ``r`` returns
    ``data_0 op data_1 op ... op data_r``, combined in rank order
    (deterministic — the order IS the contract, like the binomial tree
    for allreduce). Built on allgather so a backend's compiled gather
    carries the communication; the per-rank prefix combine is local.
    MPI_Scan parity — absent from the reference like every collective
    (mpi.go:130)."""
    check_op(op)
    items = _allgather_best(impl, data)
    return _prefix_fold(items, impl.rank() + 1, op)


def exscan(impl: Interface, data: Any, op: OpLike = "sum") -> Optional[Any]:
    """Exclusive prefix reduction: rank ``r`` returns the combination of
    ranks ``0..r-1``; rank 0 returns ``None`` (MPI_Exscan leaves its
    buffer undefined there — None makes that explicit)."""
    check_op(op)
    me = impl.rank()
    items = _allgather_best(impl, data)
    return None if me == 0 else _prefix_fold(items, me, op)


def barrier(impl: Interface) -> None:
    """Dissemination barrier: ceil(log2 n) rounds of token exchanges."""
    tag = _next_tag_base(impl)
    me, n = impl.rank(), impl.size()
    d = 1
    rnd = 0
    while d < n:
        dst = (me + d) % n
        src = (me - d) % n
        _sendrecv(impl, b"", dst, src, tag + rnd)
        d *= 2
        rnd += 1

"""Backend-selecting program entry — ``mpi_tpu.run_main``.

The reference selects a backend by calling ``mpi.Register`` in code
(mpi.go:61-67); everything else (addresses, timeouts) arrives via flags so
the same binary runs anywhere. ``run_main`` extends that flag surface with
backend selection so one program runs unmodified on either driver —
the "examples run unmodified on a v4-8" requirement (BASELINE.json):

    python prog.py --mpi-addr :6000 --mpi-alladdr :6000,:6001   # TCP ranks
    python prog.py --mpi-backend xla --mpi-ranks 8              # mesh ranks
    python prog.py --mpi-backend hybrid --mpi-ranks 4 \
        --mpi-addr :6000 --mpi-alladdr :6000,:6001   # 2 hosts x 4 locals

``--mpi-backend`` (env ``MPI_TPU_BACKEND``): ``tcp`` (default), ``xla``,
or ``hybrid`` (xla ranks within this host + TCP between hosts; the TCP
flags address the *host*, ``--mpi-ranks`` counts this host's local ranks).
``--mpi-ranks`` (env ``MPI_TPU_RANKS``): rank count for the xla/hybrid
drivers (default: every visible device).
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional, Sequence

from . import api

__all__ = ["run_main", "selected_backend"]

FLAG_BACKEND = "mpi-backend"
FLAG_RANKS = "mpi-ranks"
ENV_BACKEND = "MPI_TPU_BACKEND"
ENV_RANKS = "MPI_TPU_RANKS"


def _scan_runner_flags(argv: Optional[Sequence[str]]) -> dict:
    from .flags import scan_argv

    return scan_argv({FLAG_BACKEND, FLAG_RANKS}, argv)


def selected_backend(argv: Optional[Sequence[str]] = None) -> str:
    found = _scan_runner_flags(argv)
    choice = (found.get(FLAG_BACKEND) or os.environ.get(ENV_BACKEND)
              or "tcp").lower()
    if choice not in ("tcp", "xla", "hybrid"):
        raise api.MpiError(
            f"mpi_tpu: unknown --{FLAG_BACKEND} {choice!r} "
            f"(tcp, xla, or hybrid)")
    return choice


def run_main(main: Callable[[], Any],
             argv: Optional[Sequence[str]] = None) -> List[Any]:
    """Run a reference-style program under the configured backend.

    ``tcp``: this process is one rank; ``main()`` runs once (the launcher
    started N processes). ``xla``: this process hosts *all* ranks;
    ``main()`` runs SPMD, one thread per mesh device. Returns the per-rank
    results (single-element list under tcp)."""
    backend = selected_backend(argv)

    def ranks() -> Optional[int]:
        ranks_s = (_scan_runner_flags(argv).get(FLAG_RANKS)
                   or os.environ.get(ENV_RANKS))
        if not ranks_s:
            return None
        try:
            return int(ranks_s)
        except ValueError as exc:
            raise api.MpiError(
                f"mpi_tpu: --{FLAG_RANKS} must be an integer, "
                f"got {ranks_s!r}") from exc

    if backend in ("xla", "hybrid") \
            and os.environ.get("JAX_PLATFORMS"):
        # Honor the documented env-var spelling RELIABLY: with a TPU
        # PJRT plugin pre-registered at interpreter startup, the env
        # var alone loses and the first device query walks to the
        # plugin (observed: a dead device tunnel hangs the program in
        # C before main() runs). Pinning via jax.config before any
        # device query is the working form. The full comma list passes
        # through (JAX's own fallback semantics), and when cpu leads
        # it, --mpi-ranks sizes the virtual device mesh too — so
        # `JAX_PLATFORMS=cpu prog --mpi-backend xla --mpi-ranks 8`
        # works with no XLA_FLAGS incantation.
        from .utils.platform import force_platform

        platforms = os.environ["JAX_PLATFORMS"]
        n = ranks()
        cpu_n = n if platforms.split(",")[0] == "cpu" else None
        if not force_platform(platforms, num_cpu_devices=cpu_n):
            import warnings

            warnings.warn(
                "mpi_tpu: JAX_PLATFORMS is set but a JAX backend is "
                "already initialized — the platform pin was skipped "
                "and device queries will use the live backend",
                RuntimeWarning, stacklevel=2)
    if backend == "xla":
        from .backends.xla import run_spmd

        return run_spmd(main, n=ranks())
    if backend == "hybrid":
        from .backends.hybrid import HybridNetwork, run_spmd_hybrid

        # TCP identity (addr/alladdr/timeout/password) comes from the
        # -mpi-* flags, exactly like the tcp driver (flags.go:44-50).
        return run_spmd_hybrid(main, HybridNetwork(local_ranks=ranks()))
    return [main()]

"""TCP all-to-all driver — CPU fallback and bitwise-parity oracle.

Rebuild of the reference's ``Network`` backend (/root/reference/network.go),
preserving its observable semantics:

  * leaderless deterministic rank assignment: sort the address list, rank =
    index of own address; duplicate or missing addresses are errors
    (network.go:94-118);
  * eager all-to-all bootstrap at init: every pair of ranks holds two TCP
    connections, one dialed by each side; ``dial`` carries my sends and the
    peer's acks, ``listen`` carries the peer's sends and my acks
    (network.go:122-159, 499-506);
  * password-validated handshake with accept timeout on the listen side and
    a 100 ms dial-retry loop until the init timeout on the dial side
    (network.go:198-263, 294-351);
  * tag-demultiplexed **rendezvous** messaging: ``send`` blocks until the
    matching ``receive`` has accepted the payload, signalled by an ack
    frame written back on the same connection the data arrived on
    (network.go:518-625);
  * in-process self-send rendezvous with first-arrival-creates semantics
    (network.go:371-446);
  * config resolution: explicit constructor args win over ``-mpi-*`` flags,
    with a single-node ``":5000"`` default (network.go:55-58, 69-90).

Deliberate fixes of the reference's latent defects (SURVEY.md §2), none of
which change the documented contracts:

  * self-send releases its tag on completion (the reference leaks it —
    ``Send`` registers the tag at network.go:534 but the local path returns
    without ``Delete`` at network.go:546-547, so tag reuse panics);
  * one write lock per socket — the reference lets concurrent sends to the
    same destination interleave gob streams on one conn (network.go:562);
  * persistent per-connection reader threads replace per-call reader
    goroutines, removing the reference's race where a reader spawned by
    ``Receive(tagB)`` decodes a message for not-yet-registered ``tagA`` and
    panics (network.go:587, 614);
  * early-arriving messages for unregistered tags are buffered; rendezvous
    is unaffected because the ack is only written when a ``receive``
    actually dequeues.

Wire protocol (replaces gob; all integers little-endian)::

    frame      := kind:u8  tag:i64  length:u32  payload[length]
    kind       := 0 DATA   payload = mpi_tpu.utils.serialize codec bytes
                  1 ACK    payload = empty (length 0)
                  2 HELLO  payload = utf-8 password; tag field carries the
                           sender's claimed rank id (initialMessage
                           {Password, Id}, network.go:198-201)
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import flags as flagmod
from ..api import MpiError
from ..utils.serialize import decode as codec_decode
from ..utils.serialize import encode as codec_encode
from ..utils.serialize import encode_parts as codec_encode_parts
from .rendezvous import ReceiveCancelled, Rendezvous, TagManager
from .shm import ShmConn

__all__ = ["TcpNetwork", "InitError", "ReceiveCancelled"]

KIND_DATA = 0
KIND_ACK = 1
KIND_HELLO = 2

_FRAME_HDR = struct.Struct("<BqI")
_DIAL_RETRY_INTERVAL = 0.1  # network.go:298 — 100 ms poll

# The reference's NetProto accepts any `net` package protocol
# (network.go:26). Supported here: TCP (the default, "tcp4" an alias,
# "tcp6" for IPv6 with Go's "[::1]:5000" bracket addresses),
# unix-domain stream sockets (addresses = filesystem paths), and "shm"
# — same-host shared-memory rings via the native engine
# (backends/shm.py, native/shmcore.cpp; addresses = opaque ids).
# Anything else raises at init instead of being silently ignored.
_SUPPORTED_PROTOS = ("tcp", "tcp4", "tcp6", "unix", "shm")


class InitError(MpiError):
    """Bootstrap failure; aggregates per-peer handshake errors
    (network.go:185-195, 281-291)."""


def _split_hostport(addr: str) -> Tuple[str, int]:
    host, sep, port = addr.rpartition(":")
    if not sep:
        raise MpiError(f"mpi_tpu: address {addr!r} missing :port")
    # Go's net.SplitHostPort bracket syntax for IPv6 literals:
    # "[::1]:5000" -> host "::1".
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    return host, int(port)


def _view_cptr(view):
    """(c_void_p, keepalive) for a bytes-like without copying. The
    caller must hold ``keepalive`` until the C call returns."""
    import ctypes

    if isinstance(view, bytes):
        return ctypes.cast(ctypes.c_char_p(view), ctypes.c_void_p), view
    mv = memoryview(view).cast("B")
    if mv.readonly:
        b = bytes(mv)  # rare (readonly ndarray): one copy, still sound
        return ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p), b
    arr = (ctypes.c_ubyte * mv.nbytes).from_buffer(mv)
    return ctypes.cast(arr, ctypes.c_void_p), arr


def _send_frame(sock, lock: threading.Lock, kind: int,
                tag: int, payload: bytes = b"",
                payload2=None) -> None:
    """Write one wire frame. With ``payload2`` (the codec's
    :func:`~mpi_tpu.utils.serialize.encode_parts` view) the frame body
    is ``payload + payload2`` scatter-gathered straight from the
    caller's buffer — the zero-copy ndarray data path; the receiver
    sees one frame either way."""
    n2 = 0 if payload2 is None else memoryview(payload2).nbytes
    if isinstance(sock, ShmConn):
        # shm conns frame in the ring engine; the per-conn lock still
        # serializes concurrent senders (the SPSC ring's one-producer
        # contract).
        if not isinstance(payload, bytes):
            payload = bytes(payload)
        with lock:
            if payload2 is not None:
                sock.send_frame2(kind, tag, payload, payload2)
            else:
                sock.send_frame(kind, tag, payload)
        return
    from .. import native as _native

    # Python socket timeouts make the fd non-blocking at the OS level;
    # the native engine only speaks blocking sockets (post-handshake data
    # path — handshake frames keep the Python path). Payloads past the
    # u32 wire limit fall through so struct.pack rejects them loudly.
    lib = _native.wirecore() if sock.gettimeout() is None else None
    if lib is not None and isinstance(payload, bytes) \
            and len(payload) + n2 <= 0xFFFFFFFF:
        # Native path: header + payload (+ array view) leave in one
        # writev — no user-space concatenation copy — with the GIL
        # released for the whole syscall loop (ctypes CDLL semantics).
        # -EINTR returns here so pending Python signal handlers
        # (Ctrl+C) run between resumes.
        import ctypes
        import errno as _errno
        import os as _os

        progress = ctypes.c_uint64(0)
        if payload2 is not None:
            ptr, keep = _view_cptr(payload2)
            with lock:
                while True:
                    rc = lib.wc_send_frame2(
                        sock.fileno(), kind, tag, payload, len(payload),
                        ptr, n2, ctypes.byref(progress))
                    if rc != -_errno.EINTR:
                        break
            del keep
        else:
            with lock:
                while True:
                    rc = lib.wc_send_frame(sock.fileno(), kind, tag,
                                           payload, len(payload),
                                           ctypes.byref(progress))
                    if rc != -_errno.EINTR:
                        break
        if rc == 0:
            return
        raise OSError(-rc, _os.strerror(-rc))
    header = _FRAME_HDR.pack(kind, tag, len(payload) + n2)
    with lock:
        if payload2 is not None:
            # Two sendalls, zero concatenation: sendall accepts the
            # (possibly readonly) view directly and loops partial
            # writes itself. The lock spans both, so the frame stays
            # contiguous on the stream.
            sock.sendall(header + payload)
            sock.sendall(payload2)
        else:
            sock.sendall(header + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes. Returns the freshly-owned bytearray
    (no defensive copy — the caller is the sole owner, which lets
    decode() alias large payloads zero-copy)."""
    from .. import native as _native

    buf = bytearray(n)
    lib = _native.wirecore() if sock.gettimeout() is None else None
    if lib is not None and n:
        import ctypes
        import errno as _errno

        arr = (ctypes.c_ubyte * n).from_buffer(buf)
        progress = ctypes.c_uint64(0)
        while True:
            rc = lib.wc_recv_exact(sock.fileno(), arr, n,
                                   ctypes.byref(progress))
            if rc != -_errno.EINTR:
                break
        if rc == _native.PEER_CLOSED:
            raise ConnectionError("connection closed by peer")
        if rc != 0:
            import os as _os

            raise OSError(-rc, _os.strerror(-rc))
        return buf
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("connection closed by peer")
        got += r
    return buf


def _recv_frame(sock) -> Tuple[int, int, bytearray]:
    if isinstance(sock, ShmConn):
        return sock.recv_frame()
    kind, tag, length = _FRAME_HDR.unpack(_recv_exact(sock, _FRAME_HDR.size))
    payload = _recv_exact(sock, length) if length else bytearray()
    return kind, tag, payload


class _Peer:
    """Connection pair to one peer (``pairwiseConnection``, network.go:499-506)."""

    def __init__(self, peer_rank: int):
        self.rank = peer_rank
        self.dial_sock: Optional[socket.socket] = None   # my sends + their acks
        self.listen_sock: Optional[socket.socket] = None  # their sends + my acks
        self.dial_lock = threading.Lock()
        self.listen_lock = threading.Lock()
        self.sendtags = TagManager("send", peer_rank)
        self.receivetags = TagManager("receive", peer_rank)
        self.reader_threads: List[threading.Thread] = []


class TcpNetwork:
    """The default backend, as ``&Network{}`` is in the reference (mpi.go:56).

    Constructor args mirror the user-settable ``Network`` fields
    (network.go:25-39): ``proto``, ``addr``, ``addrs``, ``timeout``
    (seconds), ``password``. Unset values resolve from the ``-mpi-*``
    flags / ``MPI_TPU_*`` env at :meth:`init` (network.go:69-90)."""

    def __init__(self, proto: Optional[str] = None, addr: Optional[str] = None,
                 addrs: Optional[List[str]] = None,
                 timeout: Optional[float] = None,
                 password: Optional[str] = None):
        self.proto = proto
        self.addr = addr
        self.addrs = list(addrs) if addrs else []
        self.timeout = timeout
        self.password = password

        self._rank: Optional[int] = None
        self._size: Optional[int] = None
        self._peers: Dict[int, _Peer] = {}
        self._local: Optional[Rendezvous] = None
        self._listener: Optional[socket.socket] = None
        self._closed = threading.Event()
        self._initialized = False

    # -- Interface ----------------------------------------------------------

    def rank(self) -> int:
        if self._rank is None:
            raise MpiError("mpi_tpu: rank() before init()")
        return self._rank

    def size(self) -> int:
        if self._size is None:
            raise MpiError("mpi_tpu: size() before init()")
        return self._size

    def host_key(self) -> str:
        """Machine identity for ``Comm.split_type("host")``: the host part
        of this rank's address (textual match — localhost spellings
        collapse to one key; unix-domain sockets are single-machine)."""
        if self.addr is None:
            raise MpiError("mpi_tpu: host_key() before init()")
        if self.proto in ("unix", "shm"):
            return self.proto
        host, _, _ = self.addr.rpartition(":")
        host = host.lower()
        return "127.0.0.1" if host in ("", "localhost", "::1", "[::1]") \
            else host

    def init(self) -> None:
        """Resolve config, assign ranks, build the all-to-all mesh
        (network.go:53-65)."""
        if self._initialized:
            raise MpiError("mpi_tpu: init() called twice")
        self._use_flags()
        if not self.addrs:
            # Single-node default (network.go:55-58).
            self.addr = self.addr or ":5000"
            self.addrs = [self.addr]
        self._assign_ranks()
        self._local = Rendezvous(self._rank, self._rank)
        self._start_connections()
        self._initialized = True

    def finalize(self) -> None:
        """Close every connection (network.go:354-369)."""
        self._closed.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            if self._is_unix() and self.addr:
                try:
                    os.unlink(self.addr)
                except OSError:
                    pass
        for peer in self._peers.values():
            for sock in (peer.dial_sock, peer.listen_sock):
                if sock is not None:
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        sock.close()
                    except OSError:
                        pass
        for peer in self._peers.values():
            for t in peer.reader_threads:
                t.join(timeout=2.0)
        # shm conns unmap only now: their reader threads dereference the
        # mapping inside native calls, so release must follow the joins
        # (and is skipped for a reader that refused to die).
        for peer in self._peers.values():
            if any(t.is_alive() for t in peer.reader_threads):
                continue
            for sock in (peer.dial_sock, peer.listen_sock):
                if isinstance(sock, ShmConn):
                    sock.release()
        self._initialized = False

    def send(self, data: Any, dest: int, tag: int) -> None:
        """Rendezvous send (network.go:518-572): encode, frame, block on ack.

        Large contiguous arrays/bytes take the scatter-gather path
        (``encode_parts``): the type prefix and the caller's buffer
        leave as one frame with no tobytes/concat copy — measured ~2x
        on 64 MiB one-way sends, where the two encode copies cost 81 ms
        of a 155 ms transfer."""
        self._check_rank(dest)
        if dest == self._rank:
            # Self path: no tag manager involvement needed beyond the local
            # rendezvous's own misuse detection — and unlike the reference
            # we do not leak the tag (defect (a), SURVEY.md §2).
            self._local.send(tag, codec_encode(data))
            return
        prefix, view = codec_encode_parts(data)
        peer = self._peers[dest]
        ackq, gen = peer.sendtags.claim(tag)
        try:
            _send_frame(peer.dial_sock, peer.dial_lock, KIND_DATA, tag,
                        prefix, view)
            # Blocks until the receiver's ack (network.go:569).
            peer.sendtags.wait(ackq, gen)
        finally:
            peer.sendtags.release(tag)

    def receive(self, source: int, tag: int, out: Optional[Any] = None) -> Any:
        """Blocking receive (network.go:575-602): dequeue payload, ack, decode."""
        self._check_rank(source)
        if source == self._rank:
            payload = self._local.receive(tag)
            return codec_decode(payload, out=out)
        peer = self._peers[source]
        slot, gen = peer.receivetags.claim(tag)
        try:
            payload = peer.receivetags.wait(slot, gen)
            # Ack on the listen conn — this is what unblocks the sender's
            # rendezvous (network.go:617-624); written only now, when the
            # receive has genuinely accepted the data.
            _send_frame(peer.listen_sock, peer.listen_lock, KIND_ACK, tag)
        finally:
            peer.receivetags.release(tag)
        return codec_decode(payload, out=out)

    def cancel_receive(self, source: int, tag: int) -> bool:
        """Best-effort cancellation of a pending receive (no reference
        analogue; supports :func:`mpi_tpu.api.exchange` cleanup). Returns
        False when the receive already completed or cannot be cancelled
        (self-receives with a sender already engaged)."""
        self._check_rank(source)
        exc = ReceiveCancelled(
            f"mpi_tpu: receive(source={source}, tag={tag}) cancelled")
        if source == self._rank:
            return self._local.cancel(tag, exc)
        return self._peers[source].receivetags.cancel(tag, exc)

    def iprobe(self, source: int, tag: int) -> bool:
        """Non-consuming MPI_Iprobe: True when a message from ``source``
        with ``tag`` is already available — its data frame arrived (the
        sender is blocked awaiting the rendezvous ack), or a self-send
        is parked at the local rendezvous."""
        self._check_rank(source)
        if source == self._rank:
            return self._local.probe(tag)
        return self._peers[source].receivetags.has_message(tag)

    # -- bootstrap ----------------------------------------------------------

    def _is_unix(self) -> bool:
        return self.proto == "unix"

    def _is_shm(self) -> bool:
        return self.proto == "shm"

    def _tune(self, sock: socket.socket) -> None:
        """Latency tuning where applicable (TCP only)."""
        if self.proto in ("tcp", "tcp4", "tcp6"):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _use_flags(self) -> None:
        """Explicit fields win over flags/env (network.go:69-90)."""
        fl = flagmod.get_flags()
        if self.proto is None:
            self.proto = fl.protocol or flagmod.DEFAULT_PROTOCOL
        if self.proto not in _SUPPORTED_PROTOS:
            raise InitError(
                f"mpi_tpu: unsupported -mpi-protocol {self.proto!r}; "
                f"supported: {', '.join(_SUPPORTED_PROTOS)}")
        if self.addr is None and fl.addr:
            self.addr = fl.addr
        if not self.addrs and fl.alladdr:
            self.addrs = list(fl.alladdr)
        if self.timeout is None:
            self.timeout = (fl.inittimeout if fl.inittimeout is not None
                            else flagmod.DEFAULT_INIT_TIMEOUT)
        if self.password is None:
            self.password = fl.password or ""

    def _assign_ranks(self) -> None:
        """Sorted-address consensus (network.go:94-118)."""
        if self.addr is None:
            if len(self.addrs) == 1:
                self.addr = self.addrs[0]
            else:
                raise InitError("mpi_tpu: own address unset with multiple addrs")
        ordered = sorted(self.addrs)
        for a, b in zip(ordered, ordered[1:]):
            if a == b:
                raise InitError(f"mpi_tpu: duplicate address {a!r} in addrs")
        try:
            self._rank = ordered.index(self.addr)
        except ValueError:
            raise InitError(
                f"mpi_tpu: own address {self.addr!r} not in addrs {ordered}") from None
        self._size = len(ordered)
        self.addrs = ordered

    def _start_connections(self) -> None:
        """Concurrent listen-side + dial-side all-to-all handshakes
        (network.go:122-159)."""
        n = self._size
        me = self._rank
        for r in range(n):
            if r != me:
                self._peers[r] = _Peer(r)
        if n == 1:
            return

        errors: List[str] = []
        err_lock = threading.Lock()

        def note(err: str) -> None:
            with err_lock:
                errors.append(err)

        if self._is_shm():
            self._shm_bootstrap(note)
        else:
            self._socket_bootstrap(note)

        if not errors:
            for peer in self._peers.values():
                if peer.dial_sock is None:
                    errors.append(f"rank {me}: no dial conn to {peer.rank}")
                if peer.listen_sock is None:
                    errors.append(f"rank {me}: no listen conn from {peer.rank}")
        if errors:
            self.finalize()
            raise InitError("; ".join(sorted(set(errors))))

        # Persistent readers (replace per-call goroutines; see module doc).
        for peer in self._peers.values():
            t1 = threading.Thread(target=self._dial_reader, args=(peer,),
                                  name=f"mpi-ackreader-{peer.rank}", daemon=True)
            t2 = threading.Thread(target=self._listen_reader, args=(peer,),
                                  name=f"mpi-datareader-{peer.rank}", daemon=True)
            peer.reader_threads = [t1, t2]
            t1.start()
            t2.start()

    def _socket_bootstrap(self, note) -> None:
        """TCP/unix all-to-all bootstrap: listen + dial handshakes
        (network.go:122-351). Populates peer dial/listen conns; errors
        go through ``note`` for aggregation."""
        n, me = self._size, self._rank
        # Listen side: accept n-1 peers, each validated by handshake.
        if self._is_unix():
            listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                # Clear a stale socket file from a crashed previous run;
                # a *live* conflicting listener still fails below, as the
                # reference's bind would.
                os.unlink(self.addr)
            except OSError:
                pass
            try:
                listener.bind(self.addr)
            except OSError as exc:
                raise InitError(
                    f"mpi_tpu: cannot listen on {self.addr!r}: {exc}"
                ) from exc
        else:
            host, port = _split_hostport(self.addr)
            family = (socket.AF_INET6 if self.proto == "tcp6"
                      else socket.AF_INET)
            listener = socket.socket(family, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                listener.bind((host, port))
            except OSError as exc:
                raise InitError(
                    f"mpi_tpu: cannot listen on {self.addr!r}: {exc}"
                ) from exc
        listener.listen(n)
        listener.settimeout(self.timeout)  # accept timeout (network.go:223-234)
        self._listener = listener

        accepted = threading.Semaphore(0)

        def listen_side() -> None:
            pending = n - 1
            while pending > 0:
                try:
                    conn, _ = listener.accept()
                except (socket.timeout, OSError) as exc:
                    note(f"rank {me}: accept failed/timed out: {exc}")
                    for _ in range(pending):
                        accepted.release()
                    return
                threading.Thread(target=listen_handshake, args=(conn,),
                                 daemon=True).start()
                pending -= 1

        def listen_handshake(conn: socket.socket) -> None:
            """network.go:211-263: read peer hello, validate, reply."""
            try:
                conn.settimeout(self.timeout)
                self._tune(conn)
                kind, claimed_id, payload = _recv_frame(conn)
                if kind != KIND_HELLO:
                    raise InitError(f"expected HELLO, got frame kind {kind}")
                if payload.decode("utf-8") != self.password:
                    raise InitError("password mismatch")  # network.go:344-347
                if not 0 <= claimed_id < n or claimed_id == me:
                    raise InitError(f"bad peer id {claimed_id}")  # network.go:348-350
                lock = threading.Lock()
                _send_frame(conn, lock, KIND_HELLO, me,
                            self.password.encode("utf-8"))
                conn.settimeout(None)
                peer = self._peers[claimed_id]
                peer.listen_sock = conn
                peer.listen_lock = lock
            except Exception as exc:  # noqa: BLE001 - aggregated, init fails
                note(f"rank {me}: listen handshake failed: {exc}")
                try:
                    conn.close()
                except OSError:
                    pass
            finally:
                accepted.release()

        def dial_handshake(peer_rank: int) -> None:
            """network.go:297-339: retry-dial peer, send hello, validate reply."""
            target = self.addrs[peer_rank]
            if not self._is_unix():
                target_host, target_port = _split_hostport(target)
            deadline = time.monotonic() + self.timeout
            sock: Optional[socket.socket] = None
            while True:
                try:
                    if self._is_unix():
                        sock = socket.socket(socket.AF_UNIX,
                                             socket.SOCK_STREAM)
                        sock.settimeout(self.timeout)
                        sock.connect(target)
                    else:
                        default_host = ("::1" if self.proto == "tcp6"
                                        else "localhost")
                        sock = socket.create_connection(
                            (target_host or default_host, target_port),
                            timeout=self.timeout)
                    break
                except OSError as exc:
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        sock = None
                    if time.monotonic() >= deadline:
                        note(f"rank {me}: dial {target!r} "
                             f"timed out: {exc}")
                        return
                    time.sleep(_DIAL_RETRY_INTERVAL)
            try:
                self._tune(sock)
                lock = threading.Lock()
                _send_frame(sock, lock, KIND_HELLO, me,
                            self.password.encode("utf-8"))
                sock.settimeout(self.timeout)
                kind, their_id, payload = _recv_frame(sock)
                if kind != KIND_HELLO:
                    raise InitError(f"expected HELLO reply, got kind {kind}")
                if payload.decode("utf-8") != self.password:
                    raise InitError("password mismatch in reply")
                if their_id != peer_rank:
                    raise InitError(
                        f"dialed rank {peer_rank} but peer claims {their_id}")
                sock.settimeout(None)
                peer = self._peers[peer_rank]
                peer.dial_sock = sock
                peer.dial_lock = lock
            except Exception as exc:  # noqa: BLE001
                note(f"rank {me}: dial handshake with rank {peer_rank} "
                     f"failed: {exc}")
                try:
                    sock.close()
                except OSError:
                    pass

        lt = threading.Thread(target=listen_side, daemon=True)
        lt.start()
        dial_threads = [threading.Thread(target=dial_handshake, args=(r,),
                                         daemon=True)
                        for r in range(n) if r != me]
        for t in dial_threads:
            t.start()
        for t in dial_threads:
            t.join()
        lt.join()
        for _ in range(n - 1):
            accepted.acquire()

    def _shm_bootstrap(self, note) -> None:
        """All-to-all bootstrap over shared-memory rings (proto ``shm``).

        Same shape as the socket bootstrap: for conn ``a -> me`` the
        listen side *creates* the ring pair and validates the dialer's
        HELLO; the dial side *attaches* with the 100 ms retry loop until
        the init timeout and validates the reply (network.go:198-263,
        294-351). The session-keyed ring names are themselves the
        rendezvous points, so there is no listener socket; a stale ring
        from a crashed run is unlinked at create time, like the unix
        bootstrap's stale socket file. HELLO still carries the password
        and claimed rank for reference parity, though the key already
        binds both (backends/shm.py module doc)."""
        from .shm import (attach_ring, create_ring, ring_capacity,
                          ring_name, session_key)

        n, me = self._size, self._rank
        key = session_key(self.addrs, self.password)
        cap = ring_capacity()

        def listen_handshake(peer_rank: int) -> None:
            names = (ring_name(key, peer_rank, me, "d"),
                     ring_name(key, peer_rank, me, "r"))
            conn: Optional[ShmConn] = None
            rx = tx = None
            try:
                rx = create_ring(names[0], cap)   # dialer's frames to me
                tx = create_ring(names[1], cap)   # my replies out
                conn = ShmConn(tx, rx, owned_names=names)
                conn.settimeout(self.timeout)
                kind, claimed_id, payload = _recv_frame(conn)
                if kind != KIND_HELLO:
                    raise InitError(f"expected HELLO, got frame kind {kind}")
                if payload.decode("utf-8") != self.password:
                    raise InitError("password mismatch")  # network.go:344-347
                if claimed_id != peer_rank:
                    raise InitError(
                        f"ring pair for rank {peer_rank} got HELLO "
                        f"claiming rank {claimed_id}")
                lock = threading.Lock()
                _send_frame(conn, lock, KIND_HELLO, me,
                            self.password.encode("utf-8"))
                conn.settimeout(None)
                peer = self._peers[peer_rank]
                peer.listen_sock = conn
                peer.listen_lock = lock
            except Exception as exc:  # noqa: BLE001 - aggregated, init fails
                note(f"rank {me}: shm listen handshake with rank "
                     f"{peer_rank} failed: {exc}")
                if conn is not None:
                    conn.close()
                    conn.release()  # no reader threads exist yet
                else:
                    # Partial creation: close and unlink whatever ring
                    # exists, or the named /dev/shm object outlives the
                    # process (POSIX shm survives exit).
                    from .shm import unlink_ring
                    for ring in (rx, tx):
                        if ring is not None:
                            ring.mark_closed()
                            ring.close()
                    for name in names:
                        unlink_ring(name)

        def dial_handshake(peer_rank: int) -> None:
            names = (ring_name(key, me, peer_rank, "d"),
                     ring_name(key, me, peer_rank, "r"))
            deadline = time.monotonic() + self.timeout
            tx = rx = None
            try:
                while tx is None or rx is None:
                    if tx is None:
                        tx = attach_ring(names[0])
                    if tx is not None and rx is None:
                        rx = attach_ring(names[1])
                    if tx is not None and rx is not None:
                        break
                    if time.monotonic() >= deadline:
                        raise InitError("timed out waiting for rings")
                    time.sleep(_DIAL_RETRY_INTERVAL)
            except Exception as exc:  # noqa: BLE001 - aggregated, init fails
                # Route unexpected attach errors (EACCES on a stale
                # ring, ...) through note() like every other handshake
                # path, instead of dying silently in the thread.
                note(f"rank {me}: shm dial to rank {peer_rank} "
                     f"failed: {exc}")
                for ring in (tx, rx):
                    if ring is not None:
                        ring.close()
                return
            conn = ShmConn(tx, rx)  # listener owns/unlinks the names
            try:
                # Timeout BEFORE the HELLO send (as the listen side does):
                # a nearly-full stale ring attached in the unlink/recreate
                # window would otherwise block the write forever and hang
                # init past its deadline.
                conn.settimeout(self.timeout)
                lock = threading.Lock()
                _send_frame(conn, lock, KIND_HELLO, me,
                            self.password.encode("utf-8"))
                kind, their_id, payload = _recv_frame(conn)
                if kind != KIND_HELLO:
                    raise InitError(f"expected HELLO reply, got kind {kind}")
                if payload.decode("utf-8") != self.password:
                    raise InitError("password mismatch in reply")
                if their_id != peer_rank:
                    raise InitError(
                        f"dialed rank {peer_rank} but peer claims {their_id}")
                conn.settimeout(None)
                peer = self._peers[peer_rank]
                peer.dial_sock = conn
                peer.dial_lock = lock
            except Exception as exc:  # noqa: BLE001
                note(f"rank {me}: shm dial handshake with rank {peer_rank} "
                     f"failed: {exc}")
                conn.close()
                conn.release()  # no reader threads exist yet

        threads = [threading.Thread(target=listen_handshake, args=(r,),
                                    daemon=True)
                   for r in range(n) if r != me]
        threads += [threading.Thread(target=dial_handshake, args=(r,),
                                     daemon=True)
                    for r in range(n) if r != me]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    # -- data path ----------------------------------------------------------

    def _dial_reader(self, peer: _Peer) -> None:
        """Reads the peer's acks off my dial conn → unblocks my sends
        (the ack-reader goroutine of network.go:551-559)."""
        try:
            while not self._closed.is_set():
                kind, tag, _ = _recv_frame(peer.dial_sock)
                if kind != KIND_ACK:
                    raise MpiError(f"unexpected frame kind {kind} on dial conn")
                peer.sendtags.route(tag, True)
        except (ConnectionError, OSError, MpiError) as exc:
            self._poison(peer.sendtags, exc)

    def _listen_reader(self, peer: _Peer) -> None:
        """Reads the peer's data frames off my listen conn → routes by tag
        (``receiveReader``, network.go:607-625; ack deferred to receive())."""
        try:
            while not self._closed.is_set():
                kind, tag, payload = _recv_frame(peer.listen_sock)
                if kind != KIND_DATA:
                    raise MpiError(f"unexpected frame kind {kind} on listen conn")
                peer.receivetags.route(tag, payload)
        except (ConnectionError, OSError, MpiError) as exc:
            self._poison(peer.receivetags, exc)

    def _poison(self, tags: TagManager, exc: BaseException) -> None:
        """On connection loss, fail all pending *and future* ops on this
        direction instead of hanging (replaces the reference's reader
        panics, network.go:555,611): ops already blocked get the exception
        via their slot; ops issued after the loss fail at claim()."""
        if self._closed.is_set():
            exc = MpiError("mpi_tpu: network finalized")
        tags.poison(exc)

    def _check_rank(self, r: int) -> None:
        if self._size is None:
            raise MpiError("mpi_tpu: send/receive before init()")
        if not 0 <= r < self._size:
            raise MpiError(f"mpi_tpu: peer rank {r} out of range [0, {self._size})")

"""TCP all-to-all driver — CPU fallback and bitwise-parity oracle.

Rebuild of the reference's ``Network`` backend (/root/reference/network.go),
preserving its observable semantics:

  * leaderless deterministic rank assignment: sort the address list, rank =
    index of own address; duplicate or missing addresses are errors
    (network.go:94-118);
  * eager all-to-all bootstrap at init: every pair of ranks holds two TCP
    connections, one dialed by each side; ``dial`` carries my sends and the
    peer's acks, ``listen`` carries the peer's sends and my acks
    (network.go:122-159, 499-506);
  * password-validated handshake with accept timeout on the listen side and
    a 100 ms dial-retry loop until the init timeout on the dial side
    (network.go:198-263, 294-351);
  * tag-demultiplexed **rendezvous** messaging: ``send`` blocks until the
    matching ``receive`` has accepted the payload, signalled by an ack
    frame written back on the same connection the data arrived on
    (network.go:518-625);
  * in-process self-send rendezvous with first-arrival-creates semantics
    (network.go:371-446);
  * config resolution: explicit constructor args win over ``-mpi-*`` flags,
    with a single-node ``":5000"`` default (network.go:55-58, 69-90).

Deliberate fixes of the reference's latent defects (SURVEY.md §2), none of
which change the documented contracts:

  * self-send releases its tag on completion (the reference leaks it —
    ``Send`` registers the tag at network.go:534 but the local path returns
    without ``Delete`` at network.go:546-547, so tag reuse panics);
  * one write lock per socket — the reference lets concurrent sends to the
    same destination interleave gob streams on one conn (network.go:562);
  * persistent per-connection reader threads replace per-call reader
    goroutines, removing the reference's race where a reader spawned by
    ``Receive(tagB)`` decodes a message for not-yet-registered ``tagA`` and
    panics (network.go:587, 614);
  * early-arriving messages for unregistered tags are buffered; rendezvous
    is unaffected because the ack is only written when a ``receive``
    actually dequeues.

Wire protocol (replaces gob; all integers little-endian)::

    frame      := kind:u8  tag:i64  length:u32  payload[length]  [crc:u32]
    kind       := 0 DATA   payload = mpi_tpu.utils.serialize codec bytes
                  1 ACK    payload = empty (length 0)
                  2 HELLO  payload = utf-8 password, optionally followed
                           by "\\0mpi-feat:" and a comma-separated feature
                           list (see below); tag field carries the
                           sender's claimed rank id (initialMessage
                           {Password, Id}, network.go:198-201)
                  3 ABORT  payload = empty; tag field carries the abort
                           exit code (failure-propagation control frame,
                           docs/FAULT_TOLERANCE.md — no reference
                           analogue, the reference can only hang)

Integrity (``--mpi-crc``): each side advertises the ``crc32`` feature in
its HELLO; when **both** ends of a connection advertise it, every DATA
frame on that connection carries a CRC32 trailer over header+payload.
Off (the default, or a peer without the feature) the wire is bit-for-bit
today's format and the zero-copy native fast path is untouched; on, a
corrupted frame raises a typed ``ERR_TRUNCATE``-class error naming the
source rank and tag instead of a garbage decode.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from .. import flags as flagmod
from ..api import MpiError
from ..utils import trace
from ..utils.serialize import decode as codec_decode
from ..utils.serialize import encode as codec_encode
from ..utils.serialize import encode_parts as codec_encode_parts
from .rendezvous import (DeadlineError, ReceiveCancelled, Rendezvous,
                         TagManager)
from .shm import ShmConn

__all__ = ["TcpNetwork", "InitError", "ReceiveCancelled", "DeadlineError",
           "ChecksumError", "PeerDeadError", "RemoteAbortError"]

KIND_DATA = 0
KIND_ACK = 1
KIND_HELLO = 2
KIND_ABORT = 3

_FRAME_HDR = struct.Struct("<BqI")
_CRC_TRAILER = struct.Struct("<I")
_DIAL_RETRY_INTERVAL = 0.1  # network.go:298 — 100 ms poll

# HELLO feature negotiation: the password payload may be followed by this
# separator and a comma-separated feature list. A password that literally
# contains the separator would misparse — NUL bytes in passwords are
# rejected at init instead of risking a silent feature mismatch.
_FEATURE_SEP = b"\x00mpi-feat:"
_FEATURE_CRC = "crc32"

# The reference's NetProto accepts any `net` package protocol
# (network.go:26). Supported here: TCP (the default, "tcp4" an alias,
# "tcp6" for IPv6 with Go's "[::1]:5000" bracket addresses),
# unix-domain stream sockets (addresses = filesystem paths), and "shm"
# — same-host shared-memory rings via the native engine
# (backends/shm.py, native/shmcore.cpp; addresses = opaque ids).
# Anything else raises at init instead of being silently ignored.
_SUPPORTED_PROTOS = ("tcp", "tcp4", "tcp6", "unix", "shm")


class InitError(MpiError):
    """Bootstrap failure; aggregates per-peer handshake errors
    (network.go:185-195, 281-291)."""


class ChecksumError(MpiError):
    """A DATA frame failed its negotiated CRC32 integrity check.

    MPI class ``ERR_TRUNCATE`` (the class an MPI implementation reports
    when a message's bytes do not match what was sent). Carries the
    source rank and tag so the failure is attributable."""

    def __init__(self, src: int, tag: int):
        self.src = src
        self.tag = tag
        super().__init__(
            f"mpi_tpu: frame integrity check failed for message from "
            f"rank {src} tag {tag}: CRC32 mismatch — payload corrupted "
            f"in transit (MPI_ERR_TRUNCATE)")


class PeerDeadError(MpiError):
    """A peer's connection was lost; pending and future operations
    targeting it fail with this instead of hanging (MPI class
    ``ERR_PENDING`` — the operations did not complete)."""

    def __init__(self, peer: int, cause: BaseException):
        import re as _re

        self.peer = peer
        # Strip any (MPI_ERR_XXX) marker the cause carries: this error
        # classifies as ERR_PENDING, and errclass's marker scan takes
        # the FIRST marker in the message.
        cause_text = _re.sub(r"\s*\(MPI_ERR_[A-Z_]+\)", "", str(cause))
        super().__init__(
            f"mpi_tpu: peer rank {peer} is dead ({cause_text}); pending "
            f"and future operations targeting it fail (MPI_ERR_PENDING)")


class RemoteAbortError(MpiError):
    """A remote rank called ``abort()`` — its ABORT control frame
    arrived; this rank's operations involving any peer now raise."""

    def __init__(self, peer: int, code: int):
        self.peer = peer
        self.code = code
        super().__init__(
            f"mpi_tpu: rank {peer} aborted the job with code {code} "
            f"(MPI_ERR_OTHER)")


def _split_hostport(addr: str) -> Tuple[str, int]:
    host, sep, port = addr.rpartition(":")
    if not sep:
        raise MpiError(f"mpi_tpu: address {addr!r} missing :port")
    # Go's net.SplitHostPort bracket syntax for IPv6 literals:
    # "[::1]:5000" -> host "::1".
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    return host, int(port)


def _view_cptr(view):
    """(c_void_p, keepalive) for a bytes-like without copying. The
    caller must hold ``keepalive`` until the C call returns."""
    import ctypes

    if isinstance(view, bytes):
        return ctypes.cast(ctypes.c_char_p(view), ctypes.c_void_p), view
    mv = memoryview(view).cast("B")
    if mv.readonly:
        b = bytes(mv)  # rare (readonly ndarray): one copy, still sound
        return ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p), b
    arr = (ctypes.c_ubyte * mv.nbytes).from_buffer(mv)
    return ctypes.cast(arr, ctypes.c_void_p), arr


def _crc32_frame(header: bytes, payload, payload2=None) -> int:
    """CRC32 over header + payload (+ payload2): the trailer value of an
    integrity-negotiated DATA frame. Covers the header too, so a
    corrupted kind/tag/length is also caught (when the length corruption
    still framed plausibly)."""
    c = zlib.crc32(header)
    c = zlib.crc32(payload, c)
    if payload2 is not None:
        view = memoryview(payload2)
        if view.ndim != 1 or view.itemsize != 1:
            view = view.cast("B")
        try:
            c = zlib.crc32(view, c)
        except BufferError:  # non-contiguous: one copy, rare
            c = zlib.crc32(bytes(view), c)
    return c


def _chaos_wire_send(sock, lock: threading.Lock, kind: int, tag: int,
                     payload, payload2, use_crc: bool, fault) -> None:
    """Chaos-plane frame writer: assembles the full frame (including the
    CRC trailer when negotiated — computed over the CLEAN bytes, exactly
    as a real sender would), then applies the injected wire fault so the
    receiver sees genuine line damage: a flipped payload bit, a frame
    cut short, or a vanished connection."""
    body = bytearray(_FRAME_HDR.pack(
        kind, tag,
        len(payload) + (0 if payload2 is None else
                        memoryview(payload2).nbytes)))
    payload_start = len(body)
    body += payload
    if payload2 is not None:
        body += memoryview(payload2)
    payload_len = len(body) - payload_start
    if use_crc:
        body += _CRC_TRAILER.pack(
            _crc32_frame(bytes(body[:payload_start]),
                         bytes(body[payload_start:])))
    if fault.corrupt_offset is not None and payload_len:
        at = payload_start + fault.corrupt_offset % payload_len
        body[at] ^= 1 << (fault.corrupt_bit % 8)
    with lock:
        if fault.reset:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
            return
        if fault.truncate_at is not None:
            # A frame cut short desynchronizes the stream permanently,
            # so the connection dies with it — the mid-frame-death
            # scenario (peer crashed while writing).
            cut = fault.truncate_at % max(1, len(body) - 1)
            try:
                sock.sendall(bytes(body[:cut]))
            except OSError:
                pass
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
            return
        sock.sendall(bytes(body))


def _send_frame(sock, lock: threading.Lock, kind: int,
                tag: int, payload: bytes = b"",
                payload2=None, crc: bool = False, fault=None,
                stages=None) -> None:
    """Write one wire frame. With ``payload2`` (the codec's
    :func:`~mpi_tpu.utils.serialize.encode_parts` view) the frame body
    is ``payload + payload2`` scatter-gathered straight from the
    caller's buffer — the zero-copy ndarray data path; the receiver
    sees one frame either way.

    ``crc`` appends the negotiated CRC32 trailer to DATA frames (the
    integrity option takes the Python write path; with it off this
    function is byte-identical to the pre-CRC implementation).
    ``fault`` (a :class:`mpi_tpu.chaos.WireFault`) routes the frame
    through the chaos wire plane instead. ``stages`` (a caller-zeroed
    ``(ctypes.c_uint64 * 4)`` scratch) makes the native engine
    accumulate per-stage ns/counts — assemble ns, writev ns, writev
    calls, bytes — for the tracer's ``wire.write.*`` child spans; only
    the native path fills it (``stages[2]`` stays 0 otherwise)."""
    use_crc = crc and kind == KIND_DATA and not isinstance(sock, ShmConn)
    if fault is not None and fault.any() and not isinstance(sock, ShmConn):
        _chaos_wire_send(sock, lock, kind, tag, payload, payload2,
                         use_crc, fault)
        return
    n2 = 0 if payload2 is None else memoryview(payload2).nbytes
    if isinstance(sock, ShmConn):
        # shm conns frame in the ring engine; the per-conn lock still
        # serializes concurrent senders (the SPSC ring's one-producer
        # contract).
        if not isinstance(payload, bytes):
            payload = bytes(payload)
        with lock:
            if payload2 is not None:
                sock.send_frame2(kind, tag, payload, payload2)
            else:
                sock.send_frame(kind, tag, payload)
        return
    from .. import native as _native

    # Python socket timeouts make the fd non-blocking at the OS level;
    # the native engine only speaks blocking sockets (post-handshake data
    # path — handshake frames keep the Python path). Payloads past the
    # u32 wire limit fall through so struct.pack rejects them loudly.
    lib = (_native.wirecore()
           if sock.gettimeout() is None and not use_crc else None)
    if lib is not None and isinstance(payload, bytes) \
            and len(payload) + n2 <= 0xFFFFFFFF:
        # Native path: header + payload (+ array view) leave in one
        # writev — no user-space concatenation copy — with the GIL
        # released for the whole syscall loop (ctypes CDLL semantics).
        # -EINTR returns here so pending Python signal handlers
        # (Ctrl+C) run between resumes.
        import ctypes
        import errno as _errno
        import os as _os

        progress = ctypes.c_uint64(0)
        if payload2 is not None:
            ptr, keep = _view_cptr(payload2)
            with lock:
                while True:
                    rc = lib.wc_send_frame2(
                        sock.fileno(), kind, tag, payload, len(payload),
                        ptr, n2, ctypes.byref(progress), stages)
                    if rc != -_errno.EINTR:
                        break
            del keep
        else:
            with lock:
                while True:
                    rc = lib.wc_send_frame(sock.fileno(), kind, tag,
                                           payload, len(payload),
                                           ctypes.byref(progress), stages)
                    if rc != -_errno.EINTR:
                        break
        if rc == 0:
            return
        raise OSError(-rc, _os.strerror(-rc))
    header = _FRAME_HDR.pack(kind, tag, len(payload) + n2)
    trailer = (_CRC_TRAILER.pack(_crc32_frame(header, payload, payload2))
               if use_crc else b"")
    with lock:
        if payload2 is not None:
            # Two sendalls, zero concatenation: sendall accepts the
            # (possibly readonly) view directly and loops partial
            # writes itself. The lock spans both, so the frame stays
            # contiguous on the stream.
            sock.sendall(header + payload)
            sock.sendall(payload2)
            if trailer:
                sock.sendall(trailer)
        else:
            sock.sendall(header + payload + trailer)


def _recv_exact(sock: socket.socket, n: int,
                midframe: bool = False, stages=None) -> bytearray:
    """Read exactly ``n`` bytes. Returns the freshly-owned bytearray
    (no defensive copy — the caller is the sole owner, which lets
    decode() alias large payloads zero-copy).

    A ``socket.timeout`` that fires mid-frame — partway through this
    read, or on a later segment of an already-started frame
    (``midframe``) — leaves the stream desynchronized: a retry would
    resume reading from the middle of the frame and decode garbage. It
    is converted to a fatal :class:`ConnectionError` for this peer; only
    a timeout on a clean frame boundary surfaces as ``socket.timeout``
    (the handshake accept/reply deadlines rely on that)."""
    from .. import native as _native

    buf = bytearray(n)
    lib = _native.wirecore() if sock.gettimeout() is None else None
    if lib is not None and n:
        import ctypes
        import errno as _errno

        arr = (ctypes.c_ubyte * n).from_buffer(buf)
        progress = ctypes.c_uint64(0)
        while True:
            rc = lib.wc_recv_exact(sock.fileno(), arr, n,
                                   ctypes.byref(progress), stages)
            if rc != -_errno.EINTR:
                break
        if rc == _native.PEER_CLOSED:
            raise ConnectionError("connection closed by peer")
        if rc != 0:
            import os as _os

            raise OSError(-rc, _os.strerror(-rc))
        return buf
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            if got or midframe:
                raise ConnectionError(
                    f"mpi_tpu: socket timeout mid-frame after {got}/{n} "
                    f"bytes; stream desynchronized — connection is "
                    f"unusable") from None
            raise
        if r == 0:
            raise ConnectionError("connection closed by peer")
        got += r
    return buf


def _recv_frame(sock, crc: bool = False,
                src: int = -1) -> Tuple[int, int, bytearray]:
    """Read one frame; with ``crc`` (the negotiated integrity option)
    DATA frames carry a CRC32 trailer, verified here — a mismatch
    raises :class:`ChecksumError` naming ``src`` and the frame's tag."""
    if isinstance(sock, ShmConn):
        return sock.recv_frame()
    header = _recv_exact(sock, _FRAME_HDR.size)
    kind, tag, length = _FRAME_HDR.unpack(header)
    if length:
        # Native stage scratch for the payload read (the header read is
        # idle-reader wait, not transfer): the resulting
        # ``wire.recv.syscall`` span lands on this reader thread's lane
        # as the recv-side counterpart of ``wire.write.syscall``.
        stages = None
        t0 = 0
        if trace.enabled():
            import ctypes as _ctypes

            stages = (_ctypes.c_uint64 * 3)()
            t0 = time.perf_counter_ns()
        payload = _recv_exact(sock, length, midframe=True, stages=stages)
        if stages is not None and stages[1]:
            trace.add_span("wire.recv.syscall", t0 / 1e3, stages[0] / 1e3,
                           source=src, tag=tag, bytes=int(stages[2]),
                           recv_calls=int(stages[1]))
            trace.count("wire.native.rx.syscall_ns", int(stages[0]))
            trace.count("wire.native.rx.recv_calls", int(stages[1]))
    else:
        payload = bytearray()
    if crc and kind == KIND_DATA:
        trailer = _recv_exact(sock, _CRC_TRAILER.size, midframe=True)
        if trace.enabled():
            t0 = time.perf_counter_ns()
            ok = _CRC_TRAILER.unpack(trailer)[0] == \
                _crc32_frame(bytes(header), payload)
            trace.count("wire.crc.frames")
            trace.count("wire.crc.ns", time.perf_counter_ns() - t0)
            if not ok:
                raise ChecksumError(src, tag)
        elif _CRC_TRAILER.unpack(trailer)[0] != \
                _crc32_frame(bytes(header), payload):
            raise ChecksumError(src, tag)
    return kind, tag, payload


class _Peer:
    """Connection pair to one peer (``pairwiseConnection``, network.go:499-506)."""

    def __init__(self, peer_rank: int):
        self.rank = peer_rank
        self.dial_sock: Optional[socket.socket] = None   # my sends + their acks
        self.listen_sock: Optional[socket.socket] = None  # their sends + my acks
        self.dial_lock = threading.Lock()
        self.listen_lock = threading.Lock()
        self.sendtags = TagManager("send", peer_rank)
        self.receivetags = TagManager("receive", peer_rank)
        self.reader_threads: List[threading.Thread] = []
        # Negotiated per-connection CRC (both HELLOs advertised crc32).
        self.dial_crc = False
        self.listen_crc = False
        # First failure that killed this peer's connections; set once by
        # _mark_peer_dead (under dead_lock — both readers can die
        # concurrently), after which every op targeting the peer fails
        # fast instead of hanging.
        self.dead: Optional[BaseException] = None
        self.dead_lock = threading.Lock()


class TcpNetwork:
    """The default backend, as ``&Network{}`` is in the reference (mpi.go:56).

    Constructor args mirror the user-settable ``Network`` fields
    (network.go:25-39): ``proto``, ``addr``, ``addrs``, ``timeout``
    (seconds), ``password``. Unset values resolve from the ``-mpi-*``
    flags / ``MPI_TPU_*`` env at :meth:`init` (network.go:69-90)."""

    def __init__(self, proto: Optional[str] = None, addr: Optional[str] = None,
                 addrs: Optional[List[str]] = None,
                 timeout: Optional[float] = None,
                 password: Optional[str] = None,
                 optimeout: Optional[float] = None,
                 crc: Optional[bool] = None,
                 chaos: Optional[str] = None):
        self.proto = proto
        self.addr = addr
        self.addrs = list(addrs) if addrs else []
        self.timeout = timeout
        self.password = password
        # Robustness extensions (docs/FAULT_TOLERANCE.md); unset values
        # resolve from --mpi-optimeout / --mpi-crc / --mpi-chaos at init.
        self.optimeout = optimeout
        self.crc = crc
        # Chaos engine attachment point: a ChaosEngine (or a raw
        # seed:rate:modes spec string, parsed at init). The send path
        # consults it per operation; None = fault-free (the default).
        self._chaos = chaos

        self._rank: Optional[int] = None
        self._size: Optional[int] = None
        self._peers: Dict[int, _Peer] = {}
        self._local: Optional[Rendezvous] = None
        self._listener: Optional[socket.socket] = None
        self._closed = threading.Event()
        self._initialized = False

    # -- Interface ----------------------------------------------------------

    def rank(self) -> int:
        if self._rank is None:
            raise MpiError("mpi_tpu: rank() before init()")
        return self._rank

    def size(self) -> int:
        if self._size is None:
            raise MpiError("mpi_tpu: size() before init()")
        return self._size

    def host_key(self) -> str:
        """Machine identity for ``Comm.split_type("host")``: the host part
        of this rank's address (textual match — localhost spellings
        collapse to one key; unix-domain sockets are single-machine)."""
        if self.addr is None:
            raise MpiError("mpi_tpu: host_key() before init()")
        if self.proto in ("unix", "shm"):
            return self.proto
        host, _, _ = self.addr.rpartition(":")
        host = host.lower()
        return "127.0.0.1" if host in ("", "localhost", "::1", "[::1]") \
            else host

    def init(self) -> None:
        """Resolve config, assign ranks, build the all-to-all mesh
        (network.go:53-65)."""
        if self._initialized:
            raise MpiError("mpi_tpu: init() called twice")
        self._use_flags()
        if not self.addrs:
            # Single-node default (network.go:55-58).
            self.addr = self.addr or ":5000"
            self.addrs = [self.addr]
        self._assign_ranks()
        self._local = Rendezvous(self._rank, self._rank)
        self._start_connections()
        self._initialized = True

    def finalize(self) -> None:
        """Close every connection (network.go:354-369).

        Safe to call twice and after a failed ``init()`` (the second
        call is a no-op; a bootstrap-failure call sees whatever partial
        state exists) — so error-path cleanup in tests and the chaos
        harness can ``finalize()`` unconditionally."""
        if self._closed.is_set():
            return
        self._closed.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            if self._is_unix() and self.addr:
                try:
                    os.unlink(self.addr)
                except OSError:
                    pass
        for peer in self._peers.values():
            for sock in (peer.dial_sock, peer.listen_sock):
                if sock is not None:
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        sock.close()
                    except OSError:
                        pass
        for peer in self._peers.values():
            for t in peer.reader_threads:
                t.join(timeout=2.0)
        # shm conns unmap only now: their reader threads dereference the
        # mapping inside native calls, so release must follow the joins
        # (and is skipped for a reader that refused to die).
        for peer in self._peers.values():
            if any(t.is_alive() for t in peer.reader_threads):
                continue
            for sock in (peer.dial_sock, peer.listen_sock):
                if isinstance(sock, ShmConn):
                    sock.release()
        self._initialized = False

    def send(self, data: Any, dest: int, tag: int) -> None:
        """Rendezvous send (network.go:518-572): encode, frame, block on ack.

        Large contiguous arrays/bytes take the scatter-gather path
        (``encode_parts``): the type prefix and the caller's buffer
        leave as one frame with no tobytes/concat copy — measured ~2x
        on 64 MiB one-way sends, where the two encode copies cost 81 ms
        of a 155 ms transfer.

        With ``--mpi-optimeout`` the ack wait is bounded: a vanished
        receiver raises :class:`DeadlineError` instead of blocking
        forever. Under ``--mpi-chaos`` the engine may sleep here (delay
        modes) or hand back a wire fault applied to this frame."""
        self._check_rank(dest)
        fault = (self._chaos.on_op("send", dest, tag,
                                   wire=dest != self._rank)
                 if self._chaos is not None else None)
        if dest == self._rank:
            # Self path: no tag manager involvement needed beyond the local
            # rendezvous's own misuse detection — and unlike the reference
            # we do not leak the tag (defect (a), SURVEY.md §2). The
            # deadline covers it like the remote ack wait.
            self._local.send(tag, codec_encode(data),
                             timeout=self.optimeout,
                             op=f"send(dest={dest}, tag={tag}) self "
                                f"rendezvous")
            return
        # Per-stage wire spans + per-peer byte counters (observe layer):
        # frame assembly / socket write / ack wait are separately
        # attributable — the decomposition the transport-rewrite work
        # targets (docs/PERF_NOTES.md). One bool check when tracing off.
        tracing = trace.enabled()
        if tracing:
            with trace.span("wire.encode", dest=dest, tag=tag):
                prefix, view = codec_encode_parts(data)
            nbytes = len(prefix) + (0 if view is None
                                    else memoryview(view).nbytes)
            trace.count("wire.tx.frames")
            trace.count(f"wire.{self.proto}.tx.bytes.peer{dest}", nbytes)
        else:
            prefix, view = codec_encode_parts(data)
        peer = self._peers[dest]
        ackq, gen = peer.sendtags.claim(tag)
        try:
            try:
                if tracing:
                    # Native stage scratch: when _send_frame takes the
                    # wirecore path it accumulates per-stage ns here,
                    # which become child spans under wire.write — the
                    # named microseconds the transport rewrite needs
                    # (docs/PERF_NOTES.md).
                    import ctypes as _ctypes

                    stages = (_ctypes.c_uint64 * 4)()
                    with trace.span("wire.write", dest=dest, tag=tag,
                                    bytes=nbytes, crc=peer.dial_crc):
                        t0w = time.perf_counter_ns()
                        _send_frame(peer.dial_sock, peer.dial_lock,
                                    KIND_DATA, tag, prefix, view,
                                    crc=peer.dial_crc, fault=fault,
                                    stages=stages)
                    if stages[2]:
                        asm_us = stages[0] / 1e3
                        trace.add_span("wire.write.assemble", t0w / 1e3,
                                       asm_us, dest=dest, tag=tag)
                        trace.add_span("wire.write.syscall",
                                       t0w / 1e3 + asm_us,
                                       stages[1] / 1e3, dest=dest,
                                       tag=tag, bytes=int(stages[3]),
                                       writev_calls=int(stages[2]))
                        trace.count("wire.native.tx.syscall_ns",
                                    int(stages[1]))
                        trace.count("wire.native.tx.writev_calls",
                                    int(stages[2]))
                else:
                    _send_frame(peer.dial_sock, peer.dial_lock, KIND_DATA,
                                tag, prefix, view, crc=peer.dial_crc,
                                fault=fault)
            except OSError as exc:
                # The conn died under us (peer crashed; chaos reset by a
                # sibling thread) before the reader poisoned the tags —
                # surface the typed peer-death error, not a raw EBADF.
                raise (peer.dead if peer.dead is not None
                       else PeerDeadError(peer.rank, exc)) from exc
            # Blocks until the receiver's ack (network.go:569).
            if tracing:
                with trace.span("wire.ack_wait", dest=dest, tag=tag):
                    peer.sendtags.wait(
                        ackq, gen, timeout=self.optimeout,
                        op=f"send(dest={dest}, tag={tag}) ack wait")
            else:
                peer.sendtags.wait(ackq, gen, timeout=self.optimeout,
                                   op=f"send(dest={dest}, tag={tag}) "
                                      f"ack wait")
        finally:
            peer.sendtags.release(tag)

    def receive(self, source: int, tag: int, out: Optional[Any] = None) -> Any:
        """Blocking receive (network.go:575-602): dequeue payload, ack, decode.

        With ``--mpi-optimeout`` the payload wait is bounded: a sender
        that never arrives (peer wedged or dead without a detectable
        connection loss) raises :class:`DeadlineError`. The deadline
        also covers the decode phase: decode is uninterruptible
        Python/numpy work, so it runs to completion, but if the
        operation as a whole then exceeds the deadline the receive
        raises :class:`DeadlineError` instead of returning late data
        (docs/FAULT_TOLERANCE.md)."""
        self._check_rank(source)
        if self._chaos is not None:
            self._chaos.on_op("receive", source, tag)
        # Op-elapsed origin for the decode-phase deadline check. Taken
        # AFTER the chaos hook: injected pre-op latency has always been
        # outside the deadline and must stay there.
        t0_op = time.monotonic() if self.optimeout is not None else 0.0
        if source == self._rank:
            payload = self._local.receive(
                tag, timeout=self.optimeout,
                op=f"receive(source={source}, tag={tag}) self rendezvous")
            data = codec_decode(payload, out=out)
            self._check_decode_deadline(t0_op, source, tag)
            return data
        peer = self._peers[source]
        slot, gen = peer.receivetags.claim(tag)
        tracing = trace.enabled()
        try:
            if tracing:
                with trace.span("wire.payload_wait", source=source,
                                tag=tag):
                    payload = peer.receivetags.wait(
                        slot, gen, timeout=self.optimeout,
                        op=f"receive(source={source}, tag={tag})")
            else:
                payload = peer.receivetags.wait(
                    slot, gen, timeout=self.optimeout,
                    op=f"receive(source={source}, tag={tag})")
            # Ack on the listen conn — this is what unblocks the sender's
            # rendezvous (network.go:617-624); written only now, when the
            # receive has genuinely accepted the data. A failed ack write
            # means the sender died AFTER transmitting: the payload is
            # fully in hand and the ack has no one left to unblock —
            # deliver the data rather than discard a completed receive.
            try:
                _send_frame(peer.listen_sock, peer.listen_lock, KIND_ACK,
                            tag)
            except OSError:
                pass
        finally:
            peer.receivetags.release(tag)
        if tracing:
            trace.count(f"wire.{self.proto}.rx.bytes.peer{source}",
                        len(payload))
            with trace.span("wire.decode", source=source, tag=tag,
                            bytes=len(payload)):
                data = codec_decode(payload, out=out)
        else:
            data = codec_decode(payload, out=out)
        self._check_decode_deadline(t0_op, source, tag)
        return data

    def _check_decode_deadline(self, t0_op: float, source: int,
                               tag: int) -> None:
        """Deadline coverage for the decode phase: a giant payload
        whose decode outlives ``--mpi-optimeout`` used to complete
        anyway (the known gap in docs/FAULT_TOLERANCE.md). The decode
        itself cannot be interrupted mid-way, so the check runs at its
        completion — the op fails with the same typed error the wait
        phases raise, rather than silently returning after the
        deadline. The ack has already been written by this point, so
        the sender correctly sees its rendezvous complete; deadline
        semantics have always been indeterminate-at-the-boundary
        (docs/FAULT_TOLERANCE.md §--mpi-optimeout)."""
        if self.optimeout is not None and \
                time.monotonic() - t0_op > self.optimeout:
            raise DeadlineError(
                f"receive(source={source}, tag={tag}) decode",
                self.optimeout)

    def notify_abort(self, code: int) -> None:
        """Failure propagation for ``api.abort()``: best-effort ABORT
        control frame to every live peer on both connections, so remote
        ranks raise :class:`RemoteAbortError` on their pending and
        future operations instead of discovering the death by timeout.
        Never raises — the caller is about to ``os._exit``."""
        if not self._initialized:
            return
        for peer in self._peers.values():
            if peer.dead is not None:
                continue
            for sock, lock in ((peer.dial_sock, peer.dial_lock),
                               (peer.listen_sock, peer.listen_lock)):
                if sock is None:
                    continue
                try:
                    if isinstance(sock, ShmConn):
                        _send_frame(sock, lock, KIND_ABORT, code)
                        continue
                    # Timed lock: a sibling thread wedged mid-sendall to
                    # this (possibly dead) peer must not stall the abort.
                    # If the lock can't be had, write anyway — worst
                    # case the interleaved bytes desync the stream and
                    # the peer sees a connection error, which also ends
                    # its pending ops.
                    acquired = lock.acquire(timeout=0.5)
                    try:
                        sock.sendall(_FRAME_HDR.pack(KIND_ABORT, code, 0))
                    finally:
                        if acquired:
                            lock.release()
                except Exception:  # noqa: BLE001 - dying anyway
                    pass

    def cancel_receive(self, source: int, tag: int) -> bool:
        """Best-effort cancellation of a pending receive (no reference
        analogue; supports :func:`mpi_tpu.api.exchange` cleanup). Returns
        False when the receive already completed or cannot be cancelled
        (self-receives with a sender already engaged)."""
        self._check_rank(source)
        exc = ReceiveCancelled(
            f"mpi_tpu: receive(source={source}, tag={tag}) cancelled")
        if source == self._rank:
            return self._local.cancel(tag, exc)
        return self._peers[source].receivetags.cancel(tag, exc)

    def iprobe(self, source: int, tag: int) -> bool:
        """Non-consuming MPI_Iprobe: True when a message from ``source``
        with ``tag`` is already available — its data frame arrived (the
        sender is blocked awaiting the rendezvous ack), or a self-send
        is parked at the local rendezvous."""
        self._check_rank(source)
        if source == self._rank:
            return self._local.probe(tag)
        return self._peers[source].receivetags.has_message(tag)

    # -- bootstrap ----------------------------------------------------------

    def _hello_payload(self) -> bytes:
        """HELLO body: the password, plus this side's advertised features
        when any are enabled. A feature-less HELLO is byte-identical to
        the pre-negotiation wire format, so with the flag off mixed
        versions interoperate transparently; mixed *configs* (crc on one
        side only) negotiate the feature off. Caveat: a peer predating
        feature negotiation entirely sees an advertising HELLO as a
        password mismatch — enable ``--mpi-crc`` only when every rank
        runs a feature-aware build."""
        pw = self.password.encode("utf-8")
        if self.crc:
            return pw + _FEATURE_SEP + _FEATURE_CRC.encode("ascii")
        return pw

    @staticmethod
    def _parse_hello(payload) -> Tuple[str, set]:
        """Split a HELLO body into (password, advertised feature set)."""
        raw = bytes(payload)
        if _FEATURE_SEP in raw:
            pw, _, feats = raw.partition(_FEATURE_SEP)
            return (pw.decode("utf-8"),
                    {f for f in feats.decode("utf-8").split(",") if f})
        return raw.decode("utf-8"), set()

    def _is_unix(self) -> bool:
        return self.proto == "unix"

    def _is_shm(self) -> bool:
        return self.proto == "shm"

    def _tune(self, sock: socket.socket) -> None:
        """Latency tuning where applicable (TCP only)."""
        if self.proto in ("tcp", "tcp4", "tcp6"):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _use_flags(self) -> None:
        """Explicit fields win over flags/env (network.go:69-90)."""
        fl = flagmod.get_flags()
        if self.proto is None:
            self.proto = fl.protocol or flagmod.DEFAULT_PROTOCOL
        if self.proto not in _SUPPORTED_PROTOS:
            raise InitError(
                f"mpi_tpu: unsupported -mpi-protocol {self.proto!r}; "
                f"supported: {', '.join(_SUPPORTED_PROTOS)}")
        if self.addr is None and fl.addr:
            self.addr = fl.addr
        if not self.addrs and fl.alladdr:
            self.addrs = list(fl.alladdr)
        if self.timeout is None:
            self.timeout = (fl.inittimeout if fl.inittimeout is not None
                            else flagmod.DEFAULT_INIT_TIMEOUT)
        if self.password is None:
            self.password = fl.password or ""
        if "\x00" in self.password:
            raise InitError("mpi_tpu: password must not contain NUL "
                            "bytes (reserved for HELLO feature "
                            "negotiation)")
        if self.optimeout is None:
            self.optimeout = fl.optimeout  # None = no deadline (default)
        if self.crc is None:
            self.crc = bool(fl.crc)
        # CRC protects byte streams; shm rings are process memory and
        # frame in the native engine — integrity there is a follow-on.
        if self._is_shm():
            self.crc = False
        if self._chaos is None and fl.chaos:
            self._chaos = fl.chaos
        if isinstance(self._chaos, str):
            from ..chaos import ChaosEngine, parse_chaos

            self._chaos = ChaosEngine(parse_chaos(self._chaos))

    def _assign_ranks(self) -> None:
        """Sorted-address consensus (network.go:94-118)."""
        if self.addr is None:
            if len(self.addrs) == 1:
                self.addr = self.addrs[0]
            else:
                raise InitError("mpi_tpu: own address unset with multiple addrs")
        ordered = sorted(self.addrs)
        for a, b in zip(ordered, ordered[1:]):
            if a == b:
                raise InitError(f"mpi_tpu: duplicate address {a!r} in addrs")
        try:
            self._rank = ordered.index(self.addr)
        except ValueError:
            raise InitError(
                f"mpi_tpu: own address {self.addr!r} not in addrs {ordered}") from None
        self._size = len(ordered)
        self.addrs = ordered

    def _start_connections(self) -> None:
        """Concurrent listen-side + dial-side all-to-all handshakes
        (network.go:122-159)."""
        n = self._size
        me = self._rank
        for r in range(n):
            if r != me:
                self._peers[r] = _Peer(r)
        if n == 1:
            return

        errors: List[str] = []
        err_lock = threading.Lock()

        def note(err: str) -> None:
            with err_lock:
                errors.append(err)

        if self._is_shm():
            self._shm_bootstrap(note)
        else:
            self._socket_bootstrap(note)

        if not errors:
            for peer in self._peers.values():
                if peer.dial_sock is None:
                    errors.append(f"rank {me}: no dial conn to {peer.rank}")
                if peer.listen_sock is None:
                    errors.append(f"rank {me}: no listen conn from {peer.rank}")
        if errors:
            self.finalize()
            raise InitError("; ".join(sorted(set(errors))))

        # Persistent readers (replace per-call goroutines; see module doc).
        for peer in self._peers.values():
            t1 = threading.Thread(target=self._dial_reader, args=(peer,),
                                  name=f"mpi-ackreader-{peer.rank}", daemon=True)
            t2 = threading.Thread(target=self._listen_reader, args=(peer,),
                                  name=f"mpi-datareader-{peer.rank}", daemon=True)
            peer.reader_threads = [t1, t2]
            t1.start()
            t2.start()

    def _socket_bootstrap(self, note) -> None:
        """TCP/unix all-to-all bootstrap: listen + dial handshakes
        (network.go:122-351). Populates peer dial/listen conns; errors
        go through ``note`` for aggregation."""
        n, me = self._size, self._rank
        # Listen side: accept n-1 peers, each validated by handshake.
        if self._is_unix():
            listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                # Clear a stale socket file from a crashed previous run;
                # a *live* conflicting listener still fails below, as the
                # reference's bind would.
                os.unlink(self.addr)
            except OSError:
                pass
            try:
                listener.bind(self.addr)
            except OSError as exc:
                raise InitError(
                    f"mpi_tpu: cannot listen on {self.addr!r}: {exc}"
                ) from exc
        else:
            host, port = _split_hostport(self.addr)
            family = (socket.AF_INET6 if self.proto == "tcp6"
                      else socket.AF_INET)
            listener = socket.socket(family, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                listener.bind((host, port))
            except OSError as exc:
                raise InitError(
                    f"mpi_tpu: cannot listen on {self.addr!r}: {exc}"
                ) from exc
        listener.listen(n)
        listener.settimeout(self.timeout)  # accept timeout (network.go:223-234)
        self._listener = listener

        accepted = threading.Semaphore(0)

        def listen_side() -> None:
            pending = n - 1
            while pending > 0:
                try:
                    conn, _ = listener.accept()
                except (socket.timeout, OSError) as exc:
                    note(f"rank {me}: accept failed/timed out: {exc}")
                    for _ in range(pending):
                        accepted.release()
                    return
                threading.Thread(target=listen_handshake, args=(conn,),
                                 daemon=True).start()
                pending -= 1

        def listen_handshake(conn: socket.socket) -> None:
            """network.go:211-263: read peer hello, validate, reply."""
            try:
                conn.settimeout(self.timeout)
                self._tune(conn)
                kind, claimed_id, payload = _recv_frame(conn)
                if kind != KIND_HELLO:
                    raise InitError(f"expected HELLO, got frame kind {kind}")
                their_pw, their_feats = self._parse_hello(payload)
                if their_pw != self.password:
                    raise InitError("password mismatch")  # network.go:344-347
                if not 0 <= claimed_id < n or claimed_id == me:
                    raise InitError(f"bad peer id {claimed_id}")  # network.go:348-350
                lock = threading.Lock()
                _send_frame(conn, lock, KIND_HELLO, me,
                            self._hello_payload())
                conn.settimeout(None)
                peer = self._peers[claimed_id]
                peer.listen_crc = bool(self.crc) and \
                    _FEATURE_CRC in their_feats
                peer.listen_sock = conn
                peer.listen_lock = lock
            except Exception as exc:  # noqa: BLE001 - aggregated, init fails
                note(f"rank {me}: listen handshake failed: {exc}")
                try:
                    conn.close()
                except OSError:
                    pass
            finally:
                accepted.release()

        def dial_handshake(peer_rank: int) -> None:
            """network.go:297-339: retry-dial peer, send hello, validate reply."""
            target = self.addrs[peer_rank]
            if not self._is_unix():
                target_host, target_port = _split_hostport(target)
            deadline = time.monotonic() + self.timeout
            sock: Optional[socket.socket] = None
            while True:
                try:
                    if self._is_unix():
                        sock = socket.socket(socket.AF_UNIX,
                                             socket.SOCK_STREAM)
                        sock.settimeout(self.timeout)
                        sock.connect(target)
                    else:
                        default_host = ("::1" if self.proto == "tcp6"
                                        else "localhost")
                        sock = socket.create_connection(
                            (target_host or default_host, target_port),
                            timeout=self.timeout)
                    break
                except OSError as exc:
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        sock = None
                    if time.monotonic() >= deadline:
                        note(f"rank {me}: dial {target!r} "
                             f"timed out: {exc}")
                        return
                    time.sleep(_DIAL_RETRY_INTERVAL)
            try:
                self._tune(sock)
                lock = threading.Lock()
                _send_frame(sock, lock, KIND_HELLO, me,
                            self._hello_payload())
                sock.settimeout(self.timeout)
                kind, their_id, payload = _recv_frame(sock)
                if kind != KIND_HELLO:
                    raise InitError(f"expected HELLO reply, got kind {kind}")
                their_pw, their_feats = self._parse_hello(payload)
                if their_pw != self.password:
                    raise InitError("password mismatch in reply")
                if their_id != peer_rank:
                    raise InitError(
                        f"dialed rank {peer_rank} but peer claims {their_id}")
                sock.settimeout(None)
                peer = self._peers[peer_rank]
                peer.dial_crc = bool(self.crc) and \
                    _FEATURE_CRC in their_feats
                peer.dial_sock = sock
                peer.dial_lock = lock
            except Exception as exc:  # noqa: BLE001
                note(f"rank {me}: dial handshake with rank {peer_rank} "
                     f"failed: {exc}")
                try:
                    sock.close()
                except OSError:
                    pass

        lt = threading.Thread(target=listen_side, daemon=True)
        lt.start()
        dial_threads = [threading.Thread(target=dial_handshake, args=(r,),
                                         daemon=True)
                        for r in range(n) if r != me]
        for t in dial_threads:
            t.start()
        for t in dial_threads:
            t.join()
        lt.join()
        for _ in range(n - 1):
            accepted.acquire()

    def _shm_bootstrap(self, note) -> None:
        """All-to-all bootstrap over shared-memory rings (proto ``shm``).

        Same shape as the socket bootstrap: for conn ``a -> me`` the
        listen side *creates* the ring pair and validates the dialer's
        HELLO; the dial side *attaches* with the 100 ms retry loop until
        the init timeout and validates the reply (network.go:198-263,
        294-351). The session-keyed ring names are themselves the
        rendezvous points, so there is no listener socket; a stale ring
        from a crashed run is unlinked at create time, like the unix
        bootstrap's stale socket file. HELLO still carries the password
        and claimed rank for reference parity, though the key already
        binds both (backends/shm.py module doc)."""
        from .shm import (attach_ring, create_ring, ring_capacity,
                          ring_name, session_key)

        n, me = self._size, self._rank
        key = session_key(self.addrs, self.password)
        cap = ring_capacity()

        def listen_handshake(peer_rank: int) -> None:
            names = (ring_name(key, peer_rank, me, "d"),
                     ring_name(key, peer_rank, me, "r"))
            conn: Optional[ShmConn] = None
            rx = tx = None
            try:
                rx = create_ring(names[0], cap)   # dialer's frames to me
                tx = create_ring(names[1], cap)   # my replies out
                conn = ShmConn(tx, rx, owned_names=names)
                conn.settimeout(self.timeout)
                kind, claimed_id, payload = _recv_frame(conn)
                if kind != KIND_HELLO:
                    raise InitError(f"expected HELLO, got frame kind {kind}")
                if self._parse_hello(payload)[0] != self.password:
                    raise InitError("password mismatch")  # network.go:344-347
                if claimed_id != peer_rank:
                    raise InitError(
                        f"ring pair for rank {peer_rank} got HELLO "
                        f"claiming rank {claimed_id}")
                lock = threading.Lock()
                _send_frame(conn, lock, KIND_HELLO, me,
                            self.password.encode("utf-8"))
                conn.settimeout(None)
                peer = self._peers[peer_rank]
                peer.listen_sock = conn
                peer.listen_lock = lock
            except Exception as exc:  # noqa: BLE001 - aggregated, init fails
                note(f"rank {me}: shm listen handshake with rank "
                     f"{peer_rank} failed: {exc}")
                if conn is not None:
                    conn.close()
                    conn.release()  # no reader threads exist yet
                else:
                    # Partial creation: close and unlink whatever ring
                    # exists, or the named /dev/shm object outlives the
                    # process (POSIX shm survives exit).
                    from .shm import unlink_ring
                    for ring in (rx, tx):
                        if ring is not None:
                            ring.mark_closed()
                            ring.close()
                    for name in names:
                        unlink_ring(name)

        def dial_handshake(peer_rank: int) -> None:
            names = (ring_name(key, me, peer_rank, "d"),
                     ring_name(key, me, peer_rank, "r"))
            deadline = time.monotonic() + self.timeout
            tx = rx = None
            try:
                while tx is None or rx is None:
                    if tx is None:
                        tx = attach_ring(names[0])
                    if tx is not None and rx is None:
                        rx = attach_ring(names[1])
                    if tx is not None and rx is not None:
                        break
                    if time.monotonic() >= deadline:
                        raise InitError("timed out waiting for rings")
                    time.sleep(_DIAL_RETRY_INTERVAL)
            except Exception as exc:  # noqa: BLE001 - aggregated, init fails
                # Route unexpected attach errors (EACCES on a stale
                # ring, ...) through note() like every other handshake
                # path, instead of dying silently in the thread.
                note(f"rank {me}: shm dial to rank {peer_rank} "
                     f"failed: {exc}")
                for ring in (tx, rx):
                    if ring is not None:
                        ring.close()
                return
            conn = ShmConn(tx, rx)  # listener owns/unlinks the names
            try:
                # Timeout BEFORE the HELLO send (as the listen side does):
                # a nearly-full stale ring attached in the unlink/recreate
                # window would otherwise block the write forever and hang
                # init past its deadline.
                conn.settimeout(self.timeout)
                lock = threading.Lock()
                _send_frame(conn, lock, KIND_HELLO, me,
                            self.password.encode("utf-8"))
                kind, their_id, payload = _recv_frame(conn)
                if kind != KIND_HELLO:
                    raise InitError(f"expected HELLO reply, got kind {kind}")
                if self._parse_hello(payload)[0] != self.password:
                    raise InitError("password mismatch in reply")
                if their_id != peer_rank:
                    raise InitError(
                        f"dialed rank {peer_rank} but peer claims {their_id}")
                conn.settimeout(None)
                peer = self._peers[peer_rank]
                peer.dial_sock = conn
                peer.dial_lock = lock
            except Exception as exc:  # noqa: BLE001
                note(f"rank {me}: shm dial handshake with rank {peer_rank} "
                     f"failed: {exc}")
                conn.close()
                conn.release()  # no reader threads exist yet

        threads = [threading.Thread(target=listen_handshake, args=(r,),
                                    daemon=True)
                   for r in range(n) if r != me]
        threads += [threading.Thread(target=dial_handshake, args=(r,),
                                     daemon=True)
                    for r in range(n) if r != me]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    # -- data path ----------------------------------------------------------

    def _dial_reader(self, peer: _Peer) -> None:
        """Reads the peer's acks off my dial conn → unblocks my sends
        (the ack-reader goroutine of network.go:551-559)."""
        try:
            while not self._closed.is_set():
                kind, tag, _ = _recv_frame(peer.dial_sock)
                if kind == KIND_ABORT:
                    raise RemoteAbortError(peer.rank, tag)
                if kind != KIND_ACK:
                    raise MpiError(f"unexpected frame kind {kind} on dial conn")
                peer.sendtags.route(tag, True)
        except RemoteAbortError as exc:
            self._mark_job_aborted(exc)
        except (ConnectionError, OSError, MpiError) as exc:
            self._mark_peer_dead(peer, exc)

    def _listen_reader(self, peer: _Peer) -> None:
        """Reads the peer's data frames off my listen conn → routes by tag
        (``receiveReader``, network.go:607-625; ack deferred to receive())."""
        try:
            while not self._closed.is_set():
                kind, tag, payload = _recv_frame(peer.listen_sock,
                                                 crc=peer.listen_crc,
                                                 src=peer.rank)
                if kind == KIND_ABORT:
                    raise RemoteAbortError(peer.rank, tag)
                if kind != KIND_DATA:
                    raise MpiError(f"unexpected frame kind {kind} on listen conn")
                peer.receivetags.route(tag, payload)
        except RemoteAbortError as exc:
            self._mark_job_aborted(exc)
        except ChecksumError as exc:
            # Deliver the integrity failure to the receive it damages
            # first (so that call raises the attributable ERR_TRUNCATE
            # error), then retire the connection — after corruption the
            # framing cannot be trusted. Other pending/future ops on
            # this peer see peer-death (ERR_PENDING), not a ChecksumError
            # naming another operation's tag.
            peer.receivetags.route(exc.tag, exc)
            self._mark_peer_dead(peer, PeerDeadError(peer.rank, exc))
        except (ConnectionError, OSError, MpiError) as exc:
            self._mark_peer_dead(peer, exc)

    def _mark_job_aborted(self, exc: "RemoteAbortError") -> None:
        """A remote rank aborted: the whole job is over, not just one
        link — every peer's pending and future operations raise the
        abort error (MPI_Abort terminates the communicator, not an
        edge). Under ``mpirun`` the launcher reaps this process moments
        later; in-process harnesses see the typed error instead."""
        for p in self._peers.values():
            self._mark_peer_dead(p, exc)

    def _mark_peer_dead(self, peer: _Peer, exc: BaseException) -> None:
        """On connection loss (either direction's reader died) the whole
        peer is dead: fail all pending *and future* ops targeting it
        instead of hanging (replaces the reference's reader panics,
        network.go:555,611). Ops already blocked get the exception via
        their slot; ops issued after the loss fail at claim(). Raw
        socket errors are wrapped in :class:`PeerDeadError` so callers
        always see a typed, classifiable MpiError."""
        if self._closed.is_set():
            exc = MpiError("mpi_tpu: network finalized")
        elif not isinstance(exc, MpiError):
            exc = PeerDeadError(peer.rank, exc)
        # Poison with the FIRST cause of death: the sibling reader dying
        # of this call's own cross-close must not rebrand the failure.
        with peer.dead_lock:
            if peer.dead is None:
                peer.dead = exc
            exc = peer.dead
        peer.sendtags.poison(exc)
        peer.receivetags.poison(exc)
        # Drop both connections: the PEER's readers then observe EOF and
        # mark us dead too, so its blocked ops (e.g. the ack wait of the
        # send whose frame failed our CRC check) fail fast instead of
        # hanging until a deadline that may not be configured. During
        # finalize the sockets are being closed anyway; re-closing is a
        # no-op. The sibling reader of this conn pair wakes with a
        # ConnectionError and re-enters here idempotently.
        if not self._closed.is_set():
            for sock in (peer.dial_sock, peer.listen_sock):
                if sock is None:
                    continue
                try:
                    if not isinstance(sock, ShmConn):
                        sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass

    def _check_rank(self, r: int) -> None:
        if self._size is None:
            raise MpiError("mpi_tpu: send/receive before init()")
        if not 0 <= r < self._size:
            raise MpiError(f"mpi_tpu: peer rank {r} out of range [0, {self._size})")

"""Backend drivers implementing the :class:`mpi_tpu.api.Interface` SPI.

``tcp`` — faithful rebuild of the reference's all-to-all TCP ``Network``
(network.go); the CPU fallback and bitwise-parity oracle.

``xla`` — the TPU-native driver: ranks are device-mesh positions and
communication lowers to XLA collectives over ICI/DCN (imported lazily —
importing this package must not import jax).
"""

"""Shared-memory transport engine — the ``shm`` protocol's data plane.

The reference's ``NetProto`` field accepts any ``net``-package protocol
(/root/reference/network.go:26); ranks on one machine still pay the full
TCP stack. This engine is the rebuild's native answer for that case:
``-mpi-protocol shm`` keeps the driver's semantics (same frame stream,
same handshake, same rendezvous acks — backends/tcp.py) but carries the
frames through single-producer/single-consumer byte rings in POSIX
shared memory, implemented in C++ (native/shmcore.cpp) with futex
blocking and a spin fast path. Payloads larger than a ring stream
through it chunk-by-chunk (the reader drains while the writer fills),
so ring capacity bounds memory, not message size.

Addressing: with ``shm`` the ``-mpi-addr``/``-mpi-alladdr`` values are
arbitrary unique identifiers (they never hit the network); rank
assignment is still the sorted-address consensus (network.go:94-109).
Ring names are derived from a session key — a hash of the sorted
address list and the password — so concurrent shm worlds on one machine
cannot collide, and a wrong-password dialer simply finds no rings (the
HELLO password check still runs for defense in depth and reference
parity, network.go:343-351).

Topology per ordered rank pair ``a -> b`` (the conn ``a`` dials):

    ring "<key>-<a>to<b>-d"   a's frames to b   (created by b, the listener)
    ring "<key>-<a>to<b>-r"   b's frames to a   (created by b)

Each :class:`ShmConn` wraps one such ring pair; the TCP driver stores
it where a socket would go (``peer.dial_sock`` / ``peer.listen_sock``)
and the frame helpers dispatch on the type.

A pure-Python fallback ring (:class:`_PyRing`) speaks the identical
memory layout via ``mmap`` with sleep-polling, used when the native
library is unavailable (``MPI_TPU_NO_NATIVE=1``, no compiler). The
native side's futex waits are bounded (2 ms) precisely so a Python
peer — which never issues futex wakes — costs at most that latency,
never a hang. Mixing native and fallback processes in one world is
supported **on x86-64 only**: the fallback publishes head/tail with
plain mmap stores, which x86's total-store-order makes visible after
the preceding payload bytes, but a weakly-ordered CPU (aarch64) could
reorder them and a *native* peer might then read a torn frame. On
non-x86 hosts run the world all-native or all-fallback (homogeneous
installs do this naturally; the fallback-vs-fallback pairing is safe
everywhere because both sides poll whole values).
"""

from __future__ import annotations

import ctypes
import errno as _errno
import hashlib
import mmap
import os
import socket
import struct
import time
from typing import List, Optional, Tuple, Union

from ..api import MpiError
from .. import native as _native

__all__ = ["ShmConn", "ring_name", "session_key", "create_ring",
           "attach_ring", "unlink_ring", "DEFAULT_RING_BYTES"]

DEFAULT_RING_BYTES = 1 << 20

_FRAME_HDR = struct.Struct("<BqI")

# Mirror of native/shmcore.cpp RingHdr field offsets (alignas(64)):
_OFF_MAGIC = 0       # u32
_OFF_CAPACITY = 4    # u32
_OFF_READY = 8       # u32
_OFF_CLOSED = 12     # u32
_OFF_HEAD = 64       # u64 bytes produced
_OFF_WSEQ = 72       # u32 producer progress counter
_OFF_TAIL = 128      # u64 bytes consumed
_OFF_RSEQ = 136      # u32 consumer progress counter
_HDR_BYTES = 4096
_MAGIC = 0x524D4853

_POLL_S = 50e-6      # fallback ring sleep-poll interval


def session_key(addrs: List[str], password: str) -> str:
    """16-hex-char key shared by all ranks of one world (the sorted
    address list is the world's identity, network.go:94-109; the
    password folds in so a mismatched world cannot attach)."""
    h = hashlib.sha256()
    h.update("\x00".join(sorted(addrs)).encode())
    h.update(b"\x01")
    h.update(password.encode())
    return h.hexdigest()[:16]


def ring_name(key: str, src: int, dst: int, role: str) -> str:
    """POSIX shm object name for one ring of conn ``src -> dst``.
    ``role``: ``"d"`` = dialer's frames, ``"r"`` = listener's replies."""
    return f"/mpitpu-{key}-{src}to{dst}{role}"


def ring_capacity() -> int:
    try:
        return max(1 << 12, int(os.environ.get("MPI_TPU_SHM_RING_BYTES",
                                               DEFAULT_RING_BYTES)))
    except ValueError:
        return DEFAULT_RING_BYTES


# --------------------------------------------------------------------------
# Pure-Python fallback ring (same layout; sleep-polling instead of futex)
# --------------------------------------------------------------------------

class _PyRing:
    """One ring endpoint over ``mmap`` — byte-compatible with the native
    engine. u64 counters are written as single aligned 8-byte stores
    (atomic on every platform CPython runs on in practice); the seq
    words are bumped so a *native* peer's bounded futex wait re-checks
    promptly."""

    def __init__(self, fd: int, mm: mmap.mmap, name: str):
        self._fd = fd
        self._mm = mm
        self.name = name
        self.capacity = struct.unpack_from("<I", mm, _OFF_CAPACITY)[0]

    # -- shared-field accessors --------------------------------------------

    def _u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._mm, off)[0]

    def _set_u64(self, off: int, v: int) -> None:
        struct.pack_into("<Q", self._mm, off, v)

    def _bump_u32(self, off: int) -> None:
        v = struct.unpack_from("<I", self._mm, off)[0]
        struct.pack_into("<I", self._mm, off, (v + 1) & 0xFFFFFFFF)

    def _closed(self) -> bool:
        return struct.unpack_from("<I", self._mm, _OFF_CLOSED)[0] != 0

    # -- ops ----------------------------------------------------------------

    def mark_closed(self) -> None:
        struct.pack_into("<I", self._mm, _OFF_CLOSED, 1)
        self._bump_u32(_OFF_WSEQ)
        self._bump_u32(_OFF_RSEQ)

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
        try:
            os.close(self._fd)
        except OSError:
            pass

    def write(self, data: memoryview, deadline: Optional[float]) -> None:
        cap = self.capacity
        done = 0
        n = len(data)
        while done < n:
            if self._closed():
                raise ConnectionError("shm ring closed by peer")
            head = self._u64(_OFF_HEAD)
            tail = self._u64(_OFF_TAIL)
            space = cap - (head - tail)
            if space == 0:
                if deadline is not None and time.monotonic() > deadline:
                    raise socket.timeout("shm ring write timed out")
                time.sleep(_POLL_S)
                continue
            chunk = min(space, n - done)
            off = head % cap
            first = min(chunk, cap - off)
            self._mm[_HDR_BYTES + off:_HDR_BYTES + off + first] = \
                data[done:done + first]
            if chunk > first:
                self._mm[_HDR_BYTES:_HDR_BYTES + chunk - first] = \
                    data[done + first:done + chunk]
            self._set_u64(_OFF_HEAD, head + chunk)
            self._bump_u32(_OFF_WSEQ)
            done += chunk

    def read_into(self, buf: bytearray, start: int, n: int,
                  deadline: Optional[float]) -> None:
        cap = self.capacity
        done = 0
        view = memoryview(buf)
        while done < n:
            head = self._u64(_OFF_HEAD)
            tail = self._u64(_OFF_TAIL)
            avail = head - tail
            if avail == 0:
                if self._closed() and self._u64(_OFF_HEAD) == tail:
                    raise ConnectionError("connection closed by peer")
                if deadline is not None and time.monotonic() > deadline:
                    raise socket.timeout("shm ring read timed out")
                time.sleep(_POLL_S)
                continue
            chunk = min(avail, n - done)
            off = tail % cap
            first = min(chunk, cap - off)
            view[start + done:start + done + first] = \
                self._mm[_HDR_BYTES + off:_HDR_BYTES + off + first]
            if chunk > first:
                view[start + done + first:start + done + chunk] = \
                    self._mm[_HDR_BYTES:_HDR_BYTES + chunk - first]
            self._set_u64(_OFF_TAIL, tail + chunk)
            self._bump_u32(_OFF_RSEQ)
            done += chunk


class _NativeRing:
    """One ring endpoint backed by native/shmcore.cpp via ctypes."""

    def __init__(self, handle: ctypes.c_void_p, name: str):
        self._h = handle
        self.name = name

    def mark_closed(self) -> None:
        _native.shmcore().shm_ring_mark_closed(self._h)

    def close(self) -> None:
        _native.shmcore().shm_ring_close(self._h)


def _shm_dir() -> str:
    return "/dev/shm"


def _py_path(name: str) -> str:
    # shm_open("/x") maps to /dev/shm/x — the fallback uses the same
    # files so native and fallback processes interoperate.
    return os.path.join(_shm_dir(), name.lstrip("/"))


def create_ring(name: str, capacity: int) -> Union[_NativeRing, _PyRing]:
    """Create (as listener) one ring; clears any stale object first, as
    the unix-socket bootstrap clears a stale socket file."""
    lib = _native.shmcore()
    if lib is not None:
        lib.shm_ring_unlink(name.encode())
        out = ctypes.c_void_p()
        rc = lib.shm_ring_create(name.encode(), capacity, ctypes.byref(out))
        if rc != 0:
            raise MpiError(f"mpi_tpu: shm ring create {name!r} failed: "
                           f"{os.strerror(-rc)}")
        return _NativeRing(out, name)
    path = _py_path(name)
    try:
        os.unlink(path)
    except OSError:
        pass
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
    try:
        os.ftruncate(fd, _HDR_BYTES + capacity)
        mm = mmap.mmap(fd, _HDR_BYTES + capacity)
        struct.pack_into("<I", mm, _OFF_CAPACITY, capacity)
        for off in (_OFF_HEAD, _OFF_TAIL):
            struct.pack_into("<Q", mm, off, 0)
        for off in (_OFF_WSEQ, _OFF_RSEQ, _OFF_CLOSED):
            struct.pack_into("<I", mm, off, 0)
        struct.pack_into("<I", mm, _OFF_MAGIC, _MAGIC)
        struct.pack_into("<I", mm, _OFF_READY, 1)
    except BaseException:
        os.close(fd)
        try:
            os.unlink(path)
        except OSError:
            pass
        raise
    return _PyRing(fd, mm, name)


def attach_ring(name: str) -> Optional[Union[_NativeRing, _PyRing]]:
    """One attach attempt (as dialer); None when the ring does not exist
    or is not initialized yet — the caller retries until its timeout
    (the 100 ms dial-retry loop, network.go:297-312)."""
    lib = _native.shmcore()
    if lib is not None:
        out = ctypes.c_void_p()
        rc = lib.shm_ring_attach(name.encode(), ctypes.byref(out))
        if rc == 0:
            return _NativeRing(out, name)
        if rc in (-_errno.ENOENT, -_errno.EAGAIN):
            return None
        raise MpiError(f"mpi_tpu: shm ring attach {name!r} failed: "
                       f"{os.strerror(-rc)}")
    path = _py_path(name)
    try:
        fd = os.open(path, os.O_RDWR)
    except FileNotFoundError:
        return None
    try:
        size = os.fstat(fd).st_size
        if size < _HDR_BYTES:
            os.close(fd)
            return None
        mm = mmap.mmap(fd, size)
    except OSError:
        os.close(fd)
        return None
    magic, = struct.unpack_from("<I", mm, _OFF_MAGIC)
    ready, = struct.unpack_from("<I", mm, _OFF_READY)
    cap, = struct.unpack_from("<I", mm, _OFF_CAPACITY)
    if magic != _MAGIC or ready != 1 or size < _HDR_BYTES + cap:
        mm.close()
        os.close(fd)
        return None
    return _PyRing(fd, mm, name)


def unlink_ring(name: str) -> None:
    lib = _native.shmcore()
    if lib is not None:
        lib.shm_ring_unlink(name.encode())
        return
    try:
        os.unlink(_py_path(name))
    except OSError:
        pass


# --------------------------------------------------------------------------
# Frame connection over a ring pair
# --------------------------------------------------------------------------

class ShmConn:
    """Bidirectional frame connection: ``tx`` carries this side's
    frames, ``rx`` the peer's. Duck-types the slice of the socket API
    the TCP driver uses (``settimeout``/``close``); the driver's frame
    helpers dispatch here for the actual I/O. One sender at a time per
    conn (the driver's per-conn write lock) and one reader (the
    persistent reader thread) — exactly the SPSC contract the rings
    require."""

    def __init__(self, tx, rx, owned_names: Tuple[str, ...] = ()):
        self._tx = tx
        self._rx = rx
        self.owned_names = owned_names  # rings this side created → unlink
        self._timeout: Optional[float] = None
        self._released = False

    # -- socket-API slice ---------------------------------------------------

    def settimeout(self, t: Optional[float]) -> None:
        self._timeout = t

    def gettimeout(self) -> Optional[float]:
        return self._timeout

    def shutdown(self, _how: int = 0) -> None:
        self._tx.mark_closed()
        self._rx.mark_closed()

    def close(self) -> None:
        """Mark both rings closed and wake any blocked peer/reader.

        Deliberately does NOT unmap: a reader thread blocked inside the
        native recv dereferences the mapping, so tearing it down here
        would be a use-after-munmap. The driver calls :meth:`release`
        after joining its reader threads."""
        self._tx.mark_closed()
        self._rx.mark_closed()

    def release(self) -> None:
        """Unmap the rings and unlink owned names. Only safe once no
        thread can be inside this conn's frame ops (readers joined)."""
        if self._released:
            return
        self._released = True
        self._tx.close()
        self._rx.close()
        for name in self.owned_names:
            unlink_ring(name)

    # -- frame I/O ----------------------------------------------------------

    def _deadline(self) -> Optional[float]:
        return None if self._timeout is None \
            else time.monotonic() + self._timeout

    @staticmethod
    def _remaining_ms(deadline: Optional[float], what: str) -> int:
        """Milliseconds left until ``deadline`` (-1 = infinite). The
        deadline is computed ONCE per frame op and only the remainder
        is passed on each EINTR resume — restarting the full timeout
        per resume would let any periodic signal (SIGCHLD from the
        launcher, profiling timers) extend the deadline forever."""
        if deadline is None:
            return -1
        left = deadline - time.monotonic()
        if left <= 0:
            raise socket.timeout(f"shm {what} timed out")
        return max(1, int(left * 1000))

    def _native_send(self, what: str, call) -> None:
        """Run one resumable native ring op to completion: EINTR
        resumes (returning to the interpreter so pending Python signal
        handlers run between resumes), a Python-side deadline expiry
        abandons the op exactly like a native -ETIMEDOUT would
        (poisoning if that strands the stream mid-frame), and native
        rc values map to the same exceptions everywhere. ``call`` is
        ``(lib, timeout_ms) -> rc``."""
        lib = _native.shmcore()
        deadline = self._deadline()
        try:
            while True:
                rc = call(lib, self._remaining_ms(deadline, what))
                if rc != -_errno.EINTR:
                    break
        except socket.timeout:
            lib.shm_abandon(self._tx._h, 0)
            raise
        if rc == _native.PEER_CLOSED:
            raise ConnectionError("shm ring closed by peer")
        if rc == -_errno.ETIMEDOUT:
            raise socket.timeout(f"shm {what} timed out")
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc))

    def send_frame(self, kind: int, tag: int, payload: bytes = b"") -> None:
        if len(payload) > 0xFFFFFFFF:
            # The wire length field is u32; ctypes would silently
            # truncate (the TCP path's struct.pack raises — match it).
            raise MpiError(
                f"mpi_tpu: shm frame payload of {len(payload)} bytes "
                f"exceeds the u32 wire limit")
        tx = self._tx
        if isinstance(tx, _NativeRing):
            buf = bytes(payload) if not isinstance(payload, bytes) else payload
            self._native_send("send", lambda lib, ms: lib.shm_send_frame(
                tx._h, kind, tag, buf, len(buf), ms))
            return
        deadline = self._deadline()
        header = _FRAME_HDR.pack(kind, tag, len(payload))
        tx.write(memoryview(header), deadline)
        if payload:
            tx.write(memoryview(payload), deadline)

    def send_frame2(self, kind: int, tag: int, prefix: bytes,
                    view) -> None:
        """One frame whose body is ``prefix + view``, streamed without
        concatenation — the shm side of the codec's zero-copy ndarray
        path (``encode_parts``). The receiver sees an ordinary frame
        of the combined length."""
        mv = memoryview(view).cast("B")
        total = len(prefix) + mv.nbytes
        if total > 0xFFFFFFFF:
            raise MpiError(
                f"mpi_tpu: shm frame payload of {total} bytes "
                f"exceeds the u32 wire limit")
        tx = self._tx
        if isinstance(tx, _NativeRing):
            from .tcp import _view_cptr

            ptr, keep = _view_cptr(mv)
            try:
                self._native_send(
                    "send", lambda lib, ms: lib.shm_send_frame2(
                        tx._h, kind, tag, prefix, len(prefix),
                        ptr, mv.nbytes, ms))
            finally:
                del keep
            return
        deadline = self._deadline()
        header = _FRAME_HDR.pack(kind, tag, total)
        tx.write(memoryview(header), deadline)
        if prefix:
            tx.write(memoryview(prefix), deadline)
        if mv.nbytes:
            tx.write(mv, deadline)

    def recv_frame(self) -> Tuple[int, int, bytearray]:
        rx = self._rx
        if isinstance(rx, _NativeRing):
            lib = _native.shmcore()
            kind = ctypes.c_uint8()
            tag = ctypes.c_int64()
            length = ctypes.c_uint32()
            deadline = self._deadline()
            try:
                while True:
                    rc = lib.shm_recv_hdr(
                        rx._h, ctypes.byref(kind), ctypes.byref(tag),
                        ctypes.byref(length),
                        self._remaining_ms(deadline, "recv header"))
                    if rc != -_errno.EINTR:
                        break
            except socket.timeout:
                lib.shm_abandon(rx._h, 0)  # poison only if mid-header
                raise
            self._check_rc(rc, "recv header")
            n = length.value
            payload = bytearray(n)
            if n:
                arr = (ctypes.c_ubyte * n).from_buffer(payload)
                try:
                    while True:
                        rc = lib.shm_recv_payload(
                            rx._h, arr, n,
                            self._remaining_ms(deadline, "recv payload"))
                        if rc != -_errno.EINTR:
                            break
                except socket.timeout:
                    # mid-frame by definition: the header announcing
                    # this payload was already consumed (force=1).
                    lib.shm_abandon(rx._h, 1)
                    raise
                self._check_rc(rc, "recv payload")
            return kind.value, tag.value, payload
        deadline = None if self._timeout is None \
            else time.monotonic() + self._timeout
        hdr = bytearray(_FRAME_HDR.size)
        rx.read_into(hdr, 0, _FRAME_HDR.size, deadline)
        kind_v, tag_v, length_v = _FRAME_HDR.unpack(bytes(hdr))
        payload = bytearray(length_v)
        if length_v:
            rx.read_into(payload, 0, length_v, deadline)
        return kind_v, tag_v, payload

    @staticmethod
    def _check_rc(rc: int, what: str) -> None:
        if rc == 0:
            return
        if rc == _native.PEER_CLOSED:
            raise ConnectionError("connection closed by peer")
        if rc == -_errno.ETIMEDOUT:
            raise socket.timeout(f"shm {what} timed out")
        raise OSError(-rc, os.strerror(-rc))

"""Transport-agnostic rendezvous primitives shared by the drivers.

Extracted from the TCP driver so the XLA driver's in-process rank threads
reuse exactly the same tag bookkeeping and first-arrival-creates handoff
semantics (network.go:371-446, 449-497) — one implementation, one set of
misuse-detection rules, every backend.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..api import MpiError, TagError

__all__ = ["Cancel", "DeadlineError", "ReceiveCancelled", "TagManager",
           "Rendezvous"]


class ReceiveCancelled(MpiError):
    """A pending receive was cancelled via ``cancel_receive`` (used by
    :func:`mpi_tpu.api.exchange` to clean up after a failed send)."""


class DeadlineError(MpiError):
    """A blocking operation exceeded the ``--mpi-optimeout`` deadline.

    MPI class ``ERR_PENDING``: the operation did not complete — the peer
    is presumed dead or wedged. After a deadline expires the ``{peer,
    tag}`` channel is indeterminate (a late ack/payload may still arrive
    and be mis-matched to a later claim of the same tag); callers should
    treat the peer as failed rather than retry on the same tag."""

    def __init__(self, op: str, timeout: float):
        super().__init__(
            f"mpi_tpu: {op} exceeded the {timeout:g}s operation deadline "
            f"(--mpi-optimeout); peer presumed dead or wedged "
            f"(MPI_ERR_PENDING)")


class Cancel:
    """Cancellation token routed into a tag slot. Carries the claim
    generation it targets so a token that loses a race with real data
    cannot poison a *later* claim of the same tag."""

    def __init__(self, gen: int, exc: BaseException):
        self.gen = gen
        self.exc = exc


class TagManager:
    """Per-direction, per-peer tag → slot map with misuse detection.

    Rebuild of ``tagManager`` (network.go:449-497): a duplicate live tag is
    an error (the reference panics, network.go:469); early arrivals for
    unregistered tags are buffered; cancellation is generation-tagged."""

    def __init__(self, direction: str, peer: int):
        self._direction = direction
        self._peer = peer
        self._lock = threading.Lock()
        self._slots: Dict[int, queue.Queue] = {}
        self._claimed: set = set()
        self._gen: Dict[int, int] = {}
        self._dead: Optional[BaseException] = None

    def claim(self, tag: int) -> Tuple[queue.Queue, int]:
        """Register a live caller-side use of ``tag`` (send or receive).
        Returns the slot and this claim's generation.

        A poisoned direction still honors already-buffered traffic for
        the tag: a payload routed before the death is deliverable, and a
        routed per-tag failure (e.g. the ChecksumError for the exact
        frame that killed the conn) is more attributable than the
        generic poison — wait() drains the slot either way."""
        with self._lock:
            if self._dead is not None:
                q = self._slots.get(tag)
                if q is None or q.empty():
                    raise self._dead
            if tag in self._claimed:
                raise TagError(tag, self._peer, self._direction)
            self._claimed.add(tag)
            gen = self._gen.get(tag, 0) + 1
            self._gen[tag] = gen
            return self._slots.setdefault(tag, queue.Queue()), gen

    def cancel(self, tag: int, exc: BaseException) -> bool:
        """Best-effort cancel of the live claim on ``tag``.

        MPI's contract: a successful cancel means NO part of the
        message was received — so a claim whose sender's data frame
        has already been routed into the slot is NOT cancellable
        (ADVICE.md round 5): return False and let ``wait()`` deliver
        the payload. (The token-vs-payload race that remains —
        payload routed after this check — is resolved by the waiter:
        a delivered payload wins over a stale token, and
        ``api.Request.wait`` clears ``cancelled`` when data arrives.)"""
        with self._lock:
            if tag not in self._claimed:
                return False
            q = self._slots.setdefault(tag, queue.Queue())
            with q.mutex:
                if any(not isinstance(item, (Cancel, BaseException))
                       for item in q.queue):
                    return False  # message (partly) received already
            gen = self._gen.get(tag, 0)
        q.put(Cancel(gen, exc))
        return True

    def release(self, tag: int) -> None:
        with self._lock:
            self._claimed.discard(tag)
            q = self._slots.get(tag)
            if q is not None and q.empty():
                del self._slots[tag]

    def has_message(self, tag: int) -> bool:
        """Non-consuming probe: a real payload (not a cancellation
        token) is buffered for ``tag`` — on this transport a message is
        'available' exactly when the sender's frame has already arrived.
        A poisoned direction (peer died) or a buffered routed failure
        RAISES instead of returning False: the matching receive would
        raise immediately, and a blocking probe polling a dead link
        would otherwise spin forever."""
        with self._lock:
            dead = self._dead
            q = self._slots.get(tag)
        if q is not None:
            with q.mutex:
                items = list(q.queue)
            if any(not isinstance(item, (Cancel, BaseException))
                   for item in items):
                return True
            for item in items:
                if isinstance(item, BaseException):
                    raise item
        if dead is not None:
            raise dead
        return False

    def route(self, tag: int, item: Any) -> None:
        """Deliver an inbound item to the tag's slot (creating it if the
        matching call hasn't arrived yet)."""
        with self._lock:
            q = self._slots.setdefault(tag, queue.Queue())
        q.put(item)

    def poison(self, exc: BaseException) -> None:
        """Fail all pending and future operations on this direction.

        First poison wins: a second reader dying of the cross-close
        fallout must not overwrite the original (more attributable)
        cause of death."""
        with self._lock:
            if self._dead is None:
                self._dead = exc
            else:
                exc = self._dead
            slots = list(self._slots.values())
        for q in slots:
            q.put(exc)

    def wait(self, slot: queue.Queue, gen: int,
             timeout: Optional[float] = None,
             op: str = "operation") -> Any:
        """Block on ``slot`` for data, handling cancellation tokens and
        routed exceptions. Returns the payload.

        With ``timeout`` (seconds — the ``--mpi-optimeout`` plumbing) a
        slot that stays empty past the deadline raises
        :class:`DeadlineError` instead of blocking forever; ``op`` names
        the operation in the error message."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                if deadline is None:
                    item = slot.get()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        # Deadline lapsed — but an already-delivered item
                        # (payload behind a just-drained stale Cancel,
                        # or timeout=0) must still win over the error.
                        item = slot.get_nowait()
                    else:
                        item = slot.get(timeout=remaining)
            except queue.Empty:
                raise DeadlineError(op, timeout) from None
            if isinstance(item, Cancel):
                if item.gen == gen:
                    raise item.exc
                continue  # stale token from an earlier claim — drop
            if isinstance(item, BaseException):
                raise item
            return item


class Rendezvous:
    """Blocking first-arrival-creates handoff between one sender side and
    one receiver side, keyed by tag (network.go:371-446).

    Used for the self-send path in the TCP driver and for every rank pair
    in the in-process XLA driver. A second arrival from the *same* side
    while an entry is live is the misuse the reference panics on
    (network.go:417,435) — here it raises :class:`TagError`."""

    _SENDER, _RECEIVER = "send", "receive"

    class _Entry:
        __slots__ = ("creator", "q", "done", "sender_engaged")

        def __init__(self, creator: str):
            self.creator = creator
            self.q: queue.Queue = queue.Queue(maxsize=1)
            self.done = threading.Event()
            self.sender_engaged = False

    def __init__(self, send_peer: int, recv_peer: int):
        # Peer ranks reported in TagError messages: a duplicate send names
        # the destination, a duplicate receive names the source.
        self._send_peer = send_peer
        self._recv_peer = recv_peer
        self._lock = threading.Lock()
        self._entries: Dict[int, "Rendezvous._Entry"] = {}

    def _entry(self, tag: int, side: str) -> "Rendezvous._Entry":
        with self._lock:
            ent = self._entries.get(tag)
            if ent is None:
                ent = Rendezvous._Entry(side)
                self._entries[tag] = ent
            elif ent.creator == side:
                peer = self._send_peer if side == self._SENDER else self._recv_peer
                raise TagError(tag, peer, side)
            if side == self._SENDER:
                # Marked under the lock, *before* the sender's q.put runs,
                # so cancel() can never retire an entry a sender is about
                # to fill (which would wedge the sender forever).
                ent.sender_engaged = True
            return ent

    def cancel(self, tag: int, exc: BaseException) -> bool:
        """Best-effort cancel of a parked receive: only succeeds while no
        sender has engaged the entry."""
        with self._lock:
            ent = self._entries.get(tag)
            if ent is None:
                return False
            if ent.creator != self._RECEIVER or ent.sender_engaged:
                return False
            self._entries.pop(tag)
        try:
            ent.q.put_nowait(Cancel(0, exc))
            return True
        except queue.Full:  # pragma: no cover - sender_engaged excludes this
            return False

    def probe(self, tag: int) -> bool:
        """Non-consuming probe: True when a sender has arrived and is
        parked at the rendezvous for ``tag`` (its payload is immediately
        receivable)."""
        with self._lock:
            ent = self._entries.get(tag)
            return ent is not None and ent.creator == self._SENDER

    def send(self, tag: int, payload: Any,
             timeout: Optional[float] = None, op: str = "send") -> None:
        ent = self._entry(tag, self._SENDER)
        try:
            if timeout is None:
                ent.q.put(payload)
            else:
                # The maxsize-1 queue can already hold the payload of a
                # sender whose receiver deadlined mid-engagement; the
                # put must be bounded too or the deadline is defeated.
                ent.q.put(payload, timeout=timeout)
        except queue.Full:
            raise DeadlineError(op, timeout) from None
        # Rendezvous: return only after the receiver took it. With
        # ``timeout`` (--mpi-optimeout parity with the remote path) a
        # receiver that never shows raises DeadlineError; the parked
        # payload then leaves the tag indeterminate, as documented for
        # the remote deadline.
        if not ent.done.wait(timeout):
            raise DeadlineError(op, timeout)

    def receive(self, tag: int,
                timeout: Optional[float] = None, op: str = "receive") -> Any:
        ent = self._entry(tag, self._RECEIVER)
        try:
            payload = (ent.q.get() if timeout is None
                       else ent.q.get(timeout=timeout))
        except queue.Empty:
            # Retire the still-unengaged entry so a later sender parks
            # on a fresh rendezvous instead of filling this corpse; a
            # sender that engaged in the race keeps the entry (its own
            # deadline bounds it).
            with self._lock:
                if self._entries.get(tag) is ent and not ent.sender_engaged:
                    self._entries.pop(tag)
            raise DeadlineError(op, timeout) from None
        if isinstance(payload, Cancel):
            raise payload.exc
        # The receiver retires the entry *before* signalling the sender:
        # popping under the lock here closes a race where a second legal
        # use of the same tag could observe the drained entry and deadlock.
        with self._lock:
            self._entries.pop(tag, None)
        ent.done.set()
        return payload

"""Hybrid driver — XLA ranks within a host, TCP between hosts.

The tpu deployment model the reference cannot express: a TPU pod is
*hosts × local chips*, where one OS process drives several chips. The
reference's answer to multi-node is one TCP process per rank
(network.go:122-159); the tpu-native answer is hierarchical:

  * **intra-host**: ranks are threads over the local device mesh — the
    :class:`mpi_tpu.backends.xla.XlaNetwork` driver verbatim (compiled
    ICI collectives, in-process rendezvous p2p);
  * **inter-host**: one TCP connection mesh between *hosts* (the DCN
    analogue) — the :class:`mpi_tpu.backends.tcp.TcpNetwork` driver
    verbatim, carrying cross-host p2p frames and the host-leader legs of
    hierarchical collectives.

Global rank layout is contiguous per host, host order = TCP rank order
(sorted addresses, network.go:94-109): host ``h`` with ``L_h`` local ranks
owns global ranks ``[offset_h, offset_h + L_h)``. Local counts are
exchanged at init, so heterogeneous hosts work.

Collectives are hierarchical (the BASELINE.json config-5 shape): e.g.
``allreduce`` = XLA allreduce across local ranks → TCP allreduce of the
per-host partials among host leaders (canonical binomial tree,
:mod:`mpi_tpu.collectives_generic`) → XLA bcast back to local ranks. The
slow tier therefore carries one buffer per host, not one per rank.

Cross-host point-to-point composes ``(src, dst, user_tag)`` into a single
host-level wire tag (bit 62 set — disjoint from user tags, which live
below 2^48, and from the collective tag space at 2^48..2^62). Cross-host
sends therefore require ``0 <= tag < 2**32`` and at most 2**15 global
ranks; intra-host tags are unrestricted.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

from .. import collectives_generic as G
from ..api import MpiError
from .tcp import TcpNetwork
from .xla import XlaNetwork, drive_rank_threads

__all__ = ["HybridNetwork", "run_spmd_hybrid"]

_XHOST_BIT = 1 << 62
_MAX_TAG = 1 << 32
_MAX_GLOBAL = 1 << 15


def _compose_tag(src: int, dst: int, tag: int) -> int:
    if not 0 <= tag < _MAX_TAG:
        raise MpiError(
            f"mpi_tpu: cross-host tags must be in [0, 2**32), got {tag}")
    return _XHOST_BIT | (src << 47) | (dst << 32) | tag


class HybridNetwork:
    """Backend implementing the :class:`mpi_tpu.api.Interface` SPI across
    hosts. Construct one per host process with the host's TCP identity
    (constructor args or ``--mpi-*`` flags, same ABI as TcpNetwork) and the
    local rank count; run rank threads with :func:`run_spmd_hybrid`."""

    def __init__(self, local_ranks: Optional[int] = None,
                 devices: Optional[Sequence[Any]] = None,
                 oversubscribe: bool = True,
                 tcp: Optional[TcpNetwork] = None, **tcp_kwargs: Any):
        self._inner = XlaNetwork(n=local_ranks, devices=devices,
                                 oversubscribe=oversubscribe)
        self._local_n = self._inner.size()
        self._tcp = tcp if tcp is not None else TcpNetwork(**tcp_kwargs)
        self._offsets: List[int] = []        # per-host global-rank offsets
        self._counts: List[int] = []
        self._size = 0
        self._my_offset = 0
        self._init_lock = threading.Lock()
        self._init_done = threading.Event()
        self._init_error: Optional[BaseException] = None
        self._live_ranks = 0  # rank threads inited but not yet finalized

    # -- rank binding (delegates to the inner xla driver) ---------------------

    def bind_rank(self, local_rank: int) -> None:
        self._inner.bind_rank(local_rank)

    def _local(self) -> int:
        return self._inner.rank()

    # -- topology -------------------------------------------------------------

    def _host_of(self, g: int) -> int:
        for h in range(len(self._offsets)):
            if g < self._offsets[h] + self._counts[h]:
                return h
        raise MpiError(f"mpi_tpu: rank {g} out of range [0, {self._size})")

    # -- Interface ------------------------------------------------------------

    def init(self) -> None:
        """Local xla init barrier; local rank 0 additionally bootstraps the
        host-level TCP mesh and exchanges local-rank counts."""
        self._inner.init()
        if self._local() == 0:
            try:
                self._tcp.init()
                counts = G.allgather(self._tcp, self._local_n)
                self._counts = [int(c) for c in counts]
                self._offsets = []
                off = 0
                for c in self._counts:
                    self._offsets.append(off)
                    off += c
                self._size = off
                self._my_offset = self._offsets[self._tcp.rank()]
                if self._size > _MAX_GLOBAL:
                    raise MpiError(
                        f"mpi_tpu: at most {_MAX_GLOBAL} global ranks "
                        f"supported, got {self._size}")
            except BaseException as exc:  # noqa: BLE001 - re-raised on all
                self._init_error = exc
            finally:
                self._init_done.set()
        else:
            # Track the leader's TCP init timeout (which _use_flags
            # resolves while we wait) rather than a fixed bound; the extra
            # slack covers the count-exchange round after the handshake.
            import time as _time

            start = _time.monotonic()
            while not self._init_done.wait(timeout=1.0):
                limit = (self._tcp.timeout or 120.0) + 60.0
                if _time.monotonic() - start > limit:
                    break
        if self._init_error is not None:
            raise MpiError(
                f"mpi_tpu: hybrid init failed: {self._init_error}"
            ) from self._init_error
        if not self._init_done.is_set():
            raise MpiError("mpi_tpu: hybrid init timed out")
        # Everyone re-syncs so no thread races ahead of the TCP bootstrap.
        self._inner.barrier()
        with self._init_lock:
            self._live_ranks += 1

    def finalize(self) -> None:
        """Refcounted teardown: every local rank thread calls finalize once
        (directly or via the facade); the *last* one — by then every local
        rank has finished communicating — closes the host's TCP mesh.
        Cross-host p2p still in flight at a peer's finalize is a caller
        error, as in the reference (network.go:354-369)."""
        self._inner.finalize()
        with self._init_lock:
            self._live_ranks = max(0, self._live_ranks - 1)
            last = self._live_ranks == 0
        if last:
            self._tcp.finalize()

    def rank(self) -> int:
        return self._my_offset + self._local()

    def size(self) -> int:
        return self._size

    def host_key(self) -> int:
        """Machine identity for ``Comm.split_type("host")``: this host's
        index in the TCP tier, shared by all its local ranks."""
        return self._tcp.rank()

    # -- point-to-point -------------------------------------------------------

    def send(self, data: Any, dest: int, tag: int) -> None:
        me = self.rank()
        h = self._host_of(dest)
        if h == self._tcp.rank():
            self._inner.send(data, dest - self._my_offset, tag)
        else:
            self._tcp.send(data, h, _compose_tag(me, dest, tag))

    def receive(self, source: int, tag: int, out: Optional[Any] = None) -> Any:
        me = self.rank()
        h = self._host_of(source)
        if h == self._tcp.rank():
            return self._inner.receive(source - self._my_offset, tag, out=out)
        return self._tcp.receive(h, _compose_tag(source, me, tag), out=out)

    def cancel_receive(self, source: int, tag: int) -> bool:
        me = self.rank()
        h = self._host_of(source)
        if h == self._tcp.rank():
            return self._inner.cancel_receive(source - self._my_offset, tag)
        return self._tcp.cancel_receive(h, _compose_tag(source, me, tag))

    # -- hierarchical collectives --------------------------------------------
    #
    # Pattern: local xla collective → host-leader TCP leg → local
    # distribution. Local rank 0 is always the host leader. All collectives
    # must be invoked in the same order on every global rank (standard MPI
    # requirement) — that ordering also serialises the leader's TCP legs.

    def _leader_leg(self, local_result: Any,
                    leg: Callable[[Any], Any]) -> Any:
        """Run ``leg`` on the host leader only, then share its result with
        every local rank (via the inner driver's bcast)."""
        if self._nhosts() == 1:
            return local_result
        out = leg(local_result) if self._local() == 0 else None
        return self._inner.bcast(out, root=0)

    def _nhosts(self) -> int:
        return len(self._counts)

    def allreduce(self, data: Any, op: str = "sum") -> Any:
        G.check_op(op)
        local_total = self._inner.allreduce(data, op=op)
        return self._leader_leg(
            local_total, lambda t: G.allreduce(self._tcp, t, op=op))

    def reduce(self, data: Any, root: int = 0, op: str = "sum") -> Optional[Any]:
        result = self.allreduce(data, op=op)
        return result if self.rank() == root else None

    def reduce_scatter(self, data: Any, op: str = "sum") -> Any:
        """Hierarchical allreduce, then keep this *global* rank's block
        (leading axis split across all ranks of all hosts)."""
        import numpy as _np

        arr = _np.asarray(data)
        if arr.ndim < 1 or arr.shape[0] % self._size:
            raise MpiError(
                f"mpi_tpu: reduce_scatter payload leading axis "
                f"{arr.shape if arr.ndim else 'scalar'} must divide into "
                f"{self._size} equal blocks")
        total = _np.asarray(self.allreduce(data, op=op))
        m = arr.shape[0] // self._size
        r = self.rank()
        return total[r * m:(r + 1) * m]

    def barrier(self) -> None:
        self._inner.barrier()
        if self._local() == 0 and self._nhosts() > 1:
            G.barrier(self._tcp)
        self._inner.barrier()

    def bcast(self, data: Any, root: int = 0) -> Any:
        h = self._host_of(root)
        if h == self._tcp.rank():
            payload = self._inner.bcast(data, root=root - self._my_offset)
            if self._local() == 0 and self._nhosts() > 1:
                G.bcast(self._tcp, payload, root=h)
            return payload
        # Non-root host: leader receives over TCP, then local bcast.
        payload = None
        if self._local() == 0:
            payload = G.bcast(self._tcp, None, root=h)
        return self._inner.bcast(payload, root=0)

    def allgather(self, data: Any) -> List[Any]:
        locals_ = self._inner.allgather(data)

        def leg(locals_list: List[Any]) -> List[Any]:
            per_host = G.allgather(self._tcp, locals_list)
            flat: List[Any] = []
            for chunk in per_host:
                flat.extend(chunk)
            return flat

        return self._leader_leg(locals_, leg)

    def gather(self, data: Any, root: int = 0) -> Optional[List[Any]]:
        result = self.allgather(data)
        return result if self.rank() == root else None

    def scatter(self, data: Optional[List[Any]], root: int = 0) -> Any:
        h = self._host_of(root)
        # The TCP leg always carries a ``(status, payload)`` envelope so an
        # invalid list raises a clean MpiError on *every* rank of *every*
        # host — the leaders relay the verdict over TCP and then to their
        # local ranks via the inner bcast, so nobody commits to a blocking
        # scatter that will never be fed.
        if h == self._tcp.rank():
            # Move the item list to the host leader (one gather hop, not a
            # full local bcast), chunk per host, TCP scatter the chunks,
            # then local scatter.
            gathered = self._inner.gather(data, root=0)
            chunk = None
            items = None
            error = None
            if self._local() == 0:
                items = gathered[root - self._my_offset]
                if items is None or len(items) != self._size:
                    error = (f"mpi_tpu: scatter root needs a list of "
                             f"exactly {self._size} payloads")
                if self._nhosts() > 1:
                    if error is not None:
                        envelopes = [("err", error)] * self._nhosts()
                    else:
                        envelopes = [
                            ("ok", items[self._offsets[i]:
                                         self._offsets[i] + self._counts[i]])
                            for i in range(self._nhosts())
                        ]
                    G.scatter(self._tcp, envelopes, root=h)
            error = self._inner.bcast(error, root=0)
            if error is not None:
                raise MpiError(error)
            if self._local() == 0:
                chunk = items[self._my_offset:
                              self._my_offset + self._local_n]
            return self._inner.scatter(chunk, root=0)
        chunk = None
        error = None
        if self._local() == 0:
            status, payload = G.scatter(self._tcp, None, root=h)
            if status == "err":
                error = payload
            else:
                chunk = payload
        error = self._inner.bcast(error, root=0)
        if error is not None:
            raise MpiError(error)
        return self._inner.scatter(chunk, root=0)

    def alltoall(self, data: List[Any]) -> List[Any]:
        if len(data) != self._size:
            raise MpiError(
                f"mpi_tpu: alltoall needs exactly {self._size} payloads, "
                f"got {len(data)}")
        # Local matrix: rows[l] = payload list of local rank l.
        rows = self._inner.allgather(data)

        def leg(rows_: List[List[Any]]) -> List[List[Any]]:
            # bundles[h] = what this host sends to host h: rows sliced to
            # h's global-rank span (still indexed [local_src][dst_in_h]).
            bundles = [
                [row[self._offsets[h]:self._offsets[h] + self._counts[h]]
                 for row in rows_]
                for h in range(self._nhosts())
            ]
            received = G.alltoall(self._tcp, bundles)
            # received[hs][ls][l] = payload from global (hs, ls) to my
            # local rank l. Reassemble per local rank in global src order.
            out_rows = []
            for l in range(self._local_n):
                out: List[Any] = []
                for hs in range(self._nhosts()):
                    for ls in range(self._counts[hs]):
                        out.append(received[hs][ls][l])
                out_rows.append(out)
            return out_rows

        if self._nhosts() > 1:
            # Leader reassembles, then each local rank gets only its own
            # row (scatter, not bcast — rows can be large).
            out_rows = leg(rows) if self._local() == 0 else None
            return self._inner.scatter(out_rows, root=0)
        return [row[self._local()] for row in rows]


def run_spmd_hybrid(fn: Callable[[], Any], net: HybridNetwork,
                    register_facade: bool = True) -> List[Any]:
    """Run ``fn`` on one thread per *local* rank of this host — the
    per-host analogue of :func:`mpi_tpu.backends.xla.run_spmd`; the
    launcher starts one such process per host (same flag ABI as the TCP
    driver, gompirun.go:28-93)."""

    def abort() -> None:
        net._inner._init_barrier.abort()
        net._inner.abort_collectives()
        net._init_done.set()

    def on_failure() -> None:
        # Ranks that errored never reach finalize, so the refcount never
        # drains — close the host TCP mesh here or the listener socket and
        # reader threads leak past the failed run.
        try:
            net._tcp.finalize()
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass

    return drive_rank_threads(
        fn, nranks=net._inner.size(), bind=net.bind_rank, abort=abort,
        inherit_net=net._inner, facade_net=net, name_prefix="mpi-hybrid",
        register_facade=register_facade, on_failure=on_failure)

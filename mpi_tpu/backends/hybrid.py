"""Hybrid driver — XLA ranks within a host, TCP between hosts.

The tpu deployment model the reference cannot express: a TPU pod is
*hosts × local chips*, where one OS process drives several chips. The
reference's answer to multi-node is one TCP process per rank
(network.go:122-159); the tpu-native answer is hierarchical:

  * **intra-host**: ranks are threads over the local device mesh — the
    :class:`mpi_tpu.backends.xla.XlaNetwork` driver verbatim (compiled
    ICI collectives, in-process rendezvous p2p);
  * **inter-host**: one TCP connection mesh between *hosts* (the DCN
    analogue) — the :class:`mpi_tpu.backends.tcp.TcpNetwork` driver
    verbatim, carrying cross-host p2p frames and the host-leader legs of
    hierarchical collectives.

Global rank layout is contiguous per host, host order = TCP rank order
(sorted addresses, network.go:94-109): host ``h`` with ``L_h`` local ranks
owns global ranks ``[offset_h, offset_h + L_h)``. Local counts are
exchanged at init, so heterogeneous hosts work.

Collectives are hierarchical (the BASELINE.json config-5 shape): e.g.
``allreduce`` = XLA allreduce across local ranks → TCP allreduce of the
per-host partials among host leaders (canonical binomial tree,
:mod:`mpi_tpu.collectives_generic`) → XLA bcast back to local ranks. The
slow tier therefore carries one buffer per host, not one per rank.

Cross-host point-to-point composes ``(src, dst, user_tag)`` into a single
host-level wire tag (bit 62 set — disjoint from user tags, which live
below 2^48, and from the collective tag space at 2^48..2^62). Cross-host
sends therefore require ``0 <= tag < 2**32 - 2**21`` (the top 2**21 of
the field is the partitioned-p2p + RMA window-service band: those
reserved i64 tag slices remap into it so passive-target lock/unlock
and MPI-4 partitioned sends work across hosts) and at most 2**15
global ranks; intra-host tags are unrestricted.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import (TYPE_CHECKING, Any, Callable, List, Optional,
                    Sequence, Tuple)

if TYPE_CHECKING:
    from ..collectives_generic import OpLike

from .. import collectives_generic as G
from ..api import MpiError
from ..utils import trace
from .tcp import TcpNetwork
from .xla import XlaNetwork, drive_rank_threads

__all__ = ["HybridNetwork", "run_spmd_hybrid"]

_XHOST_BIT = 1 << 62
_MAX_TAG = 1 << 32
_MAX_GLOBAL = 1 << 15


_WIN_BAND_CACHE: Optional[Tuple[int, int]] = None


def _win_tag_band() -> Tuple[int, int]:
    """The reserved service tag band — the PARTITIONED-p2p slice plus
    the RMA window-service slice, contiguous by construction in
    comm.py's layout — as (lo, hi). These i64 tags must cross hosts
    for passive-target RMA and partitioned sends to work over the
    hybrid driver, so _compose_tag remaps them reversibly into the TOP
    of the 32-bit composed-tag field. Cached: this sits on the
    per-operation wire path and the service thread's poll loop."""
    global _WIN_BAND_CACHE
    if _WIN_BAND_CACHE is None:
        from ..comm import _WIN_SLICE, _part_tag_base, _win_tag_base

        lo = _part_tag_base()
        hi = _win_tag_base() + _WIN_SLICE
        _WIN_BAND_CACHE = (lo, hi)
    return _WIN_BAND_CACHE


def _compose_tag(src: int, dst: int, tag: int) -> int:
    if tag < 0:
        # Sub-communicator tag regions (mpi_tpu.comm) don't fit the
        # composed cross-host form (ctx + tag + src + dst exceed 64
        # bits); group COLLECTIVES still work hierarchically via
        # group_collectives — only cross-host group p2p is unsupported.
        raise MpiError(
            "mpi_tpu: communicator point-to-point between ranks on "
            "different hosts is not supported by the hybrid driver; use "
            "the communicator's collectives (hierarchical engines) or "
            "world-rank send/receive")
    win_lo, win_hi = _win_tag_band()
    if win_lo <= tag < win_hi:
        # Window-service traffic: same remap on every host and every
        # path (send/receive/iprobe/cancel), so no decomposition is
        # ever needed.
        tag = (_MAX_TAG - (win_hi - win_lo)) + (tag - win_lo)
    elif not 0 <= tag < _MAX_TAG - (win_hi - win_lo):
        raise MpiError(
            f"mpi_tpu: cross-host tags must be in [0, 2**32 - 2**21) "
            f"(the top 2**21 is the partitioned-p2p + RMA "
            f"window-service band), got {tag}")
    return _XHOST_BIT | (src << 47) | (dst << 32) | tag


class HybridNetwork:
    """Backend implementing the :class:`mpi_tpu.api.Interface` SPI across
    hosts. Construct one per host process with the host's TCP identity
    (constructor args or ``--mpi-*`` flags, same ABI as TcpNetwork) and the
    local rank count; run rank threads with :func:`run_spmd_hybrid`."""

    # Communicator (context-region) tags cannot cross hosts — the
    # composed wire tag has no room for a context (_compose_tag).
    # mpi_tpu.comm checks this to route neighborhood collectives through
    # the hierarchical group allgather instead of pairwise sendrecv.
    SUPPORTS_COMM_CROSS_HOST_P2P = False
    # Local ranks are threads sharing one tracer buffer (like the xla
    # driver), so trace collection writes each host process's buffer
    # once via its global-rank-0 thread rather than gathering
    # duplicate per-thread copies. (Cross-host merge of per-host
    # buffers is an observe-layer follow-on; ROADMAP.)
    SHARED_PROCESS_TRACER = True

    def __init__(self, local_ranks: Optional[int] = None,
                 devices: Optional[Sequence[Any]] = None,
                 oversubscribe: bool = True,
                 tcp: Optional[TcpNetwork] = None, **tcp_kwargs: Any):
        self._inner = XlaNetwork(n=local_ranks, devices=devices,
                                 oversubscribe=oversubscribe)
        self._local_n = self._inner.size()
        self._tcp = tcp if tcp is not None else TcpNetwork(**tcp_kwargs)
        self._offsets: List[int] = []        # per-host global-rank offsets
        self._counts: List[int] = []
        self._size = 0
        self._my_offset = 0
        self._init_lock = threading.Lock()
        self._init_done = threading.Event()
        self._init_error: Optional[BaseException] = None
        self._live_ranks = 0  # rank threads inited but not yet finalized
        # Per-communicator hierarchical engines (see group_collectives).
        self._group_colls: "OrderedDict[tuple, _HybridGroupEngine]" = \
            OrderedDict()
        # Cross-host collective tag sequences per (ctx, members): must
        # outlive engine eviction (a rebuilt engine restarting at seq 0
        # while peer hosts kept counting would desync wire tags). Tiny
        # (one int per communicator ever used), so never evicted.
        self._grp_seqs: dict = {}

    # -- rank binding (delegates to the inner xla driver) ---------------------

    def bind_rank(self, local_rank: int) -> None:
        self._inner.bind_rank(local_rank)

    def _local(self) -> int:
        return self._inner.rank()

    # -- topology -------------------------------------------------------------

    def _host_of(self, g: int) -> int:
        for h in range(len(self._offsets)):
            if g < self._offsets[h] + self._counts[h]:
                return h
        raise MpiError(f"mpi_tpu: rank {g} out of range [0, {self._size})")

    # -- Interface ------------------------------------------------------------

    def init(self) -> None:
        """Local xla init barrier; local rank 0 additionally bootstraps the
        host-level TCP mesh and exchanges local-rank counts."""
        self._inner.init()
        if self._local() == 0:
            try:
                self._tcp.init()
                counts = G.allgather(self._tcp, self._local_n)
                self._counts = [int(c) for c in counts]
                self._offsets = []
                off = 0
                for c in self._counts:
                    self._offsets.append(off)
                    off += c
                self._size = off
                self._my_offset = self._offsets[self._tcp.rank()]
                if self._size > _MAX_GLOBAL:
                    raise MpiError(
                        f"mpi_tpu: at most {_MAX_GLOBAL} global ranks "
                        f"supported, got {self._size}")
            except BaseException as exc:  # noqa: BLE001 - re-raised on all
                self._init_error = exc
            finally:
                self._init_done.set()
        else:
            # Track the leader's TCP init timeout (which _use_flags
            # resolves while we wait) rather than a fixed bound; the extra
            # slack covers the count-exchange round after the handshake.
            import time as _time

            start = _time.monotonic()
            while not self._init_done.wait(timeout=1.0):
                limit = (self._tcp.timeout or 120.0) + 60.0
                if _time.monotonic() - start > limit:
                    break
        if self._init_error is not None:
            raise MpiError(
                f"mpi_tpu: hybrid init failed: {self._init_error}"
            ) from self._init_error
        if not self._init_done.is_set():
            raise MpiError("mpi_tpu: hybrid init timed out")
        # Everyone re-syncs so no thread races ahead of the TCP bootstrap.
        self._inner.barrier()
        with self._init_lock:
            self._live_ranks += 1

    def finalize(self) -> None:
        """Refcounted teardown: every local rank thread calls finalize once
        (directly or via the facade); the *last* one — by then every local
        rank has finished communicating — closes the host's TCP mesh.
        Cross-host p2p still in flight at a peer's finalize is a caller
        error, as in the reference (network.go:354-369)."""
        self._inner.finalize()
        with self._init_lock:
            self._live_ranks = max(0, self._live_ranks - 1)
            last = self._live_ranks == 0
        if last:
            self._tcp.finalize()

    def rank(self) -> int:
        return self._my_offset + self._local()

    def size(self) -> int:
        return self._size

    def host_key(self) -> int:
        """Machine identity for ``Comm.split_type("host")``: this host's
        index in the TCP tier, shared by all its local ranks."""
        return self._tcp.rank()

    def _grp_seq_state(self, ctx: int, members: tuple) -> dict:
        """The persistent {lock, seq} record backing a group adapter's
        collective tag sequence. Caller holds no lock; _init_lock guards
        creation (group_collectives already holds it)."""
        key = (int(ctx), tuple(members))
        st = self._grp_seqs.get(key)
        if st is None:
            st = self._grp_seqs[key] = {"lock": threading.Lock(), "seq": 0}
        return st

    def group_collectives(self, members, ctx: int) -> "_HybridGroupEngine":
        """Hierarchical collective engine for a communicator group (the
        mpi_tpu.comm dispatch hook, same contract as
        :meth:`XlaNetwork.group_collectives`): local members share a
        compiled xla sub-mesh engine, host leaders bridge over the TCP
        tier. One shared engine per ``(ctx, members)``."""
        key = (int(ctx), tuple(int(m) for m in members))
        with self._init_lock:
            eng = self._group_colls.get(key)
            if eng is None:
                eng = _HybridGroupEngine(self, key[1], key[0])
                self._group_colls[key] = eng
                while len(self._group_colls) > \
                        XlaNetwork._GROUP_ENGINE_CACHE:
                    self._group_colls.popitem(last=False)
            else:
                self._group_colls.move_to_end(key)
        return eng

    def release_group_collectives(self, members, ctx: int) -> None:
        """Comm.free() hook: drop this group's engine and its inner xla
        engine (compiled programs, filler buffers)."""
        key = (int(ctx), tuple(int(m) for m in members))
        with self._init_lock:
            eng = self._group_colls.pop(key, None)
        if eng is not None:
            self._inner.release_group_collectives(
                tuple(g - self._my_offset for g in eng._local_members),
                key[0])

    # -- point-to-point -------------------------------------------------------

    def send(self, data: Any, dest: int, tag: int) -> None:
        me = self.rank()
        h = self._host_of(dest)
        if h == self._tcp.rank():
            self._inner.send(data, dest - self._my_offset, tag)
        elif trace.enabled():
            # Cross-host (DCN-tier) traffic is the scarce resource the
            # hierarchy exists to conserve — attribute it separately
            # from intra-host hops.
            from ..api import _payload_bytes

            nbytes = _payload_bytes(data)
            trace.count(f"wire.hybrid.tx.bytes.peer{dest}", nbytes)
            with trace.span("hybrid.xhost_send", dest=dest, tag=tag,
                            bytes=nbytes):
                self._tcp.send(data, h, _compose_tag(me, dest, tag))
        else:
            self._tcp.send(data, h, _compose_tag(me, dest, tag))

    def receive(self, source: int, tag: int, out: Optional[Any] = None) -> Any:
        me = self.rank()
        h = self._host_of(source)
        if h == self._tcp.rank():
            return self._inner.receive(source - self._my_offset, tag, out=out)
        if trace.enabled():
            from ..api import _payload_bytes

            with trace.span("hybrid.xhost_recv", source=source, tag=tag):
                result = self._tcp.receive(h, _compose_tag(source, me, tag),
                                           out=out)
            trace.count(f"wire.hybrid.rx.bytes.peer{source}",
                        _payload_bytes(result))
            return result
        return self._tcp.receive(h, _compose_tag(source, me, tag), out=out)

    def cancel_receive(self, source: int, tag: int) -> bool:
        me = self.rank()
        h = self._host_of(source)
        if h == self._tcp.rank():
            return self._inner.cancel_receive(source - self._my_offset, tag)
        return self._tcp.cancel_receive(h, _compose_tag(source, me, tag))

    def iprobe(self, source: int, tag: int) -> bool:
        """Non-consuming MPI_Iprobe across the hierarchy: the inner
        rendezvous for a local peer, the TCP tier (composed tag) for a
        remote one."""
        me = self.rank()
        h = self._host_of(source)
        if h == self._tcp.rank():
            return self._inner.iprobe(source - self._my_offset, tag)
        return self._tcp.iprobe(h, _compose_tag(source, me, tag))

    # -- hierarchical collectives --------------------------------------------
    #
    # The world is just the communicator group (0..size) with identity
    # layout, so every world collective delegates to ONE
    # _HybridGroupEngine over all ranks (uid 0; inner = the world xla
    # engine). The local -> host-leader TCP leg -> local shape, the
    # scatter error envelope, and the reassembly maps therefore exist in
    # exactly one place (the engine), for world and sub-communicators
    # alike. All collectives must be invoked in the same order on every
    # global rank (standard MPI requirement) — that ordering also
    # serialises the leader's TCP legs.

    def _world_engine(self) -> "_HybridGroupEngine":
        if self._size == 0:
            raise MpiError("mpi_tpu: collective before init()")
        with self._init_lock:
            eng = getattr(self, "_world_eng", None)
            if eng is None:
                eng = _HybridGroupEngine(
                    self, tuple(range(self._size)), 0)
                self._world_eng = eng
            return eng

    def allreduce(self, data: Any, op: "OpLike" = "sum") -> Any:
        return self._world_engine().allreduce(data, op=op)

    def reduce(self, data: Any, root: int = 0, op: "OpLike" = "sum") -> Optional[Any]:
        return self._world_engine().reduce(data, root=root, op=op)

    def reduce_scatter(self, data: Any, op: "OpLike" = "sum") -> Any:
        return self._world_engine().reduce_scatter(data, op=op)

    def barrier(self) -> None:
        return self._world_engine().barrier()

    def bcast(self, data: Any, root: int = 0) -> Any:
        return self._world_engine().bcast(data, root=root)

    def allgather(self, data: Any) -> List[Any]:
        return self._world_engine().allgather(data)

    def gather(self, data: Any, root: int = 0) -> Optional[List[Any]]:
        return self._world_engine().gather(data, root=root)

    def scatter(self, data: Optional[List[Any]], root: int = 0) -> Any:
        return self._world_engine().scatter(data, root=root)

    def alltoall(self, data: List[Any]) -> List[Any]:
        return self._world_engine().alltoall(data)


class _TcpGroupAdapter:
    """Host-leader sub-group view of the TCP tier for one communicator's
    hierarchical collectives: rank = index in the participating-host
    list, and collective tags map into an engine-unique block of the far
    negative tag space ``(-2^63, -2^62]`` — disjoint from user tags,
    world collective tags (>= 2^48), cross-host composed tags (bit 62),
    and Comm context regions (> -2^62). ``uid`` must be unique among
    engines that can share a host link: ``ctx * 2^15 + min(members)``
    is, because comms sharing a context are disjoint (split siblings),
    so their lowest members differ. The collective tag sequence
    (``_coll_seq``, advanced by ``collectives_generic``) is CROSS-HOST
    state — every participating host's leader must be at the same
    sequence — so it lives in ``seq_state`` (a per-(ctx, members) dict
    owned by the driver) and survives engine eviction/rebuild.
    Offsets wrap modulo ``_BLOCK``: collectives on one communicator are
    globally ordered and tags are released on completion, so a wrapped
    offset can only collide with itself 2^17 collectives later."""

    # uid < 2^33 (max ctx 2^18-1) and uid * _BLOCK + off must stay
    # within (-2^63, -2^62]: 2^33 * 2^29 == 2^62 exactly.
    _BLOCK = 1 << 29

    def __init__(self, tcp: TcpNetwork, hosts: List[int], uid: int,
                 seq_state: dict):
        if not 0 <= uid < (1 << 33):
            raise MpiError(f"mpi_tpu: group-engine uid {uid} out of range")
        self._tcp = tcp
        self._hosts = list(hosts)
        self._uid = uid
        self._seq_state = seq_state

    # collectives_generic._next_tag_base reads/writes these on the impl
    # it is handed; proxy to the driver-owned state so a rebuilt adapter
    # continues the sequence its cross-host peers are at.
    @property
    def _coll_lock(self) -> threading.Lock:
        return self._seq_state["lock"]

    @property
    def _coll_seq(self) -> int:
        return self._seq_state["seq"]

    @_coll_seq.setter
    def _coll_seq(self, value: int) -> None:
        self._seq_state["seq"] = value

    def rank(self) -> int:
        return self._hosts.index(self._tcp.rank())

    def size(self) -> int:
        return len(self._hosts)

    def _map(self, tag: int) -> int:
        off = (tag - G.COLL_TAG_BASE) % self._BLOCK
        return -(1 << 62) - self._uid * self._BLOCK - off - 1

    def send(self, data: Any, dest: int, tag: int) -> None:
        self._tcp.send(data, self._hosts[dest], self._map(tag))

    def receive(self, source: int, tag: int, out: Optional[Any] = None) -> Any:
        return self._tcp.receive(self._hosts[source], self._map(tag), out=out)

    def cancel_receive(self, source: int, tag: int) -> bool:
        return self._tcp.cancel_receive(self._hosts[source], self._map(tag))


class _HybridGroupEngine:
    """Hierarchical collectives for one communicator over the hybrid
    driver: local members run the xla driver's compiled sub-mesh engine,
    host leaders (first local member in group order) bridge hosts over
    the TCP tier, and results fan back out through the local engine —
    the same local → leader-leg → local shape as the world collectives,
    with explicit group-rank maps because a key-permuted split need not
    keep hosts contiguous. The full suite is defined here except
    scan/exscan, whose generic algorithms ride :meth:`allgather` (via
    ``collectives_generic._allgather_best`` on the Comm) — never
    cross-host p2p, which the hybrid driver rejects for communicator
    tags."""

    def __init__(self, net: "HybridNetwork", members: tuple, ctx: int):
        self._net = net
        self._members = tuple(members)
        h = net._tcp.rank()
        self._local_members = [g for g in self._members
                               if net._host_of(g) == h]
        if not self._local_members:
            raise MpiError(
                "mpi_tpu: hybrid group engine built on a host with no "
                "group members")
        self._hosts = sorted({net._host_of(g) for g in self._members})
        local_ranks = tuple(g - net._my_offset for g in self._local_members)
        if local_ranks == tuple(range(net._local_n)):
            # Full local membership in natural order: the driver's world
            # xla engine IS this group's inner engine (don't duplicate
            # its jit cache / rendezvous barrier).
            self._inner = net._inner
        else:
            self._inner = net._inner.group_collectives(local_ranks, ctx)
        self._tcp_grp = _TcpGroupAdapter(
            net._tcp, self._hosts, ctx * _MAX_GLOBAL + min(self._members),
            net._grp_seq_state(ctx, self._members))
        # group rank of each local member, in local (inner) order
        self._local_granks = [self._members.index(g)
                              for g in self._local_members]

    # -- helpers -----------------------------------------------------------

    def _is_leader(self) -> bool:
        return self._net.rank() == self._local_members[0]

    def _leader_leg(self, local_result: Any, leg: Callable[[Any], Any],
                    span_prefix: str = "") -> Any:
        """Leader bridges hosts, result fans back out locally. With
        ``span_prefix`` set, each phase records a trace span:
        ``<p>.leader_exchange`` and ``<p>.local_bcast`` on the leader
        (separately attributable costs — the leader enters its bcast
        only after its exchange, so its bcast span is pure fan-out
        work), ``<p>.follower_wait`` on non-leaders (their bcast entry
        blocks until the leader finishes the exchange, so the wait
        covers both phases and is named as such rather than
        masquerading as bcast cost)."""
        if len(self._hosts) == 1:
            return local_result
        if not span_prefix:
            out = leg(local_result) if self._is_leader() else None
            return self._inner.bcast(out, root=0)
        if self._is_leader():
            with trace.span(f"{span_prefix}.leader_exchange"):
                out = leg(local_result)
            with trace.span(f"{span_prefix}.local_bcast"):
                return self._inner.bcast(out, root=0)
        with trace.span(f"{span_prefix}.follower_wait"):
            return self._inner.bcast(None, root=0)

    # -- collectives -------------------------------------------------------

    # Large allreduces CAN pipeline the two leader-leg tiers — the
    # 1 MiB x 32-rank tier split shows exchange (~14 ms) and bcast
    # (~7 ms leader-side; followers wait out both, ~21 ms) fully
    # serialized on the critical path, and on a real
    # multi-host fabric they use different resources (NIC vs local
    # memory), so overlap should approach max() of the tiers.
    #
    # EXPERIMENTAL, DCN-ONLY (round-5 verdict #4 resolution): the
    # gate ships CLOSED and this lever must not be enabled on any
    # fabric without winning its own A/B there. The definitive
    # loopback measurement (16/64 MiB, 4+8 chunks, interleaved
    # variants on the zero-copy wire path — docs/PERF_NOTES.md) shows
    # 0.83x-1.05x, inside the serial leg's rerun spread: one core has
    # nothing to overlap. Enable on a real multi-host deployment with
    # MPI_TPU_HYBRID_PIPELINE_MIN=<bytes> after an on-fabric A/B.
    _PIPELINE_CHUNKS = 4

    @staticmethod
    def _pipeline_min_bytes() -> int:
        import os as _os

        try:
            return int(_os.environ.get("MPI_TPU_HYBRID_PIPELINE_MIN",
                                       str(1 << 62)))
        except ValueError:
            return 1 << 62

    @classmethod
    def _pipeline_eligible(cls, nbytes: int) -> bool:
        """Engage window: [threshold, RING_MIN_BYTES). The upper cap
        is a CORRECTNESS bound, not tuning: binomial-tree reduction is
        elementwise-association-invariant under chunking (chunk
        results equal the whole-buffer tree bitwise), but at ring
        sizes the serial leg switches to ring order whose per-element
        association depends on block boundaries — chunked rings would
        diverge bitwise from the whole-buffer path and break the
        cross-driver parity contract (collectives_generic.
        ring_eligible). Above the cap the ring is already the
        bandwidth-optimal leg; the pipeline's domain is the mid-size
        regime."""
        return (cls._pipeline_min_bytes() <= nbytes
                < G.RING_MIN_BYTES)

    def _pipelined_leader_leg(self, total, op) -> Any:
        """Chunked overlap of the leader leg's two serial tiers: the
        leader runs the per-chunk TCP exchange in a producer thread
        while the main thread broadcasts each exchanged chunk locally
        — chunk i's exchange rides UNDER chunk i-1's bcast, so the
        critical path approaches max(exchange, bcast) + one chunk
        instead of their sum. Deterministic chunking (np.array_split
        on the flat buffer) keeps every rank's bcast sequence
        identical; the producer is the only _tcp_grp user while it
        runs, so the leader tier's collective ordering is unchanged."""
        import numpy as np

        with trace.span("hybrid.allreduce.pipelined",
                        nbytes=int(total.nbytes)):
            shape, dtype = total.shape, total.dtype
            chunks = np.array_split(total.reshape(-1),
                                    self._PIPELINE_CHUNKS)
            if self._is_leader():
                import queue

                done: "queue.Queue" = queue.Queue()

                def producer() -> None:
                    try:
                        for ch in chunks:
                            done.put(G.allreduce(self._tcp_grp,
                                                 np.ascontiguousarray(ch),
                                                 op=op))
                    except BaseException as exc:  # noqa: BLE001
                        done.put(exc)  # surfaced by the consumer below

                th = threading.Thread(target=producer, daemon=True,
                                      name="hybrid-pipeline-exchange")
                th.start()
                out = []
                for _ in chunks:
                    item = done.get()
                    if isinstance(item, BaseException):
                        # Every local rank still gets its bcast (the
                        # exception travels), so the failure raises on
                        # the whole host instead of deadlocking it.
                        self._inner.bcast(item, root=0)
                        th.join()
                        raise item
                    out.append(self._inner.bcast(item, root=0))
                th.join()
            else:
                out = []
                for _ in chunks:
                    item = self._inner.bcast(None, root=0)
                    if isinstance(item, BaseException):
                        raise item
                    out.append(item)
            return np.concatenate(out).astype(dtype,
                                              copy=False).reshape(shape)

    def allreduce(self, data: Any, op="sum") -> Any:
        G.check_op(op)
        if callable(op):
            # User callables promise associativity only — the
            # hierarchical local-then-host fold would reorder operands
            # whenever group order interleaves hosts, silently breaking
            # non-commutative ops. allgather is group-rank-ordered, so
            # fold it in the canonical tree instead (same order as every
            # other driver).
            return G.tree_combine(self.allgather(data), op)
        # One trace span per tier (see _leader_leg): the phases hide
        # behind one opaque latency otherwise, and a regression in the
        # DCN-analogue leader tier would be indistinguishable from
        # local noise (bench reads these spans; span() is a one-bool
        # check when tracing is off).
        with trace.span("hybrid.allreduce.local_reduce"):
            local_total = self._inner.allreduce(data, op=op)
        import numpy as np

        if len(self._hosts) > 1 \
                and isinstance(local_total, np.ndarray) \
                and self._pipeline_eligible(local_total.nbytes):
            return self._pipelined_leader_leg(local_total, op)
        return self._leader_leg(
            local_total, lambda t: G.allreduce(self._tcp_grp, t, op=op),
            span_prefix="hybrid.allreduce")

    def reduce(self, data: Any, root: int = 0, op: "OpLike" = "sum"
               ) -> Optional[Any]:
        result = self.allreduce(data, op=op)
        me = self._members.index(self._net.rank())
        return result if me == root else None

    def barrier(self) -> None:
        self._inner.barrier()
        if self._is_leader() and len(self._hosts) > 1:
            G.barrier(self._tcp_grp)
        self._inner.barrier()

    def bcast(self, data: Any, root: int = 0) -> Any:
        g_root = self._members[root]
        root_host = self._net._host_of(g_root)
        if root_host == self._net._tcp.rank():
            payload = self._inner.bcast(
                data, root=self._local_members.index(g_root))
            if self._is_leader() and len(self._hosts) > 1:
                G.bcast(self._tcp_grp, payload,
                        root=self._hosts.index(root_host))
            return payload
        payload = None
        if self._is_leader():
            payload = G.bcast(self._tcp_grp, None,
                              root=self._hosts.index(root_host))
        return self._inner.bcast(payload, root=0)

    def allgather(self, data: Any) -> List[Any]:
        locals_ = self._inner.allgather(data)

        def leg(locals_list: List[Any]) -> List[Any]:
            # Tag each payload with its group rank: a key-permuted split
            # can interleave hosts arbitrarily in group order.
            tagged = list(zip(self._local_granks, locals_list))
            per_host = G.allgather(self._tcp_grp, tagged)
            flat = [p for chunk in per_host for p in chunk]
            flat.sort(key=lambda e: e[0])
            return [p for _, p in flat]

        return self._leader_leg(locals_, leg)

    def gather(self, data: Any, root: int = 0) -> Optional[List[Any]]:
        result = self.allgather(data)
        me = self._members.index(self._net.rank())
        return result if me == root else None

    def reduce_scatter(self, data: Any, op: "OpLike" = "sum") -> Any:
        """Hierarchical allreduce, then keep this group rank's block."""
        import numpy as _np

        n = len(self._members)
        arr = _np.asarray(data)
        if arr.ndim < 1 or arr.shape[0] % n:
            raise MpiError(
                f"mpi_tpu: reduce_scatter payload leading axis "
                f"{arr.shape if arr.ndim else 'scalar'} must divide into "
                f"{n} equal blocks")
        total = _np.asarray(self.allreduce(data, op=op))
        m = arr.shape[0] // n
        me = self._members.index(self._net.rank())
        return total[me * m:(me + 1) * m]

    def _host_chunk(self, items: List[Any], host: int) -> List[Any]:
        """items (ordered by group rank) restricted to ``host``'s members,
        in that host's local (inner) order."""
        return [items[gr] for gr, g in enumerate(self._members)
                if self._net._host_of(g) == host]

    def scatter(self, data: Optional[List[Any]], root: int = 0) -> Any:
        """Root's per-group-rank list → one inner gather hop to root's
        host leader, per-host chunks over TCP, local scatter. The TCP
        leg carries a (status, payload) envelope so a bad list raises on
        every member instead of deadlocking (same shape as the world
        scatter)."""
        n = len(self._members)
        g_root = self._members[root]
        root_host = self._net._host_of(g_root)
        multi = len(self._hosts) > 1
        chunk = None
        error = None
        if root_host == self._net._tcp.rank():
            gathered = self._inner.gather(
                data, root=0)  # leader collects local members' args
            items = None
            if self._is_leader():
                items = gathered[self._local_members.index(g_root)]
                if items is None or len(items) != n:
                    error = (f"mpi_tpu: scatter root needs a list of "
                             f"exactly {n} payloads")
                if multi:
                    if error is not None:
                        envelopes = [("err", error)] * len(self._hosts)
                    else:
                        envelopes = [("ok", self._host_chunk(items, hh))
                                     for hh in self._hosts]
                    G.scatter(self._tcp_grp, envelopes,
                              root=self._hosts.index(root_host))
                if error is None:
                    chunk = self._host_chunk(items, root_host)
        else:
            if self._is_leader():
                status, payload = G.scatter(
                    self._tcp_grp, None, root=self._hosts.index(root_host))
                if status == "err":
                    error = payload
                else:
                    chunk = payload
        error = self._inner.bcast(error, root=0)
        if error is not None:
            raise MpiError(error)
        return self._inner.scatter(chunk, root=0)

    def alltoall(self, data: List[Any]) -> List[Any]:
        """Rows to host bundles over TCP, reassembled per local member in
        group-rank order (world alltoall generalized to non-contiguous
        group layouts)."""
        n = len(self._members)
        if len(data) != n:
            raise MpiError(
                f"mpi_tpu: alltoall needs exactly {n} payloads, got "
                f"{len(data)}")
        rows = self._inner.allgather(data)  # [local idx] -> n-list
        if len(self._hosts) == 1:
            me_local = self._local_members.index(self._net.rank())
            my_g = self._local_granks[me_local]
            return [row[my_g] for row in rows]

        def leg(rows_: List[List[Any]]) -> Optional[List[List[Any]]]:
            # bundles[h] = (src group ranks here, rows sliced to h's
            # members); sources are tagged so the receiver can reorder.
            bundles = []
            for hh in self._hosts:
                dst_granks = [gr for gr, g in enumerate(self._members)
                              if self._net._host_of(g) == hh]
                bundles.append([
                    (src_g, [row[d] for d in dst_granks])
                    for src_g, row in zip(self._local_granks, rows_)
                ])
            received = G.alltoall(self._tcp_grp, bundles)
            # received[h] = list of (src_grank, payloads-for-my-members)
            per_src: List[tuple] = sorted(
                (entry for chunk in received for entry in chunk),
                key=lambda e: e[0])
            out_rows = []
            for li in range(len(self._local_members)):
                out_rows.append([payloads[li] for _, payloads in per_src])
            return out_rows

        out_rows = leg(rows) if self._is_leader() else None
        return self._inner.scatter(out_rows, root=0)


def run_spmd_hybrid(fn: Callable[[], Any], net: HybridNetwork,
                    register_facade: bool = True) -> List[Any]:
    """Run ``fn`` on one thread per *local* rank of this host — the
    per-host analogue of :func:`mpi_tpu.backends.xla.run_spmd`; the
    launcher starts one such process per host (same flag ABI as the TCP
    driver, gompirun.go:28-93)."""

    def abort() -> None:
        net._inner._init_barrier.abort()
        net._inner.abort_collectives()
        net._init_done.set()

    def on_failure() -> None:
        # Ranks that errored never reach finalize, so the refcount never
        # drains — close the host TCP mesh here or the listener socket and
        # reader threads leak past the failed run.
        try:
            net._tcp.finalize()
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass

    return drive_rank_threads(
        fn, nranks=net._inner.size(), bind=net.bind_rank, abort=abort,
        inherit_net=net._inner, facade_net=net, name_prefix="mpi-hybrid",
        register_facade=register_facade, on_failure=on_failure)

"""XLA driver — ranks on a device mesh, collectives over ICI.

The tpu-native realization of the reference's process model (SURVEY.md §7,
BASELINE.json north_star). Where the reference maps rank → OS process and
moves bytes over TCP (network.go), this driver maps **rank → device** on a
:class:`jax.sharding.Mesh` axis inside one process:

  * ``init``/``finalize`` — mesh construction + a rank barrier, replacing
    the O(N²) socket handshake (network.go:122-351): XLA already knows the
    slice topology, so bootstrap is local;
  * ``send``/``receive`` — blocking tagged rendezvous between rank threads
    (exactly the reference's contract, mpi.go:122-159) with device-to-device
    array movement (``jax.device_put`` → ICI transfer on TPU slices);
  * collectives — the north star: array payloads are assembled into one
    global sharded array and reduced by a **single compiled XLA collective**
    over the mesh (``mpi_tpu.parallel.collectives``), which rides ICI.
    ``deterministic=True`` uses the canonical binomial tree for
    bitwise-identical results to the TCP driver. Object payloads
    (strings, dicts, ...) use in-process handoff.

Programming model. The reference is SPMD-by-processes: one binary, N
processes, behavior branches on ``Rank()`` (mpi.go:8-14). Here the same
user code runs SPMD-by-threads: :func:`run_spmd` launches one thread per
rank, each bound to its device, so reference-style programs (helloworld,
bounce) run unmodified on a v4-8 — while ``jit``-heavy code is free to use
the functional layer directly for zero-overhead collectives inside a
single trace.

Single-process scope: this driver covers every rank the process can
address (a full v4-8). Multi-host DCN spans are the hybrid driver's job
(hierarchical: XLA within a host, TCP across hosts — see
``mpi_tpu.backends.hybrid``).
"""

from __future__ import annotations

import copy
import threading
import time
from collections import OrderedDict
from typing import TYPE_CHECKING, Any, Callable, Dict, List, Optional, Sequence, Tuple

if TYPE_CHECKING:
    from ..collectives_generic import OpLike

import numpy as np

from ..api import MpiError
from .rendezvous import ReceiveCancelled, Rendezvous

__all__ = ["XlaNetwork", "run_spmd"]


def _jax():
    import jax

    return jax


# --------------------------------------------------------------------------
# Rank-binding inheritance.
#
# The reference's rank is per-*process*, so any goroutine may call
# Send/Receive (helloworld.go:53-81 does exactly that). Here a rank is a
# per-*thread* binding, so threads the user spawns (and the facade's own
# sendrecv helper) would come up unbound. While any run_spmd is active,
# Thread.start is wrapped so a thread started by a bound thread inherits
# its binding — reference-style threaded programs run unmodified.
# --------------------------------------------------------------------------

_patch_lock = threading.Lock()
_active_networks: List["XlaNetwork"] = []
_orig_thread_start = threading.Thread.start


def _patched_start(self: threading.Thread) -> None:
    # Runs in the *parent* thread: snapshot its bindings for the child.
    bindings = [(net, net._tls.rank) for net in list(_active_networks)
                if getattr(net._tls, "rank", None) is not None]
    if bindings and not getattr(self, "_mpi_rank_bound", False):
        orig_run = self.run

        def run_bound() -> None:
            for net, r in bindings:
                net._tls.rank = r
            orig_run()

        self.run = run_bound
        self._mpi_rank_bound = True
    _orig_thread_start(self)


def _activate_inheritance(net: "XlaNetwork") -> None:
    with _patch_lock:
        _active_networks.append(net)
        if threading.Thread.start is _orig_thread_start:
            threading.Thread.start = _patched_start


def _deactivate_inheritance(net: "XlaNetwork") -> None:
    with _patch_lock:
        if net in _active_networks:
            _active_networks.remove(net)
        if not _active_networks:
            threading.Thread.start = _orig_thread_start


class _CollectiveSession:
    """Rank-thread synchronization for native collectives.

    Every rank contributes its payload, a barrier fires, the leader (one
    arbitrary barrier winner) runs the combined computation once, a second
    barrier releases everyone to read their result. Reusable across
    sequential collectives (threading.Barrier auto-resets); collectives
    must be invoked in the same order by all ranks — the standard MPI
    requirement the generic layer documents too."""

    def __init__(self, n: int):
        self._n = n
        self._barrier = threading.Barrier(n)
        self._slots: List[Any] = [None] * n
        self._results: List[Any] = [None] * n
        self._error: Optional[BaseException] = None
        # Per-collective arrival stamps (perf ns): all rank threads
        # share one clock, so the barrier winner reads EXACT skew —
        # the straggler-detection source for the in-process drivers.
        self._arrivals: List[int] = [0] * n

    def _note_skew(self, name: str) -> None:
        from ..observe import flight, metrics
        from ..utils import trace

        if not (flight.enabled or trace.enabled()):
            return
        arr = self._arrivals
        lo, hi = min(arr), max(arr)
        if lo <= 0:
            return
        metrics.note_session_skew(name, (hi - lo) / 1e3, arr.index(hi))

    def run(self, rank: int, value: Any,
            leader: Callable[[List[Any]], List[Any]],
            name: str = "collective") -> Any:
        self._slots[rank] = value
        self._arrivals[rank] = time.perf_counter_ns()
        try:
            arrival = self._barrier.wait()
        except threading.BrokenBarrierError as exc:
            raise MpiError(
                "mpi_tpu: collective aborted (another rank failed)") from exc
        if arrival == 0:
            self._note_skew(name)
            try:
                self._results = leader(list(self._slots))
                self._error = None
            except BaseException as exc:  # noqa: BLE001 - re-raised on all ranks
                self._error = exc
        try:
            self._barrier.wait()
        except threading.BrokenBarrierError as exc:
            raise MpiError(
                "mpi_tpu: collective aborted (another rank failed)") from exc
        if self._error is not None:
            raise MpiError(
                f"mpi_tpu: collective failed on leader: {self._error}"
            ) from self._error
        return self._results[rank]



class _MeshCollectives:
    """Compiled-collective engine over an ordered device list.

    All of the xla driver's native collectives live here so one
    machinery serves both the world (one engine per driver) and any
    communicator group (one engine per ``(context, members)``, built by
    :meth:`XlaNetwork.group_collectives`): a leader thread runs ONE
    compiled XLA program over the engine's (sub-)mesh — psum/all_gather/
    ppermute over ICI on TPU — with host-tree fallbacks when ranks share
    devices (oversubscription) and object-payload fallbacks preserving
    the generic driver's semantics. ``rank_of`` maps the calling thread
    to its rank WITHIN this engine (world rank for the world engine,
    group rank for a communicator's)."""

    def __init__(self, net: "XlaNetwork", devices: List[Any], mesh,
                 rank_of: Callable[[], int]):
        self._net = net
        self._devices = list(devices)
        self._n = len(self._devices)
        self._mesh = mesh
        self._rank_of = rank_of
        self._coll = _CollectiveSession(self._n)
        self._jit_cache: Dict[Tuple, Any] = {}
        self._fillers: "OrderedDict[Tuple, Any]" = OrderedDict()

    def _myrank(self) -> int:
        return self._rank_of()

    @property
    def deterministic_collectives(self) -> bool:
        return self._net.deterministic_collectives

    def _check_rank(self, r: int) -> None:
        if not 0 <= r < self._n:
            raise MpiError(
                f"mpi_tpu: rank {r} out of range [0, {self._n})")


    @staticmethod
    def _validate_payloads(slots: List[np.ndarray]) -> None:
        """Cross-rank shape/dtype agreement + the float64-downcast guard.
        Enforced identically on the mesh and oversubscribed paths so a
        program's behavior never depends on the rank/device ratio."""
        jax = _jax()
        shape, dtype = slots[0].shape, slots[0].dtype
        for i, s in enumerate(slots):
            if s.shape != shape or s.dtype != dtype:
                raise MpiError(
                    f"mpi_tpu: collective payload mismatch: rank 0 has "
                    f"{shape}/{dtype}, rank {i} has {s.shape}/{s.dtype}")
        if dtype.itemsize == 8 and dtype.kind in "fiu" \
                and not jax.config.jax_enable_x64:
            raise MpiError(
                f"mpi_tpu: {dtype} collective payload would silently "
                f"downcast — enable 64-bit mode (JAX_ENABLE_X64=1 or "
                f"jax.config.update('jax_enable_x64', True)) or send "
                f"32-bit data")

    def _global_array(self, slots: List[np.ndarray]):
        """Stack per-rank payloads into one mesh-sharded global array
        (shard i on device i) — the input format XLA collectives want."""
        jax = _jax()
        from jax.sharding import NamedSharding, PartitionSpec as P

        shape = slots[0].shape
        sharding = NamedSharding(self._mesh, P("rank"))
        shards = [
            jax.device_put(np.asarray(s)[None], d)
            for s, d in zip(slots, self._devices)
        ]
        return jax.make_array_from_single_device_arrays(
            (self._n, *shape), sharding, shards)

    def _per_rank(self, global_arr) -> List[np.ndarray]:
        """Split a (n, ...) mesh-sharded result back into per-rank arrays."""
        shards = sorted(global_arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return [np.asarray(s.data)[0] for s in shards]

    def _collective_fn(self, kind: str, op: str = "",
                       deterministic: bool = False, root: int = 0):
        key = (kind, op, deterministic, root) if kind == "bcast" \
            else (kind, op, deterministic)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        jax = _jax()
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from ..parallel import collectives as C

        if kind == "allreduce":
            def per_shard(x):
                # x: (1, *shape) block; reduce over the mesh axis.
                return C.allreduce(x, "rank", op=op,
                                   deterministic=deterministic)

            out_specs = P("rank")
        elif kind == "allgather":
            def per_shard(x):
                # x: (1, *shape) block; gather the full (n, *shape) stack,
                # replicated on every device.
                return C.allgather(x, "rank", axis=0, tiled=True)

            out_specs = P()
        elif kind == "alltoall":
            def per_shard(x):
                # x: (1, n, *shape) — row j is my payload for rank j;
                # after the exchange, slot j holds rank j's payload to me.
                return C.alltoall(x, "rank", split_axis=1, concat_axis=1)

            out_specs = P("rank")
        elif kind == "bcast":
            def per_shard(x):
                # x: (1, *shape) block, real data only on root's shard
                # (fillers elsewhere); the all_gather + static index is
                # XLA's broadcast idiom over ICI.
                return C.bcast(x, root, "rank")

            out_specs = P()
        elif kind == "prefix":
            # Rank-order prefix reduction (scan/exscan). The
            # ``deterministic`` slot carries ``exclusive`` for this kind
            # (the order is always the fixed left fold).
            def per_shard(x):
                # x: (1, *shape) block; prefix over the mesh axis.
                return C.prefix_reduce(x[0], "rank", op=op,
                                       exclusive=deterministic)[None]

            out_specs = P("rank")
        elif kind == "reduce_scatter":
            def per_shard(x):
                # x: (1, L, *shape); each rank keeps its reduced L/n block.
                y = x[0]
                # deterministic → canonical size-selected order; the
                # ring/tree choice lives in parallel.collectives next
                # to allreduce's so the rule can never fork.
                out = C.reduce_scatter(y, "rank", op=op,
                                       deterministic=deterministic)
                return out[None]

            out_specs = P("rank")
        else:  # pragma: no cover - future kinds
            raise MpiError(f"unknown collective kind {kind}")

        fn = jax.jit(jax.shard_map(per_shard, mesh=self._mesh,
                                   in_specs=P("rank"), out_specs=out_specs,
                                   check_vma=False))
        self._jit_cache[key] = fn
        return fn

    _FILLER_CACHE = 32

    def _filler_shard(self, device, shape, dtype):
        """A cached zeros block on ``device`` — the placeholder shard for
        global arrays whose real data lives on one device (bcast input);
        its contents are never read. LRU-capped like DevicePipe's."""
        key = (device, shape, str(dtype))
        arr = self._fillers.get(key)
        if arr is not None:
            self._fillers.move_to_end(key)
            return arr
        arr = _jax().device_put(np.zeros((1, *shape), dtype), device)
        self._fillers[key] = arr
        while len(self._fillers) > self._FILLER_CACHE:
            self._fillers.popitem(last=False)
        return arr

    def _canonical_array(self, payload) -> Optional[np.ndarray]:
        """``payload`` as an ndarray if it can ride a compiled path:
        array-typed, ndim >= 1, and a dtype XLA will not rewrite
        (int64/float64 without x64 fall back to the object path, which
        returns payloads untouched)."""
        jax = _jax()
        if self._mesh is None or not isinstance(
                payload, (np.ndarray, jax.Array)):
            return None
        arr = np.asarray(payload)
        if arr.ndim < 1:
            return None
        try:
            if jax.dtypes.canonicalize_dtype(arr.dtype) != arr.dtype:
                return None
        except TypeError:
            return None
        return arr

    def _uniform_arrays(self, slots: List[Any]) -> Optional[List[np.ndarray]]:
        """All payloads canonical arrays of one shape/dtype, else None."""
        np_slots = []
        for s in slots:
            arr = self._canonical_array(s)
            if arr is None:
                return None
            np_slots.append(arr)
        first = np_slots[0]
        if not all(s.shape == first.shape and s.dtype == first.dtype
                   for s in np_slots):
            return None
        return np_slots

    def allreduce(self, data: Any, op: "OpLike" = "sum",
                  deterministic: Optional[bool] = None) -> Any:
        """North-star collective: one XLA reduction over the mesh.

        Payloads must be numeric (anything ``np.asarray`` maps to a
        numeric dtype, matching what the generic driver can reduce);
        a non-numeric payload raises on every rank."""
        det = (self.deterministic_collectives if deterministic is None
               else deterministic)
        me = self._myrank()

        def leader(slots: List[Any]) -> List[Any]:
            np_slots = [np.asarray(s) for s in slots]
            if np_slots[0].dtype.kind not in "fiubc":
                raise MpiError(
                    f"mpi_tpu: allreduce requires numeric payloads, got "
                    f"dtype {np_slots[0].dtype}")
            scalar = np_slots[0].ndim == 0
            self._validate_payloads(np_slots)
            if self._mesh is None or callable(op):
                # Oversubscribed ranks share devices → no mesh; user
                # callable ops (MPI_Op_create analogue) are host
                # functions XLA cannot compile. Either way reduce on
                # the host in the canonical order — ring or tree by the
                # shared size rule (always deterministic, bitwise-equal
                # to the TCP oracle on both sides of the threshold).
                from ..collectives_generic import canonical_combine

                total = canonical_combine(np_slots, op)
                per = [total.copy() for _ in range(self._n)]
            else:
                garr = self._global_array(np_slots)
                out = self._collective_fn("allreduce", op, det)(garr)
                per = self._per_rank(out)
            if scalar:
                per = [p[()] for p in per]
            return per

        from ..collectives_generic import check_op

        check_op(op)
        return self._coll.run(me, data, leader, name="allreduce")

    def barrier(self) -> None:
        self._coll.run(self._myrank(), None,
                       lambda slots: [None] * self._n, name="barrier")

    def bcast(self, data: Any, root: int = 0) -> Any:
        """Array payloads broadcast as ONE compiled XLA program: the
        root's array becomes its shard of a mesh-global input (cached
        zero fillers stand in elsewhere — never read), and the compiled
        ``all_gather`` + static index rides ICI. Objects take the
        in-process handoff (deep-copied per rank); broadcast arrays may
        alias across ranks — treat them as read-only, as with
        ``allgather``."""
        self._check_rank(root)
        jax = _jax()

        def leader(slots: List[Any]) -> List[Any]:
            payload = slots[root]
            arr = self._canonical_array(payload)
            if arr is None:
                return [payload if i == root else copy.deepcopy(payload)
                        for i in range(self._n)]
            from jax.sharding import NamedSharding, PartitionSpec as P

            shards = [
                jax.device_put(arr[None], d) if i == root
                else self._filler_shard(d, arr.shape, arr.dtype)
                for i, d in enumerate(self._devices)
            ]
            garr = jax.make_array_from_single_device_arrays(
                (self._n, *arr.shape),
                NamedSharding(self._mesh, P("rank")), shards)
            out = self._collective_fn("bcast", root=root)(garr)
            rows = np.asarray(out)[0]
            return [rows for _ in range(self._n)]

        return self._coll.run(self._myrank(), data, leader, name="bcast")

    def gather(self, data: Any, root: int = 0) -> Optional[List[Any]]:
        """Uniform array payloads ride the compiled all_gather program
        (XLA's ICI-ring collective; the non-root copies are the cost of
        staying on one compiled path) and only root keeps the result;
        otherwise in-process handoff."""
        self._check_rank(root)

        def leader(slots: List[Any]) -> List[Any]:
            np_slots = self._uniform_arrays(slots)
            if np_slots is None:
                return [list(slots) if i == root else None
                        for i in range(self._n)]
            garr = self._global_array(np_slots)
            out = self._collective_fn("allgather")(garr)
            rows = np.asarray(out)
            gathered = [rows[i] for i in range(self._n)]
            return [gathered if i == root else None
                    for i in range(self._n)]

        return self._coll.run(self._myrank(), data, leader, name="gather")

    def allgather(self, data: Any) -> List[Any]:
        """Array payloads of matching shape/dtype gather with ONE compiled
        XLA all_gather over the mesh (ICI on TPU); anything else (objects,
        ragged shapes) uses the in-process handoff. Returned entries may
        alias between ranks, matching the generic driver's semantics.

        The dtype gate is canonicalization only — anything XLA would
        rewrite (int64/float64/complex128 without x64) takes the
        in-process handoff, which returns payloads untouched; bfloat16
        stays on the compiled path."""

        def leader(slots: List[Any]) -> List[Any]:
            np_slots = self._uniform_arrays(slots)
            if np_slots is None:
                return [list(slots) for _ in range(self._n)]
            garr = self._global_array(np_slots)
            out = self._collective_fn("allgather", "", False)(garr)
            rows = np.asarray(out)
            gathered = [rows[i] for i in range(self._n)]
            # Fresh list per rank (elements may alias; the containers must
            # not — same contract as the fallback path).
            return [list(gathered) for _ in range(self._n)]

        return self._coll.run(self._myrank(), data, leader,
                              name="allgather")

    def scatter(self, data: Optional[List[Any]], root: int = 0) -> Any:
        """A uniform array list scatters by committing the stacked
        payload straight to the ``P('rank')`` sharding: argument
        placement is the one legal entry point for root-local data onto
        the mesh (an XLA program's inputs must already live on the
        mesh's devices), and it moves each shard exactly once to its
        owner. Each rank's result is device-resident on its own device.
        Mixed payloads take the in-process handoff."""
        self._check_rank(root)
        jax = _jax()

        def leader(slots: List[Any]) -> List[Any]:
            items = slots[root]
            if items is None or len(items) != self._n:
                raise MpiError(
                    f"mpi_tpu: scatter root needs a list of exactly "
                    f"{self._n} payloads")
            np_items = self._uniform_arrays(list(items))
            if np_items is None:
                return list(items)
            from jax.sharding import NamedSharding, PartitionSpec as P

            out = jax.device_put(np.stack(np_items),
                                 NamedSharding(self._mesh, P("rank")))
            return self._per_rank(out)

        return self._coll.run(self._myrank(), data, leader, name="scatter")

    def alltoall(self, data: List[Any]) -> List[Any]:
        """Uniform payload matrices exchange with ONE compiled XLA
        AllToAll over the mesh; mixed payloads use in-process handoff."""
        if len(data) != self._n:
            raise MpiError(
                f"mpi_tpu: alltoall needs exactly {self._n} payloads, "
                f"got {len(data)}")

        def leader(slots: List[List[Any]]) -> List[List[Any]]:
            flat = [p for row in slots for p in row]
            np_flat = self._uniform_arrays(flat)
            if np_flat is None:
                return [[slots[src][dst] for src in range(self._n)]
                        for dst in range(self._n)]
            n = self._n
            stacked = [np.stack(np_flat[i * n:(i + 1) * n])
                       for i in range(n)]  # (n, *shape) per source rank
            garr = self._global_array(stacked)          # (n, n, *shape)
            out = self._collective_fn("alltoall", "", False)(garr)
            return [list(row) for row in self._per_rank(out)]

        return self._coll.run(self._myrank(), data, leader,
                              name="alltoall")

    def reduce(self, data: Any, root: int = 0, op: "OpLike" = "sum") -> Optional[Any]:
        self._check_rank(root)
        result = self.allreduce(data, op=op)
        return result if self._myrank() == root else None

    def reduce_scatter(self, data: Any, op: "OpLike" = "sum",
                       deterministic: Optional[bool] = None) -> Any:
        """Reduce across ranks and keep this rank's block of the result:
        the payload's leading axis splits into ``size`` equal blocks and
        rank ``i`` returns reduced block ``i`` — one compiled
        ``psum_scatter`` (or the binomial tree + slice when
        ``deterministic``) over the mesh."""
        det = (self.deterministic_collectives if deterministic is None
               else deterministic)
        from ..collectives_generic import canonical_combine, check_op

        check_op(op)

        def leader(slots: List[Any]) -> List[Any]:
            np_slots = [np.asarray(s) for s in slots]
            self._validate_payloads(np_slots)
            shape = np_slots[0].shape
            if len(shape) < 1 or shape[0] % self._n:
                raise MpiError(
                    f"mpi_tpu: reduce_scatter payload leading axis "
                    f"{shape or 'scalar'} must divide into {self._n} "
                    f"equal blocks")
            m = shape[0] // self._n
            if self._mesh is None or callable(op):
                total = canonical_combine(np_slots, op)
                return [total[i * m:(i + 1) * m].copy()
                        for i in range(self._n)]
            garr = self._global_array(np_slots)
            out = self._collective_fn("reduce_scatter", op, det)(garr)
            return self._per_rank(out)

        return self._coll.run(self._myrank(), data, leader,
                              name="reduce_scatter")

    def scan(self, data: Any, op: "OpLike" = "sum") -> Any:
        """Inclusive prefix reduction in rank order, as ONE compiled
        program (``parallel.collectives.prefix_reduce`` — the jittable
        MPI_Scan whose left-fold order is the cross-backend bitwise
        contract); scalars, objects, and callable ops fold on the host
        in the same order."""
        return self._prefix(data, op, exclusive=False)

    def exscan(self, data: Any, op: "OpLike" = "sum") -> Optional[Any]:
        """Exclusive prefix reduction; rank 0 gets None (MPI_Exscan)."""
        return self._prefix(data, op, exclusive=True)

    def _prefix(self, data: Any, op: "OpLike", exclusive: bool) -> Any:
        from ..collectives_generic import check_op, combine

        check_op(op)

        def leader(slots: List[Any]) -> List[Any]:
            np_slots = self._uniform_arrays(slots)
            # The compiled path is float/int/uint only: jnp's
            # add/multiply/minimum/maximum reject bool and complex in
            # ways numpy's don't, and prefix_reduce's exclusive identity
            # doesn't exist for them either — those (plus scalars,
            # objects, callable ops, oversubscription) take the host
            # fold, identical order.
            if np_slots is None or callable(op) or self._mesh is None \
                    or np_slots[0].dtype.kind not in "fiu":
                # Raw slots (combine() normalizes operands), so rank 0's
                # inclusive result stays the caller's own payload type —
                # matching collectives_generic.scan. One running left
                # fold yields every rank's prefix in n-1 combines (the
                # O(n^2) per-rank refold would be paid exactly where
                # combines are most expensive).
                items = list(slots)
                prefixes: List[Any] = []
                acc = items[0]
                for it in items[1:]:
                    prefixes.append(acc)
                    acc = combine(acc, it, op)
                if exclusive:
                    return [None] + prefixes
                return prefixes + [acc]
            self._validate_payloads(np_slots)
            fn = self._collective_fn("prefix", op, exclusive)
            per = self._per_rank(fn(self._global_array(np_slots)))
            if exclusive:
                per = [None] + list(per[1:])  # rank 0: MPI_Exscan contract
            return per

        return self._coll.run(self._myrank(), data, leader,
                              name="exscan" if exclusive else "scan")


class XlaNetwork:
    """Backend implementing the :class:`mpi_tpu.api.Interface` SPI over a
    device mesh. Construct with the rank count (defaults to every visible
    device) and hand user code to :func:`run_spmd`."""

    # Rank threads share this process's address space, so RMA windows
    # over this driver support MPI_Win_shared_query (mpi_tpu.window).
    SUPPORTS_SHARED_WINDOWS = True
    # ... and one process-global tracer buffer: the observe layer's
    # trace collection writes the shared buffer once (rank threads
    # appear as tid lanes) instead of gathering N duplicate copies.
    SHARED_PROCESS_TRACER = True

    def __init__(self, n: Optional[int] = None,
                 devices: Optional[Sequence[Any]] = None,
                 deterministic_collectives: bool = False,
                 oversubscribe: bool = False):
        jax = _jax()
        from ..parallel.mesh import make_mesh

        if devices is None:
            devices = jax.devices()[: n] if n is not None else jax.devices()
        if n is not None and len(devices) < n:
            if oversubscribe and devices:
                # Reference parity: N ranks on fewer cores is always legal
                # (gompirun spawns N processes regardless of CPU count) —
                # map ranks onto devices round-robin.
                base = list(devices)
                devices = [base[r % len(base)] for r in range(n)]
            else:
                raise MpiError(
                    f"mpi_tpu: need {n} devices for {n} ranks, have "
                    f"{len(devices)} (pass oversubscribe=True to share)")
        self._devices = list(devices)
        self._n = len(self._devices)
        # With oversubscribed (duplicate) devices there is no valid mesh;
        # native collectives then run on the canonical numpy tree instead
        # of a compiled XLA collective.
        if len(set(self._devices)) == len(self._devices):
            self._mesh = make_mesh(devices=self._devices)
        else:
            self._mesh = None
        self._tls = threading.local()
        self._init_barrier = threading.Barrier(self._n)
        # One rendezvous per ordered (src, dst) pair, created lazily.
        self._pairs: Dict[Tuple[int, int], Rendezvous] = {}
        self._pairs_lock = threading.Lock()
        self._pipe = None  # lazy DevicePipe (compiled p2p transfers)
        self._initialized = False
        self.deterministic_collectives = deterministic_collectives
        # Native collectives: one world engine + lazily-built engines per
        # communicator group (group_collectives), all sharing this
        # driver's devices and rank binding.
        self._world_coll = _MeshCollectives(self, self._devices, self._mesh,
                                            self._myrank)
        self._group_colls: "OrderedDict[Tuple, _MeshCollectives]" = \
            OrderedDict()

    # -- rank binding --------------------------------------------------------

    def bind_rank(self, rank: int) -> None:
        """Associate the calling thread with ``rank`` (run_spmd does this)."""
        if not 0 <= rank < self._n:
            raise MpiError(f"mpi_tpu: rank {rank} out of range [0, {self._n})")
        self._tls.rank = rank

    def _myrank(self) -> int:
        r = getattr(self._tls, "rank", None)
        if r is None:
            if self._n == 1:
                return 0
            raise MpiError(
                "mpi_tpu: calling thread has no rank binding — run your "
                "program under mpi_tpu.backends.xla.run_spmd(fn, n)")
        return r

    def device(self, rank: Optional[int] = None):
        """The jax device backing ``rank`` (default: calling thread's)."""
        return self._devices[self._myrank() if rank is None else rank]

    @property
    def mesh(self):
        return self._mesh

    # -- Interface ------------------------------------------------------------

    def init(self) -> None:
        """Barrier across all rank threads (the bootstrap analogue —
        network.go:122-159 collapses to a thread barrier because XLA
        already knows the topology)."""
        self._myrank()  # validates binding
        if self._n > 1:
            try:
                self._init_barrier.wait(timeout=60.0)
            except threading.BrokenBarrierError as exc:
                raise MpiError(
                    "mpi_tpu: init barrier broken (a rank failed to start)"
                ) from exc
        self._initialized = True

    def finalize(self) -> None:
        self._initialized = False

    def rank(self) -> int:
        return self._myrank()

    def size(self) -> int:
        return self._n

    def host_key(self) -> str:
        """All xla-driver ranks share one process (one host) — a single
        key, so ``Comm.split_type("host")`` yields the whole world."""
        return "local"

    # -- point-to-point -------------------------------------------------------

    def _pair(self, src: int, dst: int) -> Rendezvous:
        key = (src, dst)
        with self._pairs_lock:
            rv = self._pairs.get(key)
            if rv is None:
                rv = Rendezvous(send_peer=dst, recv_peer=src)
                self._pairs[key] = rv
            return rv

    def send(self, data: Any, dest: int, tag: int) -> None:
        """Blocking rendezvous send. Array payloads move to the
        destination rank's device through a **compiled ppermute program**
        (:class:`mpi_tpu.parallel.p2p.DevicePipe`) — a pure ICI hop on
        TPU with no host round-trip of the payload, the tpu-native data
        path replacing the reference's socket write (network.go:562-567).
        Host objects are copied, preserving the reference's value
        semantics (gob round-trip implies the receiver never aliases
        sender memory)."""
        me = self._myrank()
        self._check_rank(dest)
        jax = _jax()
        from ..utils import trace

        tracing = trace.enabled()
        if isinstance(data, jax.Array):
            if tracing:
                with trace.span("xla.transfer", dest=dest, tag=tag):
                    payload = self._device_transfer(data, dest)
            else:
                payload = self._device_transfer(data, dest)
        elif isinstance(data, np.ndarray):
            payload = data.copy()
        elif isinstance(data, (bytes, str, int, float, bool, complex,
                               type(None))):
            payload = data  # immutable
        else:
            payload = copy.deepcopy(data)
        if tracing:
            from ..api import _payload_bytes

            trace.count(f"wire.xla.tx.bytes.peer{dest}",
                        _payload_bytes(data))
            with trace.span("xla.rendezvous_send", dest=dest, tag=tag):
                self._pair(me, dest).send(tag, payload)
        else:
            self._pair(me, dest).send(tag, payload)

    def _device_transfer(self, data, dest: int):
        """Compiled device→device move of a jax.Array to ``dest``'s device.

        Single-device source arrays ride the DevicePipe's cached ppermute
        executable (ICI); already-in-place, sharded, or uncommitted
        arrays — and oversubscribed/meshless configurations — fall back
        to ``jax.device_put`` (which is a no-op when already resident)."""
        jax = _jax()
        dst_dev = self._devices[dest]
        src_devs = getattr(data, "devices", lambda: set())()
        if (self._mesh is not None and len(src_devs) == 1
                and getattr(data, "committed", True)):
            src_dev = next(iter(src_devs))
            if src_dev != dst_dev:
                with self._pairs_lock:
                    if self._pipe is None:
                        from ..parallel.p2p import DevicePipe

                        self._pipe = DevicePipe()
                    pipe = self._pipe
                return pipe.transfer(data, src_dev, dst_dev)
        return jax.device_put(data, dst_dev)

    def receive(self, source: int, tag: int, out: Optional[Any] = None) -> Any:
        me = self._myrank()
        self._check_rank(source)
        from ..utils import trace

        if trace.enabled():
            from ..api import _payload_bytes

            with trace.span("xla.recv_wait", source=source, tag=tag):
                payload = self._pair(source, me).receive(tag)
            trace.count(f"wire.xla.rx.bytes.peer{source}",
                        _payload_bytes(payload))
        else:
            payload = self._pair(source, me).receive(tag)
        if out is not None and isinstance(out, np.ndarray) \
                and isinstance(payload, np.ndarray) \
                and out.shape == payload.shape and out.dtype == payload.dtype:
            out[...] = payload
            return out
        return payload

    def cancel_receive(self, source: int, tag: int) -> bool:
        me = self._myrank()
        self._check_rank(source)
        exc = ReceiveCancelled(
            f"mpi_tpu: receive(source={source}, tag={tag}) cancelled")
        return self._pair(source, me).cancel(tag, exc)

    def iprobe(self, source: int, tag: int) -> bool:
        """Non-consuming MPI_Iprobe: True when the sender is parked at
        this pair's rendezvous with ``tag`` (a receive would complete
        immediately)."""
        me = self._myrank()
        self._check_rank(source)
        return self._pair(source, me).probe(tag)

    def _check_rank(self, r: int) -> None:
        if not 0 <= r < self._n:
            raise MpiError(f"mpi_tpu: peer rank {r} out of range [0, {self._n})")

    # -- native collectives (world engine; see _MeshCollectives) -------------

    def allreduce(self, data: Any, op: "OpLike" = "sum",
                  deterministic: Optional[bool] = None) -> Any:
        return self._world_coll.allreduce(data, op=op,
                                          deterministic=deterministic)

    def barrier(self) -> None:
        return self._world_coll.barrier()

    def bcast(self, data: Any, root: int = 0) -> Any:
        return self._world_coll.bcast(data, root=root)

    def gather(self, data: Any, root: int = 0) -> Optional[List[Any]]:
        return self._world_coll.gather(data, root=root)

    def allgather(self, data: Any) -> List[Any]:
        return self._world_coll.allgather(data)

    def scatter(self, data: Optional[List[Any]], root: int = 0) -> Any:
        return self._world_coll.scatter(data, root=root)

    def alltoall(self, data: List[Any]) -> List[Any]:
        return self._world_coll.alltoall(data)

    def reduce(self, data: Any, root: int = 0,
               op: "OpLike" = "sum") -> Optional[Any]:
        return self._world_coll.reduce(data, root=root, op=op)

    def reduce_scatter(self, data: Any, op: "OpLike" = "sum",
                       deterministic: Optional[bool] = None) -> Any:
        return self._world_coll.reduce_scatter(data, op=op,
                                               deterministic=deterministic)

    def scan(self, data: Any, op: "OpLike" = "sum") -> Any:
        return self._world_coll.scan(data, op=op)

    def exscan(self, data: Any, op: "OpLike" = "sum") -> Optional[Any]:
        return self._world_coll.exscan(data, op=op)

    # -- communicator group engines ------------------------------------------

    def group_collectives(self, members, ctx: int) -> _MeshCollectives:
        """Compiled-collective engine for a communicator group: the
        members' devices become a sub-mesh and every collective in the
        suite runs as one compiled XLA program over it (host/object
        fallbacks included), exactly like the world path. One shared
        engine per ``(ctx, members)`` — all member rank threads must use
        the same instance, since it holds their rendezvous barrier."""
        key = (int(ctx), tuple(int(m) for m in members))
        with self._pairs_lock:
            eng = self._group_colls.get(key)
            if eng is not None:
                self._group_colls.move_to_end(key)
                return eng
            from ..parallel.mesh import make_mesh

            for m in key[1]:
                self._check_rank(m)
            devs = [self._devices[m] for m in key[1]]
            mesh = (make_mesh(devices=devs)
                    if len(set(devs)) == len(devs) else None)
            members_t = key[1]
            eng = _MeshCollectives(
                self, devs, mesh,
                lambda mt=members_t: mt.index(self._myrank()))
            self._group_colls[key] = eng
            # LRU backstop for leaked communicators (dup-per-call
            # patterns): each engine pins compiled executables and filler
            # device buffers. Comm.free() is the precise release; the cap
            # only evicts least-recently-used engines, which are safe to
            # drop unless more than _GROUP_ENGINE_CACHE communicators are
            # *concurrently* mid-collective (an evicted-but-live group
            # would re-create its engine and lose barrier pairing).
            while len(self._group_colls) > self._GROUP_ENGINE_CACHE:
                self._group_colls.popitem(last=False)
        return eng

    _GROUP_ENGINE_CACHE = 128

    def release_group_collectives(self, members, ctx: int) -> None:
        """Drop the group engine for ``(ctx, members)`` (Comm.free):
        frees its compiled programs and filler buffers. Idempotent; must
        not race a collective in flight on that communicator."""
        key = (int(ctx), tuple(int(m) for m in members))
        with self._pairs_lock:
            self._group_colls.pop(key, None)

    def abort_collectives(self) -> None:
        """Break every collective barrier (world + group engines) so rank
        threads blocked in a collective fail fast when a sibling dies."""
        self._world_coll._coll._barrier.abort()
        with self._pairs_lock:
            engines = list(self._group_colls.values())
        for e in engines:
            e._coll._barrier.abort()



def drive_rank_threads(fn: Callable[[], Any], *, nranks: int,
                       bind: Callable[[int], None],
                       abort: Callable[[], None],
                       inherit_net: "XlaNetwork",
                       facade_net: Any,
                       name_prefix: str = "mpi-rank",
                       register_facade: bool = True,
                       on_failure: Optional[Callable[[], None]] = None
                       ) -> List[Any]:
    """Shared thread-per-rank driver used by ``run_spmd`` (xla) and
    ``run_spmd_hybrid``: spawn, bind, join with a bounded grace period
    once any rank errors, release the facade, and re-raise the root-cause
    error (broken-barrier collateral is reported only if nothing else
    failed)."""
    from .. import api

    if register_facade:
        api.register(facade_net)
    results: List[Any] = [None] * nranks
    errors: List[Optional[BaseException]] = [None] * nranks
    _activate_inheritance(inherit_net)

    def runner(r: int) -> None:
        bind(r)
        try:
            results[r] = fn()
        except BaseException as exc:  # noqa: BLE001 - aggregated below
            errors[r] = exc
            abort()

    threads = [threading.Thread(target=runner, args=(r,),
                                name=f"{name_prefix}-{r}", daemon=True)
               for r in range(nranks)]
    for t in threads:
        t.start()
    # Join, but once any rank has errored give stragglers a bounded grace
    # period (a failed partner can leave a rank parked in a rendezvous that
    # will never complete — don't hang the launcher on it).
    import time as _time

    try:
        deadline: Optional[float] = None
        while True:
            alive = [t for t in threads if t.is_alive()]
            if not alive:
                break
            if any(e is not None for e in errors):
                if deadline is None:
                    deadline = _time.monotonic() + 10.0
                elif _time.monotonic() > deadline:
                    break
            for t in alive:
                t.join(timeout=0.1)
    finally:
        _deactivate_inheritance(inherit_net)
        if register_facade:
            api._release_backend(facade_net)
        if on_failure is not None and any(e is not None for e in errors):
            on_failure()
    # Prefer the root-cause error: ranks that merely saw a broken barrier
    # (init or collective) are collateral of whichever rank failed first.
    secondary = None
    for e in errors:
        if e is None:
            continue
        if isinstance(e, MpiError) and \
                isinstance(e.__cause__, threading.BrokenBarrierError):
            secondary = secondary or e
            continue
        raise e
    if secondary is not None:
        raise secondary
    return results


def run_spmd(fn: Callable[[], Any], n: Optional[int] = None,
             net: Optional[XlaNetwork] = None,
             register_facade: bool = True) -> List[Any]:
    """Run ``fn`` SPMD: one thread per rank, each bound to a mesh device —
    the in-process analogue of ``gompirun N prog`` (gompirun.go:28-93).

    ``fn`` is reference-style user code: it calls ``mpi_tpu.init()``,
    branches on ``mpi_tpu.rank()``, communicates, ``mpi_tpu.finalize()``.
    Returns the per-rank return values. The first rank exception is
    re-raised after all threads stop."""
    # Explicit rank counts oversubscribe like gompirun does (N processes
    # regardless of core count, gompirun.go:46-51).
    network = net or XlaNetwork(n=n, oversubscribe=True)

    def abort() -> None:
        network._init_barrier.abort()
        network.abort_collectives()

    return drive_rank_threads(
        fn, nranks=network.size(), bind=network.bind_rank, abort=abort,
        inherit_net=network, facade_net=network,
        register_facade=register_facade)

"""``jax.distributed`` multi-process bring-up on the ``-mpi-*`` flag ABI.

The reference's cluster story is "every process receives
``-mpi-addr``/``-mpi-alladdr`` and derives its rank from the sorted
address list" (/root/reference/network.go:94-109); its bootstrap is then
an O(N²) socket handshake. The tpu-native multi-host bootstrap is
``jax.distributed.initialize`` — one coordinator, everyone else dials it,
and afterwards ``jax.devices()`` spans every chip of every process so
GSPMD programs (and their collectives) run globally over ICI/DCN.

This module reuses the reference's flag ABI verbatim for that bring-up:

  * **process id** = index of own address in the sorted address list —
    the exact ``assignRanks`` rule (network.go:94-109), so the launcher
    needs no new protocol;
  * **coordinator** = owner of the first sorted address (rank 0), the
    deterministic-leaderless analogue of the reference's "everyone knows
    everyone" bootstrap.

Usage (the launcher injects the flags, ``python -m mpi_tpu.launch.mpirun
--distributed N prog.py``)::

    import mpi_tpu.distributed as dist

    dist.initialize_from_flags()       # jax.distributed handshake
    mesh = dist.global_mesh()          # all devices of all processes
    # ... shard_map / pjit programs over `mesh`; use
    # jax.make_array_from_process_local_data for per-process inputs.

The imperative thread-per-rank drivers are deliberately NOT layered over
this: a multi-process mesh is a single-program SPMD world (every process
runs the same compiled collectives), which is the functional layer's
programming model. The hybrid driver remains the imperative multi-host
path (XLA within a host, TCP between hosts).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from . import flags as flagmod
from .api import MpiError

__all__ = [
    "resolve_topology",
    "initialize_from_flags",
    "initialize",
    "global_mesh",
]

_DEFAULT_HOST = "127.0.0.1"


def resolve_topology(addr: Optional[str] = None,
                     addrs: Optional[Sequence[str]] = None
                     ) -> Tuple[str, int, int]:
    """(coordinator_address, num_processes, process_id) from the sorted
    address list — pure function, unit-testable without jax."""
    if addr is None or addrs is None:
        fl = flagmod.get_flags()
        addr = addr if addr is not None else fl.addr
        addrs = list(addrs) if addrs is not None else list(fl.alladdr or [])
    if not addr or not addrs:
        raise MpiError(
            "mpi_tpu: distributed mode needs --mpi-addr and --mpi-alladdr "
            "(the launcher injects them; see mpi_tpu.launch.mpirun)")
    ordered = sorted(addrs)
    for a, b in zip(ordered, ordered[1:]):
        if a == b:
            raise MpiError(
                f"mpi_tpu: duplicate address {a!r} in --mpi-alladdr")
    try:
        pid = ordered.index(addr)
    except ValueError:
        raise MpiError(
            f"mpi_tpu: own address {addr!r} not in --mpi-alladdr "
            f"{ordered}") from None
    coord = ordered[0]
    if coord.startswith(":"):
        # Bare ":port" addresses (the launcher's localhost form) need a
        # dialable host for everyone else.
        coord = _DEFAULT_HOST + coord
    return coord, len(ordered), pid


def initialize(coordinator: str, num_processes: int, process_id: int,
               local_device_ids: Optional[List[int]] = None) -> None:
    """Thin wrapper over ``jax.distributed.initialize`` (idempotence
    guard included: a second call in one process is an error in jax)."""
    import jax

    state = getattr(jax._src.distributed, "global_state", None)
    if state is not None and getattr(state, "client", None) is not None:
        raise MpiError(
            "mpi_tpu: jax.distributed already initialized in this process")
    kwargs = {}
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)


def initialize_from_flags(addr: Optional[str] = None,
                          addrs: Optional[Sequence[str]] = None) -> int:
    """Bring up ``jax.distributed`` from the ``-mpi-*`` flag ABI; returns
    this process's id. After this, ``jax.devices()`` is global while
    ``jax.local_devices()`` is this process's share."""
    coord, n, pid = resolve_topology(addr, addrs)
    if n > 1:
        initialize(coord, n, pid)
    return pid


def global_mesh(axis: str = "rank"):
    """A 1-D mesh over every device of every process (call after
    :func:`initialize_from_flags`)."""
    from .parallel.mesh import make_mesh

    return make_mesh(axis=axis)

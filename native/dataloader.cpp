// dataloader — native batch-gather kernel for the training data path.
//
// The reference has no data subsystem; the rebuild's loader
// (mpi_tpu/data.py) assembles each training batch by gathering `batch`
// windows of `seq` tokens out of a (typically memory-mapped) corpus and
// widening them to int32. In Python that is a per-window loop plus a
// stack copy under the GIL — exactly the work that should overlap with
// the previous step's device compute. This kernel does the whole
// gather+widen in one ctypes call with the GIL released, optionally
// fanned across threads (row-partitioned, no false sharing: each thread
// writes disjoint output rows).
//
// Token dtypes: u8, u16, u32/i32 (token_bytes = 1, 2, 4). u32 values
// above INT32_MAX wrap negative on widen — callers must validate their
// corpus ids against the model vocab (examples/train.py shows the
// loud-check pattern); realistic vocabularies sit far below 2^31.
//
// Returns 0, or -EINVAL for bad arguments (out-of-range window index —
// checked up front so a bad index can never read past the corpus).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

template <typename T>
void gather_rows(const uint8_t *base, const int64_t *windows,
                 uint32_t row_begin, uint32_t row_end, uint32_t seq,
                 int32_t *out) {
  for (uint32_t b = row_begin; b < row_end; ++b) {
    const T *src = reinterpret_cast<const T *>(base) +
                   static_cast<uint64_t>(windows[b]) * seq;
    int32_t *dst = out + static_cast<uint64_t>(b) * seq;
    for (uint32_t i = 0; i < seq; ++i) dst[i] = static_cast<int32_t>(src[i]);
  }
}

void gather_span(const uint8_t *base, int token_bytes,
                 const int64_t *windows, uint32_t row_begin,
                 uint32_t row_end, uint32_t seq, int32_t *out) {
  switch (token_bytes) {
    case 1:
      gather_rows<uint8_t>(base, windows, row_begin, row_end, seq, out);
      break;
    case 2:
      gather_rows<uint16_t>(base, windows, row_begin, row_end, seq, out);
      break;
    case 4:
      // memcpy fast path: same width, reinterpret as int32
      for (uint32_t b = row_begin; b < row_end; ++b) {
        std::memcpy(out + static_cast<uint64_t>(b) * seq,
                    base + static_cast<uint64_t>(windows[b]) * seq * 4,
                    static_cast<uint64_t>(seq) * 4);
      }
      break;
  }
}

}  // namespace

extern "C" {

// Gather `batch` windows of `seq` tokens (window w = tokens
// [windows[b]*seq, (windows[b]+1)*seq)) from a corpus of `n_tokens`
// tokens of width `token_bytes`, widening into the int32 row-major
// output (batch, seq). `nthreads` <= 1 runs inline; otherwise rows are
// split across std::threads (use the physical core count — on a
// single-core host threads only add overhead).
int dl_gather(const uint8_t *base, uint64_t n_tokens, int token_bytes,
              const int64_t *windows, uint32_t batch, uint32_t seq,
              int32_t *out, int nthreads) {
  if (base == nullptr || windows == nullptr || out == nullptr)
    return -EINVAL;
  if (token_bytes != 1 && token_bytes != 2 && token_bytes != 4)
    return -EINVAL;
  if (seq == 0) return -EINVAL;
  const uint64_t n_windows = n_tokens / seq;
  for (uint32_t b = 0; b < batch; ++b) {
    if (windows[b] < 0 || static_cast<uint64_t>(windows[b]) >= n_windows)
      return -EINVAL;
  }
  if (nthreads <= 1 || batch < 2) {
    gather_span(base, token_bytes, windows, 0, batch, seq, out);
    return 0;
  }
  const uint32_t workers =
      static_cast<uint32_t>(nthreads) < batch ? nthreads : batch;
  std::vector<std::thread> threads;
  threads.reserve(workers);
  const uint32_t rows_per = (batch + workers - 1) / workers;
  for (uint32_t t = 0; t < workers; ++t) {
    uint32_t lo = t * rows_per;
    uint32_t hi = lo + rows_per < batch ? lo + rows_per : batch;
    if (lo >= hi) break;
    threads.emplace_back(gather_span, base, token_bytes, windows, lo, hi,
                         seq, out);
  }
  for (auto &th : threads) th.join();
  return 0;
}

int dl_version() { return 1; }

}  // extern "C"

// shmcore — shared-memory ring transport for intra-host ranks.
//
// The reference's transport speaks TCP even between ranks on one machine
// (network.go:122-159 dials every peer over the network stack). This is
// the rebuild's native fast path for that case: each connection between
// two ranks on the same host is a pair of single-producer single-consumer
// byte rings in POSIX shared memory (/dev/shm), one ring per direction,
// carrying exactly the same frame stream as the TCP driver's sockets
// (kind:u8 tag:i64le length:u32le payload — backends/tcp.py). The Python
// driver selects this engine via `-mpi-protocol shm`.
//
// Ring layout (one shared-memory object per ring):
//     [0, 4096)   RingHdr (magic, capacity, head/tail counters, futex
//                 words, ready/closed flags; 64-byte-aligned fields so
//                 producer and consumer counters sit on separate lines)
//     [4096, 4096+capacity)   data area, byte ring addressed mod capacity
//
// head counts bytes ever produced, tail bytes ever consumed; both only
// grow (u64 — no wrap at realistic lifetimes). Producer publishes with a
// release store of head after the memcpy; consumer reads with an acquire
// load, and vice versa for tail. Each side bumps its futex word after
// progress and wakes the peer; waits are BOUNDED (2 ms) so a missed wake
// — possible when the peer is the pure-Python fallback ring, which never
// issues futex calls — costs only latency, never a hang. The hot path
// spins briefly before sleeping, so same-host ping-pong latency stays in
// the sub-microsecond range.
//
// Signal cooperation mirrors wirecore.cpp: a futex wait interrupted by a
// signal returns -EINTR to the caller with the operation's progress saved
// inside the handle; re-invoking with identical arguments resumes, and
// between calls CPython runs pending signal handlers (Ctrl+C).
//
// All functions return 0 on success or -errno on failure; kPeerClosed
// (1000) means the peer marked the ring closed and no buffered bytes
// remain. Little-endian hosts only (enforced by the Python loader).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <linux/futex.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x524d4853;  // "SHMR" little-endian
constexpr int kPeerClosed = 1000;
constexpr uint64_t kHdrBytes = 4096;
constexpr uint64_t kFrameHdrLen = 13;
constexpr int kBoundedWaitMs = 2;  // cap per futex sleep; see module doc

// Spin budget before sleeping. Spinning only helps when the peer can
// make progress on ANOTHER core; on a single-core host it actively
// starves the peer (the spinner burns the timeslice the peer needs to
// produce the data), so there the budget is zero and waits go straight
// to futex — which yields the core immediately.
int spin_iters() {
  static const int iters = [] {
    long n = ::sysconf(_SC_NPROCESSORS_ONLN);
    return n > 1 ? 500 : 0;
  }();
  return iters;
}

struct alignas(64) RingHdr {
  uint32_t magic;
  uint32_t capacity;
  std::atomic<uint32_t> ready;
  std::atomic<uint32_t> closed;
  alignas(64) std::atomic<uint64_t> head;  // bytes produced
  std::atomic<uint32_t> wseq;              // futex word: producer progress
  alignas(64) std::atomic<uint64_t> tail;  // bytes consumed
  std::atomic<uint32_t> rseq;              // futex word: consumer progress
};

static_assert(sizeof(RingHdr) <= kHdrBytes, "header must fit its page");

struct Handle {
  RingHdr *hdr;
  uint8_t *data;
  uint64_t map_len;
  int fd;
  // Resumable per-frame progress (one in-flight op per handle: a ring is
  // used in exactly one direction by exactly one thread at a time).
  uint64_t op_done;
  // Latched when a timeout abandons an op MID-FRAME: the stream position
  // is then inside a half-written/half-read frame, so any further op on
  // this handle would silently corrupt framing — every later call fails
  // with -EPIPE until the ring is closed. (-EINTR resumption with
  // identical arguments stays legal: it does not latch.)
  bool poisoned;
  uint8_t frame_hdr[kFrameHdrLen];
};

inline void cpu_relax() {
#if defined(__x86_64__) || defined(__i386__)
  __builtin_ia32_pause();
#endif
}

int futex_wait_bounded(std::atomic<uint32_t> *word, uint32_t expect,
                       int remaining_ms) {
  int ms = remaining_ms < 0 ? kBoundedWaitMs
                            : (remaining_ms < kBoundedWaitMs ? remaining_ms
                                                             : kBoundedWaitMs);
  if (ms <= 0) ms = 1;
  timespec ts{ms / 1000, (ms % 1000) * 1000000L};
  long rc = ::syscall(SYS_futex, reinterpret_cast<uint32_t *>(word),
                      FUTEX_WAIT, expect, &ts, nullptr, 0);
  if (rc == -1 && errno == EINTR) return -EINTR;
  return 0;  // woken, timed out, or value changed — caller re-checks
}

void futex_wake_all(std::atomic<uint32_t> *word) {
  ::syscall(SYS_futex, reinterpret_cast<uint32_t *>(word), FUTEX_WAKE,
            INT32_MAX, nullptr, nullptr, 0);
}

// Monotonic milliseconds now.
int64_t now_ms() {
  timespec ts;
  ::clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// Copy n bytes into the ring at absolute position pos (mod capacity).
void ring_store(Handle *h, uint64_t pos, const uint8_t *src, uint64_t n) {
  uint64_t cap = h->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = n < cap - off ? n : cap - off;
  std::memcpy(h->data + off, src, first);
  if (n > first) std::memcpy(h->data, src + first, n - first);
}

void ring_load(Handle *h, uint64_t pos, uint8_t *dst, uint64_t n) {
  uint64_t cap = h->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = n < cap - off ? n : cap - off;
  std::memcpy(dst, h->data + off, first);
  if (n > first) std::memcpy(dst + first, h->data, n - first);
}

// Producer: append n bytes, blocking for space. Progress in *done.
int ring_write(Handle *h, const uint8_t *src, uint64_t n, int timeout_ms,
               uint64_t *done) {
  RingHdr *r = h->hdr;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  while (*done < n) {
    if (r->closed.load(std::memory_order_acquire)) return kPeerClosed;
    uint64_t head = r->head.load(std::memory_order_relaxed);
    uint64_t tail = r->tail.load(std::memory_order_acquire);
    uint64_t space = r->capacity - (head - tail);
    if (space == 0) {
      bool progressed = false;
      for (int i = spin_iters(); i > 0; --i) {
        cpu_relax();
        if (r->tail.load(std::memory_order_acquire) != tail ||
            r->closed.load(std::memory_order_acquire)) {
          progressed = true;
          break;
        }
      }
      if (!progressed) {
        uint32_t seq = r->rseq.load(std::memory_order_acquire);
        if (r->tail.load(std::memory_order_acquire) == tail &&
            !r->closed.load(std::memory_order_acquire)) {
          int remaining = -1;
          if (deadline >= 0) {
            remaining = int(deadline - now_ms());
            if (remaining <= 0) return -ETIMEDOUT;
          }
          int rc = futex_wait_bounded(&r->rseq, seq, remaining);
          if (rc == -EINTR) return -EINTR;
        }
      }
      continue;
    }
    uint64_t chunk = n - *done < space ? n - *done : space;
    ring_store(h, head, src + *done, chunk);
    r->head.store(head + chunk, std::memory_order_release);
    r->wseq.fetch_add(1, std::memory_order_release);
    futex_wake_all(&r->wseq);
    *done += chunk;
  }
  return 0;
}

// Consumer: read exactly n bytes, blocking for data. Progress in *done.
int ring_read(Handle *h, uint8_t *dst, uint64_t n, int timeout_ms,
              uint64_t *done) {
  RingHdr *r = h->hdr;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  while (*done < n) {
    uint64_t tail = r->tail.load(std::memory_order_relaxed);
    uint64_t head = r->head.load(std::memory_order_acquire);
    uint64_t avail = head - tail;
    if (avail == 0) {
      // closed checked only when drained: buffered bytes stay readable
      // after the peer closes, like a half-closed TCP stream.
      if (r->closed.load(std::memory_order_acquire) &&
          r->head.load(std::memory_order_acquire) == tail)
        return kPeerClosed;
      bool progressed = false;
      for (int i = spin_iters(); i > 0; --i) {
        cpu_relax();
        if (r->head.load(std::memory_order_acquire) != head ||
            r->closed.load(std::memory_order_acquire)) {
          progressed = true;
          break;
        }
      }
      if (!progressed) {
        uint32_t seq = r->wseq.load(std::memory_order_acquire);
        if (r->head.load(std::memory_order_acquire) == head &&
            !r->closed.load(std::memory_order_acquire)) {
          int remaining = -1;
          if (deadline >= 0) {
            remaining = int(deadline - now_ms());
            if (remaining <= 0) return -ETIMEDOUT;
          }
          int rc = futex_wait_bounded(&r->wseq, seq, remaining);
          if (rc == -EINTR) return -EINTR;
        }
      }
      continue;
    }
    uint64_t chunk = n - *done < avail ? n - *done : avail;
    ring_load(h, tail, dst + *done, chunk);
    r->tail.store(tail + chunk, std::memory_order_release);
    r->rseq.fetch_add(1, std::memory_order_release);
    futex_wake_all(&r->rseq);
    *done += chunk;
  }
  return 0;
}

Handle *map_handle(int fd, uint64_t map_len) {
  void *mem =
      ::mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) return nullptr;
  Handle *h = new Handle();
  h->hdr = static_cast<RingHdr *>(mem);
  h->data = static_cast<uint8_t *>(mem) + kHdrBytes;
  h->map_len = map_len;
  h->fd = fd;
  h->op_done = 0;
  h->poisoned = false;
  return h;
}

}  // namespace

extern "C" {

// Create a ring of `capacity` data bytes under shm name `name`
// (must start with '/'). Fails with -EEXIST if the name is live.
// Returns a handle via *out.
int shm_ring_create(const char *name, uint32_t capacity, void **out) {
  *out = nullptr;
  if (capacity == 0) return -EINVAL;
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return -errno;
  uint64_t map_len = kHdrBytes + capacity;
  if (::ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    int e = errno;
    ::close(fd);
    ::shm_unlink(name);
    return -e;
  }
  Handle *h = map_handle(fd, map_len);
  if (h == nullptr) {
    int e = errno;
    ::close(fd);
    ::shm_unlink(name);
    return -e;
  }
  RingHdr *r = h->hdr;
  r->capacity = capacity;
  r->head.store(0, std::memory_order_relaxed);
  r->tail.store(0, std::memory_order_relaxed);
  r->wseq.store(0, std::memory_order_relaxed);
  r->rseq.store(0, std::memory_order_relaxed);
  r->closed.store(0, std::memory_order_relaxed);
  r->magic = kMagic;
  r->ready.store(1, std::memory_order_release);
  *out = h;
  return 0;
}

// Attach to an existing ring. -ENOENT / -EAGAIN mean "not there yet /
// not initialized yet" — the caller retries until its init timeout
// (the dial-retry loop, network.go:297-312).
int shm_ring_attach(const char *name, void **out) {
  *out = nullptr;
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -errno;
  struct stat st;
  if (::fstat(fd, &st) != 0 || static_cast<uint64_t>(st.st_size) < kHdrBytes) {
    ::close(fd);
    return -EAGAIN;
  }
  Handle *probe = map_handle(fd, kHdrBytes);
  if (probe == nullptr) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  bool ready = probe->hdr->ready.load(std::memory_order_acquire) == 1 &&
               probe->hdr->magic == kMagic;
  uint32_t capacity = probe->hdr->capacity;
  ::munmap(probe->hdr, probe->map_len);
  delete probe;
  if (!ready || static_cast<uint64_t>(st.st_size) < kHdrBytes + capacity) {
    ::close(fd);
    return -EAGAIN;
  }
  Handle *h = map_handle(fd, kHdrBytes + capacity);
  if (h == nullptr) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  *out = h;
  return 0;
}

int shm_ring_unlink(const char *name) {
  return ::shm_unlink(name) == 0 ? 0 : -errno;
}

// Mark closed and wake both sides; safe from either end, idempotent.
void shm_ring_mark_closed(void *handle) {
  Handle *h = static_cast<Handle *>(handle);
  h->hdr->closed.store(1, std::memory_order_release);
  h->hdr->wseq.fetch_add(1, std::memory_order_release);
  h->hdr->rseq.fetch_add(1, std::memory_order_release);
  futex_wake_all(&h->hdr->wseq);
  futex_wake_all(&h->hdr->rseq);
}

void shm_ring_close(void *handle) {
  Handle *h = static_cast<Handle *>(handle);
  ::munmap(h->hdr, h->map_len);
  ::close(h->fd);
  delete h;
}

// A -ETIMEDOUT that strands the stream inside a frame latches the
// poison flag (see Handle::poisoned); a timeout at a frame boundary
// leaves the handle clean and retryable.
static int poison_if_midframe(Handle *h, int rc) {
  if (rc == -ETIMEDOUT && h->op_done != 0) h->poisoned = true;
  return rc;
}

// Send one frame (header + payload). Resumes after -EINTR when called
// again with identical arguments; progress lives in the handle.
int shm_send_frame(void *handle, uint8_t kind, int64_t tag,
                   const uint8_t *payload, uint32_t length, int timeout_ms) {
  Handle *h = static_cast<Handle *>(handle);
  if (h->poisoned) return -EPIPE;
  if (h->op_done == 0) {
    h->frame_hdr[0] = kind;
    std::memcpy(h->frame_hdr + 1, &tag, 8);
    std::memcpy(h->frame_hdr + 9, &length, 4);
  }
  if (h->op_done < kFrameHdrLen) {
    int rc = ring_write(h, h->frame_hdr, kFrameHdrLen, timeout_ms,
                        &h->op_done);
    if (rc != 0) return poison_if_midframe(h, rc);
  }
  uint64_t payload_done = h->op_done - kFrameHdrLen;
  int rc = ring_write(h, payload, length, timeout_ms, &payload_done);
  h->op_done = kFrameHdrLen + payload_done;
  if (rc != 0) return poison_if_midframe(h, rc);
  h->op_done = 0;
  return 0;
}

// Two-segment frame send: header + prefix + payload streamed through
// the ring as ONE frame of length prefix_len + payload_len — the
// zero-copy path for ndarray sends (the codec's type prefix and the
// array memory are never concatenated in user space). Resumable after
// -EINTR via h->op_done, which spans header + both segments.
int shm_send_frame2(void *handle, uint8_t kind, int64_t tag,
                    const uint8_t *prefix, uint32_t prefix_len,
                    const uint8_t *payload, uint32_t payload_len,
                    int timeout_ms) {
  Handle *h = static_cast<Handle *>(handle);
  if (h->poisoned) return -EPIPE;
  const uint64_t length64 =
      static_cast<uint64_t>(prefix_len) + payload_len;
  if (length64 > 0xFFFFFFFFull) return -EMSGSIZE;
  const uint32_t length = static_cast<uint32_t>(length64);
  if (h->op_done == 0) {
    h->frame_hdr[0] = kind;
    std::memcpy(h->frame_hdr + 1, &tag, 8);
    std::memcpy(h->frame_hdr + 9, &length, 4);
  }
  if (h->op_done < kFrameHdrLen) {
    int rc = ring_write(h, h->frame_hdr, kFrameHdrLen, timeout_ms,
                        &h->op_done);
    if (rc != 0) return poison_if_midframe(h, rc);
  }
  uint64_t done = h->op_done - kFrameHdrLen;
  if (done < prefix_len) {
    int rc = ring_write(h, prefix, prefix_len, timeout_ms, &done);
    h->op_done = kFrameHdrLen + done;
    if (rc != 0) return poison_if_midframe(h, rc);
  }
  uint64_t payload_done = h->op_done - kFrameHdrLen - prefix_len;
  int rc = ring_write(h, payload, payload_len, timeout_ms,
                      &payload_done);
  h->op_done = kFrameHdrLen + prefix_len + payload_done;
  if (rc != 0) return poison_if_midframe(h, rc);
  h->op_done = 0;
  return 0;
}

// Phase 1 of a receive: the 13-byte frame header. Resumable after
// -EINTR. On success the parsed fields are returned and the handle is
// ready for shm_recv_payload (which must consume exactly *length).
int shm_recv_hdr(void *handle, uint8_t *kind, int64_t *tag, uint32_t *length,
                 int timeout_ms) {
  Handle *h = static_cast<Handle *>(handle);
  if (h->poisoned) return -EPIPE;
  int rc = ring_read(h, h->frame_hdr, kFrameHdrLen, timeout_ms, &h->op_done);
  if (rc != 0) return poison_if_midframe(h, rc);
  h->op_done = 0;
  *kind = h->frame_hdr[0];
  std::memcpy(tag, h->frame_hdr + 1, 8);
  std::memcpy(length, h->frame_hdr + 9, 4);
  return 0;
}

// Phase 2: the payload bytes announced by the last shm_recv_hdr.
int shm_recv_payload(void *handle, uint8_t *buf, uint32_t length,
                     int timeout_ms) {
  Handle *h = static_cast<Handle *>(handle);
  if (h->poisoned) return -EPIPE;
  // A timeout here is mid-frame BY DEFINITION (the header announcing
  // this payload was already consumed), even at op_done == 0.
  int rc = ring_read(h, buf, length, timeout_ms, &h->op_done);
  if (rc == -ETIMEDOUT) { h->poisoned = true; return rc; }
  if (rc != 0) return rc;
  h->op_done = 0;
  return 0;
}

// The Python side abandons an in-flight op when ITS deadline expires
// between -EINTR resumes (the native call itself returned resumable).
// Latch poison if that strands the stream mid-frame; `force` covers
// ops that are mid-frame even at op_done == 0 (a payload read whose
// header was already consumed). Returns 1 if the handle is poisoned.
int shm_abandon(void *handle, int force) {
  Handle *h = static_cast<Handle *>(handle);
  if (force || h->op_done != 0) h->poisoned = true;
  return h->poisoned ? 1 : 0;
}

int shm_version() { return 2; }

}  // extern "C"

// quantcore — blockwise int8 quantization kernels for the compressed
// wire allreduce (mpi_tpu/compressed.py:allreduce_compressed_wire).
//
// The decomposition measurement behind this library (round 5,
// docs/PERF_NOTES.md): on the socket fabric the int8 path's wire
// saving (4x fewer bytes) beats the exact float allreduce at >= 64 MiB
// ONLY if quantization costs ~one memory pass — numpy's ~7 full-array
// passes (abs, max, divide, round, clip, cast, multiply) erase the
// margin. These kernels fuse each phase into a single streaming pass,
// called via ctypes (GIL released for the whole call, like wirecore).
//
// Semantics mirror mpi_tpu/parallel/quantized.py:quantize_blocks
// exactly: symmetric per-block scaling s = amax/127 (amax == 0 ->
// s = 1), q = clip(round(x/s), -127, 127); a block containing
// non-finite values gets scale = NaN so divergence stays loud through
// dequantization instead of being laundered into finite garbage.
//
// All functions return 0; n must be a multiple of block (the Python
// caller pads). Little-endian irrelevant here (no wire framing).

#include <cmath>
#include <cstdint>

extern "C" {

// q[i] = clip(round(x[i]/s_blk)); one pass, amax and quantize fused
// per block (the block re-read hits L1/L2 by construction:
// block <= 4096 floats = 16 KiB).
int qc_quantize(const float *x, uint64_t n, uint32_t block,
                int8_t *q, float *scales) {
  const uint64_t nblk = n / block;
  for (uint64_t b = 0; b < nblk; ++b) {
    const float *xb = x + b * block;
    float amax = 0.0f;
    bool finite = true;
    for (uint32_t i = 0; i < block; ++i) {
      const float v = xb[i];
      if (!std::isfinite(v)) finite = false;
      const float a = std::fabs(v);
      if (a > amax) amax = a;
    }
    // Bit-identical to the numpy reference (quantize_np): the SAFE
    // value ignores a non-finite amax (safe=127 -> s=1, matching
    // np.where(finite & (amax > 0), amax, 127.0)), and the quantize
    // DIVIDES by s — an x * (1/s) would round differently by 1 ulp
    // near half-integers and break the exact parity test.
    const float safe = (finite && amax > 0.0f) ? amax : 127.0f;
    const float s = safe / 127.0f;
    int8_t *qb = q + b * block;
    for (uint32_t i = 0; i < block; ++i) {
      float r = std::nearbyintf(xb[i] / s);
      if (r > 127.0f) r = 127.0f;
      if (r < -127.0f) r = -127.0f;
      // NaN input: NaN/s rounds to NaN, comparisons fail, and the
      // cast below is UB — map it to 0; the NaN SCALE poisons the
      // whole block at dequantization anyway.
      qb[i] = std::isnan(r) ? 0 : static_cast<int8_t>(r);
    }
    scales[b] = finite ? s : std::nanf("");
  }
  return 0;
}

// acc[i] += q[i] * s_blk — the dequantizing accumulation of one
// rank's quantized shard into the float32 partial (phase 1).
int qc_accumulate(const int8_t *q, const float *scales, uint64_t n,
                  uint32_t block, float *acc) {
  const uint64_t nblk = n / block;
  for (uint64_t b = 0; b < nblk; ++b) {
    const float s = scales[b];
    const int8_t *qb = q + b * block;
    float *ab = acc + b * block;
    for (uint32_t i = 0; i < block; ++i) {
      ab[i] += static_cast<float>(qb[i]) * s;
    }
  }
  return 0;
}

// out[i] = q[i] * s_blk (phase-2 expansion of the gathered shards).
int qc_dequantize(const int8_t *q, const float *scales, uint64_t n,
                  uint32_t block, float *out) {
  const uint64_t nblk = n / block;
  for (uint64_t b = 0; b < nblk; ++b) {
    const float s = scales[b];
    const int8_t *qb = q + b * block;
    float *ob = out + b * block;
    for (uint32_t i = 0; i < block; ++i) {
      ob[i] = static_cast<float>(qb[i]) * s;
    }
  }
  return 0;
}

int qc_version() { return 1; }

}  // extern "C"
